"""Chip-window watcher: probe the TPU tunnel, fire the measurement battery.

The axon tunnel wedges for hours at a time (round-3 postmortem: the only
chip window of the session was 15 minutes, and everything not already
scripted was lost). This watcher loops a bounded backend probe and, on the
FIRST success, runs the full round evidence agenda in priority order,
flushing each artifact to the repo root the moment it exists so a window
that dies mid-battery still leaves everything earlier on disk (ROUND below
is WATCHER_ROUND, defaulting to the single-sourced tools/ROUND file):

  1. bench.py                    -> BENCH_LOCAL_{ROUND}.json  (headline
     debt: walker, native control, kernel A/B, epoch breakdown, XLA-dense
     control, config #2, epochs-to-0.88; opportunistically refreshes
     TPU_ACCEPTANCE.json via its acceptance stage — auto backend: native
     walks on this host, training on the chip)
  2. tools/profile_walker.py     -> PROFILE_WALKER_{ROUND}.json (the
     rebuilt+segmented step's isolated throughput incl. the seg1_full A/B,
     VERDICT r4 task 3)
  3. tools/profile_ops.py        -> PROFILE_OPS_{ROUND}.json
  4. tools/tpu_acceptance.py with G2VEC_ACCEPT_WALKER=device
                                 -> TPU_ACCEPTANCE_device.json (real-chip
     device-walker acceptance coverage next to the default artifact)
  5. tools/scale_demo.py         -> SCALE_DEMO_TPU_{ROUND}.json (config #3
     chip trainer sec/epoch + config #5 TP trainer step, VERDICT r4
     task 5)

Each stage runs in a subprocess with its own timeout; a hang or crash is
recorded in the stage's artifact and the battery moves on. The watcher
exits after one battery (rerun it for another window). Progress streams to
stderr and to WATCHER_STATUS_{ROUND}.json.

Run detached:  nohup python tools/chip_watcher.py >/tmp/chip_watcher.log 2>&1 &
Artifacts are committed by whoever finds them (the round's rule: evidence
lands with the commit that cites it).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROBE_CMD = [sys.executable, os.path.join(REPO, "bench.py"), "--_probe"]
PROBE_TIMEOUT = int(os.environ.get("WATCHER_PROBE_TIMEOUT", "75"))
PROBE_INTERVAL = int(os.environ.get("WATCHER_PROBE_INTERVAL", "240"))
MAX_HOURS = float(os.environ.get("WATCHER_MAX_HOURS", "11"))


def _default_round() -> str:
    """The round id's single source (tools/ROUND, ADVICE r5 #2): bumping
    the round for a new evidence cycle is one file edit that bench.py,
    watch_loop.sh, and this watcher all see — two independently hardcoded
    defaults once let a stale round's numbers be relayed as current."""
    try:
        with open(os.path.join(REPO, "tools", "ROUND")) as f:
            return f.read().strip() or "r00"
    except OSError:
        return "r00"


ROUND = os.environ.get("WATCHER_ROUND") or _default_round()
# Child stages (bench.py's relay path) resolve the round from this env var
# ONLY — export it so a watcher launched bare keeps its battery coherent.
os.environ.setdefault("WATCHER_ROUND", ROUND)
# "first" = the from-scratch battery; "second" = the follow-up plan once
# the headline bench has landed (see battery()). WATCHER_SKIP_DONE=1 makes
# repeat batteries resume: a stage whose artifact is already on disk with
# rc==0 is not re-run (and cannot be clobbered by a window dying mid-rerun).
PLAN = os.environ.get("WATCHER_PLAN", "first")
SKIP_DONE = os.environ.get("WATCHER_SKIP_DONE") == "1"
STATUS = os.environ.get("WATCHER_STATUS_PATH",
                        os.path.join(REPO, f"WATCHER_STATUS_{ROUND}.json"))
T0 = time.time()


def note(msg: str) -> None:
    print(f"[{time.time() - T0:8.1f}s] {msg}", file=sys.stderr, flush=True)


def write_status(state: dict) -> None:
    state["updated_unix"] = int(time.time())
    with open(STATUS, "w") as f:
        json.dump(state, f, indent=2)
        f.write("\n")


def probe() -> dict | None:
    """One bounded backend probe; returns the probe info dict on success."""
    try:
        proc = subprocess.run(PROBE_CMD, capture_output=True, text=True,
                              timeout=PROBE_TIMEOUT)
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode == 0 and proc.stdout.strip():
        try:
            info = json.loads(proc.stdout.strip().splitlines()[-1])
        except ValueError:
            return None
        if info.get("platform") == "tpu":
            return info
    return None


def run_stage(name: str, cmd: list, timeout: int, out_path: str | None,
              env_extra: dict | None = None) -> dict:
    """Run one battery stage; always returns (and optionally writes) a
    record with whatever the stage produced before finishing/dying."""
    note(f"stage {name}: {' '.join(os.path.basename(c) for c in cmd)} "
         f"(timeout {timeout}s)")
    env = dict(os.environ, **(env_extra or {}))
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=env, cwd=REPO)
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc = -9
        out = (e.stdout or b"").decode(errors="replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode(errors="replace") \
            if isinstance(e.stderr, bytes) else (e.stderr or "")
        err += f"\n[watcher] killed at {timeout}s"
    parsed = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed.append(json.loads(line))
            except ValueError:
                pass
    record = {"stage": name, "rc": rc, "wall_seconds": round(time.time() - t0, 1),
              "lines": parsed, "stderr_tail": err[-2500:]}
    if out_path:
        # A re-run must not regress the evidence record: lines a previous
        # (partial) run captured with real values are salvaged into the
        # new record unless this run re-measured the same metric. Each
        # carried line is tagged with per-line provenance (ADVICE r5 #3) —
        # the new record's rc/stderr belong to THIS run, so without the
        # tag a consumer could not tell fresh from carried measurements.
        prev_rc, prev_mtime, prev_lines = None, None, []
        try:
            prev_mtime = int(os.path.getmtime(out_path))
            with open(out_path) as f:
                prev_record = json.load(f)
            prev_rc = prev_record.get("rc")
            prev_lines = prev_record.get("lines", [])
        except (OSError, ValueError):
            prev_lines = []
        have = {d.get("metric") for d in parsed
                if isinstance(d, dict) and d.get("value") is not None}
        # A line salvaged across several re-runs keeps its ORIGINAL
        # provenance (d's existing tags win over this run's).
        salvaged = [{"salvaged": True, "salvaged_from_rc": prev_rc,
                     "salvaged_from_unix": prev_mtime, **d}
                    for d in prev_lines
                    if isinstance(d, dict) and d.get("value") is not None
                    and d.get("metric") not in have]
        if salvaged:
            record["lines"] = parsed + salvaged
            record["salvaged_lines"] = len(salvaged)
        with open(out_path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        note(f"stage {name}: rc={rc}, {len(parsed)} json lines -> "
             f"{os.path.basename(out_path)}")
    else:
        note(f"stage {name}: rc={rc}, {len(parsed)} json lines")
    return record


def _stage_done(artifact: str, required_metrics: tuple = ()) -> bool:
    """True if a previous window already landed this stage: rc==0 record,
    and (for stages re-run to collect specific lines) every required
    metric present with a non-null value — bench exits 0 even when
    guarded() budget-skips a stage to a null line, so rc alone would
    declare victory with the target metrics still missing."""
    try:
        with open(artifact) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return False
    if rec.get("rc") != 0:
        return False
    # A relayed line (bench re-emitting an earlier window's number) is
    # not a fresh measurement: counting it would stop the loop from ever
    # re-measuring a metric whose stage was merely budget-skipped.
    landed = {d.get("metric"): d.get("value") for d in rec.get("lines", [])
              if isinstance(d, dict) and "chip_window_relay" not in d}
    return all(landed.get(m) is not None for m in required_metrics)


def battery(info: dict) -> None:
    py = sys.executable
    stages = [
        # (name, cmd, timeout, artifact, env)
        # The driver's own bench run lives under a ~560s kill, so bench's
        # default budgets make the child skip late stages (acceptance 180s
        # + kernel A/B + breakdown + XLA control + config2 ~= 675s of
        # stage estimates vs a 400s child). The watcher has no such kill:
        # grant the full battery in ONE bench run — every armed VERDICT
        # metric plus the opportunistic TPU_ACCEPTANCE refresh — and rely
        # on per-line flushing if the window dies mid-run.
        ("bench", [py, os.path.join(REPO, "bench.py")], 900,
         os.path.join(REPO, f"BENCH_LOCAL_{ROUND}.json"),
         {"G2VEC_BENCH_TOTAL_BUDGET": "860",
          "G2VEC_BENCH_TIMEOUT": "800",
          "G2VEC_BENCH_CHILD_BUDGET": "780"}),
        ("profile_walker",
         [py, os.path.join(REPO, "tools", "profile_walker.py")], 600,
         os.path.join(REPO, f"PROFILE_WALKER_{ROUND}.json"), None),
        ("profile_ops",
         [py, os.path.join(REPO, "tools", "profile_ops.py")], 420,
         os.path.join(REPO, f"PROFILE_OPS_{ROUND}.json"), None),
        # These two tools write their own primary artifacts
        # (TPU_ACCEPTANCE_device.json / SCALE_DEMO_TPU_{ROUND}.json); the
        # stage record still lands on disk so a killed/hung run leaves its
        # stderr diagnostics behind.
        ("acceptance_device",
         [py, os.path.join(REPO, "tools", "tpu_acceptance.py")], 420,
         os.path.join(REPO, f"WATCHER_STAGE_acceptance_device_{ROUND}.json"),
         # Cached twin: its XLA compiles persist across watcher reruns /
         # later windows, so a repeat battery pays the ~7-stage compile
         # bill once (recorded in the artifact as compilation_cache_used;
         # the primary TPU_ACCEPTANCE stays cold-start comparable).
         {"G2VEC_ACCEPT_WALKER": "device",
          "G2VEC_ACCEPT_COMPILE_CACHE": "/tmp/g2vec-accept-xla-cache"}),
        ("scale_demo",
         [py, os.path.join(REPO, "tools", "scale_demo.py"),
          "--out", os.path.join(REPO, f"SCALE_DEMO_TPU_{ROUND}.json")], 600,
         os.path.join(REPO, f"WATCHER_STAGE_scale_demo_{ROUND}.json"), None),
    ]
    if PLAN == "second":
        # Second-window plan: the headline bench already landed (window #1),
        # so the TPU_ACCEPTANCE refresh runs FIRST (on a healthy chip it's
        # ~47 s wall, r2's record; window #1's 600 s was a dying tunnel
        # blocked inside a compile) so the bench re-run's epochs-to-0.88
        # line reads the just-refreshed artifact. The bench then skips its
        # in-bench acceptance (G2VEC_BENCH_SKIP_ACCEPT) and spends the
        # whole child budget on the never-landed metric lines — kernel
        # A/B, epoch breakdown + roofline, XLA-dense control, config #2
        # (VERDICT r4 tasks 1+2) — then the profilers. A persistent XLA
        # cache on the bench stage makes a window-3 repeat cheap;
        # steady-state timings are unaffected (no metric measures compile
        # time).
        by_name = {s[0]: s for s in stages}
        b_name, b_cmd, b_to, _b_art, b_env = by_name["bench"]
        bench_art = os.path.join(REPO, f"BENCH_LOCAL_{ROUND}b.json")
        stages = [
            ("acceptance",
             [py, os.path.join(REPO, "tools", "tpu_acceptance.py")], 420,
             os.path.join(REPO, f"WATCHER_STAGE_acceptance_{ROUND}.json"),
             None),
            # Distinct artifact: window #1's headline BENCH_LOCAL_{ROUND}
            # stays immutable; this run's new lines land next to it.
            (b_name, b_cmd, b_to, bench_art,
             dict(b_env, G2VEC_BENCH_SKIP_ACCEPT="1",
                  JAX_COMPILATION_CACHE_DIR="/tmp/g2vec-bench-xla-cache")),
            by_name["profile_walker"],
            by_name["profile_ops"],
            by_name["acceptance_device"],
            by_name["scale_demo"],
        ]
    # The bench stage exists to land THESE lines; rc==0 with any of them
    # null (budget-skipped, or a truncated window-#1-style record) must
    # not count as done — keyed on the ACTIVE plan's bench artifact only,
    # so a superseded artifact from the other plan can't hold the battery
    # in "incomplete" forever.
    required = {s[3]: ("cbow_train_paths_per_sec_per_chip",
                       "packed_matmul_vs_xla_dense",
                       # Extended PR-4 breakdown: fused-eval term,
                       # superstep A/B, kernel tile attribution.
                       "cbow_epoch_breakdown",
                       "cbow_train_xla_dense_sec_per_epoch",
                       "config2_train_paths_per_sec_per_chip",
                       # The apples-to-apples 7,523-gene stage-3 walker
                       # line (VERDICT item 8) — both backends.
                       "walker_restricted_walks_per_sec")
                for s in stages if s[0] == "bench"}
    done = []
    aborted = False
    for name, cmd, timeout, artifact, env in stages:
        if SKIP_DONE and artifact and _stage_done(artifact,
                                                  required.get(artifact, ())):
            note(f"stage {name}: rc=0 artifact already on disk, skipping")
            done.append({"stage": name, "rc": 0,
                         "skipped": "landed in an earlier window"})
            continue
        rec = run_stage(name, cmd, timeout, artifact, env)
        done.append({"stage": name, "rc": rec["rc"],
                     "wall_seconds": rec["wall_seconds"]})
        write_status({"state": "battery", "probe": info, "stages": done})
        # Re-probe between stages: if the tunnel died, stop burning
        # timeouts against a wedge — artifacts so far are already on disk.
        if name != stages[-1][0] and probe() is None:
            note("tunnel died mid-battery; stopping")
            done.append({"stage": "abort", "reason": "tunnel died"})
            aborted = True
            break
    # A stage can exit rc==0 with its target lines budget-skipped to null;
    # report that as incomplete so the outer watch_loop re-arms.
    unmet = [os.path.basename(a) for a, req in required.items()
             if not _stage_done(a, req)]
    state = "aborted" if aborted else ("incomplete" if unmet else "done")
    final = {"state": state, "probe": info, "stages": done}
    if unmet:
        final["unmet_required"] = unmet
    write_status(final)
    note("battery aborted mid-window — rerun the watcher for another "
         "window" if aborted else f"battery {state}")


def check_complete() -> int:
    """--check-complete: exit 0 iff the last battery landed everything —
    state 'done' and every recorded stage rc==0 or skipped-as-done. The
    watch_loop's re-arm predicate, kept here (not as an inline heredoc in
    the shell) so it is testable and single-sourced."""
    try:
        with open(STATUS) as f:
            s = json.load(f)
    except (OSError, ValueError):
        return 1
    stages = [r for r in s.get("stages", [])
              if "rc" in r or "skipped" in r]
    ok = s.get("state") == "done" and stages and all(
        r.get("rc") == 0 or r.get("skipped") for r in stages)
    return 0 if ok else 1


def main() -> None:
    if "--check-complete" in sys.argv:
        raise SystemExit(check_complete())
    write_status({"state": "probing", "since_unix": int(T0)})
    attempt = 0
    while time.time() - T0 < MAX_HOURS * 3600:
        attempt += 1
        info = probe()
        if info is not None:
            note(f"chip alive: {info}")
            write_status({"state": "battery", "probe": info, "stages": []})
            battery(info)
            return
        if attempt % 5 == 1:
            note(f"probe {attempt}: tunnel dead")
            write_status({"state": "probing", "attempts": attempt,
                          "since_unix": int(T0)})
        time.sleep(PROBE_INTERVAL)
    note("gave up: max watch time reached")
    write_status({"state": "expired", "attempts": attempt})


if __name__ == "__main__":
    main()
