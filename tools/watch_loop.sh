#!/bin/bash
# Re-arm the chip watcher across tunnel windows until every stage of the
# current plan has landed (rc==0 or skipped-as-done). Windows last ~15 min
# and the watcher exits after one battery, so evidence collection over a
# multi-hour round needs this outer loop. WATCHER_SKIP_DONE keeps landed
# artifacts immutable across re-runs.
#
#   WATCHER_ROUND=r05 WATCHER_PLAN=second nohup bash tools/watch_loop.sh \
#       >/tmp/chip_watcher_loop.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PY="${PYTHON:-python3}"
ROUND="${WATCHER_ROUND:-$(cat tools/ROUND)}"
export WATCHER_ROUND="$ROUND" WATCHER_SKIP_DONE=1
# Bounded: a deterministically failing stage must not burn chip windows
# forever, and the loop must not outlive the round. Each watcher
# invocation gets the REMAINING loop budget as its probe bound.
# The deadline is computed in python (not bash integer arithmetic) so a
# fractional LOOP_MAX_HOURS (e.g. 0.5) works (ADVICE r5 #4).
MAX_ARMS="${LOOP_MAX_ARMS:-12}"
DEADLINE=$("$PY" -c "import sys,time;print(int(time.time()+float(sys.argv[1])*3600))" "${LOOP_MAX_HOURS:-10}")
arms=0
while [ "$arms" -lt "$MAX_ARMS" ] && [ "$(date +%s)" -lt "$DEADLINE" ]; do
    arms=$((arms + 1))
    # Resilience regression gate, re-run every arm on host CPU: the
    # single-process fault matrix, the multi-rank fleet matrix
    # (watchdogs, rank-scoped kills, degraded-mesh resume) on virtual
    # devices, the overlap/cache suite (scheduler drains cleanly on
    # stage failure — no deadlock, original exception propagates — plus
    # the walk-cache verify matrix), the batch-engine lane matrix
    # (per-lane bitwise parity vs solo runs, manifest validation, walk
    # share accounting), the serve matrix (admission control, job
    # joining, served-vs-solo byte parity, supervisor SIGKILL re-queue),
    # the stream matrix (ring backpressure/no-deadlock edges,
    # thread/depth-invariant trajectories, full-batch parity band,
    # bounded-memory + overlap assertions, shard_ring/prefetch drills),
    # and the shard matrix (gene-range partitioning, chunked KV
    # transport boundaries, 1-rank byte parity, multi-rank statistical
    # parity, shard_exchange/embed_allreduce sigkill drills), and the
    # edge matrix (owner-range partitioning, handoff-vs-halo byte
    # identity, range-reader pins, walk_handoff/halo_build sigkill
    # drills), and the scenario matrix (reducer units, plan/seed-tree
    # determinism, replicate-vs-solo byte parity, permutation walk
    # accounting, serve-path exactly-once SIGKILL drill), and the query
    # matrix (blocked top-k kernel exactness vs numpy, bundle
    # tamper/torn integrity drills, mmap LRU byte budget, daemon query
    # ops + token gating, lazy republish, result bounding, router
    # failover reads), and the autoscale matrix (token-bucket/shed/
    # scaling-policy units, weighted-fair convergence, controller
    # hysteresis, client shed backoff, router aggregate status), and the
    # ann matrix (IVF build/probe units, nprobe>=nlist bitwise equality,
    # the recall@k contract at pruning scale, index tamper/corrupt
    # exact-fallback drills, federated fquery scatter-gather with
    # dead-owner attribution), and the update matrix (delta-range/
    # fingerprint/frontier units, bootstrap->noop byte identity,
    # expr-only stage-3 skip, delta re-walk + statistical band vs cold
    # retrain, daemon update lifecycle, generation-keyed QueryCache,
    # cross-republish torn-read hammer, update_publish SIGKILL drill),
    # and the device-walker matrix (splitmix64 lane-pair fuzz, host/
    # device packed-row byte parity, suspend/resume rng word parity,
    # walk-cache cross-backend HIT, device_walk fault drills, fused
    # --device-feed zero-ring-puts e2e).
    # Non-fatal: a red matrix is reported, the chip battery still runs.
    if ! JAX_PLATFORMS=cpu "$PY" -m pytest tests/test_resilience.py \
            tests/test_fleet.py tests/test_fleet_e2e.py \
            tests/test_overlap_cache.py tests/test_batch_engine.py \
            tests/test_serve.py tests/test_stream.py tests/test_shard.py \
            tests/test_router.py tests/test_edge.py \
            tests/test_scenario.py tests/test_query.py \
            tests/test_autoscale.py tests/test_ann.py \
            tests/test_update.py tests/test_device_walker.py \
            -q -m "not slow" \
            -p no:cacheprovider >/tmp/fault_matrix_arm$arms.log 2>&1; then
        echo "[watch_loop] WARNING: fault/fleet matrix FAILED on arm $arms (log: /tmp/fault_matrix_arm$arms.log)"
    else
        echo "[watch_loop] fault/fleet matrix green (arm $arms)"
    fi
    # Static-analysis gate, every arm: the project-invariant lint suite
    # (lock discipline, jax-purity boundaries, fault-seam and metrics
    # schema registries, config/doc drift). Pure AST — sub-second, no
    # jax init — so it runs unconditionally. Exit 1 means a real
    # invariant regressed (or a baseline entry went stale); non-fatal
    # like the matrix, but loud.
    if ! "$PY" -m g2vec_tpu analyze >/tmp/analyze_arm$arms.log 2>&1; then
        echo "[watch_loop] WARNING: static analysis FAILED on arm $arms (log: /tmp/analyze_arm$arms.log)"
    else
        echo "[watch_loop] static analysis green (arm $arms)"
    fi
    # Chaos soak (every 3rd arm): the randomized fault storm against the
    # serve daemon — SIGKILL / drain / armed seams / cancels under
    # Poisson arrivals — shrunk to stay inside an arm's budget. The
    # acceptance is exactly-once accounting, so any red here is a real
    # durability regression. Non-fatal like the matrix above.
    if [ $((arms % 3)) -eq 1 ]; then
        if ! JAX_PLATFORMS=cpu G2V_CHAOS_JOBS=10 G2V_CHAOS_BUDGET=420 \
                "$PY" -m pytest tests/test_chaos.py -q -m chaos \
                -p no:cacheprovider >/tmp/chaos_arm$arms.log 2>&1; then
            echo "[watch_loop] WARNING: chaos soak FAILED on arm $arms (log: /tmp/chaos_arm$arms.log)"
        else
            echo "[watch_loop] chaos soak green (arm $arms)"
        fi
    fi
    # Partition drill (every 3rd arm, offset from the chaos soak): the
    # control-plane storm — relay-blackholed replica fenced +
    # self-quarantined, zombie-leader commands rejected by epoch,
    # standby takeovers with degraded-mode clients in the gaps —
    # shrunk to one takeover round to fit the arm. Non-fatal but loud:
    # red here means split-brain protection regressed.
    if [ $((arms % 3)) -eq 2 ]; then
        if ! JAX_PLATFORMS=cpu G2V_CHAOS_JOBS=6 G2V_CHAOS_BUDGET=420 \
                G2V_CHAOS_TAKEOVERS=1 G2V_CHAOS_STREAM_FRAC=0 \
                G2V_CHAOS_VERIFY=1 \
                "$PY" -m pytest tests/test_chaos.py -q -m partition \
                -p no:cacheprovider >/tmp/partition_arm$arms.log 2>&1; then
            echo "[watch_loop] WARNING: partition drill FAILED on arm $arms (log: /tmp/partition_arm$arms.log)"
        else
            echo "[watch_loop] partition drill green (arm $arms)"
        fi
    fi
    left_h=$("$PY" -c "import sys,time;print(max(0.1,(float(sys.argv[1])-time.time())/3600))" "$DEADLINE")
    WATCHER_MAX_HOURS="$left_h" "$PY" tools/chip_watcher.py
    if "$PY" tools/chip_watcher.py --check-complete; then
        echo "[watch_loop] all stages landed"
        exit 0
    fi
    echo "[watch_loop] battery incomplete (arm $arms/$MAX_ARMS); re-arming in 60s"
    sleep 60
done
echo "[watch_loop] gave up: arms=$arms deadline reached"
exit 1
