"""Fast path-count calibration for data/realistic.py via the native sampler.

VERDICT r2 weak #4: the realistic stand-in yields ~15% fewer unique paths
than the reference transcript (38.6k vs 45,402) at a near-exact path-GENE
match (3,858 vs 3,773) — i.e. 10.0 paths/gene vs the transcript's 12.03,
pointing at planted-module branching density, not module size. Sweeping
that with the device walker costs ~5 min per trial on this 1-core host;
the native C++ sampler (ops/host_walker.py) has identical walk semantics
and runs a full two-group, reps=10, lenPath=80 trial in ~20 s, so it is
the calibration surrogate. (Path-count statistics transfer between the
backends to within a few percent — same graphs, same walk law, different
PRNG family.)

Run:  python tools/calibrate_real.py ['name=<RealExampleSpec kwargs>' ...]
e.g.  python tools/calibrate_real.py 'shared=n_active_per_group=1500, n_shared=760'
Always runs the default spec first ("baseline"); prints one JSON line per
spec with n_paths / n_path_genes vs the transcript.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NET = "/root/reference/ex_NETWORK.txt"
CLIN = "/root/reference/ex_CLINICAL.txt"
TRANSCRIPT = {"n_paths": 45402, "n_path_genes": 3773}


def run_trial(spec) -> dict:
    import numpy as np

    from g2vec_tpu.data.realistic import make_real_expression
    from g2vec_tpu.io.readers import ExpressionData, load_clinical, load_network
    from g2vec_tpu.ops.graph import thresholded_edges
    from g2vec_tpu.ops.host_walker import generate_path_set_native
    from g2vec_tpu.ops.walker import count_gene_freq, integrate_path_sets
    from g2vec_tpu.preprocess import (edges_to_indices, find_common_genes,
                                      make_gene2idx, match_labels,
                                      restrict_data, restrict_network)

    t0 = time.time()
    expression, _ = make_real_expression(NET, CLIN, spec)
    clinical = load_clinical(CLIN)
    network = load_network(NET)
    label = match_labels(clinical, expression.sample)
    common = find_common_genes(network.genes, expression.gene)
    network = restrict_network(network, common)
    data = restrict_data(
        ExpressionData(sample=expression.sample, gene=expression.gene,
                       expr=expression.expr), common)
    gene2idx = make_gene2idx(data.gene)
    src, dst = edges_to_indices(network, gene2idx)
    n_genes = data.expr.shape[1]

    sets = []
    for i in (0, 1):
        expr_group = data.expr[label == i]
        s_k, d_k, w_k = thresholded_edges(expr_group, src, dst, threshold=0.5)
        sets.append(generate_path_set_native(
            np.asarray(s_k), np.asarray(d_k), np.asarray(w_k), n_genes,
            len_path=80, reps=10, seed=i))
    paths, labels_arr = integrate_path_sets(sets[0], sets[1], n_genes,
                                            packed=True)
    freq = count_gene_freq(paths, labels_arr, list(data.gene), packed=True)
    return {"n_paths": int(paths.shape[0]), "n_path_genes": len(freq),
            "paths_per_gene": round(paths.shape[0] / max(len(freq), 1), 2),
            "vs_transcript_paths": round(
                paths.shape[0] / TRANSCRIPT["n_paths"], 3),
            "vs_transcript_genes": round(
                len(freq) / TRANSCRIPT["n_path_genes"], 3),
            "secs": round(time.time() - t0, 1)}


def main() -> None:
    # Env alone is NOT enough: the tunnel sitecustomize pins jax_platforms
    # at interpreter startup, which outranks the variable — re-force the
    # config or the einsum below dials the (possibly wedged) TPU.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from g2vec_tpu.data.realistic import RealExampleSpec

    specs = {
        "baseline": RealExampleSpec(),
    }
    for field in sys.argv[1:]:
        name, expr = field.split("=", 1)
        specs[name] = eval(  # noqa: S307 — operator-supplied sweep points
            f"RealExampleSpec({expr})", {"RealExampleSpec": RealExampleSpec})
    for name, spec in specs.items():
        out = run_trial(spec)
        print(json.dumps({"spec": name, **out,
                          "transcript": TRANSCRIPT}), flush=True)


if __name__ == "__main__":
    main()
