"""Fast path-count calibration for data/realistic.py via the native sampler.

VERDICT r2 weak #4: the realistic stand-in yields ~15% fewer unique paths
than the reference transcript (38.6k vs 45,402) at a near-exact path-GENE
match (3,858 vs 3,773) — i.e. 10.0 paths/gene vs the transcript's 12.03,
pointing at planted-module branching density, not module size. Sweeping
that with the device walker costs ~5 min per trial on this 1-core host;
the native C++ sampler (ops/host_walker.py) has identical walk semantics
and runs a full two-group, reps=10, lenPath=80 trial in ~20 s, so it is
the calibration surrogate. (Path-count statistics transfer between the
backends to within a few percent — same graphs, same walk law, different
PRNG family.)

Run:  python tools/calibrate_real.py ['name=<RealExampleSpec kwargs>' ...]
e.g.  python tools/calibrate_real.py 'shared=n_active_per_group=1500, n_shared=760'
Always runs the default spec first ("baseline"); prints one JSON line per
spec with n_paths / n_path_genes vs the transcript.

``--frontier`` instead runs the COMMITTED paths-vs-ACC sweep (the
n_shared axis at roughly constant active mass, disjoint -> full
transcript parity), trains the CBOW at every point, and writes
CALIBRATION.json at the repo root: the measured record behind the
default spec's choice (VERDICT r3 task 5 — the tradeoff that justifies
~40k paths / ACC ~0.90 over forcing 45,402-path parity at ACC ~0.80).
tests/test_acceptance_real.py and BASELINE.md cite that artifact.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NET = os.environ.get("G2VEC_CALIBRATE_NETWORK",
                     "/root/reference/ex_NETWORK.txt")
CLIN = os.environ.get("G2VEC_CALIBRATE_CLINICAL",
                      "/root/reference/ex_CLINICAL.txt")
TRANSCRIPT = {"n_paths": 45402, "n_path_genes": 3773}


def run_trial(spec, train: bool = False) -> dict:
    import numpy as np

    from g2vec_tpu.data.realistic import make_real_expression
    from g2vec_tpu.io.readers import ExpressionData, load_clinical, load_network
    from g2vec_tpu.ops.graph import thresholded_edges
    from g2vec_tpu.ops.host_walker import generate_path_set_native
    from g2vec_tpu.ops.walker import count_gene_freq, integrate_path_sets
    from g2vec_tpu.preprocess import (edges_to_indices, find_common_genes,
                                      make_gene2idx, match_labels,
                                      restrict_data, restrict_network)

    t0 = time.time()
    expression, _ = make_real_expression(NET, CLIN, spec)
    clinical = load_clinical(CLIN)
    network = load_network(NET)
    label = match_labels(clinical, expression.sample)
    common = find_common_genes(network.genes, expression.gene)
    network = restrict_network(network, common)
    data = restrict_data(
        ExpressionData(sample=expression.sample, gene=expression.gene,
                       expr=expression.expr), common)
    gene2idx = make_gene2idx(data.gene)
    src, dst = edges_to_indices(network, gene2idx)
    n_genes = data.expr.shape[1]

    sets = []
    for i in (0, 1):
        expr_group = data.expr[label == i]
        s_k, d_k, w_k = thresholded_edges(expr_group, src, dst, threshold=0.5)
        sets.append(generate_path_set_native(
            np.asarray(s_k), np.asarray(d_k), np.asarray(w_k), n_genes,
            len_path=80, reps=10, seed=i))
    paths, labels_arr = integrate_path_sets(sets[0], sets[1], n_genes,
                                            packed=True)
    freq = count_gene_freq(paths, labels_arr, list(data.gene), packed=True)
    out = {"n_paths": int(paths.shape[0]), "n_path_genes": len(freq),
           "paths_per_gene": round(paths.shape[0] / max(len(freq), 1), 2),
           "vs_transcript_paths": round(
               paths.shape[0] / TRANSCRIPT["n_paths"], 3),
           "vs_transcript_genes": round(
               len(freq) / TRANSCRIPT["n_path_genes"], 3)}
    if train:
        # The pipeline's exact training configuration (CLI defaults), so
        # the frontier's ACC column is the number the acceptance artifact
        # reports.
        from g2vec_tpu.train.trainer import train_cbow

        res = train_cbow(paths, labels_arr, packed_genes=n_genes,
                         hidden=128, learning_rate=0.005, max_epochs=500,
                         val_fraction=0.2, decision_threshold=0.5,
                         compute_dtype="bfloat16", seed=0)
        out["acc_val"] = round(float(res.acc_val), 4)
        out["stop_epoch"] = int(res.stop_epoch)
    out["secs"] = round(time.time() - t0, 1)
    return out


# The committed frontier: the n_shared axis at roughly constant active
# mass. Endpoint facts the test docstring cites: disjoint caps path yield
# near reps*path_genes+singletons; 1500/760 reaches ~99% transcript paths
# but ~31% of walks are label-ambiguous.
FRONTIER = [
    ("disjoint", dict(n_active_per_group=2000, n_shared=0)),
    ("default", dict()),                       # 1880/120 — the shipped spec
    ("shared300", dict(n_active_per_group=1700, n_shared=300)),
    ("shared500", dict(n_active_per_group=1600, n_shared=500)),
    ("parity", dict(n_active_per_group=1500, n_shared=760)),
]


def run_frontier() -> None:
    from g2vec_tpu.data.realistic import RealExampleSpec

    points = []
    for name, kwargs in FRONTIER:
        spec = RealExampleSpec(**kwargs)
        out = run_trial(spec, train=True)
        rec = {"point": name,
               "spec": {"n_active_per_group": spec.n_active_per_group,
                        "n_shared": spec.n_shared}, **out}
        print(json.dumps(rec), flush=True)
        points.append(rec)
    artifact = {
        "what": "paths-vs-ACC calibration frontier for data/realistic.py "
                "(native sampler + the pipeline's exact CBOW training, "
                "seed=0): the measured tradeoff behind the default spec. "
                "Transcript parity is reachable (point 'parity') but the "
                "shared-module walks that buy it are label-ambiguous and "
                "cost accuracy; the default keeps ACC >= 0.88 with the "
                "calibration gain.",
        "transcript": TRANSCRIPT,
        "reference_acc_val": 0.8837,
        "points": points,
        "chosen_default": "default",
    }
    out_path = os.path.join(REPO, "CALIBRATION.json")
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"# wrote {out_path}", file=sys.stderr)


def main() -> None:
    # Env alone is NOT enough: the tunnel sitecustomize pins jax_platforms
    # at interpreter startup, which outranks the variable — re-force the
    # config or the einsum below dials the (possibly wedged) TPU.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    if "--frontier" in sys.argv:
        run_frontier()
        return
    from g2vec_tpu.data.realistic import RealExampleSpec

    argv = sys.argv[1:]
    specs = {}
    if "--no-baseline" in argv:
        # Sweep only the named specs — the default baseline is sized for
        # the real 7,523-gene network and cannot run on a tiny stand-in
        # (the CPU smoke tests drive exactly that shape).
        argv = [a for a in argv if a != "--no-baseline"]
    else:
        specs["baseline"] = RealExampleSpec()
    if not os.path.exists(NET) or not os.path.exists(CLIN):
        # Fail before any work with the fix in the message — a missing
        # reference mount must not surface as a mid-sweep traceback.
        print(json.dumps({"error": f"reference inputs missing ({NET!r} / "
                                   f"{CLIN!r}); point "
                                   f"G2VEC_CALIBRATE_NETWORK/_CLINICAL at "
                                   f"an edge list + clinical TSV"}),
              flush=True)
        sys.exit(2)
    for field in argv:
        if "=" not in field:
            print(json.dumps({"error": f"bad spec arg {field!r}; expected "
                                       f"'name=<RealExampleSpec kwargs>'"}),
                  flush=True)
            sys.exit(2)
        name, expr = field.split("=", 1)
        try:
            specs[name] = eval(  # noqa: S307 — operator-supplied sweep points
                f"RealExampleSpec({expr})", {"RealExampleSpec": RealExampleSpec})
        except Exception as e:  # noqa: BLE001 — argv error, not a run error
            print(json.dumps({"error": f"bad spec {field!r}: "
                                       f"{type(e).__name__}: {e}"}),
                  flush=True)
            sys.exit(2)
    for name, spec in specs.items():
        out = run_trial(spec)
        print(json.dumps({"spec": name, **out,
                          "transcript": TRANSCRIPT}), flush=True)


if __name__ == "__main__":
    main()
