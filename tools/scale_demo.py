"""Shape-level demonstration of BASELINE configs #3-#5 -> SCALE_DEMO.json.

BASELINE.json's configs #3-#5 name TCGA/STRING/BioGRID datasets that this
container does not mount, so their exact numbers cannot be produced here.
What CAN be demonstrated — and what this tool records — is that the
framework's scaling machinery handles their SHAPES:

- #3  TCGA-LIHC + STRING (~15k genes): single-device walker + trainer at
      15k genes.
- #4  TCGA-BRCA + BioGRID, numRepetition=50: the flat rep*gene walker axis
      (750k walkers at full scale) split into launches by the HBM
      working-set model.
- #5  pan-cancer + full STRING v12, hidden=1024 (~45k genes): 'model'-axis
      row-sharded neighbor tables + TP trainer on a (2,4) mesh — the
      pod-scale layout (virtual CPU mesh here; the same code path the
      driver's dryrun_multichip exercises).

For each config the artifact records (a) the walker HBM model's decisions
at the real 16-GiB-chip default budget — launches needed, per-walker bytes,
modeled launch working set (pure model, device-independent; the reference
dies at these scales on its dense [G, G] adjacency, ref: G2Vec.py:377) —
and (b) a BOUNDED measured slice on the current backend proving the shapes
compile and run: one walk launch and a few trainer epochs. On CPU the slice
is clamped (walker count, len_path, paths, epochs) to keep the tool
minutes-bounded; on a real TPU the slice runs at full per-launch shape.
It also records (c) ``native_full_workload``: the DEFAULT stage-3 backend
(the C++ sampler `auto` resolves to) running EVERY one of the config's
reps x n_genes walks at the real len_path — a full measurement, not a
slice (the trainer half is what still needs the accelerator).
Synthetic graphs are power-law out-degree stand-ins at the configs' scale.

Run:  python tools/scale_demo.py [--platform cpu] [--out SCALE_DEMO.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# (name, n_genes, n_edges, reps, len_path, hidden, wants_model_sharding)
CONFIGS = [
    ("config3_tcga_lihc_string", 15_000, 800_000, 10, 80, 128, False),
    ("config4_tcga_brca_biogrid_reps50", 18_000, 500_000, 50, 80, 128, False),
    ("config5_pan_cancer_string_v12", 45_000, 2_000_000, 10, 80, 1024, True),
]


# Hub degree cap for the synthetic stand-ins. An unbounded power law at 2M
# edges hands one hub ~47k out-edges, which pads the [G, D] table to
# D=65536 (~24 GB — the documented max-degree cost of the padded layout,
# ops/graph.py). Real PPI networks cap out around the low thousands after
# any confidence filter (the bundled ex_NETWORK maxes at 644), so the
# stand-ins draw from a truncated power law.
MAX_DEGREE = 2048


def _make_graph(rng, n_genes: int, n_edges: int):
    """Truncated-power-law out-degree synthetic stand-in at this scale."""
    import numpy as np

    assert n_edges <= n_genes * MAX_DEGREE, "cap infeasible at this density"
    p = (1.0 / np.arange(1, n_genes + 1)) ** 0.8
    src = rng.choice(n_genes, size=n_edges, p=p / p.sum()).astype(np.int32)
    # Re-home every edge beyond a hub's MAX_DEGREE cap to a uniform source,
    # iterating until the cap actually holds (a single pass can push other
    # genes a few edges over, and neighbor_table's pow2 rounding would then
    # DOUBLE D — the exact blowup the cap exists to prevent). Keeps n_edges
    # exact; terminates because total overflow shrinks geometrically.
    while True:
        counts = np.bincount(src, minlength=n_genes)
        over = np.flatnonzero(counts > MAX_DEGREE)
        if over.size == 0:
            break
        for g in over:
            idx = np.flatnonzero(src == g)[MAX_DEGREE:]
            src[idx] = rng.integers(0, n_genes, size=idx.size)
    dst = rng.integers(0, n_genes, size=n_edges).astype(np.int32)
    w = rng.uniform(0.5001, 1.0, size=n_edges).astype(np.float32)
    return src, dst, w


def demo_config(name: str, n_genes: int, n_edges: int, reps: int,
                len_path: int, hidden: int, wants_sharding: bool,
                on_tpu: bool, mesh_ctx) -> dict:
    import jax
    import numpy as np

    from g2vec_tpu.ops.graph import neighbor_table
    from g2vec_tpu.ops.walker import (WALKER_HBM_BUDGET, auto_walker_batch,
                                      generate_path_set, walker_working_set)
    from g2vec_tpu.train.trainer import train_cbow

    rng = np.random.default_rng(0)
    src, dst, w = _make_graph(rng, n_genes, n_edges)
    nbr_idx, nbr_w = neighbor_table(src, dst, w, n_genes)
    d_slots = int(nbr_idx.shape[1])

    # ---- (a) the HBM model's full-scale plan (device-independent) ----
    total_walkers = n_genes * reps
    per_walker = walker_working_set(n_genes, d_slots, len_path, dense=False)
    batch = auto_walker_batch(n_genes, d_slots, len_path, total_walkers,
                              dense=False)
    plan = {
        "n_genes": n_genes, "n_edges": n_edges, "d_slots": d_slots,
        "reps": reps, "len_path": len_path,
        "total_walkers": total_walkers,
        "table_bytes": int(nbr_idx.size * 8),
        "per_walker_bytes": per_walker,
        "hbm_budget_bytes": WALKER_HBM_BUDGET,
        "walkers_per_launch": batch,
        "launches": -(-total_walkers // batch),
        "dense_adjacency_bytes_reference_would_need": n_genes * n_genes * 4,
    }

    # ---- (b) bounded measured slice on this backend ----
    slice_len = len_path if on_tpu else min(len_path, 16)
    slice_walkers = min(batch, total_walkers) if on_tpu else min(256, batch)
    starts = rng.choice(n_genes, size=slice_walkers).astype(np.int32)
    key = jax.random.key(0)
    t0 = time.time()
    paths = generate_path_set(
        (nbr_idx, nbr_w), key, len_path=slice_len, reps=1, starts=starts,
        mesh_ctx=mesh_ctx if wants_sharding else None,
        shard_tables=wants_sharding and mesh_ctx is not None
        and mesh_ctx.mesh is not None)
    walk_secs = time.time() - t0

    n_paths_slice = 2048 if on_tpu else 256
    epochs = 8 if on_tpu else 2
    mh = np.zeros((n_paths_slice, n_genes), dtype=np.int8)
    idx = rng.integers(0, n_genes, size=(n_paths_slice, 40))
    np.put_along_axis(mh, idx, 1, axis=1)
    labels = (rng.random(n_paths_slice) < 0.5).astype(np.int32)
    t0 = time.time()
    res = train_cbow(mh, labels, hidden=hidden, learning_rate=0.005,
                     max_epochs=epochs, seed=0,
                     mesh_ctx=mesh_ctx if wants_sharding else None)
    train_secs = time.time() - t0

    out = {**plan, "measured_slice": {
        "walkers": slice_walkers, "len_path": slice_len,
        "walk_seconds": round(walk_secs, 2),
        "unique_paths": len(paths),
        "trainer_paths": n_paths_slice, "hidden": hidden,
        "trainer_epochs": len(res.history),
        "train_seconds": round(train_secs, 2),
        "sharded_tables_and_tp": bool(wants_sharding and mesh_ctx is not None
                                      and mesh_ctx.mesh is not None),
    }}

    # ---- (c) the DEFAULT stage-3 backend at the FULL config workload ----
    # Not a slice: the native C++ sampler (what `auto` resolves to on any
    # toolchain-equipped host) runs every one of the config's
    # reps x n_genes walks at the config's real len_path. This is the
    # measurement VERDICT r3 weak #6 said the clamped device slices could
    # not carry; the device slice above remains the accelerator-path
    # compile/shape proof.
    try:
        from g2vec_tpu.native.walker_bindings import load as load_native
        from g2vec_tpu.ops.host_walker import generate_path_set_native

        load_native()   # one-time g++ compile outside the timed region
        t0 = time.time()
        native_paths = generate_path_set_native(
            src, dst, w, n_genes, len_path=len_path, reps=reps, seed=0)
        nat_secs = time.time() - t0
        out["native_full_workload"] = {
            "walks": total_walkers, "len_path": len_path,
            "seconds": round(nat_secs, 2),
            "walks_per_sec": round(total_walkers / nat_secs, 1),
            "unique_paths": len(native_paths),
        }
    except RuntimeError as e:       # no toolchain on this host
        out["native_full_workload"] = {"error": str(e)[:200]}
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None,
                    help="cpu forces the 8-virtual-device CPU backend")
    ap.add_argument("--out", default=os.path.join(REPO, "SCALE_DEMO.json"))
    args = ap.parse_args()
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        if args.platform == "cpu":
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from g2vec_tpu.parallel.mesh import make_mesh_context

    on_tpu = jax.default_backend() == "tpu"
    n_dev = len(jax.devices())
    mesh_ctx = make_mesh_context((2, 4)) if n_dev >= 8 else None

    results = {}

    def write_artifact(partial: bool) -> None:
        # Rewritten after EVERY config: a stage kill mid-run (config #5's
        # TP compiles are the slow tail) keeps everything already
        # measured, marked partial.
        artifact = {
            "platform": jax.default_backend(),
            "n_devices": n_dev,
            "mesh": "(2,4)" if mesh_ctx is not None else None,
            "partial": partial,
            "note": "BASELINE configs #3-#5 name TCGA/STRING/BioGRID "
                    "mounts this container does not have; graphs here are "
                    "power-law synthetic stand-ins at the configs' scale, "
                    "and the measured slices are bounded (clamped on CPU).",
            "configs": results,
        }
        tmp = f"{args.out}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
        os.replace(tmp, args.out)

    for cfg in CONFIGS:
        name = cfg[0]
        print(f"# {name} ...", file=sys.stderr, flush=True)
        t0 = time.time()
        results[name] = demo_config(*cfg, on_tpu=on_tpu, mesh_ctx=mesh_ctx)
        print(f"#   done in {time.time() - t0:.1f}s", file=sys.stderr,
              flush=True)
        # One line per config for the watcher's stage record as well.
        print(json.dumps({"config": name,
                          "measured_slice": results[name]["measured_slice"]}),
              flush=True)
        write_artifact(partial=True)
    write_artifact(partial=False)
    print(json.dumps({k: v["measured_slice"] for k, v in results.items()}))


if __name__ == "__main__":
    main()
