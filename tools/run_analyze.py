#!/usr/bin/env python3
"""Standalone launcher for the static-analysis suite.

``python tools/run_analyze.py [--json] [...]`` — identical to
``python -m g2vec_tpu analyze`` but runnable from a bare checkout
without installing the package (the repo root is put on sys.path).
Exit codes: 0 clean, 1 findings, 2 usage.
"""
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from g2vec_tpu.analyze.cli import analyze_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(analyze_main(sys.argv[1:]))
