#!/usr/bin/env python3
"""Chaos soak: a seeded fault storm against the serve daemon, with
exactly-once accounting.

The harness is the supervisor: it launches ``g2vec serve`` as a child
(UNsupervised, so drain exit codes are observable), drives a seeded
Poisson schedule of job arrivals (a mix of full-batch and streaming
jobs, tenants, priorities, some with tight deadlines), and injects a
seeded rotation of faults while the jobs run:

- ``sigkill``  — SIGKILL the daemon mid-whatever; relaunch immediately.
- ``drain``    — SIGTERM; the daemon must exit 0 with in-flight
  streaming jobs checkpointed and everything unfinished journaled.
- ``fault:*``  — drain, then relaunch with a ``--fault-plan`` armed at a
  durable seam (``stream_ckpt``/``train`` sigkill, ``drain`` crash) and
  a fresh ``G2VEC_FAULT_STATE`` file so each injection fires once.
- ``cancel``   — client-cancel a random not-yet-terminal job.

After the storm a clean daemon quiesces the backlog. The soak PASSES
iff every acknowledged job reaches exactly one well-defined terminal
state (done / cancelled / deadline_exceeded — ``failed`` counts but is
reported separately), zero jobs are lost (acknowledged but never
recorded) or duplicated (more than one terminal job_state event in the
daemon-lifetime metrics JSONL), the journal is empty, and a sample of
completed jobs is byte-identical to solo uninterrupted runs of the same
configs.

``--replicas N`` switches to **router mode**: the storm runs against a
TCP router fronting N daemon replicas (serve/router.py). The op rotation
becomes replica SIGKILL (the router must detect, fence, migrate the
journal to survivors, and relaunch), synchronous replica drain (rc 0
asserted), and router SIGKILL+restart (the new router must adopt the
orphaned live replicas). The pass bar is the same exactly-once predicate
computed fleet-wide — every acked job has exactly one terminal event
across ALL replicas' metrics streams and exactly one result record
across all results dirs — plus byte parity and the death-to-requeue
latency distribution from the router's ``failover`` events.

``--autoscale`` switches to **autoscale mode**: a seeded diurnal load
model (sinusoid base rate with flash-crowd spike windows, mixed tenants
with distinct SLO classes) runs against the router — elastic
(``--max-replicas`` > min, warm spares, ``--shed``,
``--tenant-quotas``) or static (``--max-replicas 0``) — with one
replica SIGKILL scheduled mid-spike. The submit loop honors structured
``shed``/``tenant_quota`` rejections (same idempotency key, advised
backoff, bounded attempts), the router's aggregate ``/status`` is
asserted on throughout, and the summary adds per-tenant SLO
attainment, deadline-death and shed counts, goodput, and the scale-up
reaction-time distribution — the evidence behind
``bench.py --_autoscale_ab`` (BENCH_AUTOSCALE.json), which runs the
identical seeded schedule against both fleet shapes.

``--partition`` switches to **partition mode**: the control-plane
drill behind ``bench.py --_partition_chaos`` (BENCH_PARTITION.json).
The harness owns the replica daemons (the router runs
``--remote-replicas``) and slides a userspace TCP relay in front of r0
that can blackhole each direction independently. Three drill phases
run inside the job storm: (1) *false-dead* — r0 is partitioned while
alive and working; the leased router must fence it (epoch bump + fence
marker) and migrate its journal, and r0 must self-quarantine off the
shared-disk marker and stay OUT of the ring after the heal; (2)
*zombie leader* — the active router is SIGSTOPped past its lease ttl,
the standby takes over, and every mutating command the woken zombie
still emits must die with the structured ``stale_epoch`` rejection
(plus a deterministic per-replica epoch replay matrix); (3) a chain of
``--takeovers`` router SIGKILLs, each gap carrying a degraded-mode
client drill (replica-direct status, keyed submit, reconcile read).
The pass bar is the router soak's exactly-once predicate plus: fence
epoch >= 1, quarantine observed, zero terminal states or result bytes
from r0 after its fencing, all stale replays rejected, every takeover
completed, and the degraded drills answered.

Scale knobs are flags with G2V_CHAOS_* env fallbacks so CI can shrink
the soak (``G2V_CHAOS_JOBS=6 python tools/chaos_soak.py``). The
committed artifacts (BENCH_CHAOS_SOAK.json, BENCH_ROUTER_CHAOS.json) are
written by ``bench.py --_chaos_soak`` / ``--_router_chaos``, which wrap
this module.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TERMINAL_STATES = ("done", "failed", "cancelled", "deadline_exceeded")


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="chaos_soak",
        description="Seeded fault storm against g2vec serve with "
                    "exactly-once job accounting.")
    p.add_argument("--jobs", type=int,
                   default=_env_int("G2V_CHAOS_JOBS", 50))
    p.add_argument("--seed", type=int,
                   default=_env_int("G2V_CHAOS_SEED", 0))
    p.add_argument("--epochs", type=int,
                   default=_env_int("G2V_CHAOS_EPOCHS", 8),
                   help="Base epoch count per job (jittered per job).")
    p.add_argument("--mean-arrival", type=float,
                   default=_env_float("G2V_CHAOS_ARRIVAL", 0.4),
                   help="Mean exponential interarrival seconds.")
    p.add_argument("--chaos-ops", type=int,
                   default=_env_int("G2V_CHAOS_OPS", 0),
                   help="Fault injections over the soak (0 = jobs//8, "
                        "min 3).")
    p.add_argument("--chaos-every", type=float,
                   default=_env_float("G2V_CHAOS_EVERY", 7.0),
                   help="Mean seconds between fault injections.")
    p.add_argument("--stream-frac", type=float,
                   default=_env_float("G2V_CHAOS_STREAM_FRAC", 0.4),
                   help="Fraction of streaming jobs (needs g++; 0 if "
                        "no native toolchain).")
    p.add_argument("--verify", type=int,
                   default=_env_int("G2V_CHAOS_VERIFY", 4),
                   help="Completed jobs to byte-compare against solo "
                        "uninterrupted twins.")
    p.add_argument("--budget-s", type=float,
                   default=_env_float("G2V_CHAOS_BUDGET", 900.0),
                   help="Hard wall-clock budget for the whole soak.")
    p.add_argument("--workdir", type=str, default=None,
                   help="Working directory (default: a fresh tempdir, "
                        "removed unless --keep).")
    p.add_argument("--keep", action="store_true",
                   help="Keep the workdir (logs, metrics, outputs).")
    p.add_argument("--json", type=str, default=None, metavar="PATH",
                   help="Also write the summary JSON here.")
    p.add_argument("--replicas", type=int,
                   default=_env_int("G2V_CHAOS_REPLICAS", 0),
                   help="Router mode: storm a replicated fleet behind the "
                        "TCP router instead of one daemon. Op rotation "
                        "becomes replica SIGKILL / synchronous replica "
                        "drain / router SIGKILL+restart / cancel; "
                        "accounting spans every replica's results dir and "
                        "metrics stream (0 = classic single-daemon mode).")
    p.add_argument("--autoscale", action="store_true",
                   default=_env_int("G2V_CHAOS_AUTOSCALE", 0) > 0,
                   help="Autoscale mode: seeded diurnal/burst load with "
                        "tenant SLO classes against the router (elastic "
                        "when --max-replicas > min, static otherwise), "
                        "one replica SIGKILL mid-spike, aggregate-status "
                        "assertions, per-tenant attainment accounting.")
    p.add_argument("--min-replicas", type=int,
                   default=_env_int("G2V_CHAOS_MIN_REPLICAS", 0),
                   help="Elastic floor forwarded to the router "
                        "(0 = --replicas).")
    p.add_argument("--max-replicas", type=int,
                   default=_env_int("G2V_CHAOS_MAX_REPLICAS", 0),
                   help="Elastic ceiling forwarded to the router "
                        "(0 = static fleet of --replicas).")
    p.add_argument("--warm-spares", type=int,
                   default=_env_int("G2V_CHAOS_WARM", 0),
                   help="Pre-launched ringless spares kept warm by the "
                        "router for instant scale-up.")
    p.add_argument("--scale-interval", type=float,
                   default=_env_float("G2V_CHAOS_SCALE_INTERVAL", 0.5),
                   help="Router scaling-controller tick seconds.")
    p.add_argument("--shed", action="store_true",
                   default=_env_int("G2V_CHAOS_SHED", 0) > 0,
                   help="Forward --shed to the replicas: deadline-aware "
                        "admission shedding with structured retry_after_s.")
    p.add_argument("--tenant-quotas", type=str,
                   default=os.environ.get("G2V_CHAOS_QUOTAS"),
                   help="Forward --tenant-quotas SPEC to the replicas "
                        "(token-bucket rates + weighted-fair shares).")
    p.add_argument("--partition", action="store_true",
                   default=_env_int("G2V_CHAOS_PARTITION", 0) > 0,
                   help="Partition mode: the control-plane drill. The "
                        "harness launches the replicas itself (remote-"
                        "replicas router mode) with a TCP relay in front "
                        "of r0 that can blackhole either direction "
                        "independently, plus an HA router pair "
                        "(--lease-ttl-s + --standby). Drill phases: "
                        "false-dead fence + self-quarantine of a merely "
                        "partitioned replica; SIGSTOP the active router "
                        "past its ttl and prove every zombie mutating "
                        "command dies with structured stale_epoch; then "
                        "a chain of --takeovers router SIGKILLs with "
                        "degraded-mode client drills inside each gap.")
    p.add_argument("--takeovers", type=int,
                   default=_env_int("G2V_CHAOS_TAKEOVERS", 3),
                   help="Partition mode: SIGKILL-the-active-router "
                        "rounds after the zombie drill (a fresh standby "
                        "is spawned before each).")
    p.add_argument("--lease-ttl", type=float,
                   default=_env_float("G2V_CHAOS_LEASE_TTL", 1.5),
                   help="Partition mode: leadership lease ttl handed to "
                        "the routers (--lease-ttl-s). Small keeps the "
                        "takeover gaps short; the drill's clients must "
                        "ride them out regardless.")
    return p


class Soak:
    def __init__(self, opts, workdir: str):
        self.opts = opts
        self.wd = workdir
        self.rng = random.Random(opts.seed)
        self.sock = os.path.join(workdir, "chaos.sock")
        self.state = os.path.join(workdir, "state")
        self.metrics_path = os.path.join(workdir, "metrics.jsonl")
        self.log_path = os.path.join(workdir, "daemon.log")
        self.proc: Optional[subprocess.Popen] = None
        self.env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": REPO + os.pathsep
                    + os.environ.get("PYTHONPATH", "")}
        self.lock = threading.Lock()
        self.acks: Dict[str, dict] = {}      # job_id -> {"k", "job"}
        self.rejected: List[int] = []
        self.unsubmitted: List[int] = []
        self.recoveries: List[float] = []
        self.kills = 0
        self.drains = 0
        self.drain_rcs: List[int] = []
        self.fault_injections: List[str] = []
        self.cancels_sent = 0
        self.notes: List[str] = []
        self._fault_serial = 0
        self.t0 = time.time()

    def note(self, msg: str) -> None:
        line = f"[{time.time() - self.t0:7.1f}s] {msg}"
        self.notes.append(line)
        print(f"# {line}", file=sys.stderr, flush=True)

    # ---- daemon lifecycle ------------------------------------------------

    def launch(self, fault_plan: Optional[str] = None) -> None:
        from g2vec_tpu.serve import client

        env = dict(self.env)
        if fault_plan:
            self._fault_serial += 1
            env["G2VEC_FAULT_STATE"] = os.path.join(
                self.wd, f"fault-state-{self._fault_serial}.json")
        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--socket", self.sock, "--state-dir", self.state,
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--metrics-jsonl", self.metrics_path]
        if fault_plan:
            argv += ["--fault-plan", fault_plan]
        log = open(self.log_path, "a")
        self.proc = subprocess.Popen(argv, env=env, stdout=log,
                                     stderr=subprocess.STDOUT)
        log.close()
        if not client.wait_ready(self.sock, 120):
            raise RuntimeError(
                f"daemon never became ready (log: {self.log_path})")

    def relaunch_after_death(self, why: str) -> None:
        t_down = time.time()
        self.launch()
        self.recoveries.append(time.time() - t_down)
        self.note(f"relaunched after {why} "
                  f"(ready in {self.recoveries[-1]:.1f}s)")

    # ---- job construction ------------------------------------------------

    def make_job(self, k: int, paths: dict, native_ok: bool) -> dict:
        rng = random.Random((self.opts.seed << 16) ^ k)
        job = dict(
            expression_file=paths["expression"],
            clinical_file=paths["clinical"],
            network_file=paths["network"],
            result_name=os.path.join(self.wd, "out", f"job{k}"),
            lenPath=8, numRepetition=2, sizeHiddenlayer=16,
            epoch=self.opts.epochs + rng.choice((0, 2, 4)),
            learningRate=0.05, numBiomarker=5, compute_dtype="float32",
            seed=0, train_seed=k, kmeans_seed=k)
        if native_ok and rng.random() < self.opts.stream_frac:
            job.update(train_mode="streaming", walker_backend="native",
                       shard_paths=16, checkpoint_every=1)
        else:
            job["walker_backend"] = "device"
        return job

    def submit_one(self, k: int, job: dict) -> None:
        """Submit until acknowledged (or rejected); backoff with jitter
        across daemon deaths. Terminal accounting happens from durable
        records, not from this stream."""
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        priority = "interactive" if rng.random() < 0.3 else "batch"
        deadline_s = (round(rng.uniform(2.0, 8.0), 2)
                      if rng.random() < 0.15 else None)
        for attempt in range(12):
            try:
                evs = client.submit_job(
                    self.sock, job, tenant=f"t{k % 3}", timeout=600,
                    priority=priority, deadline_s=deadline_s)
                if evs and evs[-1].get("event") == "rejected":
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:     # acknowledged; journaled; never resubmit
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(5.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)

    # ---- chaos ops -------------------------------------------------------

    def op_sigkill(self) -> None:
        self.kills += 1
        self.note(f"chaos: SIGKILL daemon (kill #{self.kills})")
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except OSError:
            pass
        self.proc.wait()
        self.relaunch_after_death("SIGKILL")

    def op_drain(self, relaunch_plan: Optional[str] = None) -> None:
        self.drains += 1
        self.note(f"chaos: SIGTERM drain (drain #{self.drains}"
                  + (f", relaunch armed: {relaunch_plan}"
                     if relaunch_plan else "") + ")")
        try:
            os.kill(self.proc.pid, signal.SIGTERM)
        except OSError:
            pass
        try:
            rc = self.proc.wait(timeout=300)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            rc = -9
        self.drain_rcs.append(rc)
        t_down = time.time()
        self.launch(fault_plan=relaunch_plan)
        self.recoveries.append(time.time() - t_down)
        if relaunch_plan:
            self.fault_injections.append(relaunch_plan)

    def op_cancel(self) -> None:
        from g2vec_tpu.serve import client

        with self.lock:
            pending = [jid for jid in self.acks
                       if not os.path.exists(os.path.join(
                           self.state, "results", f"{jid}.json"))]
        if not pending:
            return
        jid = self.rng.choice(pending)
        self.cancels_sent += 1
        self.note(f"chaos: cancel {jid}")
        try:
            client.cancel(self.sock, jid)
        except (OSError, client.ServeConnectionLost):
            pass

    def run_chaos_op(self, op: str) -> None:
        if op == "sigkill":
            self.op_sigkill()
        elif op == "drain":
            self.op_drain()
        elif op == "fault_stream_ckpt":
            self.op_drain("stage=stream_ckpt,kind=sigkill")
        elif op == "fault_train":
            self.op_drain("stage=train,kind=sigkill")
        elif op == "fault_drain_seam":
            # Arm a crash INSIDE _begin_drain, then drain: the drain
            # thread dies at the seam but admission is already closed
            # and the stop flag still falls — the exit must stay clean.
            self.op_drain("stage=drain,kind=crash")
            self.op_drain()
        elif op == "cancel":
            self.op_cancel()

    # ---- accounting ------------------------------------------------------

    def results(self) -> Dict[str, dict]:
        out = {}
        rdir = os.path.join(self.state, "results")
        if not os.path.isdir(rdir):
            return out
        for fn in os.listdir(rdir):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(rdir, fn)) as f:
                        out[fn[:-5]] = json.load(f)
                except (OSError, ValueError):
                    pass
        return out

    def journal_ids(self) -> List[str]:
        jdir = os.path.join(self.state, "jobs")
        if not os.path.isdir(jdir):
            return []
        return [fn[:-5] for fn in os.listdir(jdir)
                if fn.endswith(".json")]

    def terminal_event_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        try:
            with open(self.metrics_path) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "job_state" \
                            and ev.get("state") in TERMINAL_STATES:
                        jid = ev.get("job_id")
                        counts[jid] = counts.get(jid, 0) + 1
        except OSError:
            pass
        return counts


class RouterSoak(Soak):
    """Soak state for router mode: one router subprocess fronting N
    replica daemons it launches itself. The harness only ever kills
    things — every heal (replica relaunch, journal migration, adoption
    after a router restart) must come from the router, or the
    accounting fails."""

    def __init__(self, opts, workdir: str):
        super().__init__(opts, workdir)
        self.fleet = os.path.join(workdir, "fleet")
        self.router_metrics = os.path.join(workdir, "router-metrics.jsonl")
        self.router_log = os.path.join(workdir, "router.log")
        self.addr: Optional[str] = None
        self.router_restarts = 0
        self.replica_kills = 0
        self.replica_drains = 0

    # ---- router lifecycle -------------------------------------------

    def _router_argv(self) -> List[str]:
        return [sys.executable, "-m", "g2vec_tpu", "serve",
                "--replicas", str(self.opts.replicas),
                "--listen", "127.0.0.1:0",
                "--state-dir", self.fleet,
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--probe-interval", "0.4", "--probe-deadline", "3.0",
                "--metrics-jsonl", self.router_metrics]

    def launch_router(self) -> None:
        argv = self._router_argv()
        addr_file = os.path.join(self.fleet, "router_addr")
        try:
            os.unlink(addr_file)
        except OSError:
            pass
        log = open(self.router_log, "a")
        self.proc = subprocess.Popen(argv, env=self.env, stdout=log,
                                     stderr=subprocess.STDOUT)
        log.close()
        deadline = time.time() + 600
        while time.time() < deadline:
            if os.path.exists(addr_file):
                with open(addr_file) as f:
                    self.addr = f.read().strip()
                if self.addr:
                    return
            if self.proc.poll() is not None:
                raise RuntimeError(f"router died during boot "
                                   f"(rc={self.proc.returncode}; log: "
                                   f"{self.router_log})")
            time.sleep(0.2)
        raise RuntimeError(f"router never bound (log: {self.router_log})")

    def router_status(self) -> Optional[dict]:
        from g2vec_tpu.serve import client, protocol

        try:
            return client.status(self.addr, timeout=10.0)
        except (OSError, client.ServeConnectionLost,
                protocol.ProtocolError):
            return None

    # ---- fleet-wide accounting --------------------------------------

    def _replica_dirs(self) -> List[str]:
        return [os.path.join(self.fleet, f"r{i}")
                for i in range(self.opts.replicas)]

    def results(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for rdir in self._replica_dirs():
            resd = os.path.join(rdir, "state", "results")
            if not os.path.isdir(resd):
                continue
            for fn in os.listdir(resd):
                if fn.endswith(".json"):
                    try:
                        with open(os.path.join(resd, fn)) as f:
                            out[fn[:-5]] = json.load(f)
                    except (OSError, ValueError):
                        pass
        return out

    def result_locations(self) -> Dict[str, List[str]]:
        """job_id -> replica names holding a result record. More than
        one means a job ran (terminally) on two replicas — a duplicate
        the terminal-event count alone could miss."""
        locs: Dict[str, List[str]] = {}
        for i, rdir in enumerate(self._replica_dirs()):
            resd = os.path.join(rdir, "state", "results")
            if not os.path.isdir(resd):
                continue
            for fn in os.listdir(resd):
                if fn.endswith(".json"):
                    locs.setdefault(fn[:-5], []).append(f"r{i}")
        return locs

    def journal_ids(self) -> List[str]:
        out = []
        for rdir in self._replica_dirs():
            jdir = os.path.join(rdir, "state", "jobs")
            if os.path.isdir(jdir):
                out += [fn[:-5] for fn in os.listdir(jdir)
                        if fn.endswith(".json")]
        return out

    def terminal_event_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for rdir in self._replica_dirs():
            path = os.path.join(rdir, "metrics.jsonl")
            try:
                with open(path) as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        if ev.get("event") == "job_state" \
                                and ev.get("state") in TERMINAL_STATES:
                            jid = ev.get("job_id")
                            counts[jid] = counts.get(jid, 0) + 1
            except OSError:
                pass
        return counts

    def failover_events(self) -> List[dict]:
        out = []
        try:
            with open(self.router_metrics) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "failover":
                        out.append(ev)
        except OSError:
            pass
        return out

    # ---- chaos ops ---------------------------------------------------

    def _pick_replica(self) -> Optional[str]:
        st = self.router_status()
        if not st:
            return None
        reps = st.get("replicas") or {}
        live = [n for n, r in reps.items()
                if r.get("state") in ("healthy", "suspect")
                and r.get("pid")]
        if not live:
            return None
        name = self.rng.choice(sorted(live))
        self._victim_pid = reps[name].get("pid")
        return name

    def op_replica_sigkill(self) -> None:
        name = self._pick_replica()
        if name is None:
            self.note("chaos: replica SIGKILL skipped (none healthy)")
            return
        self.replica_kills += 1
        self.note(f"chaos: SIGKILL replica {name} "
                  f"(pid {self._victim_pid}, kill "
                  f"#{self.replica_kills})")
        try:
            os.kill(self._victim_pid, signal.SIGKILL)
        except OSError:
            pass
        # NO relaunch here: detection, fencing, journal migration, and
        # the relaunch are all the router's job.

    def op_replica_drain(self) -> None:
        from g2vec_tpu.serve import client

        name = self._pick_replica()
        if name is None:
            self.note("chaos: replica drain skipped (none healthy)")
            return
        self.replica_drains += 1
        self.note(f"chaos: drain replica {name} "
                  f"(drain #{self.replica_drains})")
        try:
            for ev in client.request(self.addr,
                                     {"op": "drain_replica",
                                      "replica": name}, timeout=600.0):
                if ev.get("event") == "drained":
                    self.drain_rcs.append(ev.get("rc", -1))
                break
        except (OSError, client.ServeConnectionLost):
            self.note(f"drain of {name} lost its stream (router died?)")

    def op_router_restart(self) -> None:
        self.router_restarts += 1
        self.note(f"chaos: SIGKILL router + restart "
                  f"(#{self.router_restarts}) — replicas orphaned, "
                  f"must be adopted")
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except OSError:
            pass
        self.proc.wait()
        t_down = time.time()
        self.launch_router()
        self.recoveries.append(time.time() - t_down)

    def op_cancel_routed(self) -> None:
        from g2vec_tpu.serve import client

        results = self.results()
        with self.lock:
            pending = [jid for jid in self.acks if jid not in results]
        if not pending:
            return
        jid = self.rng.choice(sorted(pending))
        self.cancels_sent += 1
        self.note(f"chaos: cancel {jid} (via router broadcast)")
        try:
            client.cancel(self.addr, jid, timeout=30.0)
        except (OSError, client.ServeConnectionLost):
            pass

    def run_chaos_op(self, op: str) -> None:
        if op == "replica_sigkill":
            self.op_replica_sigkill()
        elif op == "replica_drain":
            self.op_replica_drain()
        elif op == "router_restart":
            self.op_router_restart()
        elif op == "cancel":
            self.op_cancel_routed()

    # ---- submission --------------------------------------------------

    def submit_one(self, k: int, job: dict) -> None:
        """Submit through the router until acked. Unlike the classic
        soak, EVERY attempt carries the same deterministic idem key, so
        resubmitting after a lost ack is safe — the fleet acks the
        original job exactly once (deduped=True on the repeat)."""
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        priority = "interactive" if rng.random() < 0.3 else "batch"
        deadline_s = (round(rng.uniform(2.0, 8.0), 2)
                      if rng.random() < 0.15 else None)
        idem = f"soak-{self.opts.seed}-{k}"
        for attempt in range(14):
            try:
                evs = client.submit_job(
                    self.addr, job, tenant=f"t{k % 3}", timeout=600,
                    priority=priority, deadline_s=deadline_s,
                    idem_key=idem)
                if evs and evs[-1].get("event") == "rejected":
                    # Transient fleet states — retry with the SAME idem
                    # key (safe by construction): the router had no
                    # eligible replica yet, or the ring target was
                    # caught mid-drain.
                    if evs[-1].get("error") in ("no_replicas",
                                                "draining"):
                        raise OSError(f"fleet busy: {evs[-1]['error']}")
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(5.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)


#: SLO classes for autoscale mode: arrival share, probability a job
#: carries a deadline, the deadline range (queue-wait budget, seconds),
#: and how often the tenant submits at interactive priority. Gold is
#: latency-critical (every job deadlined), bulk is throughput traffic
#: that can wait.
TENANT_CLASSES = {
    "gold":   {"share": 0.30, "deadline_p": 1.0, "deadline": (5.0, 8.0),
               "interactive_p": 0.8},
    "silver": {"share": 0.30, "deadline_p": 0.5, "deadline": (7.0, 11.0),
               "interactive_p": 0.3},
    "bulk":   {"share": 0.40, "deadline_p": 0.1, "deadline": (15.0, 25.0),
               "interactive_p": 0.0},
}

#: Default per-tenant token buckets + weighted-fair shares for the
#: elastic arm: gold paid for headroom and 3x queue weight, bulk gets a
#: tight bucket so a bulk flash-crowd defers to gold instead of
#: starving it.
DEFAULT_QUOTAS = "gold:6:12:3;silver:3:6:2;bulk:0.8:2:1"


def diurnal_arrivals(n: int, rng: random.Random, base_rate: float,
                     period_s: float,
                     spikes: List[Tuple[float, float, float]]) -> List[float]:
    """Seeded non-homogeneous arrival times: a sinusoid over
    ``base_rate`` (the diurnal curve, compressed to ``period_s``) with
    multiplicative flash-crowd windows ``(start_s, dur_s, mult)``. The
    same (seed, knobs) always yields the same schedule — that is what
    makes the static/elastic A/B a controlled experiment."""
    arrivals, t = [], 0.0
    for _ in range(n):
        rate = base_rate * (1.0 + 0.5 * math.sin(2 * math.pi * t / period_s))
        for (s0, dur, mult) in spikes:
            if s0 <= t < s0 + dur:
                rate *= mult
        arrivals.append(t)
        t += rng.expovariate(max(0.05, rate))
    return arrivals


class AutoscaleSoak(RouterSoak):
    """Soak state for autoscale mode: the router fronts a fleet that is
    either elastic (min..max active replicas, warm spares, deadline
    shedding, tenant quotas) or static (the baseline arm), and the load
    is the seeded diurnal/burst model with tenant SLO classes. The
    submit loop is SLO-aware: structured ``shed`` / ``tenant_quota``
    rejections are retried with the SAME idempotency key after the
    advised ``retry_after_s`` (plus jitter), for a bounded number of
    attempts; exhaustion is recorded per tenant as a final shed — never
    as a lost job, because a shed job was refused BEFORE journaling."""

    MAX_SHED_RETRIES = 8

    def __init__(self, opts, workdir: str):
        super().__init__(opts, workdir)
        self.gave_up: List[dict] = []        # exhausted shed/quota retries
        self.shed_retries = 0                # shed rejections retried
        self.quota_retries = 0               # quota rejections retried
        self.status_checks = 0
        self.status_violations: List[str] = []
        self.max_active_seen = 0
        self.arrival_t0: Optional[float] = None
        self.warmup_job: Optional[str] = None  # canary file for spares

    # ---- fleet shape -------------------------------------------------

    def _elastic(self) -> bool:
        mn = self.opts.min_replicas or self.opts.replicas
        mx = self.opts.max_replicas or self.opts.replicas
        return mx > mn

    def _fleet_width(self) -> int:
        return (max(self.opts.replicas, self.opts.max_replicas)
                + max(0, self.opts.warm_spares))

    def _replica_dirs(self) -> List[str]:
        return [os.path.join(self.fleet, f"r{i}")
                for i in range(self._fleet_width())]

    def journal_ids(self) -> List[str]:
        """Leftover journal entries, excluding warm-pool canaries: the
        shutdown can land while a spare's ``--warmup-job`` is queued,
        and an abandoned canary is not lost work — its result is
        discarded by design (the warmth was the product), and it never
        appears in the ack ledger this accounting audits."""
        out = []
        for rdir in self._replica_dirs():
            jdir = os.path.join(rdir, "state", "jobs")
            if not os.path.isdir(jdir):
                continue
            for fn in os.listdir(jdir):
                if not fn.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(jdir, fn)) as f:
                        if json.load(f).get("tenant") == "_warmup":
                            continue
                except (OSError, ValueError):
                    pass
                out.append(fn[:-5])
        return out

    def _router_argv(self) -> List[str]:
        argv = super()._router_argv()
        if self.opts.max_replicas:
            argv += ["--min-replicas", str(self.opts.min_replicas),
                     "--max-replicas", str(self.opts.max_replicas),
                     "--warm-spares", str(self.opts.warm_spares),
                     "--scale-interval", str(self.opts.scale_interval)]
            if self.warmup_job:
                argv += ["--warmup-job", self.warmup_job]
        if self.opts.shed:
            argv += ["--shed"]
        if self.opts.tenant_quotas:
            argv += ["--tenant-quotas", self.opts.tenant_quotas]
        return argv

    # ---- SLO assignment ----------------------------------------------

    def slo_of(self, k: int) -> Tuple[str, Optional[float], str]:
        """Deterministic (seed, k) -> (tenant, deadline_s, priority).
        Independent of arm shape, so the static and elastic runs submit
        byte-identical SLO mixes."""
        rng = random.Random((self.opts.seed << 24) ^ k)
        r, acc = rng.random(), 0.0
        tenant = "bulk"
        for name, cls in TENANT_CLASSES.items():
            acc += cls["share"]
            if r < acc:
                tenant = name
                break
        cls = TENANT_CLASSES[tenant]
        deadline_s = (round(rng.uniform(*cls["deadline"]), 2)
                      if rng.random() < cls["deadline_p"] else None)
        priority = ("interactive"
                    if rng.random() < cls["interactive_p"] else "batch")
        return tenant, deadline_s, priority

    def make_job(self, k: int, paths: dict, native_ok: bool) -> dict:
        """Tenant-shaped job mix with DISTINCT batch-join keys. The
        base soak submits config-identical jobs, which the daemon joins
        into one amortized batch — a load so compressible that a single
        replica absorbs any spike, and the ring (which places by join
        key) sends every job to ONE owner. Real multi-tenant traffic is
        the opposite. Gold/silver are interactive: small jobs on cached
        engine shapes (cheap after the first compile). Bulk is batch
        analytics: each job wants its own walk length and model width,
        so nearly every bulk job pays a fresh XLA compile — seconds of
        head-of-line blocking on the daemon's single scheduler. That
        cost asymmetry is what the flash crowd weaponizes: a wall of
        bulk compiles lands in front of deadlined gold traffic."""
        job = super().make_job(k, paths, native_ok)
        job["numBiomarker"] = 2 + (k % 25)
        tenant, _, _ = self.slo_of(k)
        if tenant == "bulk":
            job["lenPath"] = 10 + 2 * (k % 16)
            job["sizeHiddenlayer"] = 24
        else:
            job["lenPath"] = 8
        job["numRepetition"] = 3
        return job

    # ---- chaos: kill an ACTIVE replica only --------------------------

    def _pick_replica(self) -> Optional[str]:
        st = self.router_status()
        if not st:
            return None
        reps = st.get("replicas") or {}
        live = [n for n, r in reps.items()
                if r.get("state") in ("healthy", "suspect")
                and r.get("pid") and r.get("role") == "active"]
        if not live:
            return None
        name = self.rng.choice(sorted(live))
        self._victim_pid = reps[name].get("pid")
        return name

    # ---- aggregate-status assertions ---------------------------------

    def check_router_status(self) -> None:
        """One assertion pass over the router's fleet-wide /status: the
        keys the dashboard (and this accounting) depend on must exist
        and the scale state must respect the configured bounds. Any
        violation fails the soak."""
        st = self.router_status()
        if not st:
            return                 # router mid-restart: not a violation
        self.status_checks += 1
        probs: List[str] = []
        for key in ("replicas", "active", "warm_pool", "warm_pool_size",
                    "autoscale", "last_scale_event", "scale_ups",
                    "scale_downs", "fleet"):
            if key not in st:
                probs.append(f"missing key {key!r}")
        auto = st.get("autoscale") or {}
        active = st.get("active") or []
        self.max_active_seen = max(self.max_active_seen, len(active))
        mn = self.opts.min_replicas or self.opts.replicas
        mx = self.opts.max_replicas or self.opts.replicas
        if bool(auto.get("elastic")) != self._elastic():
            probs.append(f"autoscale.elastic={auto.get('elastic')!r}, "
                         f"expected {self._elastic()}")
        if active and not (1 <= len(active) <= mx):
            probs.append(f"active={len(active)} outside [1, {mx}]")
        # Transient overfill is legal (a demote parks its replica even
        # when the pool is full) but bounded by the fleet width.
        warm_cap = mx + max(0, self.opts.warm_spares) - mn
        if st.get("warm_pool_size", 0) > warm_cap:
            probs.append(f"warm_pool_size={st.get('warm_pool_size')} "
                         f"exceeds bound {warm_cap}")
        if st.get("scale_ups", 0) > 0:
            ev = st.get("last_scale_event") or {}
            for field in ("kind", "replica", "at"):
                if field not in ev:
                    probs.append(f"last_scale_event missing {field!r}")
        fleet = st.get("fleet") or {}
        if fleet:
            for key in ("queued", "running", "est_wait_s", "tenants"):
                if key not in fleet:
                    probs.append(f"fleet aggregate missing {key!r}")
        for p in probs:
            if p not in self.status_violations:
                self.status_violations.append(p)
                self.note(f"STATUS VIOLATION: {p}")

    # ---- router metrics ----------------------------------------------

    def router_events(self, kinds: Tuple[str, ...]) -> List[dict]:
        out = []
        try:
            with open(self.router_metrics) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") in kinds:
                        out.append(ev)
        except OSError:
            pass
        return out

    def slo_events(self) -> Dict[str, int]:
        """Fleet-wide admission-SLO event counts from every replica's
        durable metrics stream (the in-memory per-tenant ledgers die
        with a SIGKILLed replica; the JSONL does not)."""
        counts = {"shed": 0, "tenant_quota": 0}
        for rdir in self._replica_dirs():
            path = os.path.join(rdir, "metrics.jsonl")
            try:
                with open(path) as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        if ev.get("event") in counts:
                            counts[ev.get("event")] += 1
            except OSError:
                pass
        return counts

    # ---- SLO-aware submission ----------------------------------------

    def submit_one(self, k: int, job: dict) -> None:
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        tenant, deadline_s, priority = self.slo_of(k)
        idem = f"soak-{self.opts.seed}-{k}"
        sheds = 0
        for attempt in range(16):
            try:
                evs = client.submit_job(
                    self.addr, job, tenant=tenant, timeout=600,
                    priority=priority, deadline_s=deadline_s,
                    idem_key=idem)
                if evs and evs[-1].get("event") == "rejected":
                    err = evs[-1].get("error")
                    if err in ("no_replicas", "draining", "queue_full"):
                        raise OSError(f"fleet busy: {err}")
                    if err in ("shed", "tenant_quota"):
                        sheds += 1
                        with self.lock:
                            if err == "shed":
                                self.shed_retries += 1
                            else:
                                self.quota_retries += 1
                        if sheds > self.MAX_SHED_RETRIES:
                            with self.lock:
                                self.gave_up.append(
                                    {"k": k, "tenant": tenant,
                                     "deadline_s": deadline_s,
                                     "error": err})
                            return
                        ra = evs[-1].get("retry_after_s")
                        ra = float(ra) if isinstance(ra, (int, float)) \
                            else 0.5
                        time.sleep(min(8.0, max(0.05, ra))
                                   + rng.uniform(0.0, 0.3))
                        continue
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s,
                                          "tenant": tenant}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s,
                                               "tenant": tenant}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(5.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)


def run_router_soak(opts, workdir: str) -> dict:
    """The replicated-fleet storm: N replicas behind the router, seeded
    replica-SIGKILL / replica-drain / router-restart rotation, fleet-wide
    exactly-once accounting, byte parity vs solo twins, and the
    death-to-first-requeue latency distribution from the router's
    ``failover`` events."""
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client

    soak = RouterSoak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    n = opts.jobs
    n_ops = opts.chaos_ops or max(3, n // 8)
    rng = soak.rng
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / opts.mean_arrival)
    op_pool = ["replica_sigkill", "replica_drain", "router_restart",
               "cancel", "replica_sigkill"]
    ops = [op_pool[i % len(op_pool)] for i in range(n_ops)]
    rng.shuffle(ops)

    soak.note(f"router soak: {n} jobs over {opts.replicas} replicas "
              f"(stream_frac={opts.stream_frac if native_ok else 0}), "
              f"{n_ops} chaos ops {ops}, seed {opts.seed}")
    soak.launch_router()

    threads: List[threading.Thread] = []

    def arrival_loop():
        t0 = time.time()
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()

    deadline = soak.t0 + opts.budget_s
    next_chaos = time.time() + rng.uniform(1.0, opts.chaos_every)
    budget_blown = False
    while True:
        if time.time() > deadline:
            budget_blown = True
            soak.note("BUDGET BLOWN — abandoning the storm")
            break
        if soak.proc.poll() is not None:
            # The router must never die except when we kill it.
            soak.note(f"router self-death rc={soak.proc.returncode} — "
                      f"restarting (counts against it)")
            soak.launch_router()
        if ops and time.time() >= next_chaos:
            soak.run_chaos_op(ops.pop(0))
            next_chaos = time.time() + rng.uniform(
                0.5 * opts.chaos_every, 1.5 * opts.chaos_every)
        if not ops and not arr.is_alive() \
                and all(not th.is_alive() for th in threads):
            with soak.lock:
                acked = set(soak.acks)
            if acked and acked <= set(soak.results()) \
                    and not soak.journal_ids():
                break
        time.sleep(0.25)

    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    while not budget_blown and time.time() < deadline:
        if soak.proc.poll() is not None:
            soak.launch_router()
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    try:
        client.shutdown(soak.addr)
        soak.proc.wait(timeout=180)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()

    # ---- accounting --------------------------------------------------
    results = soak.results()
    locations = soak.result_locations()
    with soak.lock:
        acks = dict(soak.acks)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(set(
        [jid for jid, c in term_counts.items() if c > 1]
        + [jid for jid, where in locations.items() if len(where) > 1]))
    by_status: Dict[str, int] = {}
    for jid in acks:
        st = results.get(jid, {}).get("status", "LOST")
        by_status[st] = by_status.get(st, 0) + 1

    failovers = soak.failover_events()
    requeue_lat = [ev.get("latency_s", 0.0) for ev in failovers]

    # ---- byte parity vs solo twins -----------------------------------
    done_ids = [jid for jid in acks
                if results.get(jid, {}).get("status") == "done"]
    sample = sorted(done_ids)[:max(0, opts.verify)]
    byte_checked, byte_identical, mismatches = 0, 0, []
    if sample:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        for jid in sample:
            k = acks[jid]["k"]
            job = acks[jid]["job"]
            cfg = config_from_job(
                {**job, "result_name": os.path.join(workdir, "out",
                                                    f"solo{k}")})
            v = _variant_from_dict(0, {"name": "v"}, cfg)
            sres = solo_run(lane_config(cfg, v), console=lambda s: None)
            outs = results[jid]["variants"]["v"]["outputs"]
            byte_checked += 1
            same = True
            for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        same = False
                        mismatches.append(f"{jid}: {fa} != {fb}")
            byte_identical += int(same)
            soak.note(f"parity {jid} (job{k}): "
                      f"{'identical' if same else 'MISMATCH'}")

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          # rc None = the drained replica was ADOPTED (router restarted
          # mid-soak; not our child, so no exit code is collectible) —
          # the drain itself still completed synchronously.
          and all(rc in (0, None) for rc in soak.drain_rcs))
    return {
        "ok": ok, "mode": "router", "seed": opts.seed, "jobs": n,
        "replicas": opts.replicas,
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "terminal_by_status": by_status,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "replica_kills": soak.replica_kills,
        "replica_drains": soak.replica_drains,
        "router_restarts": soak.router_restarts,
        "drain_exit_codes": soak.drain_rcs,
        "cancels_sent": soak.cancels_sent,
        "failovers": len(failovers),
        "requeue_p50_s": _percentile(requeue_lat, 0.5),
        "requeue_p99_s": _percentile(requeue_lat, 0.99),
        "router_restart_p99_s": _percentile(soak.recoveries, 0.99),
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(time.time() - soak.t0, 1),
    }


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)


def _byte_parity(soak, acks: Dict[str, dict], results: Dict[str, dict],
                 workdir: str, n_verify: int):
    """Re-run a sample of completed jobs solo and uninterrupted in THIS
    process; their outputs must be byte-identical to what the stormed
    fleet recorded. Returns (checked, identical, mismatches)."""
    done_ids = [jid for jid in acks
                if results.get(jid, {}).get("status") == "done"]
    sample = sorted(done_ids)[:max(0, n_verify)]
    byte_checked, byte_identical, mismatches = 0, 0, []
    if not sample:
        return byte_checked, byte_identical, mismatches
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
    from g2vec_tpu.config import config_from_job
    from g2vec_tpu.pipeline import run as solo_run

    for jid in sample:
        k = acks[jid]["k"]
        job = acks[jid]["job"]
        cfg = config_from_job(
            {**job, "result_name": os.path.join(workdir, "out",
                                                f"solo{k}")})
        v = _variant_from_dict(0, {"name": "v"}, cfg)
        sres = solo_run(lane_config(cfg, v), console=lambda s: None)
        outs = results[jid]["variants"]["v"]["outputs"]
        byte_checked += 1
        same = True
        for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
            with open(fa, "rb") as a, open(fb, "rb") as b:
                if a.read() != b.read():
                    same = False
                    mismatches.append(f"{jid}: {fa} != {fb}")
        byte_identical += int(same)
        soak.note(f"parity {jid} (job{k}): "
                  f"{'identical' if same else 'MISMATCH'}")
    return byte_checked, byte_identical, mismatches


def run_autoscale_soak(opts, workdir: str) -> dict:
    """The elastic-vs-static proof harness: the seeded diurnal/burst
    storm with tenant SLO classes against ONE fleet shape (the caller —
    bench.py --_autoscale_ab — runs it twice, static then elastic, under
    the identical schedule). One active replica is SIGKILLed mid-spike;
    every heal and every scale event must come from the router. The
    summary carries deadline deaths, per-tenant attainment, shed/quota
    traffic, goodput, and the scale-up reaction distribution on top of
    the fleet-wide exactly-once predicate."""
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client

    soak = AutoscaleSoak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    # Heavier cohort than the base soak: per-job cost must be real for
    # a flash crowd to build an actual queue (the tiny spec services in
    # ~0.3 s/job and no arrival rate this side of silly saturates it).
    spec = SyntheticSpec(n_good=44, n_poor=40, module_size=12,
                         n_background=44, n_expr_only=6, n_net_only=6,
                         module_chords=2, background_edges=80, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    # The warm-pool canary: a gold/silver-shaped job. XLA programs are
    # keyed by walk length and model width (biomarker count, epochs,
    # seeds don't change shapes), so one canary at the interactive
    # tier's lenPath/sizeHiddenlayer pre-compiles EVERY gold and silver
    # job's programs on a spare before it is ever promoted — the
    # deadlined traffic lands on a hot process. Bulk's unique shapes
    # stay cold by design; bulk carries (almost) no deadlines to miss.
    if soak._elastic() and opts.warm_spares > 0:
        canary = soak.make_job(0, paths, native_ok)
        canary.update(lenPath=8, sizeHiddenlayer=16, numRepetition=3,
                      numBiomarker=2, epoch=opts.epochs,
                      result_name=os.path.join(workdir, "out", "warmup"))
        soak.warmup_job = os.path.join(workdir, "warmup_job.json")
        with open(soak.warmup_job, "w") as fh:
            json.dump(canary, fh)

    n = opts.jobs
    rng = soak.rng
    # The load model: one compressed "day" with two flash crowds. The
    # spike times are seed-jittered, then shared verbatim by both arms.
    spikes = [(rng.uniform(14.0, 17.0), 6.0, 12.0),
              (rng.uniform(52.0, 58.0), 8.0, 4.0)]
    arrivals = diurnal_arrivals(n, rng, base_rate=0.6, period_s=70.0,
                                spikes=spikes)
    # The acceptance kill: one ACTIVE replica dies 2.5 s into the first
    # flash crowd, when the queue is deepest and a lost journal would
    # hurt the most. By then the elastic arm has already scaled up
    # (the crowd trips the queue threshold within a tick or two), so a
    # survivor is in the ring to inherit the dead journal; the static
    # arm's queued jobs instead wait out the full fence+relaunch window
    # with their deadline clocks running.
    kill_at = spikes[0][0] + 2.5

    soak.note(f"autoscale soak ({'elastic' if soak._elastic() else 'static'}"
              f"): {n} jobs over base {opts.replicas} replica(s), "
              f"max={opts.max_replicas or opts.replicas} "
              f"warm={opts.warm_spares} shed={opts.shed} "
              f"quotas={'yes' if opts.tenant_quotas else 'no'}, "
              f"spikes={[(round(s, 1), d, m) for s, d, m in spikes]}, "
              f"kill_at={kill_at:.1f}s, seed {opts.seed}")
    soak.launch_router()

    if soak.warmup_job:
        # Bring-up discipline: the storm opens only after the initial
        # warm pool is WARM (canary complete). Operators finish
        # provisioning before opening the doors — and on a shared-CPU
        # host, a mid-storm canary compile steals exactly the cycles
        # the active set needs to hold its deadlines. Bounded wait: a
        # failed warmup degrades to the old cold-spare behavior.
        warm_wait_t0 = time.time()
        while time.time() - warm_wait_t0 < 120.0:
            warmed = sum(1 for ev in soak.router_events(("warm_spare",))
                         if ev.get("outcome") == "warmed")
            if warmed >= opts.warm_spares:
                soak.note(f"warm pool warmed ({warmed} spare(s), "
                          f"{time.time() - warm_wait_t0:.1f}s) — "
                          f"opening the storm")
                break
            time.sleep(0.5)
        else:
            soak.note("warm pool never finished warming (120s) — "
                      "storm opens against cold spares")

    threads: List[threading.Thread] = []
    soak.arrival_t0 = time.time()

    def arrival_loop():
        t0 = soak.arrival_t0
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()

    deadline = soak.t0 + opts.budget_s
    kill_wall = soak.arrival_t0 + kill_at
    killed = False
    next_status = time.time() + 1.0
    budget_blown = False
    while True:
        if time.time() > deadline:
            budget_blown = True
            soak.note("BUDGET BLOWN — abandoning the storm")
            break
        if soak.proc.poll() is not None:
            soak.note(f"router self-death rc={soak.proc.returncode} — "
                      f"restarting (counts against it)")
            soak.launch_router()
        if not killed and time.time() >= kill_wall:
            killed = True
            soak.op_replica_sigkill()
        if time.time() >= next_status:
            soak.check_router_status()
            next_status = time.time() + 1.0
        if killed and not arr.is_alive() \
                and all(not th.is_alive() for th in threads):
            with soak.lock:
                acked = set(soak.acks)
            if acked and acked <= set(soak.results()) \
                    and not soak.journal_ids():
                break
        time.sleep(0.25)

    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    while not budget_blown and time.time() < deadline:
        if soak.proc.poll() is not None:
            soak.launch_router()
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    soak.check_router_status()
    try:
        client.shutdown(soak.addr)
        soak.proc.wait(timeout=180)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()

    # ---- accounting --------------------------------------------------
    results = soak.results()
    locations = soak.result_locations()
    with soak.lock:
        acks = dict(soak.acks)
        gave_up = list(soak.gave_up)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(set(
        [jid for jid, c in term_counts.items() if c > 1]
        + [jid for jid, where in locations.items() if len(where) > 1]))
    by_status: Dict[str, int] = {}
    for jid in acks:
        st = results.get(jid, {}).get("status", "LOST")
        by_status[st] = by_status.get(st, 0) + 1
    deadline_deaths = by_status.get("deadline_exceeded", 0)

    # Per-tenant SLO attainment over DEADLINED traffic: done /
    # (deadlined acked + deadlined given-up-after-sheds). A finally-shed
    # job counts against the tenant — refusing it is still a miss, just
    # an honest, early, cheap one.
    attainment: Dict[str, Optional[float]] = {}
    att_num_total, att_den_total = 0, 0
    gave_up_by_tenant: Dict[str, int] = {}
    for g in gave_up:
        gave_up_by_tenant[g["tenant"]] = \
            gave_up_by_tenant.get(g["tenant"], 0) + 1
    for tenant in TENANT_CLASSES:
        acked_dl = [jid for jid, a in acks.items()
                    if a.get("tenant") == tenant
                    and a.get("deadline_s") is not None]
        num = sum(1 for jid in acked_dl
                  if results.get(jid, {}).get("status") == "done")
        den = len(acked_dl) + sum(1 for g in gave_up
                                  if g["tenant"] == tenant
                                  and g["deadline_s"] is not None)
        attainment[tenant] = round(num / den, 3) if den else None
        att_num_total += num
        att_den_total += den
    attainment_overall = (round(att_num_total / att_den_total, 3)
                          if att_den_total else None)

    # Scale evidence from the router's durable metrics stream.
    ups = soak.router_events(("scale_up",))
    downs = soak.router_events(("scale_down",))
    warm_evs = soak.router_events(("warm_spare",))
    warm_outcomes: Dict[str, int] = {}
    for ev in warm_evs:
        o = ev.get("outcome", "?")
        warm_outcomes[o] = warm_outcomes.get(o, 0) + 1
    reactions = [float(ev.get("reaction_s", 0.0)) for ev in ups]
    spike1_wall = soak.arrival_t0 + spikes[0][0]
    spike_to_scale = None
    for ev in ups:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)) and ts >= spike1_wall:
            spike_to_scale = round(ts - spike1_wall, 2)
            break
    slo_evs = soak.slo_events()

    # Goodput over the STORM window (arrivals open -> now), not process
    # lifetime: the elastic arm's pre-storm warm bring-up is
    # provisioning time, not serving time, and must not dilute its
    # throughput against the static arm's.
    wall_s = time.time() - (soak.arrival_t0 or soak.t0)
    done_n = by_status.get("done", 0)

    byte_checked, byte_identical, mismatches = _byte_parity(
        soak, acks, results, workdir, opts.verify)

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.rejected
          and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          and not soak.status_violations
          and soak.replica_kills >= 1)
    if soak._elastic():
        # The elastic arm must actually have scaled — a run that never
        # left min_replicas proved nothing about the controller.
        ok = ok and len(ups) >= 1 and soak.max_active_seen \
            > (opts.min_replicas or opts.replicas)
    return {
        "ok": ok, "mode": "autoscale",
        "elastic": soak._elastic(), "seed": opts.seed, "jobs": n,
        "min_replicas": opts.min_replicas or opts.replicas,
        "max_replicas": opts.max_replicas or opts.replicas,
        "warm_spares": opts.warm_spares, "shed": bool(opts.shed),
        "tenant_quotas": opts.tenant_quotas,
        "spikes": [[round(s, 2), d, m] for s, d, m in spikes],
        "kill_at_s": round(kill_at, 2),
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "gave_up": len(gave_up),
        "gave_up_by_tenant": gave_up_by_tenant,
        "terminal_by_status": by_status,
        "deadline_deaths": deadline_deaths,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "replica_kills": soak.replica_kills,
        "shed_events": slo_evs["shed"],
        "quota_events": slo_evs["tenant_quota"],
        "shed_retries": soak.shed_retries,
        "quota_retries": soak.quota_retries,
        "shed_fraction": round(len(gave_up) / n, 3),
        "attainment": attainment,
        "attainment_overall": attainment_overall,
        "goodput_done_per_min": round(60.0 * done_n / wall_s, 2),
        "scale_ups": len(ups), "scale_downs": len(downs),
        "scale_up_reaction_p50_s": _percentile(reactions, 0.5),
        "scale_up_reaction_max_s": _percentile(reactions, 1.0),
        "spike_to_scale_s": spike_to_scale,
        "max_active_seen": soak.max_active_seen,
        "warm_pool_events": warm_outcomes,
        "failovers": len(soak.failover_events()),
        "status_checks": soak.status_checks,
        "status_violations": soak.status_violations,
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(wall_s, 1),
    }


class _Relay:
    """A userspace TCP partition injector for ONE replica: listens on
    its own port, forwards byte streams to the replica's real address,
    and can blackhole each direction independently (``drop_to_replica``
    / ``drop_to_client``). Blackholing is accept-then-discard: SYNs
    still complete (the kernel backlog answers those), but bytes die in
    the relay — observably identical to an asymmetric partition for the
    length-prefixed JSONL protocol, where a request that draws no reply
    is a dead peer. jax-free and dependency-free by construction."""

    def __init__(self, backend: str):
        host, port = backend.rsplit(":", 1)
        self.backend = (host, int(port))
        self.drop_to_replica = threading.Event()
        self.drop_to_client = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(64)
        self._srv.settimeout(0.25)
        self.addr = f"127.0.0.1:{self._srv.getsockname()[1]}"
        threading.Thread(target=self._accept_loop,
                         name="chaos-relay", daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                back = socket.create_connection(self.backend, timeout=10)
            except OSError:
                conn.close()
                continue
            with self._lock:
                self._conns += [conn, back]
            threading.Thread(target=self._pump,
                             args=(conn, back, self.drop_to_replica),
                             daemon=True).start()
            threading.Thread(target=self._pump,
                             args=(back, conn, self.drop_to_client),
                             daemon=True).start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              drop: threading.Event) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                if drop.is_set():
                    continue       # the partition: read and discard
                dst.sendall(data)
        except OSError:
            pass
        finally:
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def partition(self, to_replica: bool = True,
                  to_client: bool = True) -> None:
        if to_replica:
            self.drop_to_replica.set()
        if to_client:
            self.drop_to_client.set()

    def heal(self) -> None:
        self.drop_to_replica.clear()
        self.drop_to_client.clear()

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class PartitionSoak(RouterSoak):
    """Soak state for partition mode. Unlike RouterSoak, the HARNESS
    owns the replica daemons (the router runs --remote-replicas, so it
    adopts and fences but never forks), which is what lets a relay sit
    between the router and r0: r0's published tcp_addr file is
    overwritten with the relay's address after boot, and the router
    (deliberately) keeps using the published address instead of the
    daemon's self-reported direct one."""

    def __init__(self, opts, workdir: str):
        super().__init__(opts, workdir)
        self.replica_procs: Dict[str, subprocess.Popen] = {}
        self.relay: Optional[_Relay] = None
        self.router_serial = 0
        self.router_metrics_files: List[str] = []
        self.standby: Optional[subprocess.Popen] = None
        self.takeover_s: List[float] = []
        self.degraded_status_ok = 0
        self.degraded_submits = 0
        self.degraded_results_seen = 0
        self.quiesce_rcs: List[Optional[int]] = []

    # ---- fleet the harness owns -------------------------------------

    def _replica_argv(self, i: int) -> List[str]:
        rdir = os.path.join(self.fleet, f"r{i}")
        return [sys.executable, "-m", "g2vec_tpu", "serve",
                "--socket", os.path.join(rdir, "sock"),
                "--state-dir", os.path.join(rdir, "state"),
                "--listen", "127.0.0.1:0",
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--metrics-jsonl", os.path.join(rdir, "metrics.jsonl")]

    def launch_replicas(self) -> None:
        for i in range(self.opts.replicas):
            rdir = os.path.join(self.fleet, f"r{i}")
            os.makedirs(os.path.join(rdir, "state"), exist_ok=True)
            log = open(os.path.join(rdir, "serve.log"), "a")
            self.replica_procs[f"r{i}"] = subprocess.Popen(
                self._replica_argv(i), env=self.env, stdout=log,
                stderr=subprocess.STDOUT)
            log.close()
        deadline = time.time() + 600
        for i in range(self.opts.replicas):
            af = os.path.join(self.fleet, f"r{i}", "state", "tcp_addr")
            while time.time() < deadline:
                try:
                    with open(af) as fh:
                        if fh.read().strip():
                            break
                except OSError:
                    pass
                if self.replica_procs[f"r{i}"].poll() is not None:
                    raise RuntimeError(f"replica r{i} died during boot")
                time.sleep(0.1)
            else:
                raise RuntimeError(f"replica r{i} never bound")
        # The relay slides in front of r0: real address behind it, the
        # relay's address published where the router (and fleet_addrs)
        # will look.
        af0 = os.path.join(self.fleet, "r0", "state", "tcp_addr")
        with open(af0) as fh:
            real = fh.read().strip()
        self.relay = _Relay(real)
        with open(af0 + ".tmp", "w") as fh:
            fh.write(self.relay.addr + "\n")
        os.replace(af0 + ".tmp", af0)
        self.note(f"replicas up; relay {self.relay.addr} fronts "
                  f"r0 ({real})")

    # ---- HA router pair ---------------------------------------------

    def _router_argv(self, standby: bool = False) -> List[str]:
        self.router_serial += 1
        m = os.path.join(self.wd,
                         f"router-metrics-{self.router_serial}.jsonl")
        self.router_metrics_files.append(m)
        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--replicas", str(self.opts.replicas),
                "--listen", "127.0.0.1:0",
                "--state-dir", self.fleet,
                "--remote-replicas",
                "--lease-ttl-s", str(self.opts.lease_ttl),
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--probe-interval", "0.3", "--probe-deadline", "1.0",
                "--metrics-jsonl", m]
        if standby:
            argv.append("--standby")
        return argv

    def launch_standby(self) -> None:
        argv = self._router_argv(standby=True)
        log = open(self.router_log, "a")
        self.standby = subprocess.Popen(argv, env=self.env, stdout=log,
                                        stderr=subprocess.STDOUT)
        log.close()
        self.note(f"standby router #{self.router_serial} watching "
                  f"the lease")

    def await_takeover(self, old_addr: str, t_from: float,
                       timeout: float = 90.0) -> bool:
        """Takeover latency as a CLIENT measures it: the moment a
        router at a NEW published address answers status. The router's
        own leader_elected takeover_s starts at its standby loop, not
        at the kill — this is the end-to-end number."""
        from g2vec_tpu.serve import client, protocol

        addr_file = os.path.join(self.fleet, "router_addr")
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                with open(addr_file) as fh:
                    a = fh.read().strip()
            except OSError:
                a = ""
            if a and a != old_addr:
                try:
                    if client.status(a, timeout=5.0):
                        took = time.time() - t_from
                        self.addr = a
                        self.proc = self.standby
                        self.standby = None
                        self.takeover_s.append(took)
                        self.note(f"takeover: {a} answering "
                                  f"{took:.2f}s after the fault")
                        return True
                except (OSError, client.ServeConnectionLost,
                        protocol.ProtocolError):
                    pass
            time.sleep(0.1)
        return False

    # ---- accounting across every router incarnation -----------------

    def router_events(self, kinds: Tuple[str, ...]) -> List[dict]:
        out = []
        for path in self.router_metrics_files:
            try:
                with open(path) as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        if ev.get("event") in kinds:
                            out.append(ev)
            except OSError:
                pass
        return out

    def failover_events(self) -> List[dict]:
        return self.router_events(("failover",))

    def replica_events(self, name: str, kind: str) -> List[dict]:
        out = []
        try:
            with open(os.path.join(self.fleet, name,
                                   "metrics.jsonl")) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == kind:
                        out.append(ev)
        except OSError:
            pass
        return out

    # ---- submission (takeover- and zombie-aware) --------------------

    def submit_one(self, k: int, job: dict) -> None:
        """Same exactly-once submit loop as RouterSoak, with two more
        transient rejections in the retry set: ``stale_epoch`` (the
        attempt raced a takeover and reached the zombie — the SAME idem
        key retried against the new leader is safe by construction) and
        ``fenced`` (the ring briefly offered a quarantined replica).
        ``self.addr`` is re-read every attempt, so retries follow the
        published router_addr across takeovers."""
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        priority = "interactive" if rng.random() < 0.3 else "batch"
        deadline_s = (round(rng.uniform(2.0, 8.0), 2)
                      if rng.random() < 0.15 else None)
        idem = f"soak-{self.opts.seed}-{k}"
        for attempt in range(16):
            try:
                evs = client.submit_job(
                    self.addr, job, tenant=f"t{k % 3}", timeout=600,
                    priority=priority, deadline_s=deadline_s,
                    idem_key=idem)
                if evs and evs[-1].get("event") == "rejected":
                    if evs[-1].get("error") in (
                            "no_replicas", "draining", "stale_epoch",
                            "fenced"):
                        raise OSError(f"transient: {evs[-1]['error']}")
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(3.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)

    # ---- degraded-mode client drill ---------------------------------

    def degraded_drill(self, round_i: int, paths: dict,
                       native_ok: bool) -> None:
        """Runs INSIDE a takeover gap: no router is answering, so the
        client falls back to the fleet's published replica addresses —
        status roll-up, then a keyed submit (rotating the key when the
        deterministic target turns out to be the fenced replica), then
        the reconcile read of the job it just placed."""
        from g2vec_tpu.serve import client

        st = client.degraded_status(self.fleet)
        if st.get("replicas"):
            self.degraded_status_ok += 1
        k = self.opts.jobs + round_i
        job = self.make_job(k, paths, native_ok)
        for j in range(6):
            key = f"deg-{self.opts.seed}-{round_i}-{j}"
            try:
                evs = client.degraded_submit(self.fleet, job,
                                             tenant="degraded",
                                             idem_key=key, timeout=600)
            except (client.ServeConnectionLost, client.ServeTimeout,
                    OSError):
                return
            if evs and evs[-1].get("event") == "rejected":
                continue       # crc32 target was the fenced replica
            jid = evs[0].get("job_id") if evs else None
            if not jid:
                return
            with self.lock:
                self.acks[jid] = {"k": k, "job": job,
                                  "deadline_s": None}
                self.degraded_submits += 1
            # The reconcile read: a durable record (it carries the
            # terminal ``status``) or an honest ``pending`` — anything
            # but a connection-level failure.
            rec = client.degraded_result(self.fleet, jid)
            if rec.get("status") or rec.get("event") == "pending":
                self.degraded_results_seen += 1
            self.note(f"degraded drill #{round_i}: submitted {jid} "
                      f"router-less (key {key})")
            return

    # ---- quiesce ----------------------------------------------------

    def stop_fleet(self) -> None:
        """The harness owns the daemons (remote-replicas mode: the
        router's own stop_all skips non-local replicas), so it drains
        them itself. The fenced r0 exits too — its parked jobs were
        migrated long ago, and drain does not need admission."""
        for name in sorted(self.replica_procs):
            proc = self.replica_procs[name]
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        for name in sorted(self.replica_procs):
            proc = self.replica_procs[name]
            try:
                self.quiesce_rcs.append(proc.wait(timeout=120))
            except subprocess.TimeoutExpired:
                proc.kill()
                self.quiesce_rcs.append(proc.wait())


def run_partition_soak(opts, workdir: str) -> dict:
    """The partition-tolerance drill: false-dead fencing + replica
    self-quarantine under a relay blackhole, zombie-leader command
    rejection after a SIGSTOP-induced takeover, a chain of router
    SIGKILLs each ridden out by a standby, and degraded-mode client
    drills inside every takeover gap — all under the fleet-wide
    exactly-once accounting of the router soak."""
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client, leader

    soak = PartitionSoak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    n = opts.jobs
    rng = soak.rng
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / opts.mean_arrival)

    soak.note(f"partition soak: {n} jobs over {opts.replicas} replicas "
              f"(harness-owned), lease ttl {opts.lease_ttl}s, "
              f"{opts.takeovers} takeover round(s), seed {opts.seed}")
    soak.launch_replicas()
    soak.launch_router()
    soak.launch_standby()

    threads: List[threading.Thread] = []
    deg_threads: List[threading.Thread] = []

    def arrival_loop():
        t0 = time.time()
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()
    deadline = soak.t0 + opts.budget_s
    budget_blown = False
    r0_state = os.path.join(soak.fleet, "r0", "state")
    drill = {"fence_epoch": None, "fenced_at": None,
             "quarantine_to_park_s": None, "fenced_stays_out": False,
             "stale_probe_rejects": 0, "stale_probe_targets": 0,
             "zombie_rejects": 0}

    def overdue() -> bool:
        return time.time() > deadline

    # ---- phase 1: false-dead — partition r0, fence, quarantine ------
    t_wait = time.time() + 30
    while time.time() < t_wait and not overdue():
        with soak.lock:
            if len(soak.acks) >= min(3, n):
                break
        time.sleep(0.2)
    soak.note("phase 1: blackholing r0's replies (asymmetric), then "
              "both directions")
    soak.relay.partition(to_replica=False, to_client=True)
    time.sleep(1.0)
    soak.relay.partition(to_replica=True, to_client=True)
    marker_path = leader.fence_marker_path(r0_state)
    t_limit = time.time() + 60
    while not os.path.exists(marker_path) and time.time() < t_limit \
            and not overdue():
        time.sleep(0.1)
    if os.path.exists(marker_path):
        try:
            with open(marker_path) as fh:
                raw = json.load(fh)
            drill["fence_epoch"] = int(raw.get("epoch", 0))
            drill["fenced_at"] = float(raw.get("fenced_at", 0.0))
        except (OSError, ValueError, TypeError):
            drill["fence_epoch"] = 0
        soak.note(f"r0 fenced at epoch {drill['fence_epoch']} "
                  f"(false-dead: the daemon is alive behind the relay)")
    t_limit = time.time() + 60
    quarantine = None
    while quarantine is None and time.time() < t_limit and not overdue():
        evs = soak.replica_events("r0", "quarantine")
        quarantine = evs[0] if evs else None
        time.sleep(0.2)
    if quarantine and drill["fenced_at"]:
        drill["quarantine_to_park_s"] = round(
            quarantine["ts"] - drill["fenced_at"], 3)
        soak.note(f"r0 self-quarantined {drill['quarantine_to_park_s']}s "
                  f"after the marker landed ({quarantine.get('parked')} "
                  f"job(s) parked)")
    soak.relay.heal()
    soak.note("phase 1: partition healed — r0 must STAY out of the ring")
    time.sleep(3.0)
    st = soak.router_status()
    if st:
        r0 = (st.get("replicas") or {}).get("r0") or {}
        drill["fenced_stays_out"] = r0.get("state") not in ("healthy",
                                                            "suspect")

    # ---- phase 2: zombie leader — SIGSTOP past the ttl --------------
    if not overdue():
        soak.note("phase 2: SIGSTOP active router past its lease ttl")
        old_addr, old_proc = soak.addr, soak.proc
        t_stop = time.time()
        try:
            os.kill(old_proc.pid, signal.SIGSTOP)
        except OSError:
            pass
        soak.await_takeover(old_addr, t_stop)
        soak.launch_standby()
        # Deterministic stale-epoch matrix: prime every replica's
        # watermark with the NEW leader's epoch, then replay at
        # epoch-1 — each must answer the structured stale_epoch
        # rejection (the fenced r0 included: the gate runs before the
        # quarantine check).
        lease_st = leader.read_lease(
            os.path.join(soak.fleet, leader.LEASE_FILE))
        epoch = lease_st.epoch if lease_st else 0
        if epoch > 1:
            for addr in client.fleet_addrs(soak.fleet):
                drill["stale_probe_targets"] += 1
                try:
                    list(client.request(addr, {"op": "cancel",
                                               "job_id": "fence-probe",
                                               "router_epoch": epoch},
                                        timeout=10.0))
                    evs = list(client.request(
                        addr, {"op": "cancel",
                               "job_id": "fence-probe",
                               "router_epoch": epoch - 1},
                        timeout=10.0))
                    if evs and evs[-1].get("error") == "stale_epoch":
                        drill["stale_probe_rejects"] += 1
                except (OSError, client.ServeConnectionLost):
                    pass
        # Wake the old leader: it is a zombie now (its renew fails),
        # and every mutating command it still emits carries its stale
        # epoch — the daemons must refuse each one.
        try:
            os.kill(old_proc.pid, signal.SIGCONT)
        except OSError:
            pass
        try:
            client.cancel(old_addr, "zombie-victim", timeout=30.0)
        except (OSError, client.ServeConnectionLost):
            pass
        t_limit = time.time() + 20
        while time.time() < t_limit and not overdue():
            zr = [ev for ev in soak.router_events(("stale_epoch",))
                  if ev.get("side") == "router"]
            if zr:
                drill["zombie_rejects"] = len(zr)
                break
            time.sleep(0.25)
        soak.note(f"zombie drill: {drill['stale_probe_rejects']}/"
                  f"{drill['stale_probe_targets']} replicas rejected "
                  f"the stale epoch; {drill['zombie_rejects']} zombie "
                  f"command(s) refused")
        # A zombie is never shut down gracefully — its exit path would
        # SIGTERM replicas now owned by the new leader. SIGKILL only.
        try:
            os.kill(old_proc.pid, signal.SIGKILL)
        except OSError:
            pass
        old_proc.wait()

    # ---- phase 3: takeover chain with degraded-mode gaps ------------
    for round_i in range(opts.takeovers):
        if overdue():
            break
        soak.note(f"phase 3.{round_i}: SIGKILL active router "
                  f"(takeover chain)")
        old_addr, victim = soak.addr, soak.proc
        t_kill = time.time()
        try:
            os.kill(victim.pid, signal.SIGKILL)
        except OSError:
            pass
        victim.wait()
        dth = threading.Thread(target=soak.degraded_drill,
                               args=(round_i, paths, native_ok),
                               daemon=True)
        dth.start()
        deg_threads.append(dth)
        soak.await_takeover(old_addr, t_kill)
        soak.launch_standby()

    # ---- drain ------------------------------------------------------
    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    for th in deg_threads:
        th.join(timeout=600)
    while not overdue():
        if soak.proc.poll() is not None:
            # The active died without us killing it: the standby is the
            # recovery path even here.
            soak.note("active router self-death mid-drain — waiting "
                      "for the standby")
            if not soak.await_takeover(soak.addr, time.time()):
                break
            soak.launch_standby()
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    else:
        budget_blown = True
        soak.note("BUDGET BLOWN — abandoning the drill")
    # Kill the waiting standby FIRST: a clean router shutdown releases
    # the lease, and a live standby would take over and reboot the
    # fleet the harness is about to stop.
    if soak.standby is not None and soak.standby.poll() is None:
        soak.standby.kill()
        soak.standby.wait()
    try:
        client.shutdown(soak.addr)
        soak.proc.wait(timeout=180)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()
    soak.stop_fleet()
    soak.relay.close()

    # ---- accounting --------------------------------------------------
    results = soak.results()
    locations = soak.result_locations()
    with soak.lock:
        acks = dict(soak.acks)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(set(
        [jid for jid, c in term_counts.items() if c > 1]
        + [jid for jid, where in locations.items() if len(where) > 1]))
    by_status: Dict[str, int] = {}
    for jid in acks:
        st_ = results.get(jid, {}).get("status", "LOST")
        by_status[st_] = by_status.get(st_, 0) + 1

    # The fenced replica's silence: after the marker landed, r0 must
    # never mint another terminal state or result record (quiesce-drain
    # job_drained notices are fine — those are parks, not results).
    r0_violations: List[str] = []
    if drill["fenced_at"]:
        for ev in soak.replica_events("r0", "job_state"):
            if ev.get("state") in TERMINAL_STATES \
                    and ev.get("ts", 0.0) > drill["fenced_at"] + 0.05:
                r0_violations.append(
                    f"terminal {ev.get('state')} for "
                    f"{ev.get('job_id')} at +"
                    f"{ev['ts'] - drill['fenced_at']:.2f}s")
        resd = os.path.join(r0_state, "results")
        if os.path.isdir(resd):
            for fn in os.listdir(resd):
                path = os.path.join(resd, fn)
                try:
                    if os.stat(path).st_mtime > \
                            drill["fenced_at"] + 0.05:
                        r0_violations.append(f"result file {fn} "
                                             f"written after fencing")
                except OSError:
                    pass

    failovers = soak.failover_events()
    requeue_lat = [ev.get("latency_s", 0.0) for ev in failovers]
    elected = soak.router_events(("leader_elected",))
    daemon_stales = sum(
        len([ev for ev in soak.replica_events(f"r{i}", "stale_epoch")
             if ev.get("side") == "daemon"])
        for i in range(opts.replicas))

    byte_checked, byte_identical, mismatches = _byte_parity(
        soak, acks, results, workdir, opts.verify)

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          and (drill["fence_epoch"] or 0) >= 1
          and quarantine is not None
          and not r0_violations
          and drill["fenced_stays_out"]
          and drill["stale_probe_targets"] > 0
          and drill["stale_probe_rejects"]
          == drill["stale_probe_targets"]
          and drill["zombie_rejects"] >= 1
          and len(soak.takeover_s) >= opts.takeovers + 1
          and soak.degraded_status_ok >= 1
          and soak.degraded_submits >= 1)
    return {
        "ok": ok, "mode": "partition", "seed": opts.seed, "jobs": n,
        "replicas": opts.replicas, "lease_ttl_s": opts.lease_ttl,
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "terminal_by_status": by_status,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "fence_epoch": drill["fence_epoch"],
        "quarantine_to_park_s": drill["quarantine_to_park_s"],
        "quarantine_parked": (quarantine or {}).get("parked"),
        "fenced_replica_violations": r0_violations,
        "fenced_stays_out": drill["fenced_stays_out"],
        "stale_probe_rejects": drill["stale_probe_rejects"],
        "stale_probe_targets": drill["stale_probe_targets"],
        "zombie_rejects": drill["zombie_rejects"],
        "daemon_stale_events": daemon_stales,
        "leader_elections": len(elected),
        "takeovers": len(soak.takeover_s),
        "takeover_p50_s": _percentile(soak.takeover_s, 0.5),
        "takeover_p99_s": _percentile(soak.takeover_s, 0.99),
        "degraded_status_ok": soak.degraded_status_ok,
        "degraded_submits": soak.degraded_submits,
        "degraded_results_seen": soak.degraded_results_seen,
        "failovers": len(failovers),
        "requeue_p50_s": _percentile(requeue_lat, 0.5),
        "requeue_p99_s": _percentile(requeue_lat, 0.99),
        "replica_quiesce_rcs": soak.quiesce_rcs,
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(time.time() - soak.t0, 1),
    }


def run_soak(opts, workdir: str) -> dict:
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client

    soak = Soak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    n = opts.jobs
    n_ops = opts.chaos_ops or max(3, n // 8)
    rng = soak.rng
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / opts.mean_arrival)
    op_pool = ["sigkill", "drain", "cancel", "fault_train"]
    if native_ok:
        op_pool += ["fault_stream_ckpt", "fault_drain_seam"]
    ops = [op_pool[i % len(op_pool)] for i in range(n_ops)]
    rng.shuffle(ops)

    soak.note(f"soak: {n} jobs (stream_frac="
              f"{opts.stream_frac if native_ok else 0}), "
              f"{n_ops} chaos ops {ops}, seed {opts.seed}")
    soak.launch()

    threads: List[threading.Thread] = []

    def arrival_loop():
        t0 = time.time()
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()

    deadline = soak.t0 + opts.budget_s
    next_chaos = time.time() + rng.uniform(1.0, opts.chaos_every)
    budget_blown = False
    while True:
        if time.time() > deadline:
            budget_blown = True
            soak.note("BUDGET BLOWN — abandoning the storm")
            break
        if soak.proc.poll() is not None:
            # Died on its own: an armed fault plan fired.
            soak.relaunch_after_death(
                f"self-death rc={soak.proc.returncode}")
        if ops and time.time() >= next_chaos:
            soak.run_chaos_op(ops.pop(0))
            next_chaos = time.time() + rng.uniform(
                0.5 * opts.chaos_every, 1.5 * opts.chaos_every)
        if not ops and not arr.is_alive() \
                and all(not th.is_alive() for th in threads):
            with soak.lock:
                acked = set(soak.acks)
            if acked and acked <= set(soak.results()) \
                    and not soak.journal_ids():
                break
        time.sleep(0.25)

    # Quiesce: a clean daemon finishes whatever the storm left behind.
    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    while not budget_blown and time.time() < deadline:
        if soak.proc.poll() is not None:
            soak.relaunch_after_death(
                f"self-death rc={soak.proc.returncode}")
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    try:
        client.shutdown(soak.sock)
        soak.proc.wait(timeout=120)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()

    # ---- accounting ------------------------------------------------------
    results = soak.results()
    with soak.lock:
        acks = dict(soak.acks)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(jid for jid, c in term_counts.items() if c > 1)
    by_status: Dict[str, int] = {}
    for jid in acks:
        st = results.get(jid, {}).get("status", "LOST")
        by_status[st] = by_status.get(st, 0) + 1

    # ---- byte parity on a sample of completed jobs -----------------------
    done_ids = [jid for jid in acks
                if results.get(jid, {}).get("status") == "done"]
    sample = sorted(done_ids)[:max(0, opts.verify)]
    byte_checked, byte_identical, mismatches = 0, 0, []
    if sample:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        for jid in sample:
            k = acks[jid]["k"]
            job = acks[jid]["job"]
            cfg = config_from_job(
                {**job, "result_name": os.path.join(workdir, "out",
                                                    f"solo{k}")})
            v = _variant_from_dict(0, {"name": "v"}, cfg)
            sres = solo_run(lane_config(cfg, v), console=lambda s: None)
            outs = results[jid]["variants"]["v"]["outputs"]
            byte_checked += 1
            same = True
            for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        same = False
                        mismatches.append(f"{jid}: {fa} != {fb}")
            byte_identical += int(same)
            soak.note(f"parity {jid} (job{k}): "
                      f"{'identical' if same else 'MISMATCH'}")

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          and all(rc == 0 for rc in soak.drain_rcs))
    return {
        "ok": ok, "seed": opts.seed, "jobs": n,
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "terminal_by_status": by_status,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "kills": soak.kills, "drains": soak.drains,
        "drain_exit_codes": soak.drain_rcs,
        "fault_injections": soak.fault_injections,
        "cancels_sent": soak.cancels_sent,
        "recover_p50_s": _percentile(soak.recoveries, 0.5),
        "recover_p99_s": _percentile(soak.recoveries, 0.99),
        "recoveries": len(soak.recoveries),
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(time.time() - soak.t0, 1),
    }


def main(argv: Optional[List[str]] = None) -> int:
    opts = build_parser().parse_args(argv)
    workdir = opts.workdir or tempfile.mkdtemp(prefix="g2vec-chaos-")
    os.makedirs(workdir, exist_ok=True)
    try:
        if opts.partition:
            if opts.replicas < 2:
                opts.replicas = 3
            summary = run_partition_soak(opts, workdir)
        elif opts.autoscale:
            if opts.replicas < 1:
                opts.replicas = 1
            summary = run_autoscale_soak(opts, workdir)
        elif opts.replicas:
            summary = run_router_soak(opts, workdir)
        else:
            summary = run_soak(opts, workdir)
    finally:
        if not opts.keep and not opts.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(summary, indent=1), flush=True)
    if opts.json:
        with open(opts.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
