#!/usr/bin/env python3
"""Chaos soak: a seeded fault storm against the serve daemon, with
exactly-once accounting.

The harness is the supervisor: it launches ``g2vec serve`` as a child
(UNsupervised, so drain exit codes are observable), drives a seeded
Poisson schedule of job arrivals (a mix of full-batch and streaming
jobs, tenants, priorities, some with tight deadlines), and injects a
seeded rotation of faults while the jobs run:

- ``sigkill``  — SIGKILL the daemon mid-whatever; relaunch immediately.
- ``drain``    — SIGTERM; the daemon must exit 0 with in-flight
  streaming jobs checkpointed and everything unfinished journaled.
- ``fault:*``  — drain, then relaunch with a ``--fault-plan`` armed at a
  durable seam (``stream_ckpt``/``train`` sigkill, ``drain`` crash) and
  a fresh ``G2VEC_FAULT_STATE`` file so each injection fires once.
- ``cancel``   — client-cancel a random not-yet-terminal job.

After the storm a clean daemon quiesces the backlog. The soak PASSES
iff every acknowledged job reaches exactly one well-defined terminal
state (done / cancelled / deadline_exceeded — ``failed`` counts but is
reported separately), zero jobs are lost (acknowledged but never
recorded) or duplicated (more than one terminal job_state event in the
daemon-lifetime metrics JSONL), the journal is empty, and a sample of
completed jobs is byte-identical to solo uninterrupted runs of the same
configs.

Scale knobs are flags with G2V_CHAOS_* env fallbacks so CI can shrink
the soak (``G2V_CHAOS_JOBS=6 python tools/chaos_soak.py``). The
committed artifact (BENCH_CHAOS_SOAK.json) is written by
``bench.py --_chaos_soak``, which wraps this module.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TERMINAL_STATES = ("done", "failed", "cancelled", "deadline_exceeded")


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="chaos_soak",
        description="Seeded fault storm against g2vec serve with "
                    "exactly-once job accounting.")
    p.add_argument("--jobs", type=int,
                   default=_env_int("G2V_CHAOS_JOBS", 50))
    p.add_argument("--seed", type=int,
                   default=_env_int("G2V_CHAOS_SEED", 0))
    p.add_argument("--epochs", type=int,
                   default=_env_int("G2V_CHAOS_EPOCHS", 8),
                   help="Base epoch count per job (jittered per job).")
    p.add_argument("--mean-arrival", type=float,
                   default=_env_float("G2V_CHAOS_ARRIVAL", 0.4),
                   help="Mean exponential interarrival seconds.")
    p.add_argument("--chaos-ops", type=int,
                   default=_env_int("G2V_CHAOS_OPS", 0),
                   help="Fault injections over the soak (0 = jobs//8, "
                        "min 3).")
    p.add_argument("--chaos-every", type=float,
                   default=_env_float("G2V_CHAOS_EVERY", 7.0),
                   help="Mean seconds between fault injections.")
    p.add_argument("--stream-frac", type=float,
                   default=_env_float("G2V_CHAOS_STREAM_FRAC", 0.4),
                   help="Fraction of streaming jobs (needs g++; 0 if "
                        "no native toolchain).")
    p.add_argument("--verify", type=int,
                   default=_env_int("G2V_CHAOS_VERIFY", 4),
                   help="Completed jobs to byte-compare against solo "
                        "uninterrupted twins.")
    p.add_argument("--budget-s", type=float,
                   default=_env_float("G2V_CHAOS_BUDGET", 900.0),
                   help="Hard wall-clock budget for the whole soak.")
    p.add_argument("--workdir", type=str, default=None,
                   help="Working directory (default: a fresh tempdir, "
                        "removed unless --keep).")
    p.add_argument("--keep", action="store_true",
                   help="Keep the workdir (logs, metrics, outputs).")
    p.add_argument("--json", type=str, default=None, metavar="PATH",
                   help="Also write the summary JSON here.")
    return p


class Soak:
    def __init__(self, opts, workdir: str):
        self.opts = opts
        self.wd = workdir
        self.rng = random.Random(opts.seed)
        self.sock = os.path.join(workdir, "chaos.sock")
        self.state = os.path.join(workdir, "state")
        self.metrics_path = os.path.join(workdir, "metrics.jsonl")
        self.log_path = os.path.join(workdir, "daemon.log")
        self.proc: Optional[subprocess.Popen] = None
        self.env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": REPO + os.pathsep
                    + os.environ.get("PYTHONPATH", "")}
        self.lock = threading.Lock()
        self.acks: Dict[str, dict] = {}      # job_id -> {"k", "job"}
        self.rejected: List[int] = []
        self.unsubmitted: List[int] = []
        self.recoveries: List[float] = []
        self.kills = 0
        self.drains = 0
        self.drain_rcs: List[int] = []
        self.fault_injections: List[str] = []
        self.cancels_sent = 0
        self.notes: List[str] = []
        self._fault_serial = 0
        self.t0 = time.time()

    def note(self, msg: str) -> None:
        line = f"[{time.time() - self.t0:7.1f}s] {msg}"
        self.notes.append(line)
        print(f"# {line}", file=sys.stderr, flush=True)

    # ---- daemon lifecycle ------------------------------------------------

    def launch(self, fault_plan: Optional[str] = None) -> None:
        from g2vec_tpu.serve import client

        env = dict(self.env)
        if fault_plan:
            self._fault_serial += 1
            env["G2VEC_FAULT_STATE"] = os.path.join(
                self.wd, f"fault-state-{self._fault_serial}.json")
        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--socket", self.sock, "--state-dir", self.state,
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--metrics-jsonl", self.metrics_path]
        if fault_plan:
            argv += ["--fault-plan", fault_plan]
        log = open(self.log_path, "a")
        self.proc = subprocess.Popen(argv, env=env, stdout=log,
                                     stderr=subprocess.STDOUT)
        log.close()
        if not client.wait_ready(self.sock, 120):
            raise RuntimeError(
                f"daemon never became ready (log: {self.log_path})")

    def relaunch_after_death(self, why: str) -> None:
        t_down = time.time()
        self.launch()
        self.recoveries.append(time.time() - t_down)
        self.note(f"relaunched after {why} "
                  f"(ready in {self.recoveries[-1]:.1f}s)")

    # ---- job construction ------------------------------------------------

    def make_job(self, k: int, paths: dict, native_ok: bool) -> dict:
        rng = random.Random((self.opts.seed << 16) ^ k)
        job = dict(
            expression_file=paths["expression"],
            clinical_file=paths["clinical"],
            network_file=paths["network"],
            result_name=os.path.join(self.wd, "out", f"job{k}"),
            lenPath=8, numRepetition=2, sizeHiddenlayer=16,
            epoch=self.opts.epochs + rng.choice((0, 2, 4)),
            learningRate=0.05, numBiomarker=5, compute_dtype="float32",
            seed=0, train_seed=k, kmeans_seed=k)
        if native_ok and rng.random() < self.opts.stream_frac:
            job.update(train_mode="streaming", walker_backend="native",
                       shard_paths=16, checkpoint_every=1)
        else:
            job["walker_backend"] = "device"
        return job

    def submit_one(self, k: int, job: dict) -> None:
        """Submit until acknowledged (or rejected); backoff with jitter
        across daemon deaths. Terminal accounting happens from durable
        records, not from this stream."""
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        priority = "interactive" if rng.random() < 0.3 else "batch"
        deadline_s = (round(rng.uniform(2.0, 8.0), 2)
                      if rng.random() < 0.15 else None)
        for attempt in range(12):
            try:
                evs = client.submit_job(
                    self.sock, job, tenant=f"t{k % 3}", timeout=600,
                    priority=priority, deadline_s=deadline_s)
                if evs and evs[-1].get("event") == "rejected":
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:     # acknowledged; journaled; never resubmit
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(5.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)

    # ---- chaos ops -------------------------------------------------------

    def op_sigkill(self) -> None:
        self.kills += 1
        self.note(f"chaos: SIGKILL daemon (kill #{self.kills})")
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except OSError:
            pass
        self.proc.wait()
        self.relaunch_after_death("SIGKILL")

    def op_drain(self, relaunch_plan: Optional[str] = None) -> None:
        self.drains += 1
        self.note(f"chaos: SIGTERM drain (drain #{self.drains}"
                  + (f", relaunch armed: {relaunch_plan}"
                     if relaunch_plan else "") + ")")
        try:
            os.kill(self.proc.pid, signal.SIGTERM)
        except OSError:
            pass
        try:
            rc = self.proc.wait(timeout=300)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            rc = -9
        self.drain_rcs.append(rc)
        t_down = time.time()
        self.launch(fault_plan=relaunch_plan)
        self.recoveries.append(time.time() - t_down)
        if relaunch_plan:
            self.fault_injections.append(relaunch_plan)

    def op_cancel(self) -> None:
        from g2vec_tpu.serve import client

        with self.lock:
            pending = [jid for jid in self.acks
                       if not os.path.exists(os.path.join(
                           self.state, "results", f"{jid}.json"))]
        if not pending:
            return
        jid = self.rng.choice(pending)
        self.cancels_sent += 1
        self.note(f"chaos: cancel {jid}")
        try:
            client.cancel(self.sock, jid)
        except (OSError, client.ServeConnectionLost):
            pass

    def run_chaos_op(self, op: str) -> None:
        if op == "sigkill":
            self.op_sigkill()
        elif op == "drain":
            self.op_drain()
        elif op == "fault_stream_ckpt":
            self.op_drain("stage=stream_ckpt,kind=sigkill")
        elif op == "fault_train":
            self.op_drain("stage=train,kind=sigkill")
        elif op == "fault_drain_seam":
            # Arm a crash INSIDE _begin_drain, then drain: the drain
            # thread dies at the seam but admission is already closed
            # and the stop flag still falls — the exit must stay clean.
            self.op_drain("stage=drain,kind=crash")
            self.op_drain()
        elif op == "cancel":
            self.op_cancel()

    # ---- accounting ------------------------------------------------------

    def results(self) -> Dict[str, dict]:
        out = {}
        rdir = os.path.join(self.state, "results")
        if not os.path.isdir(rdir):
            return out
        for fn in os.listdir(rdir):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(rdir, fn)) as f:
                        out[fn[:-5]] = json.load(f)
                except (OSError, ValueError):
                    pass
        return out

    def journal_ids(self) -> List[str]:
        jdir = os.path.join(self.state, "jobs")
        if not os.path.isdir(jdir):
            return []
        return [fn[:-5] for fn in os.listdir(jdir)
                if fn.endswith(".json")]

    def terminal_event_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        try:
            with open(self.metrics_path) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "job_state" \
                            and ev.get("state") in TERMINAL_STATES:
                        jid = ev.get("job_id")
                        counts[jid] = counts.get(jid, 0) + 1
        except OSError:
            pass
        return counts


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)


def run_soak(opts, workdir: str) -> dict:
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client

    soak = Soak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    n = opts.jobs
    n_ops = opts.chaos_ops or max(3, n // 8)
    rng = soak.rng
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / opts.mean_arrival)
    op_pool = ["sigkill", "drain", "cancel", "fault_train"]
    if native_ok:
        op_pool += ["fault_stream_ckpt", "fault_drain_seam"]
    ops = [op_pool[i % len(op_pool)] for i in range(n_ops)]
    rng.shuffle(ops)

    soak.note(f"soak: {n} jobs (stream_frac="
              f"{opts.stream_frac if native_ok else 0}), "
              f"{n_ops} chaos ops {ops}, seed {opts.seed}")
    soak.launch()

    threads: List[threading.Thread] = []

    def arrival_loop():
        t0 = time.time()
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()

    deadline = soak.t0 + opts.budget_s
    next_chaos = time.time() + rng.uniform(1.0, opts.chaos_every)
    budget_blown = False
    while True:
        if time.time() > deadline:
            budget_blown = True
            soak.note("BUDGET BLOWN — abandoning the storm")
            break
        if soak.proc.poll() is not None:
            # Died on its own: an armed fault plan fired.
            soak.relaunch_after_death(
                f"self-death rc={soak.proc.returncode}")
        if ops and time.time() >= next_chaos:
            soak.run_chaos_op(ops.pop(0))
            next_chaos = time.time() + rng.uniform(
                0.5 * opts.chaos_every, 1.5 * opts.chaos_every)
        if not ops and not arr.is_alive() \
                and all(not th.is_alive() for th in threads):
            with soak.lock:
                acked = set(soak.acks)
            if acked and acked <= set(soak.results()) \
                    and not soak.journal_ids():
                break
        time.sleep(0.25)

    # Quiesce: a clean daemon finishes whatever the storm left behind.
    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    while not budget_blown and time.time() < deadline:
        if soak.proc.poll() is not None:
            soak.relaunch_after_death(
                f"self-death rc={soak.proc.returncode}")
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    try:
        client.shutdown(soak.sock)
        soak.proc.wait(timeout=120)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()

    # ---- accounting ------------------------------------------------------
    results = soak.results()
    with soak.lock:
        acks = dict(soak.acks)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(jid for jid, c in term_counts.items() if c > 1)
    by_status: Dict[str, int] = {}
    for jid in acks:
        st = results.get(jid, {}).get("status", "LOST")
        by_status[st] = by_status.get(st, 0) + 1

    # ---- byte parity on a sample of completed jobs -----------------------
    done_ids = [jid for jid in acks
                if results.get(jid, {}).get("status") == "done"]
    sample = sorted(done_ids)[:max(0, opts.verify)]
    byte_checked, byte_identical, mismatches = 0, 0, []
    if sample:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        for jid in sample:
            k = acks[jid]["k"]
            job = acks[jid]["job"]
            cfg = config_from_job(
                {**job, "result_name": os.path.join(workdir, "out",
                                                    f"solo{k}")})
            v = _variant_from_dict(0, {"name": "v"}, cfg)
            sres = solo_run(lane_config(cfg, v), console=lambda s: None)
            outs = results[jid]["variants"]["v"]["outputs"]
            byte_checked += 1
            same = True
            for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        same = False
                        mismatches.append(f"{jid}: {fa} != {fb}")
            byte_identical += int(same)
            soak.note(f"parity {jid} (job{k}): "
                      f"{'identical' if same else 'MISMATCH'}")

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          and all(rc == 0 for rc in soak.drain_rcs))
    return {
        "ok": ok, "seed": opts.seed, "jobs": n,
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "terminal_by_status": by_status,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "kills": soak.kills, "drains": soak.drains,
        "drain_exit_codes": soak.drain_rcs,
        "fault_injections": soak.fault_injections,
        "cancels_sent": soak.cancels_sent,
        "recover_p50_s": _percentile(soak.recoveries, 0.5),
        "recover_p99_s": _percentile(soak.recoveries, 0.99),
        "recoveries": len(soak.recoveries),
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(time.time() - soak.t0, 1),
    }


def main(argv: Optional[List[str]] = None) -> int:
    opts = build_parser().parse_args(argv)
    workdir = opts.workdir or tempfile.mkdtemp(prefix="g2vec-chaos-")
    os.makedirs(workdir, exist_ok=True)
    try:
        summary = run_soak(opts, workdir)
    finally:
        if not opts.keep and not opts.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(summary, indent=1), flush=True)
    if opts.json:
        with open(opts.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
