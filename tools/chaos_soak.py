#!/usr/bin/env python3
"""Chaos soak: a seeded fault storm against the serve daemon, with
exactly-once accounting.

The harness is the supervisor: it launches ``g2vec serve`` as a child
(UNsupervised, so drain exit codes are observable), drives a seeded
Poisson schedule of job arrivals (a mix of full-batch and streaming
jobs, tenants, priorities, some with tight deadlines), and injects a
seeded rotation of faults while the jobs run:

- ``sigkill``  — SIGKILL the daemon mid-whatever; relaunch immediately.
- ``drain``    — SIGTERM; the daemon must exit 0 with in-flight
  streaming jobs checkpointed and everything unfinished journaled.
- ``fault:*``  — drain, then relaunch with a ``--fault-plan`` armed at a
  durable seam (``stream_ckpt``/``train`` sigkill, ``drain`` crash) and
  a fresh ``G2VEC_FAULT_STATE`` file so each injection fires once.
- ``cancel``   — client-cancel a random not-yet-terminal job.

After the storm a clean daemon quiesces the backlog. The soak PASSES
iff every acknowledged job reaches exactly one well-defined terminal
state (done / cancelled / deadline_exceeded — ``failed`` counts but is
reported separately), zero jobs are lost (acknowledged but never
recorded) or duplicated (more than one terminal job_state event in the
daemon-lifetime metrics JSONL), the journal is empty, and a sample of
completed jobs is byte-identical to solo uninterrupted runs of the same
configs.

``--replicas N`` switches to **router mode**: the storm runs against a
TCP router fronting N daemon replicas (serve/router.py). The op rotation
becomes replica SIGKILL (the router must detect, fence, migrate the
journal to survivors, and relaunch), synchronous replica drain (rc 0
asserted), and router SIGKILL+restart (the new router must adopt the
orphaned live replicas). The pass bar is the same exactly-once predicate
computed fleet-wide — every acked job has exactly one terminal event
across ALL replicas' metrics streams and exactly one result record
across all results dirs — plus byte parity and the death-to-requeue
latency distribution from the router's ``failover`` events.

Scale knobs are flags with G2V_CHAOS_* env fallbacks so CI can shrink
the soak (``G2V_CHAOS_JOBS=6 python tools/chaos_soak.py``). The
committed artifacts (BENCH_CHAOS_SOAK.json, BENCH_ROUTER_CHAOS.json) are
written by ``bench.py --_chaos_soak`` / ``--_router_chaos``, which wrap
this module.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TERMINAL_STATES = ("done", "failed", "cancelled", "deadline_exceeded")


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, str(default)))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, str(default)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="chaos_soak",
        description="Seeded fault storm against g2vec serve with "
                    "exactly-once job accounting.")
    p.add_argument("--jobs", type=int,
                   default=_env_int("G2V_CHAOS_JOBS", 50))
    p.add_argument("--seed", type=int,
                   default=_env_int("G2V_CHAOS_SEED", 0))
    p.add_argument("--epochs", type=int,
                   default=_env_int("G2V_CHAOS_EPOCHS", 8),
                   help="Base epoch count per job (jittered per job).")
    p.add_argument("--mean-arrival", type=float,
                   default=_env_float("G2V_CHAOS_ARRIVAL", 0.4),
                   help="Mean exponential interarrival seconds.")
    p.add_argument("--chaos-ops", type=int,
                   default=_env_int("G2V_CHAOS_OPS", 0),
                   help="Fault injections over the soak (0 = jobs//8, "
                        "min 3).")
    p.add_argument("--chaos-every", type=float,
                   default=_env_float("G2V_CHAOS_EVERY", 7.0),
                   help="Mean seconds between fault injections.")
    p.add_argument("--stream-frac", type=float,
                   default=_env_float("G2V_CHAOS_STREAM_FRAC", 0.4),
                   help="Fraction of streaming jobs (needs g++; 0 if "
                        "no native toolchain).")
    p.add_argument("--verify", type=int,
                   default=_env_int("G2V_CHAOS_VERIFY", 4),
                   help="Completed jobs to byte-compare against solo "
                        "uninterrupted twins.")
    p.add_argument("--budget-s", type=float,
                   default=_env_float("G2V_CHAOS_BUDGET", 900.0),
                   help="Hard wall-clock budget for the whole soak.")
    p.add_argument("--workdir", type=str, default=None,
                   help="Working directory (default: a fresh tempdir, "
                        "removed unless --keep).")
    p.add_argument("--keep", action="store_true",
                   help="Keep the workdir (logs, metrics, outputs).")
    p.add_argument("--json", type=str, default=None, metavar="PATH",
                   help="Also write the summary JSON here.")
    p.add_argument("--replicas", type=int,
                   default=_env_int("G2V_CHAOS_REPLICAS", 0),
                   help="Router mode: storm a replicated fleet behind the "
                        "TCP router instead of one daemon. Op rotation "
                        "becomes replica SIGKILL / synchronous replica "
                        "drain / router SIGKILL+restart / cancel; "
                        "accounting spans every replica's results dir and "
                        "metrics stream (0 = classic single-daemon mode).")
    return p


class Soak:
    def __init__(self, opts, workdir: str):
        self.opts = opts
        self.wd = workdir
        self.rng = random.Random(opts.seed)
        self.sock = os.path.join(workdir, "chaos.sock")
        self.state = os.path.join(workdir, "state")
        self.metrics_path = os.path.join(workdir, "metrics.jsonl")
        self.log_path = os.path.join(workdir, "daemon.log")
        self.proc: Optional[subprocess.Popen] = None
        self.env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "PYTHONPATH": REPO + os.pathsep
                    + os.environ.get("PYTHONPATH", "")}
        self.lock = threading.Lock()
        self.acks: Dict[str, dict] = {}      # job_id -> {"k", "job"}
        self.rejected: List[int] = []
        self.unsubmitted: List[int] = []
        self.recoveries: List[float] = []
        self.kills = 0
        self.drains = 0
        self.drain_rcs: List[int] = []
        self.fault_injections: List[str] = []
        self.cancels_sent = 0
        self.notes: List[str] = []
        self._fault_serial = 0
        self.t0 = time.time()

    def note(self, msg: str) -> None:
        line = f"[{time.time() - self.t0:7.1f}s] {msg}"
        self.notes.append(line)
        print(f"# {line}", file=sys.stderr, flush=True)

    # ---- daemon lifecycle ------------------------------------------------

    def launch(self, fault_plan: Optional[str] = None) -> None:
        from g2vec_tpu.serve import client

        env = dict(self.env)
        if fault_plan:
            self._fault_serial += 1
            env["G2VEC_FAULT_STATE"] = os.path.join(
                self.wd, f"fault-state-{self._fault_serial}.json")
        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--socket", self.sock, "--state-dir", self.state,
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--metrics-jsonl", self.metrics_path]
        if fault_plan:
            argv += ["--fault-plan", fault_plan]
        log = open(self.log_path, "a")
        self.proc = subprocess.Popen(argv, env=env, stdout=log,
                                     stderr=subprocess.STDOUT)
        log.close()
        if not client.wait_ready(self.sock, 120):
            raise RuntimeError(
                f"daemon never became ready (log: {self.log_path})")

    def relaunch_after_death(self, why: str) -> None:
        t_down = time.time()
        self.launch()
        self.recoveries.append(time.time() - t_down)
        self.note(f"relaunched after {why} "
                  f"(ready in {self.recoveries[-1]:.1f}s)")

    # ---- job construction ------------------------------------------------

    def make_job(self, k: int, paths: dict, native_ok: bool) -> dict:
        rng = random.Random((self.opts.seed << 16) ^ k)
        job = dict(
            expression_file=paths["expression"],
            clinical_file=paths["clinical"],
            network_file=paths["network"],
            result_name=os.path.join(self.wd, "out", f"job{k}"),
            lenPath=8, numRepetition=2, sizeHiddenlayer=16,
            epoch=self.opts.epochs + rng.choice((0, 2, 4)),
            learningRate=0.05, numBiomarker=5, compute_dtype="float32",
            seed=0, train_seed=k, kmeans_seed=k)
        if native_ok and rng.random() < self.opts.stream_frac:
            job.update(train_mode="streaming", walker_backend="native",
                       shard_paths=16, checkpoint_every=1)
        else:
            job["walker_backend"] = "device"
        return job

    def submit_one(self, k: int, job: dict) -> None:
        """Submit until acknowledged (or rejected); backoff with jitter
        across daemon deaths. Terminal accounting happens from durable
        records, not from this stream."""
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        priority = "interactive" if rng.random() < 0.3 else "batch"
        deadline_s = (round(rng.uniform(2.0, 8.0), 2)
                      if rng.random() < 0.15 else None)
        for attempt in range(12):
            try:
                evs = client.submit_job(
                    self.sock, job, tenant=f"t{k % 3}", timeout=600,
                    priority=priority, deadline_s=deadline_s)
                if evs and evs[-1].get("event") == "rejected":
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:     # acknowledged; journaled; never resubmit
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(5.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)

    # ---- chaos ops -------------------------------------------------------

    def op_sigkill(self) -> None:
        self.kills += 1
        self.note(f"chaos: SIGKILL daemon (kill #{self.kills})")
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except OSError:
            pass
        self.proc.wait()
        self.relaunch_after_death("SIGKILL")

    def op_drain(self, relaunch_plan: Optional[str] = None) -> None:
        self.drains += 1
        self.note(f"chaos: SIGTERM drain (drain #{self.drains}"
                  + (f", relaunch armed: {relaunch_plan}"
                     if relaunch_plan else "") + ")")
        try:
            os.kill(self.proc.pid, signal.SIGTERM)
        except OSError:
            pass
        try:
            rc = self.proc.wait(timeout=300)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            rc = -9
        self.drain_rcs.append(rc)
        t_down = time.time()
        self.launch(fault_plan=relaunch_plan)
        self.recoveries.append(time.time() - t_down)
        if relaunch_plan:
            self.fault_injections.append(relaunch_plan)

    def op_cancel(self) -> None:
        from g2vec_tpu.serve import client

        with self.lock:
            pending = [jid for jid in self.acks
                       if not os.path.exists(os.path.join(
                           self.state, "results", f"{jid}.json"))]
        if not pending:
            return
        jid = self.rng.choice(pending)
        self.cancels_sent += 1
        self.note(f"chaos: cancel {jid}")
        try:
            client.cancel(self.sock, jid)
        except (OSError, client.ServeConnectionLost):
            pass

    def run_chaos_op(self, op: str) -> None:
        if op == "sigkill":
            self.op_sigkill()
        elif op == "drain":
            self.op_drain()
        elif op == "fault_stream_ckpt":
            self.op_drain("stage=stream_ckpt,kind=sigkill")
        elif op == "fault_train":
            self.op_drain("stage=train,kind=sigkill")
        elif op == "fault_drain_seam":
            # Arm a crash INSIDE _begin_drain, then drain: the drain
            # thread dies at the seam but admission is already closed
            # and the stop flag still falls — the exit must stay clean.
            self.op_drain("stage=drain,kind=crash")
            self.op_drain()
        elif op == "cancel":
            self.op_cancel()

    # ---- accounting ------------------------------------------------------

    def results(self) -> Dict[str, dict]:
        out = {}
        rdir = os.path.join(self.state, "results")
        if not os.path.isdir(rdir):
            return out
        for fn in os.listdir(rdir):
            if fn.endswith(".json"):
                try:
                    with open(os.path.join(rdir, fn)) as f:
                        out[fn[:-5]] = json.load(f)
                except (OSError, ValueError):
                    pass
        return out

    def journal_ids(self) -> List[str]:
        jdir = os.path.join(self.state, "jobs")
        if not os.path.isdir(jdir):
            return []
        return [fn[:-5] for fn in os.listdir(jdir)
                if fn.endswith(".json")]

    def terminal_event_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        try:
            with open(self.metrics_path) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "job_state" \
                            and ev.get("state") in TERMINAL_STATES:
                        jid = ev.get("job_id")
                        counts[jid] = counts.get(jid, 0) + 1
        except OSError:
            pass
        return counts


class RouterSoak(Soak):
    """Soak state for router mode: one router subprocess fronting N
    replica daemons it launches itself. The harness only ever kills
    things — every heal (replica relaunch, journal migration, adoption
    after a router restart) must come from the router, or the
    accounting fails."""

    def __init__(self, opts, workdir: str):
        super().__init__(opts, workdir)
        self.fleet = os.path.join(workdir, "fleet")
        self.router_metrics = os.path.join(workdir, "router-metrics.jsonl")
        self.router_log = os.path.join(workdir, "router.log")
        self.addr: Optional[str] = None
        self.router_restarts = 0
        self.replica_kills = 0
        self.replica_drains = 0

    # ---- router lifecycle -------------------------------------------

    def launch_router(self) -> None:
        argv = [sys.executable, "-m", "g2vec_tpu", "serve",
                "--replicas", str(self.opts.replicas),
                "--listen", "127.0.0.1:0",
                "--state-dir", self.fleet,
                "--platform", "cpu",
                "--cache-dir", os.path.join(self.wd, "cache"),
                "--queue-depth", "64", "--max-join", "6",
                "--probe-interval", "0.4", "--probe-deadline", "3.0",
                "--metrics-jsonl", self.router_metrics]
        addr_file = os.path.join(self.fleet, "router_addr")
        try:
            os.unlink(addr_file)
        except OSError:
            pass
        log = open(self.router_log, "a")
        self.proc = subprocess.Popen(argv, env=self.env, stdout=log,
                                     stderr=subprocess.STDOUT)
        log.close()
        deadline = time.time() + 600
        while time.time() < deadline:
            if os.path.exists(addr_file):
                with open(addr_file) as f:
                    self.addr = f.read().strip()
                if self.addr:
                    return
            if self.proc.poll() is not None:
                raise RuntimeError(f"router died during boot "
                                   f"(rc={self.proc.returncode}; log: "
                                   f"{self.router_log})")
            time.sleep(0.2)
        raise RuntimeError(f"router never bound (log: {self.router_log})")

    def router_status(self) -> Optional[dict]:
        from g2vec_tpu.serve import client, protocol

        try:
            return client.status(self.addr, timeout=10.0)
        except (OSError, client.ServeConnectionLost,
                protocol.ProtocolError):
            return None

    # ---- fleet-wide accounting --------------------------------------

    def _replica_dirs(self) -> List[str]:
        return [os.path.join(self.fleet, f"r{i}")
                for i in range(self.opts.replicas)]

    def results(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for rdir in self._replica_dirs():
            resd = os.path.join(rdir, "state", "results")
            if not os.path.isdir(resd):
                continue
            for fn in os.listdir(resd):
                if fn.endswith(".json"):
                    try:
                        with open(os.path.join(resd, fn)) as f:
                            out[fn[:-5]] = json.load(f)
                    except (OSError, ValueError):
                        pass
        return out

    def result_locations(self) -> Dict[str, List[str]]:
        """job_id -> replica names holding a result record. More than
        one means a job ran (terminally) on two replicas — a duplicate
        the terminal-event count alone could miss."""
        locs: Dict[str, List[str]] = {}
        for i, rdir in enumerate(self._replica_dirs()):
            resd = os.path.join(rdir, "state", "results")
            if not os.path.isdir(resd):
                continue
            for fn in os.listdir(resd):
                if fn.endswith(".json"):
                    locs.setdefault(fn[:-5], []).append(f"r{i}")
        return locs

    def journal_ids(self) -> List[str]:
        out = []
        for rdir in self._replica_dirs():
            jdir = os.path.join(rdir, "state", "jobs")
            if os.path.isdir(jdir):
                out += [fn[:-5] for fn in os.listdir(jdir)
                        if fn.endswith(".json")]
        return out

    def terminal_event_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for rdir in self._replica_dirs():
            path = os.path.join(rdir, "metrics.jsonl")
            try:
                with open(path) as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        if ev.get("event") == "job_state" \
                                and ev.get("state") in TERMINAL_STATES:
                            jid = ev.get("job_id")
                            counts[jid] = counts.get(jid, 0) + 1
            except OSError:
                pass
        return counts

    def failover_events(self) -> List[dict]:
        out = []
        try:
            with open(self.router_metrics) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "failover":
                        out.append(ev)
        except OSError:
            pass
        return out

    # ---- chaos ops ---------------------------------------------------

    def _pick_replica(self) -> Optional[str]:
        st = self.router_status()
        if not st:
            return None
        reps = st.get("replicas") or {}
        live = [n for n, r in reps.items()
                if r.get("state") in ("healthy", "suspect")
                and r.get("pid")]
        if not live:
            return None
        name = self.rng.choice(sorted(live))
        self._victim_pid = reps[name].get("pid")
        return name

    def op_replica_sigkill(self) -> None:
        name = self._pick_replica()
        if name is None:
            self.note("chaos: replica SIGKILL skipped (none healthy)")
            return
        self.replica_kills += 1
        self.note(f"chaos: SIGKILL replica {name} "
                  f"(pid {self._victim_pid}, kill "
                  f"#{self.replica_kills})")
        try:
            os.kill(self._victim_pid, signal.SIGKILL)
        except OSError:
            pass
        # NO relaunch here: detection, fencing, journal migration, and
        # the relaunch are all the router's job.

    def op_replica_drain(self) -> None:
        from g2vec_tpu.serve import client

        name = self._pick_replica()
        if name is None:
            self.note("chaos: replica drain skipped (none healthy)")
            return
        self.replica_drains += 1
        self.note(f"chaos: drain replica {name} "
                  f"(drain #{self.replica_drains})")
        try:
            for ev in client.request(self.addr,
                                     {"op": "drain_replica",
                                      "replica": name}, timeout=600.0):
                if ev.get("event") == "drained":
                    self.drain_rcs.append(ev.get("rc", -1))
                break
        except (OSError, client.ServeConnectionLost):
            self.note(f"drain of {name} lost its stream (router died?)")

    def op_router_restart(self) -> None:
        self.router_restarts += 1
        self.note(f"chaos: SIGKILL router + restart "
                  f"(#{self.router_restarts}) — replicas orphaned, "
                  f"must be adopted")
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except OSError:
            pass
        self.proc.wait()
        t_down = time.time()
        self.launch_router()
        self.recoveries.append(time.time() - t_down)

    def op_cancel_routed(self) -> None:
        from g2vec_tpu.serve import client

        results = self.results()
        with self.lock:
            pending = [jid for jid in self.acks if jid not in results]
        if not pending:
            return
        jid = self.rng.choice(sorted(pending))
        self.cancels_sent += 1
        self.note(f"chaos: cancel {jid} (via router broadcast)")
        try:
            client.cancel(self.addr, jid, timeout=30.0)
        except (OSError, client.ServeConnectionLost):
            pass

    def run_chaos_op(self, op: str) -> None:
        if op == "replica_sigkill":
            self.op_replica_sigkill()
        elif op == "replica_drain":
            self.op_replica_drain()
        elif op == "router_restart":
            self.op_router_restart()
        elif op == "cancel":
            self.op_cancel_routed()

    # ---- submission --------------------------------------------------

    def submit_one(self, k: int, job: dict) -> None:
        """Submit through the router until acked. Unlike the classic
        soak, EVERY attempt carries the same deterministic idem key, so
        resubmitting after a lost ack is safe — the fleet acks the
        original job exactly once (deduped=True on the repeat)."""
        from g2vec_tpu.serve import client

        rng = random.Random((self.opts.seed << 20) ^ k)
        priority = "interactive" if rng.random() < 0.3 else "batch"
        deadline_s = (round(rng.uniform(2.0, 8.0), 2)
                      if rng.random() < 0.15 else None)
        idem = f"soak-{self.opts.seed}-{k}"
        for attempt in range(14):
            try:
                evs = client.submit_job(
                    self.addr, job, tenant=f"t{k % 3}", timeout=600,
                    priority=priority, deadline_s=deadline_s,
                    idem_key=idem)
                if evs and evs[-1].get("event") == "rejected":
                    # Transient fleet states — retry with the SAME idem
                    # key (safe by construction): the router had no
                    # eligible replica yet, or the ring target was
                    # caught mid-drain.
                    if evs[-1].get("error") in ("no_replicas",
                                                "draining"):
                        raise OSError(f"fleet busy: {evs[-1]['error']}")
                    with self.lock:
                        self.rejected.append(k)
                    return
                jid = evs[0].get("job_id") if evs else None
                if jid:
                    with self.lock:
                        self.acks[jid] = {"k": k, "job": job,
                                          "deadline_s": deadline_s}
                    return
                break
            except client.ServeConnectionLost as e:
                if e.job_id:
                    with self.lock:
                        self.acks[e.job_id] = {"k": k, "job": job,
                                               "deadline_s": deadline_s}
                    return
            except (client.ServeTimeout, OSError):
                pass
            time.sleep(min(5.0, 0.2 * (2 ** attempt))
                       + rng.uniform(0.0, 0.25))
        with self.lock:
            self.unsubmitted.append(k)


def run_router_soak(opts, workdir: str) -> dict:
    """The replicated-fleet storm: N replicas behind the router, seeded
    replica-SIGKILL / replica-drain / router-restart rotation, fleet-wide
    exactly-once accounting, byte parity vs solo twins, and the
    death-to-first-requeue latency distribution from the router's
    ``failover`` events."""
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client

    soak = RouterSoak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    n = opts.jobs
    n_ops = opts.chaos_ops or max(3, n // 8)
    rng = soak.rng
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / opts.mean_arrival)
    op_pool = ["replica_sigkill", "replica_drain", "router_restart",
               "cancel", "replica_sigkill"]
    ops = [op_pool[i % len(op_pool)] for i in range(n_ops)]
    rng.shuffle(ops)

    soak.note(f"router soak: {n} jobs over {opts.replicas} replicas "
              f"(stream_frac={opts.stream_frac if native_ok else 0}), "
              f"{n_ops} chaos ops {ops}, seed {opts.seed}")
    soak.launch_router()

    threads: List[threading.Thread] = []

    def arrival_loop():
        t0 = time.time()
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()

    deadline = soak.t0 + opts.budget_s
    next_chaos = time.time() + rng.uniform(1.0, opts.chaos_every)
    budget_blown = False
    while True:
        if time.time() > deadline:
            budget_blown = True
            soak.note("BUDGET BLOWN — abandoning the storm")
            break
        if soak.proc.poll() is not None:
            # The router must never die except when we kill it.
            soak.note(f"router self-death rc={soak.proc.returncode} — "
                      f"restarting (counts against it)")
            soak.launch_router()
        if ops and time.time() >= next_chaos:
            soak.run_chaos_op(ops.pop(0))
            next_chaos = time.time() + rng.uniform(
                0.5 * opts.chaos_every, 1.5 * opts.chaos_every)
        if not ops and not arr.is_alive() \
                and all(not th.is_alive() for th in threads):
            with soak.lock:
                acked = set(soak.acks)
            if acked and acked <= set(soak.results()) \
                    and not soak.journal_ids():
                break
        time.sleep(0.25)

    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    while not budget_blown and time.time() < deadline:
        if soak.proc.poll() is not None:
            soak.launch_router()
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    try:
        client.shutdown(soak.addr)
        soak.proc.wait(timeout=180)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()

    # ---- accounting --------------------------------------------------
    results = soak.results()
    locations = soak.result_locations()
    with soak.lock:
        acks = dict(soak.acks)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(set(
        [jid for jid, c in term_counts.items() if c > 1]
        + [jid for jid, where in locations.items() if len(where) > 1]))
    by_status: Dict[str, int] = {}
    for jid in acks:
        st = results.get(jid, {}).get("status", "LOST")
        by_status[st] = by_status.get(st, 0) + 1

    failovers = soak.failover_events()
    requeue_lat = [ev.get("latency_s", 0.0) for ev in failovers]

    # ---- byte parity vs solo twins -----------------------------------
    done_ids = [jid for jid in acks
                if results.get(jid, {}).get("status") == "done"]
    sample = sorted(done_ids)[:max(0, opts.verify)]
    byte_checked, byte_identical, mismatches = 0, 0, []
    if sample:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        for jid in sample:
            k = acks[jid]["k"]
            job = acks[jid]["job"]
            cfg = config_from_job(
                {**job, "result_name": os.path.join(workdir, "out",
                                                    f"solo{k}")})
            v = _variant_from_dict(0, {"name": "v"}, cfg)
            sres = solo_run(lane_config(cfg, v), console=lambda s: None)
            outs = results[jid]["variants"]["v"]["outputs"]
            byte_checked += 1
            same = True
            for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        same = False
                        mismatches.append(f"{jid}: {fa} != {fb}")
            byte_identical += int(same)
            soak.note(f"parity {jid} (job{k}): "
                      f"{'identical' if same else 'MISMATCH'}")

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          # rc None = the drained replica was ADOPTED (router restarted
          # mid-soak; not our child, so no exit code is collectible) —
          # the drain itself still completed synchronously.
          and all(rc in (0, None) for rc in soak.drain_rcs))
    return {
        "ok": ok, "mode": "router", "seed": opts.seed, "jobs": n,
        "replicas": opts.replicas,
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "terminal_by_status": by_status,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "replica_kills": soak.replica_kills,
        "replica_drains": soak.replica_drains,
        "router_restarts": soak.router_restarts,
        "drain_exit_codes": soak.drain_rcs,
        "cancels_sent": soak.cancels_sent,
        "failovers": len(failovers),
        "requeue_p50_s": _percentile(requeue_lat, 0.5),
        "requeue_p99_s": _percentile(requeue_lat, 0.99),
        "router_restart_p99_s": _percentile(soak.recoveries, 0.99),
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(time.time() - soak.t0, 1),
    }


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return round(s[min(len(s) - 1, int(round(q * (len(s) - 1))))], 3)


def run_soak(opts, workdir: str) -> dict:
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.serve import client

    soak = Soak(opts, workdir)
    native_ok = bool(shutil.which("g++")) and opts.stream_frac > 0
    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    paths = write_synthetic_tsv(spec, os.path.join(workdir, "data"))
    os.makedirs(os.path.join(workdir, "out"), exist_ok=True)

    n = opts.jobs
    n_ops = opts.chaos_ops or max(3, n // 8)
    rng = soak.rng
    arrivals, t = [], 0.0
    for _ in range(n):
        arrivals.append(t)
        t += rng.expovariate(1.0 / opts.mean_arrival)
    op_pool = ["sigkill", "drain", "cancel", "fault_train"]
    if native_ok:
        op_pool += ["fault_stream_ckpt", "fault_drain_seam"]
    ops = [op_pool[i % len(op_pool)] for i in range(n_ops)]
    rng.shuffle(ops)

    soak.note(f"soak: {n} jobs (stream_frac="
              f"{opts.stream_frac if native_ok else 0}), "
              f"{n_ops} chaos ops {ops}, seed {opts.seed}")
    soak.launch()

    threads: List[threading.Thread] = []

    def arrival_loop():
        t0 = time.time()
        jobs = [soak.make_job(k, paths, native_ok) for k in range(n)]
        for k in range(n):
            now = time.time() - t0
            if now < arrivals[k]:
                time.sleep(arrivals[k] - now)
            th = threading.Thread(target=soak.submit_one,
                                  args=(k, jobs[k]), daemon=True)
            th.start()
            threads.append(th)

    arr = threading.Thread(target=arrival_loop, daemon=True)
    arr.start()

    deadline = soak.t0 + opts.budget_s
    next_chaos = time.time() + rng.uniform(1.0, opts.chaos_every)
    budget_blown = False
    while True:
        if time.time() > deadline:
            budget_blown = True
            soak.note("BUDGET BLOWN — abandoning the storm")
            break
        if soak.proc.poll() is not None:
            # Died on its own: an armed fault plan fired.
            soak.relaunch_after_death(
                f"self-death rc={soak.proc.returncode}")
        if ops and time.time() >= next_chaos:
            soak.run_chaos_op(ops.pop(0))
            next_chaos = time.time() + rng.uniform(
                0.5 * opts.chaos_every, 1.5 * opts.chaos_every)
        if not ops and not arr.is_alive() \
                and all(not th.is_alive() for th in threads):
            with soak.lock:
                acked = set(soak.acks)
            if acked and acked <= set(soak.results()) \
                    and not soak.journal_ids():
                break
        time.sleep(0.25)

    # Quiesce: a clean daemon finishes whatever the storm left behind.
    arr.join(timeout=60)
    for th in threads:
        th.join(timeout=120)
    while not budget_blown and time.time() < deadline:
        if soak.proc.poll() is not None:
            soak.relaunch_after_death(
                f"self-death rc={soak.proc.returncode}")
        with soak.lock:
            acked = set(soak.acks)
        if acked <= set(soak.results()) and not soak.journal_ids():
            break
        time.sleep(0.5)
    try:
        client.shutdown(soak.sock)
        soak.proc.wait(timeout=120)
    except (OSError, client.ServeConnectionLost,
            subprocess.TimeoutExpired):
        soak.proc.kill()
        soak.proc.wait()

    # ---- accounting ------------------------------------------------------
    results = soak.results()
    with soak.lock:
        acks = dict(soak.acks)
    lost = sorted(jid for jid in acks if jid not in results)
    term_counts = soak.terminal_event_counts()
    duplicated = sorted(jid for jid, c in term_counts.items() if c > 1)
    by_status: Dict[str, int] = {}
    for jid in acks:
        st = results.get(jid, {}).get("status", "LOST")
        by_status[st] = by_status.get(st, 0) + 1

    # ---- byte parity on a sample of completed jobs -----------------------
    done_ids = [jid for jid in acks
                if results.get(jid, {}).get("status") == "done"]
    sample = sorted(done_ids)[:max(0, opts.verify)]
    byte_checked, byte_identical, mismatches = 0, 0, []
    if sample:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        for jid in sample:
            k = acks[jid]["k"]
            job = acks[jid]["job"]
            cfg = config_from_job(
                {**job, "result_name": os.path.join(workdir, "out",
                                                    f"solo{k}")})
            v = _variant_from_dict(0, {"name": "v"}, cfg)
            sres = solo_run(lane_config(cfg, v), console=lambda s: None)
            outs = results[jid]["variants"]["v"]["outputs"]
            byte_checked += 1
            same = True
            for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
                with open(fa, "rb") as a, open(fb, "rb") as b:
                    if a.read() != b.read():
                        same = False
                        mismatches.append(f"{jid}: {fa} != {fb}")
            byte_identical += int(same)
            soak.note(f"parity {jid} (job{k}): "
                      f"{'identical' if same else 'MISMATCH'}")

    ok = (not budget_blown and not lost and not duplicated
          and not soak.unsubmitted and not soak.journal_ids()
          and by_status.get("failed", 0) == 0
          and byte_identical == byte_checked
          and all(rc == 0 for rc in soak.drain_rcs))
    return {
        "ok": ok, "seed": opts.seed, "jobs": n,
        "accepted": len(acks), "rejected": len(soak.rejected),
        "unsubmitted": len(soak.unsubmitted),
        "terminal_by_status": by_status,
        "lost": lost, "duplicated": duplicated,
        "journal_leftover": soak.journal_ids(),
        "kills": soak.kills, "drains": soak.drains,
        "drain_exit_codes": soak.drain_rcs,
        "fault_injections": soak.fault_injections,
        "cancels_sent": soak.cancels_sent,
        "recover_p50_s": _percentile(soak.recoveries, 0.5),
        "recover_p99_s": _percentile(soak.recoveries, 0.99),
        "recoveries": len(soak.recoveries),
        "byte_checked": byte_checked, "byte_identical": byte_identical,
        "mismatches": mismatches,
        "budget_blown": budget_blown,
        "wall_s": round(time.time() - soak.t0, 1),
    }


def main(argv: Optional[List[str]] = None) -> int:
    opts = build_parser().parse_args(argv)
    workdir = opts.workdir or tempfile.mkdtemp(prefix="g2vec-chaos-")
    os.makedirs(workdir, exist_ok=True)
    try:
        summary = (run_router_soak(opts, workdir) if opts.replicas
                   else run_soak(opts, workdir))
    finally:
        if not opts.keep and not opts.workdir:
            shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(summary, indent=1), flush=True)
    if opts.json:
        with open(opts.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
