#!/usr/bin/env python3
"""Generate a seeded scale-free synthetic dataset at any gene count.

The streaming trainer's beyond-bundled-scale input generator
(g2vec_tpu/data/synth.py) as a CLI — the first brick of ROADMAP item 2's
million-node scale-out. Writes the three reference-format TSVs and
prints a JSON summary (paths, gene/edge counts) to stdout.

    python tools/make_synth_graph.py --genes 50000 --out /tmp/big
    python -m g2vec_tpu /tmp/big/big_EXPRESSION.txt /tmp/big/big_CLINICAL.txt \
        /tmp/big/big_NETWORK.txt RESULT --train-mode streaming ...

Deterministic: the same flags reproduce byte-identical files.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="make_synth_graph",
        description="Seeded scale-free synthetic dataset generator "
                    "(expression/clinical/network TSVs).")
    p.add_argument("--genes", "--nodes", dest="genes", type=int,
                   default=20000,
                   help="gene/node count (default 20000)")
    p.add_argument("--good", type=int, default=40,
                   help="good-prognosis samples (default 40)")
    p.add_argument("--poor", type=int, default=40,
                   help="poor-prognosis samples (default 40)")
    p.add_argument("--attach", type=int, default=3,
                   help="preferential-attachment edges per node (default 3)")
    p.add_argument("--active-prob", type=float, default=0.7,
                   help="per-(gene,group) activity probability (default .7)")
    p.add_argument("--noise", type=float, default=0.3,
                   help="in-group residual std (default 0.3; edge survives "
                        "|PCC|>0.5 while 1/(1+noise^2) stays above it)")
    p.add_argument("--shift", type=float, default=1.0,
                   help="mean shift for single-group-active genes")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", type=str, required=True, metavar="DIR",
                   help="output directory (created if missing)")
    p.add_argument("--prefix", type=str, default="big")
    p.add_argument("--stream", action="store_true",
                   help="bounded-memory writer: edges and expression "
                        "stream to disk in fixed chunks instead of "
                        "materializing [S, G] + the edge list (auto at "
                        ">= 200000 nodes; same formats, different rng "
                        "stream layout than the in-memory writer)")
    p.add_argument("--partitions", type=int, default=0, metavar="R",
                   help="write the network pre-partitioned into R "
                        "per-rank shard files + a sha256 manifest "
                        "(point --edge-partition runs at the manifest "
                        ".json; implies --stream; concatenating the "
                        "parts reproduces the unpartitioned file)")
    args = p.parse_args(argv)
    if args.genes < args.attach + 2:
        p.error(f"--genes must be >= attach+2 = {args.attach + 2}")
    if args.good < 2 or args.poor < 2:
        p.error("--good/--poor must be >= 2 (PCC needs 2+ samples/group)")
    if args.partitions < 0:
        p.error("--partitions must be >= 0")

    from g2vec_tpu.data.synth import (SynthGraphSpec, write_synth_graph,
                                      write_synth_graph_streamed)

    spec = SynthGraphSpec(
        n_genes=args.genes, n_good=args.good, n_poor=args.poor,
        attach=args.attach, active_prob=args.active_prob,
        noise=args.noise, shift=args.shift, seed=args.seed)
    streamed = args.stream or args.partitions > 0 or args.genes >= 200_000
    if args.partitions > 0:
        paths = write_synth_graph_streamed(spec, args.out,
                                           prefix=args.prefix,
                                           partitions=args.partitions)
    else:
        writer = (write_synth_graph_streamed if streamed
                  else write_synth_graph)
        paths = writer(spec, args.out, prefix=args.prefix)
    print(json.dumps({"spec": vars(args), "streamed": streamed, **paths},
                     indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
