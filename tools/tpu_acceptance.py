"""End-to-end pipeline acceptance ON THE REAL TPU -> TPU_ACCEPTANCE.json.

VERDICT r2 missing #1: the only full seven-stage artifact on record
(REAL_ACCEPTANCE.json) ran on CPU virtual devices. This runs the exact
tests/test_acceptance_real.py configuration — the real bundled network
(298,799 edges, 9,904 genes) + real clinical file (135 samples) + the
statistically matched expression matrix (g2vec_tpu/data/realistic.py),
reference CLI defaults (reps=10, lenPath=80, hidden=128) — on the real
chip, and records per-stage seconds, path counts, and ACC[val] next to the
reference transcript's numbers (/root/reference/README.md:26-41: ~63 s of
training alone plus self-declared minutes of walking on its CPU).

Run (ambient axon env, no platform override):  python tools/tpu_acceptance.py
Writes TPU_ACCEPTANCE.json at the repo root. With
``G2VEC_ACCEPT_PLATFORM=cpu`` (set in-process — see bench.py's
_apply_platform_override for why not env JAX_PLATFORMS) it instead
refreshes REAL_ACCEPTANCE.json, the CPU-virtual-mesh twin.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NET = os.environ.get("G2VEC_ACCEPT_NETWORK", "/root/reference/ex_NETWORK.txt")
CLIN = os.environ.get("G2VEC_ACCEPT_CLINICAL",
                      "/root/reference/ex_CLINICAL.txt")


def _git_head() -> str:
    """Current commit hash, or "" — provenance only (see :func:`_code_key`)."""
    import subprocess
    try:
        return subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO,
                              capture_output=True, text=True,
                              timeout=10).stdout.strip()
    except Exception:  # noqa: BLE001
        return ""


def _code_key() -> str:
    """Hash of the source trees the acceptance run depends on — the
    artifact's freshness key. Deliberately NOT the commit hash: committing
    TPU_ACCEPTANCE.json itself creates a new HEAD, so a HEAD-based key
    self-invalidates the moment the artifact lands and every later bench
    re-burns the ~180s acceptance stage on identical code. The g2vec_tpu/
    tree hash changes only when the measured pipeline code does (harness
    edits don't retroactively change what was measured)."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD:g2vec_tpu"], cwd=REPO,
            capture_output=True, text=True, timeout=10)
        key = out.stdout.strip()
        # Uncommitted g2vec_tpu/ edits mean HEAD's tree does not describe
        # the code actually measured; suffix a hash of the working-tree
        # diff so the key tracks exactly what ran (clean vs any dirt, and
        # one dirt state vs another, never collide).
        diff = subprocess.run(
            ["git", "diff", "HEAD", "--", "g2vec_tpu"], cwd=REPO,
            capture_output=True, text=True, timeout=10).stdout
        # status --porcelain additionally catches untracked new modules,
        # which `git diff HEAD` does not show (by name — untracked CONTENT
        # changes collide, acceptable for a freshness key).
        status = subprocess.run(
            ["git", "status", "--porcelain", "g2vec_tpu"], cwd=REPO,
            capture_output=True, text=True, timeout=10).stdout
        if diff or status:
            import hashlib
            key += "-dirty-" + hashlib.sha256(
                (status + diff).encode()).hexdigest()[:12]
        return key
    except Exception:  # noqa: BLE001
        return ""


def run_acceptance(out_path: str) -> dict:
    """Run the acceptance configuration on the CURRENT backend; write + return
    the artifact dict. Importable (bench.py runs this opportunistically on
    the driver's chip when TPU_ACCEPTANCE.json does not exist yet)."""
    t_start = time.time()
    import jax

    backend = jax.default_backend()
    device = str(jax.devices()[0])
    print(f"# backend={backend} device={device}", file=sys.stderr)

    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.data.realistic import write_real_expression_tsv
    from g2vec_tpu.pipeline import run

    with tempfile.TemporaryDirectory() as tmp:
        expr_path = os.path.join(tmp, "real_EXPRESSION.txt")
        t0 = time.time()
        write_real_expression_tsv(NET, CLIN, expr_path)
        gen_secs = time.time() - t0
        walker_backend = os.environ.get("G2VEC_ACCEPT_WALKER")  # pin, or None
        # Optional persistent XLA cache (G2VEC_ACCEPT_COMPILE_CACHE=dir):
        # the watcher sets it for the SECONDARY (device-pinned) twin so
        # repeat batteries across windows skip its compiles. ENFORCED to
        # pinned runs only: the primary (unpinned) artifact never warms —
        # its wall stays cold-start comparable across rounds even if the
        # env leaks into an unpinned invocation (e.g. bench's in-process
        # opportunistic refresh inherits os.environ). Recorded in the
        # artifact either way.
        compile_cache = (os.environ.get("G2VEC_ACCEPT_COMPILE_CACHE")
                         if walker_backend else None)
        cfg = G2VecConfig(expression_file=expr_path, clinical_file=CLIN,
                          network_file=NET,
                          result_name=os.path.join(tmp, "real"), seed=0,
                          compilation_cache=compile_cache,
                          **({"walker_backend": walker_backend}
                             if walker_backend else {}))
        t0 = time.time()
        res = run(cfg, console=lambda s: print(f"# {s}", file=sys.stderr))
        total = time.time() - t0

    artifact = {
        "platform": backend,
        "device": device,
        "config": "real ex_NETWORK + ex_CLINICAL + realistic expression, "
                  "CLI defaults (reps=10, lenPath=80, hidden=128), seed=0",
        "n_samples": res.n_samples,
        "n_genes": res.n_genes,
        "n_edges": res.n_edges,
        "n_paths": res.n_paths,
        "n_path_genes": res.n_path_genes,
        # Which stage-3 sampler the run ACTUALLY used ("auto" resolves per
        # ops/backend.py: native on single-host; the pipeline reports its
        # resolution). The two samplers share the output contract but draw
        # from different PRNG families, so path counts / ACC differ
        # slightly between backends at the same seed — artifacts are only
        # comparable within one backend.
        "walker_backend": res.walker_backend,
        # True = wall times may include warm-cache compiles (not
        # comparable to cold-start artifacts).
        "compilation_cache_used": bool(compile_cache),
        "acc_val": res.acc_val,     # full precision: the >= 0.88 gate and
                                    # vs_baseline must not see rounding
        # BASELINE.json's second target metric: first epoch with
        # ACC[val] >= 0.88 (the reference transcript crosses at epoch 25
        # with 0.8812, README.md:35-41). None = never reached.
        "epochs_to_acc_088": next(
            (h["epoch"] for h in res.train_history
             if h["acc_val"] >= 0.88), None),
        "n_epochs_run": len(res.train_history),
        # Every-5th-epoch val trajectory (the reference logs the same
        # cadence, G2Vec.py:269-272) — enough to eyeball convergence
        # without shipping the full history.
        "acc_val_trajectory": [
            {"epoch": h["epoch"], "acc_val": round(float(h["acc_val"]), 4)}
            for i, h in enumerate(res.train_history)
            if h["epoch"] % 5 == 0 or i == len(res.train_history) - 1],
        "git_head": _git_head(),
        "code_key": _code_key(),
        "stage_seconds": {k: round(v, 2)
                          for k, v in res.stage_seconds.items()},
        # Overlap attribution (parallel/overlap.py): stage_seconds alone
        # understate what ran — these say how many host threads sampled
        # and how much background (compile-warm / concurrent-walk) time
        # hid under foreground stages in THIS run.
        "sampler_threads": res.sampler_threads,
        "overlap_saved_s": res.overlap_saved_s,
        "walk_cache_hits": res.walk_cache_hits,
        "pipeline_wall_seconds": round(total, 2),
        "expression_gen_seconds": round(gen_secs, 2),
        "script_wall_seconds": round(time.time() - t_start, 2),
        "reference_transcript": {
            "n_paths": 45402, "n_path_genes": 3773, "acc_val": 0.8837,
            "train_wall_seconds": 63,
            "walk_wall": "unreported; self-declared 'most time consuming "
                         "step' (G2Vec.py:58)",
            "source": "/root/reference/README.md:26-41",
        },
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    return artifact


def main() -> None:
    plat = os.environ.get("G2VEC_ACCEPT_PLATFORM")
    if plat:
        os.environ["JAX_PLATFORMS"] = plat
        if plat == "cpu" and "host_platform_device_count" not in os.environ.get(
                "XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", plat)
    base = "REAL_ACCEPTANCE" if plat == "cpu" else "TPU_ACCEPTANCE"
    # A pinned-backend run (e.g. G2VEC_ACCEPT_WALKER=device on the chip, to
    # keep real-chip device-walker acceptance coverage alongside the
    # default auto->native artifact) writes a suffixed twin, never
    # clobbering the default-config artifact.
    pin = os.environ.get("G2VEC_ACCEPT_WALKER")
    out = os.path.join(REPO, f"{base}_{pin}.json" if pin else f"{base}.json")
    artifact = run_acceptance(out)
    print(json.dumps(artifact))
    ok = artifact["acc_val"] >= 0.88 and (artifact["platform"] == "tpu"
                                          or plat == "cpu")
    print(f"# {'OK' if ok else 'NOT-OK'}: backend={artifact['platform']} "
          f"acc_val={artifact['acc_val']:.4f} "
          f"stages={artifact['stage_seconds']}", file=sys.stderr)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
