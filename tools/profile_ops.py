"""Time each candidate per-step op of the walker in isolation.

Small jitted programs (fast compiles through the remote-compile tunnel, one
op per program) at bench scale: W=G=9904 walkers, D=1024 neighbor slots.
Each op is run in a 20-iteration lax.scan so per-op dispatch overhead does
not drown sub-millisecond kernels. All inputs are generated ON DEVICE —
host->device uploads through the tunnel are far slower than the ops being
measured.

Run: python tools/profile_ops.py [op ...]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Bench scale by default; env-shrinkable so the CPU smoke tests can walk
# the full battery (arg parsing, schema, alarm plumbing) at toy cost.
G = int(os.environ.get("G2VEC_PROFILE_G", "9904"))
W = int(os.environ.get("G2VEC_PROFILE_W", "9904"))
D = int(os.environ.get("G2VEC_PROFILE_D", "1024"))
ITERS = int(os.environ.get("G2VEC_PROFILE_ITERS", "20"))
COMPILE_TIMEOUT = int(os.environ.get("PROFILE_COMPILE_TIMEOUT", "150"))
# Separate bound for the timed run (same knob as profile_walker.py).
RUN_TIMEOUT = int(os.environ.get("PROFILE_RUN_TIMEOUT", "240"))
T0 = time.time()


def note(msg):
    print(f"[{time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def bench(name, fn, *args):
    import jax

    from tools.alarm_guard import alarm

    run = jax.jit(fn)
    try:
        with alarm(COMPILE_TIMEOUT, f"compile/run exceeded {COMPILE_TIMEOUT}s"):
            jax.block_until_ready(run(*args))
    except Exception as e:  # noqa: BLE001 — battery must move on
        note(f"{name}: compile/first-run failed: {str(e)[:120]}")
        return {"error": str(e)[:200]}
    # The timed call is bounded too: one pathological op must cost its own
    # number, not the rest of the battery stage. Any exception (tunnel
    # drop, device OOM) likewise degrades to this op's error record.
    try:
        with alarm(RUN_TIMEOUT, f"timed run exceeded {RUN_TIMEOUT}s"):
            t0 = time.time()
            jax.block_until_ready(run(*args))
            dt = (time.time() - t0) / ITERS * 1e3
    except Exception as e:  # noqa: BLE001
        note(f"{name}: timed run failed: {str(e)[:160]}")
        return {"error": f"timed run: {e}"[:300]}
    note(f"{name:24s} {dt:8.3f} ms/iter")
    return round(dt, 4)


def main():
    import jax
    import jax.numpy as jnp

    note(f"backend={jax.default_backend()}")

    @jax.jit
    def make_inputs(key):
        ks = jax.random.split(key, 8)
        nbr_idx = jax.random.randint(ks[0], (G, D), 0, G, dtype=jnp.int32)
        nbr_w = jax.random.uniform(ks[1], (G, D))
        visited = jax.random.uniform(ks[2], (W, G)) < 0.005
        visited_u32 = jax.random.randint(
            ks[3], (W, (G + 31) // 32), 0, 1 << 30, dtype=jnp.int32
        ).astype(jnp.uint32)
        cand0 = jax.random.randint(ks[4], (W, D), 0, G, dtype=jnp.int32)
        w0 = jax.random.uniform(ks[5], (W, D))
        u0 = jax.random.uniform(ks[6], (W,))
        gumb = jax.random.gumbel(ks[7], (W, D))
        return nbr_idx, nbr_w, visited, visited_u32, cand0, w0, u0, gumb

    key = jax.random.key(0)
    (nbr_idx, nbr_w, visited, visited_u32, cand0, w0, u0, gumb
     ) = jax.block_until_ready(make_inputs(key))
    walker_keys = jax.block_until_ready(
        jax.jit(jax.vmap(lambda i: jax.random.fold_in(key, i)))(jnp.arange(W)))
    note("inputs ready on device")

    def scan20(body):
        def fn(x):
            def step(c, _):
                return body(c), None
            out, _ = jax.lax.scan(step, x, None, length=ITERS)
            return out
        return fn

    ops = {}

    # Row gather from the [G, D] tables (both tables, as the walker does).
    ops["row_gather"] = (scan20(
        lambda c: (nbr_idx[c[:, 0] % G][:, :1] +
                   nbr_w[c[:, 0] % G][:, :1].astype(jnp.int32) + c) % G), cand0)

    # Visited-bit gather: [W, D] take_along_axis from [W, G] bool.
    ops["visited_gather_bool"] = (scan20(
        lambda c: (c + jnp.take_along_axis(visited, c % G, axis=1)) % G), cand0)

    # Path-list compare: seen[w,d] = any_l(path[w,l] == cand[w,d]), L=80.
    path_list = (cand0[:, :80] % G).astype(jnp.int32)

    def seen_compare(c):
        seen = jnp.any(c[:, :, None] % G == path_list[:, None, :], axis=2)
        return (c + seen) % G
    ops["seen_compare_L80"] = (scan20(seen_compare), cand0)

    # Prefix-bounded compare (r4 segmentation, ops/walker._SCAN_SEGMENTS):
    # the same op against a 20-slot prefix — the first-segment cost; with
    # seen_compare_L80 it brackets the 0.625x average-work model.
    prefix20 = path_list[:, :20]

    def seen_compare_prefix(c):
        seen = jnp.any(c[:, :, None] % G == prefix20[:, None, :], axis=2)
        return (c + seen) % G
    ops["seen_compare_L20"] = (scan20(seen_compare_prefix), cand0)

    # PRNG, shipping form: per-walker fold_in + gumbel (D,) under vmap.
    def prng_vmap(c):
        g = jax.vmap(lambda k: jax.random.gumbel(
            jax.random.fold_in(k, c[0, 0]), (D,)))(walker_keys)
        return (c + g[:, :1].astype(jnp.int32)) % G
    ops["prng_vmap_WxD"] = (scan20(prng_vmap), cand0)

    # PRNG, single-key [W, D] gumbel (what a per-step fold would cost).
    def prng_flat(c):
        g = jax.random.gumbel(jax.random.fold_in(key, c[0, 0]), (W, D))
        return (c + g[:, :1].astype(jnp.int32)) % G
    ops["prng_flat_WxD"] = (scan20(prng_flat), cand0)

    # PRNG, one uniform per walker (inverse-CDF needs only this per step).
    def prng_W(c):
        u = jax.random.uniform(jax.random.fold_in(key, c[0, 0]), (W,))
        return (c + u[:, None].astype(jnp.int32)) % G
    ops["prng_W_only"] = (scan20(prng_W), cand0)

    # Masked log + gumbel-argmax sample over D slots (no PRNG).
    def gumbel_argmax(c):
        w = jnp.where(c % 2 == 0, w0, 0.0)
        logits = jnp.where(w > 0, jnp.log(jnp.where(w > 0, w, 1.0)), -1e30)
        slot = jnp.argmax(logits + gumb, axis=1)
        return (c + slot[:, None]) % G
    ops["mask_log_argmax"] = (scan20(gumbel_argmax), cand0)

    # Inverse-CDF sample over D slots: cumsum + count + masked-reduce pick.
    def invcdf(c):
        w = jnp.where(c % 2 == 0, w0, 0.0)
        cum = jnp.cumsum(w, axis=1)
        total = cum[:, -1]
        slot = jnp.sum(cum <= (u0 * total)[:, None], axis=1).astype(jnp.int32)
        slot = jnp.minimum(slot, D - 1)
        sel = jnp.arange(D)[None, :] == slot[:, None]
        nxt = jnp.sum(jnp.where(sel, c % G, 0), axis=1)
        return (c + nxt[:, None]) % G
    ops["invcdf_sample"] = (scan20(invcdf), cand0)

    # Visited update, shipping form: one_hot [W, G] + OR.
    def onehot_or(v):
        nxt = v[:, 0].astype(jnp.int32) % G
        moved = jax.nn.one_hot(nxt, G, dtype=jnp.bool_)
        return v | moved
    ops["visited_onehot_or"] = (scan20(onehot_or), visited)

    # Visited update, scatter form.
    def scatter_set(v):
        nxt = v[:, 0].astype(jnp.int32) % G
        return v.at[jnp.arange(W), nxt].set(True)
    ops["visited_scatter"] = (scan20(scatter_set), visited)

    # Path-list update: dynamic_update_slice one column (static step index
    # inside the 20-iteration scan is the realistic pattern: index = carry).
    def pathlist_update(c):
        col = (c[:, :1] + 1) % G
        out = jax.lax.dynamic_update_slice(c, col, (0, c[0, 0] % jnp.int32(D)))
        return out
    ops["pathlist_update"] = (scan20(pathlist_update), cand0)

    only = sys.argv[1:] or list(ops)
    unknown = [n for n in only if n not in ops]
    if unknown:
        # Fail loudly on a typo'd op name — the silent skip exited 0
        # having measured nothing (VERDICT item 9).
        print(json.dumps({"error": f"unknown op(s) {unknown}; "
                                   f"valid: {sorted(ops)}"}), flush=True)
        sys.exit(2)
    results = {}
    contaminated = False
    for name, (fn, arg) in ops.items():
        if name not in only:
            continue
        res = bench(name, fn, arg)
        if contaminated and not isinstance(res, dict):
            # An abandoned (timed-out) predecessor may still be executing
            # on the device — flag numbers measured under contention.
            res = {"ms_per_iter_contended": res, "after_abandoned_run": True}
        results[name] = res
        # Any alarm-abandoned call (timed run, or the compile/first-run
        # bound firing mid-execution) may leave live device work behind.
        if isinstance(res, dict) and "exceeded" in str(res.get("error", "")):
            contaminated = True
        # Flush per op: a stage kill mid-battery keeps what was measured.
        print(json.dumps({"op": name, "ms_per_iter": res}), flush=True)
    print(json.dumps({"backend": jax.default_backend(), "W": W, "G": G,
                      "D": D, "ms_per_iter": results}))


if __name__ == "__main__":
    main()
