"""Before/after walker profile on the real backend.

Times, at bench scale (the real bundled network: 9,904 genes, ~216k
surviving edges, neighbor-table D = max out-degree rounded to pow2):

  r2_step   — an inline reproduction of the ROUND-2 walk step (per-walker
              fold_in + [W, D] gumbel each step, visited take_along_axis
              + one_hot OR; what BENCH_r02 measured at 578.9 walks/s);
  new_1rep  — the shipping walker (ops/walker.py random_walks_sparse +
              device packbits) at W = n_genes (one repetition);
  new_full  — the shipping walker at W = reps*n_genes = the single fused
              launch generate_path_set now dispatches;
  seg1_full — new_full with the r4 prefix-segmented no-revisit compare
              disabled (n_segments=1): the A/B isolating the
              segmentation gain, bit-identical outputs.

Results feed PROFILE.md's before/after table.

Run:  python tools/profile_walker.py [variant ...]   (real backend)
      G2VEC_PROFILE_NETWORK=... to point at another edge list.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEN_PATH = int(os.environ.get("G2VEC_PROFILE_LEN_PATH", "80"))
REPS = int(os.environ.get("G2VEC_PROFILE_REPS", "10"))
NEG_INF = -1e30
NETWORK = os.environ.get("G2VEC_PROFILE_NETWORK",
                         "/root/reference/ex_NETWORK.txt")
COMPILE_TIMEOUT = int(os.environ.get("PROFILE_COMPILE_TIMEOUT", "240"))
# The timed call is alarm-bounded too: a slow backend (XLA:CPU walks the
# full workload at ~180 walks/s ~= 9 min/variant) must cost ONE variant
# its number, not the whole battery stage.
RUN_TIMEOUT = int(os.environ.get("PROFILE_RUN_TIMEOUT", "240"))
T0 = time.time()


def note(msg):
    print(f"[{time.time() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def load_network():
    from g2vec_tpu.ops.graph import neighbor_table
    rng = np.random.default_rng(42)
    src_names, dst_names = [], []
    with open(NETWORK) as f:
        next(f)
        for line in f:
            parts = line.rstrip().split("\t")
            if len(parts) == 2:
                src_names.append(parts[0])
                dst_names.append(parts[1])
    genes = sorted(set(src_names) | set(dst_names))
    g2i = {g: i for i, g in enumerate(genes)}
    src = np.fromiter((g2i[g] for g in src_names), np.int32)
    dst = np.fromiter((g2i[g] for g in dst_names), np.int32)
    keep = rng.random(src.size) < (216540 / 298799)
    src, dst = src[keep], dst[keep]
    w = rng.uniform(0.5001, 1.0, size=src.size).astype(np.float32)
    return neighbor_table(src, dst, w, len(genes)), len(genes)


def timed(name, fn, n_walks):
    """Compile (alarm-bounded), then time; returns a result dict."""
    import jax

    from tools.alarm_guard import alarm

    try:
        with alarm(COMPILE_TIMEOUT, f"compile exceeded {COMPILE_TIMEOUT}s"):
            t0 = time.time()
            jax.block_until_ready(fn())
            compile_s = time.time() - t0
    except Exception as e:  # noqa: BLE001 — costs this variant only
        note(f"{name}: {str(e)[:160]}")
        return {"error": str(e)[:300]}
    try:
        with alarm(RUN_TIMEOUT, f"timed run exceeded {RUN_TIMEOUT}s"):
            t0 = time.time()
            jax.block_until_ready(fn())
            dt = time.time() - t0
    except Exception as e:  # noqa: BLE001 — tunnel drop/OOM costs one
        note(f"{name}: timed run failed: {str(e)[:160]}")   # variant only
        return {"error": f"timed run: {e}"[:300],
                "first_call_s": round(compile_s, 1)}
    res = {"launch_s": round(dt, 3),
           "per_step_ms": round(dt / (LEN_PATH - 1) * 1e3, 3),
           "walks_per_sec": round(n_walks / dt, 1),
           "first_call_s": round(compile_s, 1)}
    note(f"{name}: {res}")
    return res


def main():
    import jax
    import jax.numpy as jnp

    (nbr_idx_np, nbr_w_np), n_genes = load_network()
    D = nbr_idx_np.shape[1]
    note(f"backend={jax.default_backend()} G={n_genes} D={D} "
         f"steps={LEN_PATH - 1}")

    nbr_idx = jax.device_put(jnp.asarray(nbr_idx_np, jnp.int32))
    nbr_w = jax.device_put(jnp.asarray(nbr_w_np, jnp.float32))
    key = jax.random.key(0)

    # ---- r2_step: the round-2 walk, reproduced inline ----
    def r2_walk(starts):
        W = starts.shape[0]
        walker_keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(W))
        visited0 = jax.nn.one_hot(starts, n_genes, dtype=jnp.bool_)
        state0 = (visited0, starts.astype(jnp.int32),
                  jnp.ones((W,), dtype=jnp.bool_))

        def step(state, step_idx):
            visited, current, alive = state
            cand = nbr_idx[current]
            seen = jnp.take_along_axis(visited, cand, axis=1)
            w = jnp.where(seen, 0.0, nbr_w[current])
            can_move = alive & (w.sum(axis=1) > 0.0)
            logits = jnp.where(w > 0.0, jnp.log(jnp.where(w > 0.0, w, 1.0)),
                               NEG_INF)
            gumbel = jax.vmap(lambda k: jax.random.gumbel(
                jax.random.fold_in(k, step_idx), (D,)))(walker_keys)
            slot = jnp.argmax(logits + gumbel, axis=1)
            nxt = jnp.take_along_axis(cand, slot[:, None], axis=1)[:, 0]
            current = jnp.where(can_move, nxt, current)
            moved = (jax.nn.one_hot(nxt, n_genes, dtype=jnp.bool_)
                     & can_move[:, None])
            return (visited | moved, current, can_move), None

        (visited, _, _), _ = jax.lax.scan(
            step, state0, jnp.arange(LEN_PATH - 1))
        return visited

    r2_jit = jax.jit(r2_walk)

    from g2vec_tpu.ops.walker import _packed_walk_sparse

    starts_1 = jnp.arange(n_genes, dtype=jnp.int32)
    keys_1 = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(n_genes))
    starts_n = jnp.tile(starts_1, REPS)
    keys_n = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(n_genes * REPS))

    # seg1_full: the shipping step with the prefix-segmented no-revisit
    # compare DISABLED (n_segments=1 — one scan over the full [W, L]
    # buffer): the r4 A/B that isolates the segmentation gain vs new_full.
    # Bit-identical outputs by construction (tests pin this).
    import g2vec_tpu.ops.walker as W

    seg1_jit = jax.jit(
        lambda a, b, s, k: W._packed_from_path_list(
            W._sparse_path_list(a, b, s, k, LEN_PATH, n_segments=1),
            n_genes))

    variants = {
        "r2_step": (lambda: r2_jit(starts_1), n_genes),
        "new_1rep": (lambda: _packed_walk_sparse(
            nbr_idx, nbr_w, starts_1, keys_1, LEN_PATH), n_genes),
        "new_full": (lambda: _packed_walk_sparse(
            nbr_idx, nbr_w, starts_n, keys_n, LEN_PATH), n_genes * REPS),
        "seg1_full": (lambda: seg1_jit(nbr_idx, nbr_w, starts_n, keys_n),
                      n_genes * REPS),
    }
    only = sys.argv[1:] or list(variants)
    unknown = [n for n in only if n not in variants]
    if unknown:
        # A typo'd variant name must FAIL HERE, loudly — the old silent
        # skip ran nothing, exited 0, and would burn a chip window on a
        # battery that measured nothing (VERDICT item 9).
        print(json.dumps({"error": f"unknown variant(s) {unknown}; "
                                   f"valid: {sorted(variants)}"}),
              flush=True)
        sys.exit(2)
    results = {}
    contaminated = False
    for name, (fn, n_walks) in variants.items():
        if name in only:
            res = timed(name, fn, n_walks)
            if contaminated and "error" not in res:
                # A timed-out predecessor's dispatch cannot be cancelled
                # and may still be executing — this number ran under
                # contention; flag it rather than report it as clean.
                res["after_abandoned_run"] = True
            results[name] = res
            # Any ALARM (timed run, or the compile bound firing during
            # the first call's execution) may have abandoned live device
            # work; compile bounds firing during pure tracing flag a
            # harmless false positive.
            if "exceeded" in str(res.get("error", "")):
                contaminated = True
            # Flush each variant as its own line the moment it exists: a
            # stage kill mid-battery keeps everything already measured.
            print(json.dumps({"variant": name, **res}), flush=True)
    print(json.dumps({"backend": jax.default_backend(), "G": n_genes,
                      "D": int(D), "len_path": LEN_PATH, "variants": results}))


if __name__ == "__main__":
    main()
