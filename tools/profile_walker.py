"""Decompose the walker's per-step cost on the real backend.

Times isolated variants of the sparse walk step at bench scale (the real
bundled network: 9,904 genes, ~216k surviving edges, D=max out-degree) so the
optimization targets measured numbers, not guesses (VERDICT r2 weak #1:
"Nothing has been profiled").

Variants (each a full scan over len_path-1 steps, W = n_genes walkers):
  full            — the shipping _walk step (fold_in+gumbel per walker/step)
  no_prng         — same step but a constant gumbel tensor (isolates PRNG)
  no_visited      — PRNG + gather + sample, but no visited mask bookkeeping
  gather_only     — just the [W, D] neighbor-table row gathers
  invcdf          — candidate redesign: precomputed per-walker uniforms
                    (one per step, drawn outside the scan) + masked cumsum
                    inverse-CDF sampling + index-scatter visited

Run:  python tools/profile_walker.py            (real backend)
      JAX_PLATFORMS=cpu python tools/profile_walker.py   (host sanity)
"""
from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEN_PATH = 80
NEG_INF = -1e30
NETWORK = os.environ.get("G2VEC_PROFILE_NETWORK",
                         "/root/reference/ex_NETWORK.txt")


def load_network():
    from g2vec_tpu.ops.graph import neighbor_table
    rng = np.random.default_rng(42)
    src_names, dst_names = [], []
    with open(NETWORK) as f:
        next(f)
        for line in f:
            parts = line.rstrip().split("\t")
            if len(parts) == 2:
                src_names.append(parts[0])
                dst_names.append(parts[1])
    genes = sorted(set(src_names) | set(dst_names))
    g2i = {g: i for i, g in enumerate(genes)}
    src = np.fromiter((g2i[g] for g in src_names), np.int32)
    dst = np.fromiter((g2i[g] for g in dst_names), np.int32)
    keep = rng.random(src.size) < (216540 / 298799)
    src, dst = src[keep], dst[keep]
    w = rng.uniform(0.5001, 1.0, size=src.size).astype(np.float32)
    return neighbor_table(src, dst, w, len(genes)), len(genes)


def main():
    import jax
    import jax.numpy as jnp

    (nbr_idx, nbr_w), n_genes = load_network()
    D = nbr_idx.shape[1]
    W = n_genes
    print(f"# backend={jax.default_backend()} G={n_genes} D={D} W={W} "
          f"steps={LEN_PATH - 1}", file=sys.stderr)

    nbr_idx = jax.device_put(jnp.asarray(nbr_idx, jnp.int32))
    nbr_w = jax.device_put(jnp.asarray(nbr_w, jnp.float32))
    starts = jnp.arange(W, dtype=jnp.int32)
    key = jax.random.key(0)
    walker_keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(W))

    def scan_over(step_fn, init_extra=None):
        visited0 = jax.nn.one_hot(starts, n_genes, dtype=jnp.bool_)
        state0 = (visited0, starts, jnp.ones((W,), dtype=jnp.bool_))
        if init_extra is not None:
            state0 = state0 + init_extra

        def run():
            state, _ = jax.lax.scan(step_fn, state0, jnp.arange(LEN_PATH - 1))
            return state[0]
        return run

    # --- full: the shipping step ------------------------------------------
    def step_full(state, step_idx):
        visited, current, alive = state
        cand = nbr_idx[current]
        seen = jnp.take_along_axis(visited, cand, axis=1)
        w = jnp.where(seen, 0.0, nbr_w[current])
        can_move = alive & (w.sum(axis=1) > 0.0)
        logits = jnp.where(w > 0.0, jnp.log(jnp.where(w > 0.0, w, 1.0)), NEG_INF)
        gumbel = jax.vmap(lambda k: jax.random.gumbel(
            jax.random.fold_in(k, step_idx), (D,)))(walker_keys)
        slot = jnp.argmax(logits + gumbel, axis=1)
        nxt = jnp.take_along_axis(cand, slot[:, None], axis=1)[:, 0]
        current = jnp.where(can_move, nxt, current)
        moved = jax.nn.one_hot(nxt, n_genes, dtype=jnp.bool_) & can_move[:, None]
        visited = visited | moved
        return (visited, current, can_move), None

    # --- no_prng: constant "gumbel" ---------------------------------------
    const_gumbel = jax.random.gumbel(key, (W, D))

    def step_no_prng(state, step_idx):
        visited, current, alive = state
        cand = nbr_idx[current]
        seen = jnp.take_along_axis(visited, cand, axis=1)
        w = jnp.where(seen, 0.0, nbr_w[current])
        can_move = alive & (w.sum(axis=1) > 0.0)
        logits = jnp.where(w > 0.0, jnp.log(jnp.where(w > 0.0, w, 1.0)), NEG_INF)
        slot = jnp.argmax(logits + const_gumbel, axis=1)
        nxt = jnp.take_along_axis(cand, slot[:, None], axis=1)[:, 0]
        current = jnp.where(can_move, nxt, current)
        moved = jax.nn.one_hot(nxt, n_genes, dtype=jnp.bool_) & can_move[:, None]
        visited = visited | moved
        return (visited, current, can_move), None

    # --- no_visited: PRNG + gather + sample, no mask upkeep ---------------
    def step_no_visited(state, step_idx):
        visited, current, alive = state
        cand = nbr_idx[current]
        w = nbr_w[current]
        can_move = alive & (w.sum(axis=1) > 0.0)
        logits = jnp.where(w > 0.0, jnp.log(jnp.where(w > 0.0, w, 1.0)), NEG_INF)
        gumbel = jax.vmap(lambda k: jax.random.gumbel(
            jax.random.fold_in(k, step_idx), (D,)))(walker_keys)
        slot = jnp.argmax(logits + gumbel, axis=1)
        nxt = jnp.take_along_axis(cand, slot[:, None], axis=1)[:, 0]
        current = jnp.where(can_move, nxt, current)
        return (visited, current, can_move), None

    # --- gather_only ------------------------------------------------------
    def step_gather(state, step_idx):
        visited, current, alive = state
        cand = nbr_idx[current]
        w = nbr_w[current]
        current = (current + cand[:, 0] + w[:, 0].astype(jnp.int32)) % n_genes
        return (visited, current, alive), None

    # --- invcdf: candidate redesign ---------------------------------------
    # One uniform per (walker, step), drawn OUTSIDE the scan from the
    # per-walker key (keeps walker_batch invariance); visited updated by
    # index scatter, not one_hot OR.
    uniforms = jax.vmap(
        lambda k: jax.random.uniform(k, (LEN_PATH - 1,)))(walker_keys)  # [W, S]
    uniforms = uniforms.T  # [S, W]

    def step_invcdf(state, per_step):
        step_idx = per_step if not isinstance(per_step, tuple) else per_step[0]
        visited, current, alive = state
        u = uniforms[step_idx]
        cand = nbr_idx[current]
        seen = jnp.take_along_axis(visited, cand, axis=1)
        w = jnp.where(seen, 0.0, nbr_w[current])
        cum = jnp.cumsum(w, axis=1)
        total = cum[:, -1]
        can_move = alive & (total > 0.0)
        target = u * total
        slot = jnp.sum(cum <= target[:, None], axis=1).astype(jnp.int32)
        slot = jnp.minimum(slot, D - 1)
        nxt = jnp.take_along_axis(cand, slot[:, None], axis=1)[:, 0]
        current = jnp.where(can_move, nxt, current)
        visited = visited.at[jnp.arange(W), nxt].max(can_move)
        return (visited, current, can_move), None

    variants = {
        "full": step_full,
        "no_prng": step_no_prng,
        "no_visited": step_no_visited,
        "gather_only": step_gather,
        "invcdf": step_invcdf,
    }
    only = sys.argv[1:] or list(variants)
    results = {}
    for name, fn in variants.items():
        if name not in only:
            continue
        run = jax.jit(scan_over(fn))
        for attempt in range(3):             # compile (tunnel can flake)
            try:
                run().block_until_ready()
                break
            except Exception as e:  # noqa: BLE001
                print(f"# {name}: compile attempt {attempt} failed: "
                      f"{str(e)[:120]}", file=sys.stderr)
                time.sleep(5)
        else:
            results[name] = {"error": "compile failed"}
            continue
        t0 = time.time()
        run().block_until_ready()
        first = time.time() - t0
        reps = 1 if first > 3.0 else 3
        t0 = time.time()
        for _ in range(reps):
            out = run()
        out.block_until_ready()
        dt = (time.time() - t0) / reps
        per_step_ms = dt / (LEN_PATH - 1) * 1e3
        walks_per_sec = W / dt
        results[name] = {"launch_s": round(dt, 4),
                         "per_step_ms": round(per_step_ms, 3),
                         "walks_per_sec": round(walks_per_sec, 1)}
        print(f"{name:12s} launch={dt:.4f}s  step={per_step_ms:.3f}ms  "
              f"{walks_per_sec:.0f} walks/s", file=sys.stderr)
    print(json.dumps({"backend": jax.default_backend(), "G": n_genes,
                      "D": int(D), "W": W, "variants": results}))


if __name__ == "__main__":
    main()
