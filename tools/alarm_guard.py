"""One SIGALRM-bounded region helper for the profiler tools.

Four near-identical save-handler/alarm/try/finally/restore blocks lived
across profile_ops.py and profile_walker.py; this is the single copy.
Note the bound is best-effort: Python delivers the signal only between
bytecodes, so a single long native call (an XLA compile) defers it until
that call returns.
"""
from __future__ import annotations

import signal
from contextlib import contextmanager


@contextmanager
def alarm(seconds: int, message: str):
    """Raise TimeoutError(message) if the body runs past ``seconds``."""
    def _handler(signum, frame):
        raise TimeoutError(message)

    old = signal.signal(signal.SIGALRM, _handler)
    try:
        signal.alarm(seconds)
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
