"""One SIGALRM-bounded region helper for the profiler tools.

Four near-identical save-handler/alarm/try/finally/restore blocks lived
across profile_ops.py and profile_walker.py; this is the single copy.
Note the bound is best-effort: Python delivers the signal only between
bytecodes, so a single long native call (an XLA compile) defers it until
that call returns.
"""
from __future__ import annotations

import signal
from contextlib import contextmanager


@contextmanager
def alarm(seconds: int, message: str):
    """Raise TimeoutError(message) if the body runs past ``seconds``.

    Nesting-safe: SIGALRM has one process-wide timer, so an inner region
    records the outer deadline's remaining seconds and re-arms it (less
    the time the inner body consumed, floor 1 s) on exit — an outer
    bound survives an inner region that completes quickly.
    """
    import time as _time

    def _handler(signum, frame):
        raise TimeoutError(message)

    old = signal.signal(signal.SIGALRM, _handler)
    prev_remaining = signal.alarm(seconds)
    t0 = _time.monotonic()
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        if prev_remaining:
            left = prev_remaining - (_time.monotonic() - t0)
            signal.alarm(max(1, int(left)))
