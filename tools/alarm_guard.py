"""Deadline guard for the profiler tools — now a shim over tools/watchdog.

The original implementation armed SIGALRM, whose handler runs only
between bytecodes on the main thread: one blocked native call (an XLA
compile on a dead tunnel) deferred it forever, which is how the r5 chip
window died inside the kmeans compile (PROFILE.md). The replacement is
the thread watchdog (tools/watchdog.py): async-exception injection at
the deadline, re-injection while the body stays wedged, optional hard
process exit for bounded subprocesses. This module keeps the old entry
point's name and contract (raise TimeoutError(message) on overrun,
nesting-safe, nothing leaks after completion) so the profiler batteries
did not need to change call sites.
"""
from __future__ import annotations

from tools.watchdog import WatchdogTimeout, watchdog  # noqa: F401


def alarm(seconds: int, message: str):
    """Raise TimeoutError(message) if the body runs past ``seconds``.

    Thin wrapper over :func:`tools.watchdog.watchdog`; each region owns
    its own watcher thread, so nested regions need no timer arithmetic —
    the inner deadline fires inside the outer one and both restore
    nothing process-wide.
    """
    return watchdog(seconds, message)
