"""Thread-based deadline guard for the profiler/bench stages.

Replaces the SIGALRM guard (tools/alarm_guard.py, now a shim over this):
SIGALRM's Python handler only runs between bytecodes ON THE MAIN THREAD,
so one long-blocked native call — an XLA compile dialing a dead TPU
tunnel — defers it indefinitely. That exact failure burned the r5 chip
window: the kmeans compile wedged inside the acceptance stage, the alarm
never fired, and the measure child hung until the parent's hard kill
threw away every later stage (PROFILE.md).

This guard arms one daemon WATCHER THREAD per region instead:

1. At the deadline it injects :class:`WatchdogTimeout` into the guarded
   thread via ``PyThreadState_SetAsyncExc`` — same delivery power as the
   signal path (next bytecode boundary) but it works on any thread, needs
   no process-wide timer (regions nest without re-arming arithmetic), and
   cannot be swallowed by a foreign SIGALRM handler.
2. If the region is STILL inside the body ``grace`` seconds later, the
   guarded thread is blocked in a native call the injection cannot reach.
   With ``hard=True`` the watcher prints a diagnostic (with the stuck
   region's name) and ``os._exit(124)``s the process — for a bounded
   subprocess (bench's measure child, the profiler batteries) an early
   honest death returns the window to the parent's retry loop, where the
   old guard's silent hang forfeited it. With ``hard=False`` (default)
   the watcher keeps re-injecting each ``grace`` so a body that pops back
   into Python even briefly still dies with the timeout.

The injection/exit race at body completion is closed with a per-region
lock: the watcher checks-and-injects under it, ``__exit__`` flips the
done flag under it — after a clean exit no stale timeout can surface in
the caller's frame.
"""
from __future__ import annotations

import ctypes
import os
import sys
import threading
from contextlib import contextmanager


class WatchdogTimeout(TimeoutError):
    """Raised in the guarded thread when a region overruns its deadline."""


def _make_timeout_cls(message: str):
    # PyThreadState_SetAsyncExc takes an exception CLASS and instantiates
    # it with no arguments at the raise site — bake the message in.
    class _Timeout(WatchdogTimeout):
        def __init__(self, *args):  # noqa: D401 — fixed message
            super().__init__(message)

    _Timeout.__name__ = "WatchdogTimeout"
    return _Timeout


def _inject(thread_id: int, exc_cls) -> None:
    ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_id), ctypes.py_object(exc_cls))


@contextmanager
def watchdog(seconds: float, message: str, *, grace: float = 10.0,
             hard: bool = False):
    """Raise ``WatchdogTimeout(message)`` in the calling thread if the body
    runs past ``seconds``; escalate per the module docstring when the body
    is wedged in a native call (``hard=True`` -> ``os._exit(124)`` after
    ``grace`` more seconds).
    """
    if seconds <= 0:
        raise ValueError(f"watchdog needs seconds > 0, got {seconds}")
    target = threading.get_ident()
    exc_cls = _make_timeout_cls(message)
    done = threading.Event()
    lock = threading.Lock()

    def watch():
        if done.wait(seconds):
            return
        with lock:
            if done.is_set():
                return
            _inject(target, exc_cls)
        # The injection lands at the next bytecode; a thread blocked in a
        # native call never reaches one. Escalate after each grace.
        while not done.wait(grace):
            if hard:
                print(f"[watchdog] region {message!r} still wedged "
                      f"{grace:.0f}s past its {seconds:.0f}s deadline "
                      f"(blocked native call?) — exiting 124",
                      file=sys.stderr, flush=True)
                sys.stderr.flush()
                sys.stdout.flush()
                os._exit(124)
            with lock:
                if done.is_set():
                    return
                _inject(target, exc_cls)

    watcher = threading.Thread(target=watch, daemon=True,
                               name=f"watchdog({message[:40]})")
    watcher.start()
    try:
        yield
    finally:
        with lock:
            done.set()
        watcher.join(timeout=5.0)
