"""CPU smoke tests for the window-critical tools (VERDICT item 9).

The r5 chip window lost a whole battery stage to a tool failure that a
10-second CPU run would have caught. These tests drive
``tools/profile_walker.py``, ``tools/profile_ops.py`` and
``tools/calibrate_real.py`` as REAL subprocesses at env-shrunk tiny
shapes: argv handling, the JSON line schema, and the failure modes a chip
window cannot afford to discover (typo'd variant names used to be a
silent exit-0 no-op; a missing reference mount used to be a mid-sweep
traceback) are all pinned here.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, env_extra=None, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def _json_lines(stdout):
    return [json.loads(line) for line in stdout.splitlines() if line.strip()]


@pytest.fixture(scope="module")
def tiny_network(tmp_path_factory):
    """A small connected edge list + matching clinical file on disk."""
    d = tmp_path_factory.mktemp("toolnet")
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=10, n_poor=10, module_size=8,
                         n_background=16, n_expr_only=2, n_net_only=2,
                         module_chords=2, background_edges=30, seed=1)
    return write_synthetic_tsv(spec, str(d))


def test_profile_walker_schema(tiny_network):
    res = _run("profile_walker.py", "new_1rep",
               env_extra={"G2VEC_PROFILE_NETWORK": tiny_network["network"],
                          "G2VEC_PROFILE_LEN_PATH": "6",
                          "G2VEC_PROFILE_REPS": "2"})
    assert res.returncode == 0, res.stderr[-2000:]
    lines = _json_lines(res.stdout)
    variants = [ln for ln in lines if "variant" in ln]
    assert [ln["variant"] for ln in variants] == ["new_1rep"]
    assert "walks_per_sec" in variants[0] or "error" in variants[0]
    summary = lines[-1]
    assert {"backend", "G", "D", "len_path", "variants"} <= set(summary)
    assert summary["backend"] == "cpu" and summary["len_path"] == 6


def test_profile_walker_unknown_variant_fails_loudly(tiny_network):
    res = _run("profile_walker.py", "new_1repp",  # typo
               env_extra={"G2VEC_PROFILE_NETWORK": tiny_network["network"],
                          "G2VEC_PROFILE_LEN_PATH": "6",
                          "G2VEC_PROFILE_REPS": "1"})
    assert res.returncode == 2
    err = _json_lines(res.stdout)[-1]
    assert "new_1repp" in err["error"] and "new_1rep" in err["error"]


def test_profile_ops_schema():
    res = _run("profile_ops.py", "visited_scatter",
               env_extra={"G2VEC_PROFILE_G": "64", "G2VEC_PROFILE_W": "16",
                          "G2VEC_PROFILE_D": "8", "G2VEC_PROFILE_ITERS": "2"})
    assert res.returncode == 0, res.stderr[-2000:]
    lines = _json_lines(res.stdout)
    ops = [ln for ln in lines if "op" in ln]
    assert [ln["op"] for ln in ops] == ["visited_scatter"]
    summary = lines[-1]
    assert {"backend", "W", "G", "D", "ms_per_iter"} <= set(summary)
    assert summary["G"] == 64 and summary["W"] == 16


def test_profile_ops_unknown_op_fails_loudly():
    res = _run("profile_ops.py", "no_such_op",
               env_extra={"G2VEC_PROFILE_G": "64", "G2VEC_PROFILE_W": "16",
                          "G2VEC_PROFILE_D": "8"})
    assert res.returncode == 2
    assert "no_such_op" in _json_lines(res.stdout)[-1]["error"]


@pytest.mark.skipif(__import__("shutil").which("g++") is None,
                    reason="calibrate_real drives the native sampler")
def test_calibrate_real_tiny_sweep(tiny_network):
    # A tiny two-point sweep end to end: spec-arg parsing, the native
    # walk, and the per-spec JSON schema the calibration notes cite.
    env = {"G2VEC_CALIBRATE_NETWORK": tiny_network["network"],
           "G2VEC_CALIBRATE_CLINICAL": tiny_network["clinical"]}
    spec = ("tiny=n_common=24, target_edges=60, n_active_per_group=8, "
            "n_shared=4, seed=1")
    res = _run("calibrate_real.py", "--no-baseline", spec, env_extra=env,
               timeout=300)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    lines = _json_lines(res.stdout)
    assert [ln["spec"] for ln in lines] == ["tiny"]
    for ln in lines:
        assert {"n_paths", "n_path_genes", "transcript"} <= set(ln), ln


def test_calibrate_real_bad_spec_arg(tiny_network):
    env = {"G2VEC_CALIBRATE_NETWORK": tiny_network["network"],
           "G2VEC_CALIBRATE_CLINICAL": tiny_network["clinical"]}
    res = _run("calibrate_real.py", "garbage-without-equals", env_extra=env)
    assert res.returncode == 2
    assert "bad spec arg" in _json_lines(res.stdout)[-1]["error"]


def test_calibrate_real_missing_inputs_fail_fast(tmp_path):
    env = {"G2VEC_CALIBRATE_NETWORK": str(tmp_path / "nope.txt"),
           "G2VEC_CALIBRATE_CLINICAL": str(tmp_path / "also_nope.txt")}
    res = _run("calibrate_real.py", env_extra=env)
    assert res.returncode == 2
    err = _json_lines(res.stdout)[-1]["error"]
    assert "G2VEC_CALIBRATE_NETWORK" in err


# ---------------------------------------------------------------------------
# g2vec analyze: the exit-code contract (0 clean / 1 findings / 2 usage)
# ---------------------------------------------------------------------------

def _run_analyze(*args, timeout=120):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "g2vec_tpu", "analyze", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


@pytest.mark.analyze
def test_analyze_clean_repo_exits_zero():
    res = _run_analyze("--json")
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    report = json.loads(res.stdout)
    assert report["clean"] is True
    assert report["counts"]["active"] == 0
    assert report["counts"]["stale_baseline"] == 0
    assert len(report["checkers"]) == 6
    assert report["elapsed_s"] < 30.0


@pytest.mark.analyze
def test_analyze_findings_exit_one(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import threading\n\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._xs = []       # guarded-by: _lock\n\n"
        "    def poke(self):\n"
        "        self._xs.append(1)\n")
    res = _run_analyze("--json", "--root", str(tmp_path))
    assert res.returncode == 1, res.stdout[-2000:] + res.stderr[-2000:]
    report = json.loads(res.stdout)
    assert report["clean"] is False
    assert report["counts"]["active"] == 1
    f = report["findings"][0]
    assert f["checker"] == "lock-discipline" and f["path"] == "bad.py"


@pytest.mark.analyze
def test_analyze_usage_errors_exit_two():
    res = _run_analyze("--checker", "no-such-checker")
    assert res.returncode == 2
    assert "no-such-checker" in res.stderr
    res2 = _run_analyze("--not-a-flag")
    assert res2.returncode == 2
