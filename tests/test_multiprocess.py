"""TRUE multi-process distributed test: 2 JAX processes over localhost.

Round-1 gap (VERDICT.md "what's weak" #4): every multi-host code path had
only ever run single-process with mocks. Here two real CPU processes
(2 virtual devices each) form a 4-device cluster and exercise the
cpu_fleet() contract end to end: replicated local-mesh training,
coordinator-broadcast single-layout resume over the KV transport,
coordinator-written shared-dir sharded (orbax) resume, and
cross-process-sharded native walks.

Triage record (this test was a seed failure): the original worker built a
cross-process (2, 2) GLOBAL mesh and trained over it, which the pinned
jaxlib cannot do off-TPU — ``jax.device_put`` onto a non-addressable
sharding (and every other cross-process XLA computation) dies with
``Multiprocess computations aren't implemented on the CPU backend``. That
is a backend limitation, not a framework bug; the global-mesh SPMD path
still exists for real pods (parallel/distributed.make_global_mesh) and the
worker now covers everything a CPU fleet genuinely runs — see
tests/two_process_worker.py's docstring for the full scope note.
"""
import json
import os
import shutil
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "two_process_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(port: int, process_id: int) -> dict:
    """Two local virtual CPU devices per process; no TPU plugin leakage."""
    drop = ("PALLAS_AXON", "AXON_", "TPU_", "JAX_", "XLA_", "LIBTPU", "PJRT_")
    env = {k: v for k, v in os.environ.items() if not k.startswith(drop)}
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p.lower()]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + parts)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["G2VEC_COORDINATOR"] = f"127.0.0.1:{port}"
    env["G2VEC_PROCESS_ID"] = str(process_id)
    env["G2VEC_NUM_PROCESSES"] = "2"
    return env


def test_two_process_cluster(tmp_path):
    # ~20 s: stays in the default suite — it is the only true 2-process
    # coverage of jax.distributed init + sharded walks + checkpointing.
    port = _free_port()
    shared = tmp_path / "shared_ck"     # the sharded-layout phase needs it
    shared.mkdir()
    procs = []
    for i in range(2):
        scratch = tmp_path / f"p{i}"
        scratch.mkdir()
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER, str(scratch), str(shared)],
            env=_worker_env(port, i), cwd=_REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    try:
        for i, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                pytest.fail(f"process {i} timed out")
            assert p.returncode == 0, f"process {i} failed:\n{err[-3000:]}"
            results.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # One worker failing must not leave its sibling blocked forever in
        # a distributed collective holding the port.
        for q in procs:
            if q.poll() is None:
                q.kill()

    assert all(r["n_global_devices"] == 4 for r in results), results
    assert {r["process"] for r in results} == {0, 1}
    # The ADVICE.md hazard: divergent post-restore state across processes.
    assert results[0]["resumed_digest"] == results[1]["resumed_digest"]
    assert (results[0]["sharded_fetch_digest"]
            == results[1]["sharded_fetch_digest"])
    assert (results[0]["sharded_layout_digest"]
            == results[1]["sharded_layout_digest"])
    # Sharded-table walk across the process boundary: both processes must
    # see the same path set, equal to their single-process local run (the
    # worker asserts the local equality; this pins cross-process equality).
    assert results[0]["walker_digest"] == results[1]["walker_digest"]
    # Sharded NATIVE walks (each process samples a walker-axis shard with
    # the C++ sampler, rows allgathered): same set on both processes, and
    # the worker asserts equality with the single-host native result.
    assert (results[0]["native_walker_digest"]
            == results[1]["native_walker_digest"])
    if shutil.which("g++"):
        # Not vacuous: with a toolchain present the section must have run.
        assert results[0]["native_walker_digest"] != "native-unavailable"
    assert results[0]["acc_val"] == pytest.approx(results[1]["acc_val"])
