"""Scenario engine (stats/): reducers against hand-built inputs, plan
determinism, replicate-vs-solo byte parity, permutation walk accounting,
CV fold invariants, and the serve-path chaos drill.

The scenario contract extends the PR 5 parity contract one level up:
``--scenario`` is a generated manifest, so every sampled replicate must
be byte-identical to its solo twin, and the reduced stability artifact
must be a deterministic function of (plan, inputs) alone — rerunning the
same plan into a different directory reproduces it byte for byte, on
the lane path and the serve path alike."""
import dataclasses
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from g2vec_tpu.config import G2VecConfig

pytestmark = pytest.mark.scenario

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Reduction layer: hand-built replicate outputs with known answers
# ---------------------------------------------------------------------------

def test_selection_stats_known_frequencies_and_ranks():
    from g2vec_tpu.stats.reduce import selection_stats

    genes = ["A", "B", "C", "D"]
    reps = [["A", "B"], ["A", "C"], ["B", "A"]]
    s = selection_stats(genes, reps)
    np.testing.assert_array_equal(s["n_sel"], [3, 2, 1, 0])
    np.testing.assert_allclose(s["sel_freq"], [1.0, 2 / 3, 1 / 3, 0.0])
    # A's ranks: 1, 1, 2 -> mean 4/3, var ddof=0 = 2/9.
    assert s["mean_rank"][0] == pytest.approx(4 / 3)
    assert s["rank_var"][0] == pytest.approx(2 / 9)
    # D never selected: na sentinels downstream.
    assert np.isnan(s["mean_rank"][3]) and np.isnan(s["rank_var"][3])


def test_selection_stats_duplicate_lines_count_once():
    """A gene can top BOTH L-group blocks of a biomarker file; the first
    line fixes its rank and the duplicate adds nothing."""
    from g2vec_tpu.stats.reduce import selection_stats

    s = selection_stats(["A", "B"], [["A", "B", "A"]])
    np.testing.assert_array_equal(s["n_sel"], [1, 1])
    assert s["mean_rank"][0] == 1.0 and s["mean_rank"][1] == 2.0
    with pytest.raises(ValueError, match="unknown gene"):
        selection_stats(["A"], [["A", "Z"]])


def test_perm_pvalues_add_one_allties_and_zero_variance():
    from g2vec_tpu.stats.reduce import perm_pvalues

    # Zero-variance gene: t = 0 observed AND in every null — all ties,
    # p must be exactly 1, never 0.
    p = perm_pvalues(np.array([0.0]), np.zeros((4, 1)))
    np.testing.assert_allclose(p, [1.0])
    # Add-one estimator: 1 of 2 nulls >= observed -> (1+1)/(1+2).
    p = perm_pvalues(np.array([2.0]), np.array([[1.0], [3.0]]))
    np.testing.assert_allclose(p, [2 / 3])
    # A never-beaten gene still gets the 1/(1+R) floor.
    p = perm_pvalues(np.array([9.0]), np.array([[1.0], [3.0]]))
    np.testing.assert_allclose(p, [1 / 3])


def test_bh_fdr_known_values_and_cap():
    from g2vec_tpu.stats.reduce import bh_fdr

    q = bh_fdr(np.array([0.005, 0.009, 0.05, 0.5]))
    # p*m/rank = [.02, .018, .0667, .5]; reversed running min fixes the
    # non-monotone head.
    np.testing.assert_allclose(q, [0.018, 0.018, 0.2 / 3, 0.5])
    np.testing.assert_allclose(bh_fdr(np.array([1.0, 1.0])), [1.0, 1.0])


def test_np_tscores_matches_device_op():
    from g2vec_tpu.ops.stats import tscores
    from g2vec_tpu.stats.reduce import np_tscores

    rng = np.random.default_rng(1)
    good = rng.normal(size=(9, 6)).astype(np.float32)
    poor = rng.normal(loc=0.5, size=(7, 6)).astype(np.float32)
    np.testing.assert_allclose(np_tscores(good, poor),
                               np.asarray(tscores(good, poor)),
                               rtol=1e-4, atol=1e-5)
    # Exact-zero pooled variance is well-defined in the float64 host
    # twin: the guarded branch emits 0 (and perm p-values become 1).
    good[:, 2] = 3.0
    poor[:, 2] = 3.0
    assert np_tscores(good, poor)[2] == 0.0


def test_percentile_ci_and_centroid_accuracy():
    from g2vec_tpu.stats.reduce import centroid_accuracy, percentile_ci

    lo, hi = percentile_ci([0.5, 0.6, 0.7, 0.8, 0.9])
    assert lo == pytest.approx(np.percentile(
        [0.5, 0.6, 0.7, 0.8, 0.9], 2.5))
    assert hi == pytest.approx(np.percentile(
        [0.5, 0.6, 0.7, 0.8, 0.9], 97.5))
    train_x = np.array([[0.0], [0.0], [2.0], [2.0]])
    train_y = np.array([0, 0, 1, 1])
    # Separable test points + one EXACT tie (x=1): ties predict class 0.
    acc = centroid_accuracy(train_x, train_y,
                            np.array([[0.1], [1.9], [1.0]]),
                            np.array([0, 1, 0]))
    assert acc == 1.0
    with pytest.raises(ValueError, match="lost a class"):
        centroid_accuracy(train_x, np.zeros(4, dtype=int),
                          train_x, train_y)


def test_reduce_cv_extras_carry_ci():
    from g2vec_tpu.stats.reduce import reduce_cv

    cols, rows, extras = reduce_cv(["A", "B"], [["A"], ["A", "B"]],
                                   [0.5, 0.9])
    assert cols == ["sel_freq", "n_sel", "mean_rank", "rank_var"]
    assert rows[0][0] == "1.000000" and rows[1][0] == "0.500000"
    assert extras["acc_mean"] == pytest.approx(0.7)
    assert extras["ci_lo"] <= 0.7 <= extras["ci_hi"]
    assert extras["fold_acc"] == ["0.500000", "0.900000"]


# ---------------------------------------------------------------------------
# Planning: seed tree, scenario id, origin-named validation errors
# ---------------------------------------------------------------------------

def _plan_cfg(**overrides):
    defaults = dict(expression_file="E.tsv", clinical_file="C.tsv",
                    network_file="N.tsv", result_name="out")
    defaults.update(overrides)
    return G2VecConfig(**defaults)


def test_expand_plan_deterministic_and_seed_tree_distinct():
    from g2vec_tpu.stats.plan import ScenarioPlan, derive_seed, expand_plan

    cfg = _plan_cfg()
    plan = ScenarioPlan("bootstrap", replicates=4, scenario_seed=9)
    a, b = expand_plan(plan, cfg), expand_plan(plan, cfg)
    assert a == b
    seeds = [obj["subsample_seed"] for obj, _ in a]
    assert len(set(seeds)) == 4
    # Roles are separate branches of the tree: a permutation replicate
    # never reuses a bootstrap replicate's seed.
    assert derive_seed(9, 0, "bootstrap") != derive_seed(9, 0, "permutation")
    assert derive_seed(9, 0, "bootstrap") != derive_seed(10, 0, "bootstrap")
    # Permutation: lane 0 is the observed run with NO permute_seed.
    pplan = ScenarioPlan("permutation", replicates=2, scenario_seed=9)
    objs = expand_plan(pplan, cfg)
    assert objs[0] == ({"name": "obs"}, "observed")
    assert all("permute_seed" in o for o, _ in objs[1:])
    # CV: all folds share ONE partition seed.
    cplan = ScenarioPlan("cv", folds=3, scenario_seed=9)
    cobjs = expand_plan(cplan, cfg)
    assert len({o["subsample_seed"] for o, _ in cobjs}) == 1
    assert [o["cv_fold"] for o, _ in cobjs] == [0, 1, 2]


def test_scenario_id_ignores_output_paths_not_inputs():
    from g2vec_tpu.stats.plan import ScenarioPlan, scenario_id

    plan = ScenarioPlan("bootstrap", replicates=3, scenario_seed=1)
    base = scenario_id(plan, _plan_cfg())
    # Output location and input DIRECTORIES are not identity: a rerun
    # elsewhere must produce the same id (and artifact bytes).
    assert scenario_id(plan, _plan_cfg(
        result_name="/tmp/other/out",
        expression_file="/data/elsewhere/E.tsv")) == base
    assert scenario_id(plan, _plan_cfg(expression_file="E2.tsv")) != base
    assert scenario_id(plan, _plan_cfg(seed=5)) != base
    assert scenario_id(
        ScenarioPlan("bootstrap", replicates=3, scenario_seed=2),
        _plan_cfg()) != base


def test_scenario_validation_errors_name_scenario_and_replicate():
    """Satellite: a scenario-expanded variant failing manifest validation
    must say which scenario and which replicate — not just 'variant 3'."""
    from g2vec_tpu.batch.engine import ManifestError
    from g2vec_tpu.stats.plan import (ScenarioPlan, scenario_id,
                                      scenario_variants)

    cfg = _plan_cfg(patient_subsample=1.5)  # invalid fraction
    plan = ScenarioPlan("bootstrap", replicates=2, scenario_seed=0)
    sid = scenario_id(plan, cfg)
    with pytest.raises(ManifestError) as ei:
        scenario_variants(plan, cfg)
    msg = str(ei.value)
    assert f"scenario {sid}" in msg and "replicate 0" in msg
    # Hand-written manifests keep their plain origin.
    from g2vec_tpu.batch.engine import _variant_from_dict
    with pytest.raises(ManifestError, match=r"manifest variant 0:"):
        _variant_from_dict(0, {"subsample_mode": "bogus"}, _plan_cfg())


def test_config_gates_scenario_flags():
    cfg = _plan_cfg(scenario="bootstrap")
    with pytest.raises(ValueError, match="--replicates"):
        cfg.validate()
    with pytest.raises(ValueError, match="--scenario"):
        _plan_cfg(replicates=3).validate()
    with pytest.raises(ValueError, match="mutually exclusive"):
        _plan_cfg(scenario="bootstrap", replicates=2,
                  batch_seeds=4).validate()
    with pytest.raises(ValueError, match="--folds"):
        _plan_cfg(scenario="cv").validate()
    _plan_cfg(scenario="cv", folds=3).validate()
    _plan_cfg(scenario="permutation", replicates=5).validate()


# ---------------------------------------------------------------------------
# End-to-end scenarios on the lane substrate
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _cfg(tsv_paths, tmp_path, sub, **overrides):
    os.makedirs(os.path.join(str(tmp_path), sub), exist_ok=True)
    defaults = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), sub, "out"),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        kmeans_iters=50, seed=0, walker_backend="device",
    )
    defaults.update(overrides)
    return G2VecConfig(**defaults)


def test_bootstrap_scenario_deterministic_and_solo_twin_parity(
        tsv_paths, tmp_path):
    """The two headline guarantees in one run: rerunning the same plan
    into a different directory reproduces the stability artifact byte
    for byte, and a sampled replicate is byte-identical to its solo
    twin (pipeline.run over lane_config of the expanded variant)."""
    from g2vec_tpu.batch.engine import lane_config
    from g2vec_tpu.pipeline import run as solo_run
    from g2vec_tpu.stats.run import run_scenario

    kw = dict(scenario="bootstrap", replicates=3, scenario_seed=11)
    cfg_a = _cfg(tsv_paths, tmp_path, "a", **kw)
    res_a = run_scenario(cfg_a, console=lambda s: None)
    cfg_b = _cfg(tsv_paths, tmp_path, "b", **kw)
    res_b = run_scenario(cfg_b, console=lambda s: None)
    with open(res_a.output, "rb") as fa, open(res_b.output, "rb") as fb:
        assert fa.read() == fb.read()
    assert res_a.scenario_id == res_b.scenario_id

    # Solo twin of replicate b001: same variant, fresh process-state run.
    from g2vec_tpu.stats.plan import plan_from_config, scenario_variants
    _, variants = scenario_variants(plan_from_config(cfg_a), cfg_a)
    v = variants[1]
    solo_cfg = lane_config(_cfg(tsv_paths, tmp_path, "solo", **kw), v)
    sr = solo_run(solo_cfg, console=lambda s: None)
    for suffix in ("_biomarkers.txt", "_lgroups.txt", "_vectors.txt"):
        lane_file = cfg_a.result_name + ".b001" + suffix
        twin = [p for p in sr.output_files if p.endswith(suffix)][0]
        with open(lane_file, "rb") as a, open(twin, "rb") as b:
            assert a.read() == b.read(), f"{lane_file} differs from twin"

    # The resamples differ: replicate selections are not all identical.
    head = open(res_a.output).readline()
    assert head == "# g2vec stability v1\tscenario=bootstrap\n"


def test_permutation_scenario_walks_each_group_exactly_once(
        tsv_paths, tmp_path):
    """Acceptance: permutation lanes differ only at stage-6 labels, so a
    COLD engine samples exactly the 2 (cohort, group) walk products and
    every null lane shares them — asserted from walk-tier accounting."""
    from g2vec_tpu.stats.run import run_scenario

    cfg = _cfg(tsv_paths, tmp_path, "perm", scenario="permutation",
               replicates=2, scenario_seed=5,
               metrics_jsonl=os.path.join(str(tmp_path), "perm.jsonl"))
    res = run_scenario(cfg, console=lambda s: None)
    assert res.n_variants == 3  # obs + 2 nulls
    assert res.walk_stats["walked"] == 2
    assert res.walk_stats["lane_shared"] == 4  # 3 lanes * 2 - 2
    lines = open(res.output).read().splitlines()
    assert lines[0].endswith("scenario=permutation")
    header = lines[[i for i, ln in enumerate(lines)
                    if ln.startswith("GeneSymbol")][0]]
    assert header.split("\t")[1:] == ["t_obs", "p_value", "q_value",
                                      "selected_obs"]
    # p-values live in (0, 1]; the add-one floor for R=2 is 1/3.
    rows = [ln.split("\t") for ln in lines if not ln.startswith(("#",
                                                                 "Gene"))]
    ps = np.array([float(r[2]) for r in rows])
    # cells are "%.6f"-rendered, so allow formatting granularity
    assert ps.min() >= 1 / 3 - 1e-6 and ps.max() <= 1.0
    # Metrics stream: one scenario event, one replicate event per lane,
    # one stability event.
    evs = [json.loads(ln) for ln in open(cfg.metrics_jsonl)]
    kinds = [e["event"] for e in evs]
    assert kinds.count("scenario") == 1
    assert kinds.count("replicate") == 3
    assert kinds.count("stability") == 1
    scn = evs[kinds.index("scenario")]
    assert scn["via"] == "lanes" and scn["n_variants"] == 3


def test_cv_scenario_artifact_and_fold_invariants(tsv_paths, tmp_path):
    from g2vec_tpu.preprocess import fold_assignments
    from g2vec_tpu.stats.plan import derive_seed
    from g2vec_tpu.stats.run import run_scenario

    cfg = _cfg(tsv_paths, tmp_path, "cv", scenario="cv", folds=3,
               scenario_seed=5)
    res = run_scenario(cfg, console=lambda s: None)
    assert res.n_variants == 3
    assert 0.0 <= res.extras["ci_lo"] <= res.extras["acc_mean"] \
        <= res.extras["ci_hi"] <= 1.0
    lines = open(res.output).read().splitlines()
    meta = dict(ln[2:].split("\t") for ln in lines
                if ln.startswith("# ") and "\t" in ln[2:])
    assert meta["folds"] == "3"
    accs = [float(x) for x in meta["fold_acc"].split(",")]
    assert len(accs) == 3
    assert np.mean(accs) == pytest.approx(float(meta["acc_mean"]),
                                          abs=1e-6)
    # The partition the reducer scored against covers every patient
    # exactly once and is reproducible from the plan's seed tree.
    from g2vec_tpu.io.readers import load_clinical, load_expression
    from g2vec_tpu.preprocess import match_labels
    data = load_expression(cfg.expression_file)
    labels = match_labels(load_clinical(cfg.clinical_file), data.sample)
    folds = fold_assignments(labels, 3, derive_seed(5, 0, "folds"))
    assert (folds >= 0).all() and set(folds) == {0, 1, 2}


def test_cli_scenario_dispatch(tsv_paths, tmp_path):
    """python -m g2vec_tpu EXPR CLIN NET NAME --scenario ... writes the
    stability artifact (the __main__ branch, through the real parser)."""
    out = os.path.join(str(tmp_path), "cli", "out")
    os.makedirs(os.path.dirname(out))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    proc = subprocess.run(
        [sys.executable, "-m", "g2vec_tpu", tsv_paths["expression"],
         tsv_paths["clinical"], tsv_paths["network"], out, "-p", "8",
         "-r", "2", "-s", "16", "-e", "10", "-l", "0.05", "-n", "5",
         "--compute-dtype", "float32", "--platform", "cpu",
         "--walker-backend", "device", "--scenario", "bootstrap",
         "--replicates", "2", "--scenario-seed", "3"],
        capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stderr[-800:]
    assert os.path.exists(out + "_stability.txt")
    assert "scenario bootstrap" in proc.stdout


# ---------------------------------------------------------------------------
# Serve path: exactly-once replicates across a daemon SIGKILL
# ---------------------------------------------------------------------------

def _spawn_daemon(tmp_path, extra=()):
    sock = os.path.join(str(tmp_path), "g.sock")
    state = os.path.join(str(tmp_path), "state")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    log = open(os.path.join(str(tmp_path), "daemon.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "g2vec_tpu", "serve", "--socket", sock,
         "--state-dir", state, "--platform", "cpu",
         "--cache-dir", os.path.join(str(tmp_path), "cache"), *extra],
        env=env, stdout=log, stderr=subprocess.STDOUT)
    return proc, sock, state


def test_serve_scenario_survives_sigkill_exactly_once(tsv_paths, tmp_path):
    """Chaos acceptance: a scenario submitted as serve jobs rides out a
    mid-scenario daemon SIGKILL — every replicate accounted exactly once
    (one durable result record each, resubmission dedups), and the final
    artifact is byte-identical to the lane-path run of the same plan."""
    from g2vec_tpu.serve import client
    from g2vec_tpu.stats.run import run_scenario
    from g2vec_tpu.stats.serve import run_scenario_serve

    proc, sock, state = _spawn_daemon(
        tmp_path, extra=("--supervise", "--supervise-backoff", "0.1",
                         "--fault-plan", "stage=train,kind=sigkill"))
    try:
        assert client.wait_ready(sock, 120), "daemon never became ready"
        os.makedirs(os.path.join(str(tmp_path), "srv"))
        base_job = dict(
            expression_file=tsv_paths["expression"],
            clinical_file=tsv_paths["clinical"],
            network_file=tsv_paths["network"],
            result_name=os.path.join(str(tmp_path), "srv", "out"),
            lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=10,
            learningRate=0.05, numBiomarker=5, compute_dtype="float32",
            walker_backend="device")
        kw = dict(scenario="bootstrap", replicates=2, scenario_seed=11,
                  state_dir=state, timeout=300, poll_deadline_s=240,
                  console=lambda s: None)
        res = run_scenario_serve(sock, base_job, **kw)
        assert os.path.exists(res.output)
        # Exactly-once: one durable result record per replicate, each
        # carrying the scenario idempotency key.
        recs = []
        for fn in sorted(os.listdir(os.path.join(state, "results"))):
            with open(os.path.join(state, "results", fn)) as f:
                recs.append(json.load(f))
        assert len(recs) == 2
        assert sorted(r["idem_key"] for r in recs) == [
            f"scn-{res.scenario_id}-b000", f"scn-{res.scenario_id}-b001"]
        assert all(r["status"] == "done" for r in recs)

        # Resubmitting the whole scenario dedups: same records, same
        # artifact bytes, no third result file.
        art1 = open(res.output, "rb").read()
        res2 = run_scenario_serve(sock, base_job, **kw)
        assert open(res2.output, "rb").read() == art1
        assert len(os.listdir(os.path.join(state, "results"))) == 2

        # Byte parity with the lane path: same plan, local engine.
        lane_cfg = G2VecConfig(**{
            **base_job,
            "result_name": os.path.join(str(tmp_path), "lane", "out")},
            scenario="bootstrap", replicates=2, scenario_seed=11)
        os.makedirs(os.path.join(str(tmp_path), "lane"))
        lres = run_scenario(lane_cfg, console=lambda s: None)
        assert open(lres.output, "rb").read() == art1

        client.shutdown(sock)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            try:
                os.kill(proc.pid, signal.SIGTERM)
            except OSError:
                pass
            proc.kill()
            proc.wait()
