"""The dead-tunnel bench path (bench.py --_hostonly / the probe-failure
fallback) is the round's evidence of last resort — it must keep producing
a real metric line with NO jax backend available. Runs at toy walk shapes
via the bench env overrides; the child never imports jax, so these tests
are fast and tunnel-proof."""
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain in this environment")

_TOY = {"G2VEC_BENCH_LEN_PATH": "8", "G2VEC_BENCH_WALKER_REPS": "1",
        "G2VEC_BENCH_BASELINE_BUDGET": "2"}


def _last_metric(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip().startswith("{")]
    assert lines, stdout
    return json.loads(lines[-1])


def test_hostonly_child_emits_real_native_metric(tmp_path):
    # Empty window dir: this pins the NO-chip-window behavior (the real
    # repo root may hold landed BENCH_LOCAL_* artifacts, which the child
    # would relay — covered by the relay test below).
    proc = subprocess.run(
        [sys.executable, BENCH, "--_hostonly"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_WINDOW_DIR": str(tmp_path)})
    assert proc.returncode == 0, proc.stderr[-800:]
    last = _last_metric(proc.stdout)
    assert last["metric"] == "walker_native_walks_per_sec"
    assert last["value"] and last["value"] > 0
    assert last["chip_free_fallback"] is True
    assert last["vs_baseline"] and last["vs_baseline"] > 1
    # Config #2's walker half rides along chip-free (its trainer half is
    # chip-gated), at 2x the default lenPath. The headline-last ordering
    # is already pinned by the _last_metric assertion above.
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    c2 = [d for d in lines if d["metric"]
          == "config2_walker_native_walks_per_sec"]
    assert len(c2) == 1 and c2[0]["value"] > 0
    assert c2[0]["len_path"] == 2 * int(_TOY["G2VEC_BENCH_LEN_PATH"])
    # Chip-gated metrics appear as explicit honest nulls, not absences —
    # the FULL advertised surface, pinned against bench's own tuple.
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    gated = {d["metric"]: d for d in lines if d.get("skipped")}
    assert set(gated) == {m for m, _ in bench.GATED_CHIP_METRICS}
    assert all(d["value"] is None for d in gated.values())


def test_hostonly_relays_landed_window_lines(tmp_path):
    """Chip numbers the watcher battery landed earlier in the round are
    relayed (with provenance) instead of nulls, the headline train line
    prints LAST, and a later window artifact overrides an earlier one."""
    win1 = {"stage": "bench", "rc": 0, "lines": [
        {"metric": "cbow_train_paths_per_sec_per_chip", "value": 5591382.3,
         "unit": "paths/s", "vs_baseline": 338.68},
        {"metric": "walker_walks_per_sec", "value": 8107.2,
         "unit": "walks/s", "vs_baseline": 41.11},
        {"metric": "packed_matmul_vs_xla_dense", "value": None,
         "skipped": "budget"}]}
    win2 = {"stage": "bench", "rc": 0, "lines": [
        {"metric": "walker_walks_per_sec", "value": 9000.0,
         "unit": "walks/s", "vs_baseline": 45.0}]}
    (tmp_path / "BENCH_LOCAL_r05.json").write_text(json.dumps(win1))
    (tmp_path / "BENCH_LOCAL_r05b.json").write_text(json.dumps(win2))
    os.utime(tmp_path / "BENCH_LOCAL_r05b.json",
             (2_000_000_000, 2_000_000_000))   # r05b is the later window
    proc = subprocess.run(
        [sys.executable, BENCH, "--_hostonly"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_WINDOW_ROUND": "r05",
             "G2VEC_BENCH_WINDOW_DIR": str(tmp_path)})
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    by_metric = {}
    for d in lines:
        by_metric.setdefault(d["metric"], []).append(d)
    # Landed metrics relayed with provenance; the null line in the window
    # artifact does NOT count as landed (stays an honest null).
    walker = by_metric["walker_walks_per_sec"][-1]
    assert walker["value"] == 9000.0                  # later window wins
    assert walker["chip_window_relay"] == "BENCH_LOCAL_r05b.json"
    ab = by_metric["packed_matmul_vs_xla_dense"][-1]
    assert ab["value"] is None and ab.get("skipped")
    # Headline relay is the LAST line (the driver's parsed result).
    assert lines[-1]["metric"] == "cbow_train_paths_per_sec_per_chip"
    assert lines[-1]["value"] == 5591382.3
    assert lines[-1]["chip_window_relay"] == "BENCH_LOCAL_r05.json"


def test_probe_failure_falls_back_and_exits_3(tmp_path):
    # Poison the probe deterministically: G2VEC_BENCH_PLATFORM names a
    # platform jax cannot initialize, so every probe attempt fails fast
    # regardless of how warm this host's jax import is. The host-only
    # fallback must still deliver the native line LAST (the driver parses
    # the last line) and exit 3.
    proc = subprocess.run(
        [sys.executable, BENCH],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_WINDOW_DIR": str(tmp_path),
             "G2VEC_BENCH_PLATFORM": "no_such_platform",
             "G2VEC_BENCH_PROBE_TIMEOUT": "30",
             "G2VEC_BENCH_TOTAL_BUDGET": "240"})
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-800:])
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert lines[0]["metric"] == "cbow_train_paths_per_sec_per_chip"
    assert lines[0]["value"] is None          # honestly unmeasurable
    assert "backend-probe" in lines[0]["error"]
    assert lines[-1]["metric"] == "walker_native_walks_per_sec"
    assert lines[-1]["value"] > 0


def test_measure_death_pre_metric_relays_and_exits_3(tmp_path):
    """A chip bench whose measure child dies before ANY metric (the
    mid-train tunnel wedge) must still put the landed in-round window
    evidence into the round's record: relayed lines, headline last,
    rc=3 (partial) instead of the rc=2 nothing."""
    (tmp_path / "BENCH_LOCAL_r05.json").write_text(json.dumps(
        {"stage": "bench", "rc": 0, "lines": [
            {"metric": "cbow_train_paths_per_sec_per_chip",
             "value": 5591382.3, "unit": "paths/s", "vs_baseline": 338.68},
            {"metric": "walker_walks_per_sec", "value": 8107.2,
             "unit": "walks/s"}]}))
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=300,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_WINDOW_DIR": str(tmp_path),
             "G2VEC_BENCH_PLATFORM": "cpu",
             # Poison only the child's runtime (the parent never calls
             # make_paths): 0 genes makes the train stage raise before
             # its first metric line.
             "G2VEC_BENCH_WINDOW_ROUND": "r05",
             "G2VEC_BENCH_N_GENES": "0",
             "G2VEC_BENCH_TOTAL_BUDGET": "200",
             "G2VEC_BENCH_TIMEOUT": "90",
             "G2VEC_BENCH_CHILD_BUDGET": "80"})
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-800:])
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert any(d["metric"] == "bench_stage_error" for d in lines)
    assert lines[-1]["metric"] == "cbow_train_paths_per_sec_per_chip"
    assert lines[-1]["value"] == 5591382.3
    assert "died pre-metric" in lines[-1]["relay_note"]
    walker = [d for d in lines if d["metric"] == "walker_walks_per_sec"
              and d.get("chip_window_relay")]
    assert walker and walker[0]["value"] == 8107.2


def test_measure_death_without_landed_headline_closes_on_null(tmp_path):
    """Same pre-metric death, but the window never landed the headline:
    the record still relays what exists and must CLOSE on an explicit
    null headline line (the driver's parsed result stays semantic)."""
    (tmp_path / "BENCH_LOCAL_r05.json").write_text(json.dumps(
        {"stage": "bench", "rc": 0, "lines": [
            {"metric": "walker_walks_per_sec", "value": 8107.2,
             "unit": "walks/s"}]}))
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=300,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_WINDOW_DIR": str(tmp_path),
             "G2VEC_BENCH_PLATFORM": "cpu",
             "G2VEC_BENCH_WINDOW_ROUND": "r05",
             "G2VEC_BENCH_N_GENES": "0",
             "G2VEC_BENCH_TOTAL_BUDGET": "200",
             "G2VEC_BENCH_TIMEOUT": "90",
             "G2VEC_BENCH_CHILD_BUDGET": "80"})
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-800:])
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert lines[-1]["metric"] == "cbow_train_paths_per_sec_per_chip"
    assert lines[-1]["value"] is None and "measure:" in lines[-1]["error"]
    assert any(d.get("chip_window_relay") for d in lines)


def test_acceptance_relay_line_codekey_gated(tmp_path, monkeypatch):
    """SKIP_ACCEPT's line carries the dedicated stage's acc_val only when
    the artifact's code_key matches the current tree; anything else (or
    no artifact) stays the honest skip."""
    sys.path.insert(0, REPO)
    try:
        import bench
        import tools.tpu_acceptance as acc
    finally:
        sys.path.remove(REPO)
    monkeypatch.setattr(acc, "_code_key", lambda: "tree-NOW")

    line = bench._acceptance_relay_line(str(tmp_path))
    assert line["value"] is None and "skipped" in line   # no artifact

    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"code_key": "tree-OLD", "acc_val": 0.89,
         "reference_transcript": {"acc_val": 0.8812}}))
    line = bench._acceptance_relay_line(str(tmp_path))
    assert line["value"] is None                         # stale code_key

    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"code_key": "tree-NOW", "acc_val": 0.8948, "n_paths": 40014,
         "pipeline_wall_seconds": 31.2,
         "reference_transcript": {"acc_val": 0.8812}}))
    line = bench._acceptance_relay_line(str(tmp_path))
    assert line["value"] == 0.8948
    assert line["vs_baseline"] == round(0.8948 / 0.8812, 3)
    assert "TPU_ACCEPTANCE.json" in line["from_artifact"]


def test_landed_window_lines_provenance_rules(tmp_path, monkeypatch):
    """Harvest rules: relayed/host-fallback lines are never re-harvested
    (their provenance would be rewritten to the wrong artifact), and the
    per-metric winner is deterministic when a fresh checkout flattens
    mtimes (name order breaks the tie: r05 < r05b = window order)."""
    monkeypatch.setenv("G2VEC_BENCH_WINDOW_ROUND", "r05")
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    (tmp_path / "BENCH_LOCAL_r05.json").write_text(json.dumps(
        {"rc": 0, "lines": [
            {"metric": "walker_walks_per_sec", "value": 8107.2}]}))
    (tmp_path / "BENCH_LOCAL_r05b.json").write_text(json.dumps(
        {"rc": 3, "lines": [
            {"metric": "walker_walks_per_sec", "value": 9000.0},
            # A relay of the r05 headline and a host-side fallback line:
            # neither is a chip measurement OF THIS artifact.
            {"metric": "cbow_train_paths_per_sec_per_chip",
             "value": 5591382.3, "chip_window_relay": "BENCH_LOCAL_r05.json"},
            {"metric": "walker_native_walks_per_sec", "value": 94213.0,
             "chip_free_fallback": True},
            {"metric": "tpu_acceptance_acc_val", "value": 0.8948,
             "from_artifact": "TPU_ACCEPTANCE.json"}]}))
    # Identical mtimes (fresh-checkout shape): r05b must still win by name.
    os.utime(tmp_path / "BENCH_LOCAL_r05.json", (1_900_000_000,) * 2)
    os.utime(tmp_path / "BENCH_LOCAL_r05b.json", (1_900_000_000,) * 2)
    landed = bench._landed_window_lines(str(tmp_path))
    assert landed["walker_walks_per_sec"][0]["value"] == 9000.0
    assert landed["walker_walks_per_sec"][1] == "BENCH_LOCAL_r05b.json"
    assert "cbow_train_paths_per_sec_per_chip" not in landed
    assert "walker_native_walks_per_sec" not in landed
    assert "tpu_acceptance_acc_val" not in landed   # artifact-carried


def test_landed_window_lines_requires_round_env(tmp_path, monkeypatch,
                                                capsys):
    """With NEITHER round env var set the relay is skipped with a warning
    (ADVICE r5 #2): bench must not guess the round and re-stamp a stale
    round's numbers as current."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    monkeypatch.delenv("G2VEC_BENCH_WINDOW_ROUND", raising=False)
    monkeypatch.delenv("WATCHER_ROUND", raising=False)
    (tmp_path / "BENCH_LOCAL_r05.json").write_text(json.dumps(
        {"rc": 0, "lines": [
            {"metric": "walker_walks_per_sec", "value": 8107.2}]}))
    assert bench._landed_window_lines(str(tmp_path)) == {}
    assert "window-relay skipped" in capsys.readouterr().err


def test_relay_line_backend_provenance():
    """Host-side metrics relayed out of a chip-window artifact must not
    carry chip provenance (ADVICE r5 #1/#3)."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    chip = bench._relay_line(
        {"metric": "cbow_train_paths_per_sec_per_chip", "value": 1.0},
        "BENCH_LOCAL_r05.json")
    assert chip["relay_measured_on"] == "tpu"
    assert "real chip" in chip["relay_note"]
    host = bench._relay_line(
        {"metric": "walker_native_walks_per_sec", "value": 2.0},
        "BENCH_LOCAL_r05.json")
    assert host["relay_measured_on"] == "host-cpu"
    assert "not the chip" in host["relay_note"]
    assert "measured on the real chip" not in host["relay_note"]


def test_measure_child_budget_skip_relays_landed_lines(tmp_path):
    """A live-backend measure child whose budget runs out before a stage
    relays that stage's landed chip-window value instead of a null."""
    (tmp_path / "BENCH_LOCAL_r05.json").write_text(json.dumps(
        {"stage": "bench", "rc": 0, "lines": [
            {"metric": "packed_matmul_vs_xla_dense", "value": 7.9,
             "unit": "x"}]}))
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=340,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_WINDOW_DIR": str(tmp_path),
             "G2VEC_BENCH_WINDOW_ROUND": "r05",
             "G2VEC_BENCH_PLATFORM": "cpu",
             "G2VEC_BENCH_SKIP_ACCEPT": "1",
             "G2VEC_BENCH_N_PATHS": "1024", "G2VEC_BENCH_N_GENES": "256",
             "G2VEC_BENCH_MEASURE_EPOCHS": "4",
             "G2VEC_BENCH_TOTAL_BUDGET": "180",
             "G2VEC_BENCH_TIMEOUT": "170",
             # Deliberately below every guarded stage's 60s estimate:
             # by the time the guards run some budget is spent, so
             # remaining() < est is guaranteed and the skip path (and its
             # relay) is deterministic.
             "G2VEC_BENCH_CHILD_BUDGET": "60"})
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    ab = [d for d in lines if d["metric"] == "packed_matmul_vs_xla_dense"]
    assert len(ab) == 1
    assert ab[0]["value"] == 7.9
    assert ab[0]["chip_window_relay"] == "BENCH_LOCAL_r05.json"
    assert "budget ran out" in ab[0]["relay_note"]


def test_epochs_to_088_line_reads_freshest_artifact(tmp_path):
    # BASELINE's second target metric comes from the acceptance artifact's
    # history record; TPU artifact outranks the CPU twin; artifacts
    # without the field (pre-r5) are skipped, not misread.
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    # No artifacts at all -> honest null.
    line = bench._epochs_to_088_line(str(tmp_path))
    assert line["value"] is None and "error" in line

    # CPU twin with a history record.
    (tmp_path / "REAL_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "cpu", "acc_val": 0.8948, "epochs_to_acc_088": 12,
         "n_epochs_run": 30}))
    line = bench._epochs_to_088_line(str(tmp_path))
    assert line["value"] == 12 and line["platform"] == "cpu"
    assert line["vs_baseline"] == round(25 / 12, 2)

    # Stale TPU artifact WITHOUT the field must not shadow the CPU twin.
    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "tpu", "acc_val": 0.89}))
    assert bench._epochs_to_088_line(str(tmp_path))["platform"] == "cpu"

    # Fresh TPU artifact with the field outranks it.
    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "tpu", "acc_val": 0.891, "epochs_to_acc_088": 14,
         "n_epochs_run": 40}))
    line = bench._epochs_to_088_line(str(tmp_path))
    assert line["value"] == 14 and line["platform"] == "tpu"

    # A run that never crossed the gate: value null, explicit error.
    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "tpu", "acc_val": 0.71, "epochs_to_acc_088": None,
         "n_epochs_run": 500}))
    line = bench._epochs_to_088_line(str(tmp_path))
    assert line["value"] is None and "never reached" in line["error"]


def test_epochs_to_088_freshness_outranks_platform(tmp_path, monkeypatch):
    # A stale chip artifact (code_key from an old tree) must not shadow a
    # CPU twin regenerated at the current tree.
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "tpu", "acc_val": 0.891, "epochs_to_acc_088": 14,
         "code_key": "old-tree"}))
    (tmp_path / "REAL_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "cpu", "acc_val": 0.8948, "epochs_to_acc_088": 12,
         "code_key": "current-tree", "git_head": "abcdef0123456789"}))
    monkeypatch.setattr(bench, "_current_code_key",
                        lambda _d: "current-tree")
    line = bench._epochs_to_088_line(str(tmp_path))
    assert line["platform"] == "cpu" and line["value"] == 12
    assert line["code_fresh"] is True
    assert line["source_git_head"] == "abcdef012345"
    # Both fresh -> the chip artifact wins again.
    (tmp_path / "TPU_ACCEPTANCE.json").write_text(json.dumps(
        {"platform": "tpu", "acc_val": 0.891, "epochs_to_acc_088": 14,
         "code_key": "current-tree"}))
    assert bench._epochs_to_088_line(str(tmp_path))["platform"] == "tpu"


def test_measure_child_wedge_kill_and_partial_capture():
    # The parent's pre-metric cutoff is what saves a tunnel window from a
    # child wedged on a dead backend (round-3 postmortem): no metric by
    # the cutoff -> early kill; metric seen -> only the budget kill
    # applies and already-streamed lines are preserved.
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)

    env = dict(os.environ)
    # Wedged child: emits nothing, must die at the cutoff, not the budget.
    out, err, fail = bench._run_measure_child(
        60, env, 3,
        cmd=[sys.executable, "-c", "import time; time.sleep(50)"])
    assert fail and "no metric after 3s" in fail
    assert out == ""
    # Healthy-then-hung child: the metric line arrived before the cutoff,
    # so the early kill is disarmed; the budget kill preserves the line.
    out, err, fail = bench._run_measure_child(
        8, env, 3,
        cmd=[sys.executable, "-c",
             "import json,time;"
             "print(json.dumps({'metric':'m','value':1}), flush=True);"
             "time.sleep(50)"])
    assert fail and "exceeded 8s" in fail
    assert json.loads(out.splitlines()[0]) == {"metric": "m", "value": 1}


def test_exhausted_budget_skips_hostonly_child():
    # Probe retries that already consumed the driver's whole budget must
    # NOT spawn a >=30s host-only child past the deadline (an external
    # kill there would lose the partial-line cleanup): the fallback bails
    # with the headline error line only, rc=2.
    proc = subprocess.run(
        [sys.executable, BENCH],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_PLATFORM": "no_such_platform",
             "G2VEC_BENCH_PROBE_TIMEOUT": "10",
             "G2VEC_BENCH_TOTAL_BUDGET": "5"})
    assert proc.returncode == 2, (proc.returncode, proc.stderr[-800:])
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert lines and lines[0]["metric"] == "cbow_train_paths_per_sec_per_chip"
    assert lines[0]["value"] is None
    assert "no budget left" in proc.stderr


def test_ambient_nontpu_backend_routes_to_hostonly(tmp_path):
    # Tunnel gone but jax healthy on CPU (no explicit platform override):
    # the full-scale CPU train would burn the budget for nothing, so the
    # bench must record the chip-free truths instead, rc=3. (If the
    # ambient env makes the probe hang instead, that IS the probe-failure
    # path — same fallback, same rc.) Empty window dir: the repo root's
    # real landed BENCH_LOCAL_* artifacts would otherwise relay the chip
    # headline last (covered by the relay test).
    env = {**os.environ, **_TOY,
           "G2VEC_BENCH_WINDOW_DIR": str(tmp_path),
           "JAX_PLATFORMS": "cpu",
           "G2VEC_BENCH_PROBE_TIMEOUT": "20",
           "G2VEC_BENCH_TOTAL_BUDGET": "200"}
    env.pop("G2VEC_BENCH_PLATFORM", None)
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=340, env=env)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-800:])
    last = _last_metric(proc.stdout)
    assert last["metric"] == "walker_native_walks_per_sec"
    assert last["value"] > 0
