"""The dead-tunnel bench path (bench.py --_hostonly / the probe-failure
fallback) is the round's evidence of last resort — it must keep producing
a real metric line with NO jax backend available. Runs at toy walk shapes
via the bench env overrides; the child never imports jax, so these tests
are fast and tunnel-proof."""
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain in this environment")

_TOY = {"G2VEC_BENCH_LEN_PATH": "8", "G2VEC_BENCH_WALKER_REPS": "1"}


def _last_metric(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip().startswith("{")]
    assert lines, stdout
    return json.loads(lines[-1])


def test_hostonly_child_emits_real_native_metric():
    proc = subprocess.run(
        [sys.executable, BENCH, "--_hostonly"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, **_TOY})
    assert proc.returncode == 0, proc.stderr[-800:]
    last = _last_metric(proc.stdout)
    assert last["metric"] == "walker_native_walks_per_sec"
    assert last["value"] and last["value"] > 0
    assert last["chip_free_fallback"] is True
    assert last["vs_baseline"] and last["vs_baseline"] > 1


def test_probe_failure_falls_back_and_exits_3():
    # Poison the probe deterministically: G2VEC_BENCH_PLATFORM names a
    # platform jax cannot initialize, so every probe attempt fails fast
    # regardless of how warm this host's jax import is. The host-only
    # fallback must still deliver the native line LAST (the driver parses
    # the last line) and exit 3.
    proc = subprocess.run(
        [sys.executable, BENCH],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, **_TOY,
             "G2VEC_BENCH_PLATFORM": "no_such_platform",
             "G2VEC_BENCH_PROBE_TIMEOUT": "30",
             "G2VEC_BENCH_TOTAL_BUDGET": "240"})
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-800:])
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert lines[0]["metric"] == "cbow_train_paths_per_sec_per_chip"
    assert lines[0]["value"] is None          # honestly unmeasurable
    assert "backend-probe" in lines[0]["error"]
    assert lines[-1]["metric"] == "walker_native_walks_per_sec"
    assert lines[-1]["value"] > 0


def test_ambient_nontpu_backend_routes_to_hostonly():
    # Tunnel gone but jax healthy on CPU (no explicit platform override):
    # the full-scale CPU train would burn the budget for nothing, so the
    # bench must record the chip-free truths instead, rc=3. (If the
    # ambient env makes the probe hang instead, that IS the probe-failure
    # path — same fallback, same rc.)
    env = {**os.environ, **_TOY,
           "JAX_PLATFORMS": "cpu",
           "G2VEC_BENCH_PROBE_TIMEOUT": "20",
           "G2VEC_BENCH_TOTAL_BUDGET": "200"}
    env.pop("G2VEC_BENCH_PLATFORM", None)
    proc = subprocess.run([sys.executable, BENCH], capture_output=True,
                          text=True, timeout=340, env=env)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-800:])
    last = _last_metric(proc.stdout)
    assert last["metric"] == "walker_native_walks_per_sec"
    assert last["value"] > 0
