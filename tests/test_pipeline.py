"""End-to-end pipeline tests on the synthetic dataset (SURVEY.md §4 item 4):
full CLI-config run producing the three output files, format checks against
the reference's published samples, and same-seed byte determinism."""
import os

import numpy as np
import pytest

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.data.synthetic import write_synthetic_tsv


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory, ):
    from g2vec_tpu.data.synthetic import SyntheticSpec

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12, n_background=24,
                         n_expr_only=4, n_net_only=4, module_chords=2,
                         background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _cfg(tsv_paths, tmp_path, **overrides):
    defaults = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out"),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        kmeans_iters=50, seed=0,
    )
    defaults.update(overrides)
    return G2VecConfig(**defaults)


def test_full_pipeline_end_to_end(tsv_paths, tmp_path):
    from g2vec_tpu.pipeline import run

    lines = []
    result = run(_cfg(tsv_paths, tmp_path), console=lines.append)

    # --- console transcript structure (ref: README.md:21-49) ---
    banners = [ln for ln in lines if ln.startswith(">>>")]
    assert banners[0] == ">>> 0. Arguments"
    assert banners[-1] == ">>> 7. Save results"
    assert len(banners) == 8
    assert any(ln.startswith("    - Epoch: 000") for ln in lines)

    # --- artifacts ---
    assert len(result.output_files) == 3
    for path in result.output_files:
        assert os.path.exists(path)
    assert result.n_samples == 44
    assert result.embeddings.shape == (result.n_genes, 16)
    assert set(np.unique(result.lgroup_idx)) <= {0, 1, 2}
    assert result.biomarkers == sorted(result.biomarkers)
    assert len(result.biomarkers) <= 2 * 5

    # --- output formats (ref: G2Vec.py:127-131,159-165,203-215) ---
    bio, lg, vec = result.output_files
    with open(bio) as f:
        assert f.readline() == "GeneSymbol\n"
    with open(lg) as f:
        assert f.readline() == "GeneSymbol\tLgroup(0:good,1:poor,2:other)\n"
        rows = f.readlines()
        assert len(rows) == result.n_genes
        for row in rows[:5]:
            gene, idx = row.rstrip("\n").split("\t")
            assert idx in ("0", "1", "2")
    with open(vec) as f:
        header = f.readline().rstrip("\n").split("\t")
        assert header == ["GeneSymbol"] + [f"V{i}" for i in range(16)]
        first = f.readline().rstrip("\n").split("\t")
        assert len(first) == 17
        float(first[1])  # parses


def test_pipeline_is_deterministic_per_seed(tsv_paths, tmp_path):
    from g2vec_tpu.pipeline import run

    r1 = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "a")),
             console=lambda s: None)
    r2 = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "b")),
             console=lambda s: None)
    for f1, f2 in zip(r1.output_files, r2.output_files):
        with open(f1, "rb") as a, open(f2, "rb") as b:
            assert a.read() == b.read(), f"{f1} differs from {f2}"


def test_compilation_cache_populates(tsv_paths, tmp_path):
    """--compilation-cache points jax at a persistent XLA cache dir; a run
    must create and write it (the warm-run speedup itself is a TPU
    property; here we pin the plumbing)."""
    import os as _os

    from g2vec_tpu.pipeline import run

    cache = str(tmp_path / "xla-cache")
    # Shapes unseen by earlier tests in this process: the in-memory jit
    # caches would otherwise satisfy every program and nothing would
    # compile (or persist).
    run(_cfg(tsv_paths, tmp_path, compilation_cache=cache,
             sizeHiddenlayer=24, lenPath=9),
        console=lambda s: None)
    assert _os.path.isdir(cache) and _os.listdir(cache), (
        "compilation cache dir missing or empty after a cached run")


def test_pipeline_recovers_planted_modules(tsv_paths, tmp_path):
    """The planted good/poor modules should dominate the biomarker list."""
    from g2vec_tpu.pipeline import run

    result = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "m"),
                      numBiomarker=8),
                 console=lambda s: None)
    planted = sum(1 for g in result.biomarkers
                  if g.startswith("GMOD") or g.startswith("PMOD"))
    assert planted >= len(result.biomarkers) * 0.5, result.biomarkers
