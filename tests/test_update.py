"""Incremental update plane (incremental.py + the serve ``update`` op):
delta re-walk, warm-start fine-tune, and generation-atomic republish.

The contract under test, end to end:

- Delta detection is OWNER-RANGE granular: unchanged ranges hit the
  walk cache, changed ranges plus their 1-hop frontier re-walk, and an
  expression-only change skips stage 3 entirely.
- A fingerprint-identical input set is a NO-OP: ``walked_rows == 0``,
  every range a cache hit, and the republished generation's array
  files byte-identical to the prior one (the ISSUE invariant).
- Warm-start correctness is STATISTICAL, not bitwise: the PR 7 band
  (|dACC| <= 0.20, top-N biomarker overlap >= 0.6) vs a cold retrain
  of the same updated inputs.
- The republish is generation-atomic: QueryCache keys carry the live
  generation (a lost invalidate cannot serve a stale answer), readers
  hammering across a flip see complete-old or complete-new, never a
  torn mix, and a SIGKILL at the ``update_publish`` seam (after the
  gen rename, before the pointer flip) leaves the prior generation
  serving and the journaled update replayable to completion.
"""
import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from g2vec_tpu.resilience import faults

pytestmark = pytest.mark.update

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    # Bigger cohort than the serve-suite spec: the statistical-band
    # tests need BOTH the warm fine-tune and the cold retrain to
    # converge to the module answer, which the 24/20-patient spec's
    # noisier PCC estimates don't guarantee.
    spec = SyntheticSpec(n_good=48, n_poor=40, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _job(tsv_paths, tmp_path, name, **overrides):
    job = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out", name),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        walker_backend="device")
    job.update(overrides)
    return job


def _daemon(tmp_path, **opt_overrides):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=os.path.join(str(tmp_path), "serve.sock"),
        state_dir=os.path.join(str(tmp_path), "state"), **opt_overrides)
    return ServeDaemon(opts, console=lambda s: None)


def _result(daemon, job_id):
    path = os.path.join(daemon.opts.state_dir, "results",
                        f"{job_id}.json")
    with open(path) as f:
        return json.load(f)


def _gen(bundle_root):
    from g2vec_tpu.io.writers import read_generation

    return os.path.join(bundle_root, read_generation(bundle_root))


ARRAYS = ("embeddings.npy", "norms.npy", "scores.npy", "genes.txt")


def _array_bytes(gen_dir):
    out = {}
    for fn in ARRAYS:
        with open(os.path.join(gen_dir, fn), "rb") as f:
            out[fn] = f.read()
    return out


# ---------------------------------------------------------------------------
# Delta model units: ranges, fingerprints, frontier
# ---------------------------------------------------------------------------

def test_resolve_ranges_partitions_the_gene_axis():
    from g2vec_tpu.incremental import RANGE_CAP, resolve_ranges

    for n in (1, 5, RANGE_CAP - 1, RANGE_CAP, RANGE_CAP + 1, 1000):
        ranges = resolve_ranges(n)
        assert len(ranges) <= RANGE_CAP
        assert ranges[0][0] == 0 and ranges[-1][1] == n
        for (alo, ahi), (blo, bhi) in zip(ranges, ranges[1:]):
            assert ahi == blo and alo < ahi    # contiguous, non-empty
    assert resolve_ranges(0) == []
    # Fewer genes than the cap: one gene per range, nothing empty.
    assert resolve_ranges(3) == [(0, 1), (1, 2), (2, 3)]


def test_range_fingerprint_is_range_local():
    from g2vec_tpu.incremental import range_fingerprint

    s = np.array([0, 1, 4, 5], dtype=np.int32)
    d = np.array([1, 0, 5, 4], dtype=np.int32)
    w = np.array([0.9, 0.9, 0.7, 0.7], dtype=np.float32)
    base = range_fingerprint(s, d, w, 0, 2, "tag")
    # Same-range re-hash is stable; a weight change INSIDE the range
    # changes it; a change OUTSIDE the range does not.
    assert range_fingerprint(s, d, w, 0, 2, "tag") == base
    w_in = w.copy()
    w_in[0] = 0.5
    assert range_fingerprint(s, d, w_in, 0, 2, "tag") != base
    w_out = w.copy()
    w_out[2] = 0.1
    assert range_fingerprint(s, d, w_out, 0, 2, "tag") == base
    # The walk-params tag is part of the hash (a lenPath change must
    # never reuse old walks).
    assert range_fingerprint(s, d, w, 0, 2, "other") != base


def test_frontier_covers_one_hop_neighbors_both_directions():
    from g2vec_tpu.incremental import frontier_ranges

    ranges = [(0, 2), (2, 4), (4, 6)]
    # Edge 0->5 only (asymmetric list): changing range 0 must dirty
    # range 2 (dst side), and changing range 2 must dirty range 0.
    s = np.array([0], dtype=np.int64)
    d = np.array([5], dtype=np.int64)
    assert frontier_ranges({0}, ranges, s, d) == {2}
    assert frontier_ranges({2}, ranges, s, d) == {0}
    assert frontier_ranges({1}, ranges, s, d) == set()
    assert frontier_ranges(set(), ranges, s, d) == set()


def test_query_cache_key_carries_the_generation():
    from g2vec_tpu.serve import inventory

    a = inventory.cache_key("j/v0", "neighbors", "G1", 5, "exact", 0,
                            "gen-000001")
    b = inventory.cache_key("j/v0", "neighbors", "G1", 5, "exact", 0,
                            "gen-000002")
    assert a != b


# ---------------------------------------------------------------------------
# Engine: bootstrap -> noop byte identity -> expr-only -> delta + band
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def prior(tsv_paths, tmp_path_factory):
    """Cold run -> published bundle -> bootstrap update -> republished
    generation WITH fingerprints. The shared starting point for every
    engine-level delta scenario."""
    from g2vec_tpu.cache import resolve_cache_tiers
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.incremental import run_update
    from g2vec_tpu.io.writers import write_inventory_bundle
    from g2vec_tpu.pipeline import run

    tmp = tmp_path_factory.mktemp("upd_engine")
    os.makedirs(os.path.join(str(tmp), "out"), exist_ok=True)
    cfg = G2VecConfig(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp), "out", "cold"),
        # numRepetition sized so the delta-vs-cold top-10 band check
        # has statistical margin: cached ranges replay pre-delta walks
        # by design, so the comparison needs enough path volume that
        # one noisy walk cannot swing a top-10 seat (PR 20's bit-exact
        # device walks shifted the sampled bytes; 4 reps left the
        # overlap one gene short of the 0.6 band).
        lenPath=12, numRepetition=6, sizeHiddenlayer=16, epoch=40,
        learningRate=0.05, numBiomarker=10, compute_dtype="float32",
        walker_backend="device",
        cache_dir=os.path.join(str(tmp), "cache"))
    cold = run(cfg, console=lambda s: None)
    bundle = os.path.join(str(tmp), "bundle")
    write_inventory_bundle(bundle, cold.embeddings, list(cold.genes),
                           cold.biomarker_scores, {"source": "cold"},
                           ann_nlist=4, seed_centroids=cold.km_centers)
    _, wc = resolve_cache_tiers(cfg.cache_dir, None, True)
    up1 = run_update(cfg, bundle, walk_cache=wc)
    gen2 = write_inventory_bundle(
        bundle, up1.embeddings, up1.genes, up1.biomarker_scores,
        {"source": "update"}, ann_nlist=4,
        seed_centroids=up1.km_centers,
        extra_files={"delta_fingerprints.json": up1.fingerprints})
    return {"cfg": cfg, "bundle": bundle, "wc": wc, "cold": cold,
            "up1": up1, "gen2": gen2, "tmp": str(tmp)}


def test_bootstrap_update_rewalks_everything_once(prior):
    """A cold bundle has no fingerprints: the first update re-walks
    every range, records per-range artifacts + fingerprints, and the
    published generation carries them on the lenient manifest tier."""
    up1, gen2 = prior["up1"], prior["gen2"]
    st = up1.stats
    assert st["mode"] == "bootstrap"
    assert st["ranges_rewalked"] == st["ranges_total"] > 0
    assert st["walked_rows"] > 0
    assert st["carried_rows"] == st["n_genes"]   # same gene set
    fp = up1.fingerprints
    assert fp["format"] == "g2vec-delta-v1"
    assert len(fp["groups"]) == 2
    assert all(len(g["ranges"]) == fp["n_ranges"] for g in fp["groups"])
    assert os.path.basename(gen2) == "gen-000002"
    with open(os.path.join(gen2, "delta_fingerprints.json")) as f:
        assert json.load(f)["genes_sha256"] == fp["genes_sha256"]


def test_noop_update_republishes_byte_identical_arrays(prior):
    """The ISSUE invariant: 1 rank, no delta -> walked_rows == 0, every
    range a cache hit, and the new generation's array files are
    byte-for-byte the prior generation's."""
    from g2vec_tpu.incremental import run_update
    from g2vec_tpu.io.writers import write_inventory_bundle

    cfg, bundle, wc = prior["cfg"], prior["bundle"], prior["wc"]
    up2 = run_update(cfg, bundle, walk_cache=wc)
    st = up2.stats
    assert st["mode"] == "noop"
    assert st["walked_rows"] == 0 and st["ranges_rewalked"] == 0
    assert st["cache_hits"] == st["ranges_total"] > 0
    assert st["prior_generation"] == "gen-000002"
    assert up2.acc_val != up2.acc_val            # NaN: nothing trained
    gen3 = write_inventory_bundle(
        bundle, up2.embeddings, up2.genes, up2.biomarker_scores,
        {"source": "update"}, ann_nlist=4,
        extra_files={"delta_fingerprints.json": up2.fingerprints})
    assert os.path.basename(gen3) == "gen-000003"
    assert _array_bytes(prior["gen2"]) == _array_bytes(gen3)


def test_expression_only_change_skips_stage3(prior):
    """Perturbing a gene whose incident |PCC| edges all sit below the
    threshold leaves both thresholded CSRs bit-identical: the walks are
    all cache hits (walked == 0) but the expression hash moved, so
    training + rescoring re-run — mode 'expr_only'."""
    from g2vec_tpu.incremental import _load_inputs, run_update
    from g2vec_tpu.ops.graph import thresholded_edges

    cfg, bundle, wc = prior["cfg"], prior["bundle"], prior["wc"]
    data, src, dst = _load_inputs(cfg)
    in_csr = set()
    for i in range(2):
        s, d, _w = thresholded_edges(data.expr[data.label == i], src,
                                     dst, threshold=cfg.pcc_threshold)
        in_csr |= set(np.asarray(s)) | set(np.asarray(d))
    quiet = [g for gi, g in enumerate(data.gene) if gi not in in_csr]
    assert quiet, "synthetic graph left no below-threshold gene"

    new_expr = os.path.join(prior["tmp"], "expr_perturbed.tsv")
    with open(cfg.expression_file) as f:
        lines = f.readlines()
    hit = False
    for i, line in enumerate(lines):
        parts = line.rstrip("\n").split("\t")
        if parts[0] == quiet[0]:
            parts[1] = repr(float(parts[1]) + 0.005)
            lines[i] = "\t".join(parts) + "\n"
            hit = True
    assert hit
    with open(new_expr, "w") as f:
        f.writelines(lines)

    cfg2 = dataclasses.replace(cfg, expression_file=new_expr)
    up = run_update(cfg2, bundle, walk_cache=wc, epochs=3)
    st = up.stats
    assert st["mode"] == "expr_only"
    assert st["walked_rows"] == 0 and st["ranges_rewalked"] == 0
    assert st["cache_hits"] == st["ranges_total"]
    assert up.acc_val == up.acc_val              # trained: finite acc
    assert up.biomarkers                         # rescoring re-ran


def test_edge_delta_rewalks_subset_and_holds_the_band(prior):
    """New intra-module edges dirty only the endpoints' owner ranges
    plus their 1-hop frontier; the warm-start fine-tune over the mixed
    (cached + re-walked) path set stays inside the PR 7 statistical
    band of a cold retrain on the same updated inputs."""
    from g2vec_tpu.incremental import run_update, within_band
    from g2vec_tpu.pipeline import run

    cfg, bundle, wc = prior["cfg"], prior["bundle"], prior["wc"]
    with open(cfg.network_file) as f:
        net_lines = f.readlines()
    have = set()
    for line in net_lines[1:]:
        a, b = line.split("\t")[0], line.split("\t")[1].strip()
        have |= {(a, b), (b, a)}
    added = []
    for i in range(12):
        for j in range(i + 1, 12):
            pair = (f"GMOD{i:04d}", f"GMOD{j:04d}")
            if pair not in have:
                added.append(pair)
            if len(added) == 3:
                break
        if len(added) == 3:
            break
    assert len(added) == 3, "module graph is complete; widen the spec"
    new_net = os.path.join(prior["tmp"], "net_delta.tsv")
    with open(new_net, "w") as f:
        f.writelines(net_lines)
        for a, b in added:
            f.write(f"{a}\t{b}\n")

    cfg3 = dataclasses.replace(
        cfg, network_file=new_net,
        result_name=os.path.join(prior["tmp"], "out", "delta"))
    up = run_update(cfg3, bundle, walk_cache=wc)
    st = up.stats
    assert st["mode"] == "delta"
    assert 0 < st["ranges_rewalked"] < st["ranges_total"]
    assert st["cache_hits"] > 0 and st["walked_rows"] > 0

    cold = run(dataclasses.replace(
        cfg3, result_name=os.path.join(prior["tmp"], "out", "cold3")),
        console=lambda s: None)
    ok, detail = within_band(up.acc_val, cold.acc_val,
                             up.biomarkers, cold.biomarkers)
    assert ok, f"delta retrain left the band: {detail}"


# ---------------------------------------------------------------------------
# Daemon: the `update` op end to end
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served(tsv_paths, tmp_path_factory):
    """One daemon with a finished (and auto-published) base job."""
    tmp = tmp_path_factory.mktemp("upd_daemon")
    d = _daemon(tmp, cache_dir=os.path.join(str(tmp), "cache"),
                ann_nlist=4)
    job = _job(tsv_paths, tmp, "base", epoch=16,
               variants=[{"name": "v0", "train_seed": 1}])
    ack = d.admit({"tenant": "alice", "job": job})
    assert ack["event"] == "accepted"
    assert d.step() == 1
    jid = ack["job_id"]
    yield {"d": d, "jid": jid, "key": f"{jid}/v0", "tmp": tmp,
           "root": os.path.join(d.opts.state_dir, "inventory", jid,
                                "v0")}
    d.close()


def test_daemon_update_bootstrap_then_noop_then_dedup(
        served, tsv_paths):
    from g2vec_tpu.io.writers import read_generation
    from g2vec_tpu.serve.protocol import idem_job_id

    d, jid, tmp = served["d"], served["jid"], served["tmp"]
    upayload = {"op": "update", "job_id": jid, "variant": "v0",
                "tenant": "alice", "idem_key": "uk-1", "epochs": 3,
                "job": _job(tsv_paths, tmp, "u1")}
    ack = d.admit(upayload)
    assert ack["event"] == "accepted"
    assert ack["job_id"] == idem_job_id("uk-1")
    assert d.step() == 1
    rec1 = _result(d, ack["job_id"])
    assert rec1["event"] == "job_done"
    assert rec1["update_of"] == served["key"]
    assert rec1["stats"]["mode"] == "bootstrap"
    assert rec1["generation"] == "gen-000002"
    assert read_generation(served["root"]) == "gen-000002"

    # Fingerprint-identical resubmit: a real republish (the pointer
    # moves) whose array files are byte-identical — and walked == 0.
    ack2 = d.admit({**upayload, "idem_key": "uk-2",
                    "job": _job(tsv_paths, tmp, "u2")})
    assert d.step() == 1
    rec2 = _result(d, ack2["job_id"])
    assert rec2["stats"]["mode"] == "noop"
    assert rec2["stats"]["walked_rows"] == 0
    assert rec2["generation"] == "gen-000003"
    g2 = os.path.join(served["root"], "gen-000002")
    g3 = os.path.join(served["root"], "gen-000003")
    assert _array_bytes(g2) == _array_bytes(g3)

    # Same idem_key again: deduped ack with the ORIGINAL job_id, no
    # third run queued.
    ack3 = d.admit({**upayload, "idem_key": "uk-2",
                    "job": _job(tsv_paths, tmp, "u2b")})
    assert ack3.get("deduped") is True
    assert ack3["job_id"] == ack2["job_id"]
    assert d.step() == 0


def test_daemon_update_admission_contract(served, tsv_paths):
    d, jid, tmp = served["d"], served["jid"], served["tmp"]
    good = {"op": "update", "job_id": jid, "variant": "v0",
            "tenant": "alice", "idem_key": "uk-x",
            "job": _job(tsv_paths, tmp, "ux")}
    for mutate, needle in [
        (lambda p: p.pop("idem_key"), "idem_key"),
        (lambda p: p.pop("job_id"), "job_id"),
        (lambda p: p.update(epochs=-1), "epochs"),
        (lambda p: p.update(epochs=True), "epochs"),
        (lambda p: p.update(variant=7), "variant"),
        (lambda p: p["job"].update(variants=[{"name": "v1"}]),
         "variants"),
        (lambda p: p["job"].update(seeds=2), "seeds"),
    ]:
        payload = {**good, "job": dict(good["job"])}
        mutate(payload)
        rej = d.admit(payload)
        assert rej["event"] == "rejected", (needle, rej)
        assert rej["error"] == "bad_job"
        assert needle in rej["detail"], rej["detail"]

    # An unknown target is a RUN-time fatal (resolution happens on the
    # scheduler thread, like every other bundle read).
    miss = {**good, "idem_key": "uk-miss",
            "job_id": "i" + "f" * 12}
    ack = d.admit(miss)
    assert ack["event"] == "accepted"
    assert d.step() == 0
    rec = _result(d, ack["job_id"])
    assert rec["status"] == "failed"
    assert rec["classified"] == "fatal"


def test_lost_qcache_invalidate_cannot_serve_stale_answers(tmp_path):
    """Regression for generation-keyed QueryCache entries: republish a
    bundle, drop ONLY the catalog mapping (simulating a lost/partial
    invalidation), and the pre-flip cached answer must be structurally
    unreachable because the key embeds the live generation pointer."""
    from g2vec_tpu.io.writers import write_inventory_bundle

    d = _daemon(tmp_path)
    try:
        jid = "i" + "b" * 12
        root = os.path.join(d.opts.state_dir, "inventory", jid, "v0")
        genes = ["GAAA0000", "GAAA0001", "GAAA0002", "GAAA0003"]
        emb1 = np.array([[1, 0, 0, 0], [0.9, 0.1, 0, 0],
                         [0, 1, 0, 0], [0, 0, 1, 0]], dtype=np.float32)
        write_inventory_bundle(root, emb1, genes, None, {"v": 1})
        q = {"q": "neighbors", "job_id": jid, "variant": "v0",
             "gene": "GAAA0000", "k": 1, "mode": "exact"}
        r1 = d.handle_query(q)
        assert r1["event"] == "query_result"
        assert r1["neighbors"] == ["GAAA0001"]
        assert d.handle_query(q)["neighbors"] == ["GAAA0001"]  # primed

        emb2 = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                         [0.9, 0.1, 0, 0], [0, 1, 0, 0]],
                        dtype=np.float32)
        write_inventory_bundle(root, emb2, genes, None, {"v": 2})
        key = f"{jid}/v0"
        d.catalog.invalidate(key)
        d._inv_known = {}
        # Deliberately NOT calling d.qcache.invalidate_bundle(key):
        # the generation in the key must protect us on its own.
        r2 = d.handle_query(q)
        assert r2["event"] == "query_result"
        assert r2["neighbors"] == ["GAAA0002"]
        assert r2["generation"] == "gen-000002"
    finally:
        d.close()


def test_readers_across_republish_see_old_or_new_never_torn(tmp_path):
    """ISSUE acceptance: >= 100 queries spanning repeated generation
    flips; every answer equals the complete pre-flip answer or the
    complete post-flip answer for its gene — zero torn reads."""
    from g2vec_tpu.io.writers import write_inventory_bundle

    d = _daemon(tmp_path)
    try:
        rng = np.random.default_rng(0)
        g, h = 24, 8
        genes = [f"GENE{i:04d}" for i in range(g)]
        emb_a = rng.standard_normal((g, h)).astype(np.float32)
        emb_b = np.ascontiguousarray(emb_a[::-1])
        probes = genes[:4]

        def plant(jid, emb):
            root = os.path.join(d.opts.state_dir, "inventory", jid,
                                "v0")
            write_inventory_bundle(root, emb, genes, None, {})
            return root

        plant("i" + "c" * 12, emb_a)
        plant("i" + "d" * 12, emb_b)
        live = plant("i" + "e" * 12, emb_a)

        def answer(jid, gene):
            r = d.handle_query({"q": "neighbors", "job_id": jid,
                                "variant": "v0", "gene": gene, "k": 5,
                                "mode": "exact"})
            assert r["event"] == "query_result", r
            return (tuple(r["neighbors"]), tuple(r["sims"]))

        expect = {gene: {answer("i" + "c" * 12, gene),
                         answer("i" + "d" * 12, gene)}
                  for gene in probes}
        flips = 6
        stop = threading.Event()

        def writer():
            for i in range(flips):
                emb = emb_b if i % 2 == 0 else emb_a
                write_inventory_bundle(live, emb, genes, None, {})
                key = "i" + "e" * 12 + "/v0"
                d.catalog.invalidate(key)
                d.qcache.invalidate_bundle(key)
                d._inv_known = {}
                time.sleep(0.05)
            stop.set()

        t = threading.Thread(target=writer)
        t.start()
        reads = 0
        torn = []
        while not stop.is_set() or reads < 120:
            gene = probes[reads % len(probes)]
            got = answer("i" + "e" * 12, gene)
            if got not in expect[gene]:
                torn.append((gene, got))
            reads += 1
            if reads > 5000:
                break
        t.join()
        assert reads >= 100
        assert not torn, f"{len(torn)} torn answers, e.g. {torn[0]}"
    finally:
        d.close()


# ---------------------------------------------------------------------------
# Crash drill: SIGKILL between the gen rename and the pointer flip
# ---------------------------------------------------------------------------

def test_update_publish_sigkill_leaves_prior_generation_serving(
        tsv_paths, tmp_path):
    """Kill the daemon at the ``update_publish`` seam — AFTER the new
    generation directory is renamed into place, BEFORE the pointer
    flip. The prior generation must keep serving (pointer untouched,
    orphan present, no result record), and a restart WITHOUT the fault
    replays the journaled update to a clean flip."""
    from g2vec_tpu.io.writers import read_generation, \
        write_inventory_bundle
    from g2vec_tpu.serve import client
    from g2vec_tpu.serve.protocol import idem_job_id

    state = os.path.join(str(tmp_path), "state")
    tgt = "i" + "a" * 12
    root = os.path.join(state, "inventory", tgt, "v0")
    rng = np.random.default_rng(3)
    write_inventory_bundle(
        root, rng.standard_normal((30, 16)).astype(np.float32),
        [f"SEED{i:04d}" for i in range(30)], None, {"source": "plant"})
    assert read_generation(root) == "gen-000001"

    ujid = idem_job_id("drill-1")
    jobs_dir = os.path.join(state, "jobs")
    os.makedirs(jobs_dir, exist_ok=True)
    with open(os.path.join(jobs_dir, f"{ujid}.json"), "w") as f:
        json.dump({"job_id": ujid, "tenant": "alice",
                   "submitted_at": time.time(),
                   "payload": {"op": "update", "job_id": tgt,
                               "variant": "v0", "idem_key": "drill-1",
                               "tenant": "alice", "epochs": 2,
                               "job": _job(tsv_paths, tmp_path,
                                           "drill")}}, f)

    sock = os.path.join(str(tmp_path), "g.sock")
    base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                    "PYTHONPATH", "")}
    argv = [sys.executable, "-m", "g2vec_tpu", "serve", "--socket",
            sock, "--state-dir", state, "--platform", "cpu",
            "--cache-dir", os.path.join(str(tmp_path), "cache")]
    log = open(os.path.join(str(tmp_path), "daemon.log"), "w")
    proc = subprocess.Popen(
        argv, env={**base_env,
                   faults.ENV_PLAN: "stage=update_publish,kind=sigkill"},
        stdout=log, stderr=subprocess.STDOUT)
    rc = proc.wait(timeout=300)
    assert rc == -signal.SIGKILL
    # The fault fired between the rename and the flip: the orphan
    # generation is on disk, the pointer still names the prior one,
    # the journal entry survived, and no terminal record exists.
    assert read_generation(root) == "gen-000001"
    assert os.path.isdir(os.path.join(root, "gen-000002"))
    assert os.path.exists(os.path.join(jobs_dir, f"{ujid}.json"))
    assert not os.path.exists(
        os.path.join(state, "results", f"{ujid}.json"))

    proc2 = subprocess.Popen(argv, env=base_env, stdout=log,
                             stderr=subprocess.STDOUT)
    try:
        rec = client.poll_result(state, ujid, deadline_s=300)
        assert rec["event"] == "job_done"
        assert rec["stats"]["mode"] == "bootstrap"
        # The orphan's serial is never reused: recovery publishes PAST
        # it, flips the pointer, and the GC sweeps the stale prior.
        assert rec["generation"] == "gen-000003"
        assert read_generation(root) == "gen-000003"
        assert not os.path.isdir(os.path.join(root, "gen-000001"))
        assert client.wait_ready(sock, 60)
        client.shutdown(sock, timeout=60)
        assert proc2.wait(timeout=60) == 0
    finally:
        if proc2.poll() is None:
            proc2.kill()
        log.close()
