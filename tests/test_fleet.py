"""Unit tests for the fleet resilience layer (resilience/fleet.py,
parallel/hostcomm.py) — everything that can be certified in one process on
virtual devices. The true multi-rank behavior (KV collectives across
processes, degraded-mesh relaunch, rank-scoped kills) runs in
tests/test_multiprocess.py and the `fleet`-marked tests/test_fleet_e2e.py.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from g2vec_tpu.parallel import hostcomm
from g2vec_tpu.resilience import faults, fleet


@pytest.fixture(autouse=True)
def _clean_fleet(monkeypatch):
    """Fleet config, heartbeat, and fault state are process-global: every
    test starts and ends inert."""
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    monkeypatch.delenv("G2VEC_PROCESS_ID", raising=False)
    faults._reset_for_tests()
    fleet.configure()
    yield
    fleet.stop_heartbeat()
    fleet.configure()
    faults._reset_for_tests()


# ------------------------------------------------------------ mesh planning

def test_plan_mesh_factorizations():
    assert fleet.plan_mesh(4, prefer_model=1) == (4, 1)
    assert fleet.plan_mesh(4, prefer_model=2) == (2, 2)
    assert fleet.plan_mesh(2, prefer_model=2) == (1, 2)
    # Model axis may shrink to the largest divisor, never grow.
    assert fleet.plan_mesh(6, prefer_model=4) == (2, 3)
    assert fleet.plan_mesh(3, prefer_model=2) == (3, 1)
    assert fleet.plan_mesh(1, prefer_model=8) == (1, 1)
    with pytest.raises(ValueError, match="0 devices"):
        fleet.plan_mesh(0)


# ------------------------------------------------- per-rank fault scoping

def test_fault_plan_process_scoping(monkeypatch, tmp_path):
    entries = faults.parse_plan("process=1,stage=allgather,kind=stall")
    assert entries[0].process == 1 and entries[0].stage == "allgather"
    with pytest.raises(faults.FaultPlanError, match="non-numeric"):
        faults.parse_plan("stage=train,process=one")

    faults.install_plan("process=1,stage=load,kind=crash")
    monkeypatch.setenv("G2VEC_PROCESS_ID", "0")
    faults.fault_point("load")          # rank 0: entry must not fire
    monkeypatch.setenv("G2VEC_PROCESS_ID", "1")
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("load")


def test_distributed_seams_accepted_by_config():
    from g2vec_tpu.config import G2VecConfig

    cfg = G2VecConfig(fault_plan="process=1,stage=stage_barrier,kind=sigkill;"
                                 "stage=heartbeat,kind=crash")
    cfg.validate()


# ------------------------------------------------------------- heartbeats

def test_heartbeat_writes_liveness_and_metrics(tmp_path):
    from g2vec_tpu.utils.metrics import MetricsWriter

    mpath = str(tmp_path / "m.jsonl")
    fleet.configure(liveness_dir=str(tmp_path), heartbeat_interval=0.02)
    with MetricsWriter(mpath) as metrics:
        hb = fleet.start_heartbeat(metrics)
        assert hb is not None
        fleet.note_phase("train")
        deadline = time.time() + 5.0
        while hb.beats < 4 and time.time() < deadline:
            time.sleep(0.01)
        fleet.stop_heartbeat()
    rec = fleet.read_liveness(str(tmp_path), 0)
    assert rec is not None and rec["rank"] == 0 and rec["beats"] >= 3
    assert rec["phase"] == "train"
    with open(mpath) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    beats = [e for e in events if e["event"] == "heartbeat"]
    assert len(beats) >= 4 and beats[-1]["phase"] == "train"


def test_heartbeat_fault_seam_kills_only_the_thread(tmp_path):
    faults.install_plan("stage=heartbeat,kind=crash")
    fleet.configure(liveness_dir=str(tmp_path), heartbeat_interval=0.02)
    hb = fleet.start_heartbeat()
    deadline = time.time() + 5.0
    while hb._thread.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    # The injected crash stopped the beats (thread dead, liveness going
    # stale) — but the process lives: exactly "monitoring died first".
    assert not hb._thread.is_alive()
    assert hb.beats == 1     # only the synchronous start() beat landed


# ------------------------------------------------------ collective watchdog

def test_watchdog_passes_results_and_errors_through():
    fleet.configure(watchdog_deadline=5.0)
    assert fleet.collective_watchdog("ok", lambda: 42) == 42
    with pytest.raises(KeyError):
        fleet.collective_watchdog("boom", lambda: {}["x"])


def test_watchdog_times_out_and_names_suspects(tmp_path, monkeypatch):
    fleet.configure(liveness_dir=str(tmp_path), heartbeat_interval=5.0,
                    watchdog_deadline=0.3)
    fleet.start_heartbeat()
    # Fabricate a peer whose heartbeat went stale mid-collective.
    with open(fleet.liveness_path(str(tmp_path), 1), "w") as f:
        json.dump({"rank": 1, "ts": time.time() - 120.0, "beats": 7,
                   "phase": "train", "collective": None,
                   "collective_seq": None}, f)
    monkeypatch.setattr(fleet, "_nranks", lambda: 2)
    t0 = time.time()
    with pytest.raises(fleet.PeerTimeoutError) as ei:
        fleet.collective_watchdog("unit", lambda: time.sleep(30))
    assert time.time() - t0 < 5.0       # raised at the deadline, not at 30s
    assert ei.value.suspects == (1,)
    assert "rank 1" in str(ei.value) and "stale" in str(ei.value)


def test_watchdog_inline_when_disabled():
    fleet.configure(watchdog_deadline=0.0)
    evt = threading.Event()
    assert fleet.collective_watchdog("inline", lambda: evt.is_set()) is False


# ------------------------------------------------- single-process hostcomm

def test_hostcomm_single_process_shortcuts():
    assert hostcomm.allgather_bytes("a", b"payload") == [b"payload"]
    arr = np.arange(6.0).reshape(2, 3)
    out = hostcomm.allgather_array("b", arr)
    assert out.shape == (1, 2, 3) and np.array_equal(out[0], arr)
    assert hostcomm.broadcast_bytes("c", b"xyz") == b"xyz"
    hostcomm.barrier("d")               # no-op, must not raise


# ------------------------------------------------------ straggler detection

class _Recorder:
    def __init__(self):
        self.events = []

    def emit(self, event, **fields):
        self.events.append({"event": event, **fields})


def test_stage_barrier_flags_stragglers(monkeypatch):
    fleet.configure(watchdog_deadline=5.0, straggler_factor=3.0)
    monkeypatch.setattr(fleet, "_nranks", lambda: 4)
    durs = np.asarray([[0.1], [0.11], [0.09], [1.2]])
    monkeypatch.setattr(hostcomm, "allgather_array",
                        lambda name, arr, deadline=None: durs)
    rec = _Recorder()
    lines = []
    fleet.stage_barrier("paths", 0.1, rec, lines.append)
    warns = [e for e in rec.events if e["event"] == "straggler_warning"]
    assert len(warns) == 1 and warns[0]["rank"] == 3
    assert warns[0]["stage"] == "paths"
    assert any("rank 3" in ln for ln in lines)


def test_stage_barrier_noop_when_inert(monkeypatch):
    # Single process: never calls the transport at all.
    called = []
    monkeypatch.setattr(hostcomm, "allgather_array",
                        lambda *a, **k: called.append(1))
    fleet.configure(watchdog_deadline=5.0, straggler_factor=3.0)
    fleet.stage_barrier("load", 0.1)
    assert not called


# --------------------------------------------------- supervisor integration

def test_peer_timeout_classifies_retryable():
    from g2vec_tpu.resilience.supervisor import (classify_child,
                                                 classify_exception)

    err = fleet.PeerTimeoutError("collective 'x' missing rank(s): [1]",
                                 collective="x", suspects=(1,))
    assert classify_exception(err) == "retryable"
    assert classify_child(1, "g2vec_tpu.resilience.fleet.PeerTimeoutError: "
                             "collective 'x' missing rank(s): [1]") \
        == "retryable"


def test_scrub_fleet_argv_keeps_child_flags():
    argv = ["e.txt", "c.txt", "n.txt", "out", "--fleet-size", "2",
            "--fleet-devices-per-rank", "2", "--mesh", "4x1", "--supervise",
            "--supervise-retries", "3", "--fault-plan", "stage=load",
            "--resume", "--fleet-watchdog-deadline", "5",
            "--checkpoint-dir", "ck"]
    out = fleet._scrub_fleet_argv(argv)
    assert "--fleet-size" not in out and "--mesh" not in out
    assert "--fault-plan" not in out and "--resume" not in out
    assert "--supervise" not in out and "3" not in out
    assert out[:4] == ["e.txt", "c.txt", "n.txt", "out"]
    assert "--fleet-watchdog-deadline" in out and "--checkpoint-dir" in out


def test_fleet_config_validation():
    from g2vec_tpu.config import G2VecConfig, config_from_args

    with pytest.raises(ValueError, match="fleet_size"):
        G2VecConfig(fleet_size=1).validate()
    with pytest.raises(ValueError, match="sharded"):
        G2VecConfig(fleet_size=2, checkpoint_dir="ck").validate()
    with pytest.raises(ValueError, match="evenly"):
        G2VecConfig(fleet_size=2, mesh_shape=(3, 1)).validate()
    cfg = config_from_args([
        "e.txt", "c.txt", "n.txt", "out", "--fleet-size", "2", "--mesh",
        "4x1", "--checkpoint-dir", "ck", "--checkpoint-layout", "sharded",
        "--fleet-watchdog-deadline", "6", "--fleet-straggler-factor", "3",
        "--fleet-liveness-dir", "L"])
    assert cfg.fleet_size == 2 and cfg.fleet_watchdog_deadline == 6.0
    assert cfg.fleet_straggler_factor == 3.0 and cfg.fleet_liveness_dir == "L"


# ------------------------------------------- degraded-mesh reshard on load

def _planted(rng, n_paths=120, n_genes=40):
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        idx = rng.choice(half, size=5, replace=False) + (0 if lab == 0 else half)
        paths[i, idx] = 1
    return paths, labels


def test_sharded_checkpoint_reshards_onto_degraded_mesh(tmp_path):
    """The resume half of degraded-mesh recovery, single-process on virtual
    devices: a sharded checkpoint written under a (4, 1) mesh restores onto
    a (2, 1) mesh (orbax reshards each leaf onto the new shardings at
    load). Terminal-state resume must hand back bit-identical vectors; a
    mid-train resume must keep training without error."""
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.train.trainer import train_cbow

    paths, labels = _planted(np.random.default_rng(0))
    common = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=0, checkpoint_every=3, checkpoint_layout="sharded")
    ck = str(tmp_path / "ck")
    full = train_cbow(paths, labels, max_epochs=6, checkpoint_dir=ck,
                      mesh_ctx=make_mesh_context((4, 1)), **common)
    assert not full.stopped_early
    resumed = train_cbow(paths, labels, max_epochs=6, checkpoint_dir=ck,
                         resume=True, mesh_ctx=make_mesh_context((2, 1)),
                         **common)
    # Zero epochs left to retrain: the restored (resharded) state is final.
    np.testing.assert_array_equal(resumed.w_ih, full.w_ih)

    # Mid-train degrade: checkpoint at epoch 5 of 12 under (4, 1), then
    # finish under (2, 1). Retrained epochs reassociate FP reductions, so
    # parity with the uninterrupted (4, 1) run is close, not bit-exact —
    # the boundary ARCHITECTURE.md documents.
    ck2 = str(tmp_path / "ck2")
    train_cbow(paths, labels, max_epochs=6, checkpoint_dir=ck2,
               mesh_ctx=make_mesh_context((4, 1)), **common)
    ref = train_cbow(paths, labels, max_epochs=12,
                     mesh_ctx=make_mesh_context((4, 1)),
                     **{k: v for k, v in common.items()
                        if not k.startswith("checkpoint")})
    degraded = train_cbow(paths, labels, max_epochs=12, checkpoint_dir=ck2,
                          resume=True, mesh_ctx=make_mesh_context((2, 1)),
                          **common)
    assert not degraded.stopped_early
    np.testing.assert_allclose(degraded.w_ih, ref.w_ih, rtol=2e-4, atol=1e-5)


# ------------------------------------------------ initialize() satellites

def test_initialize_fallback_emits_structured_event(monkeypatch):
    import jax

    from g2vec_tpu.parallel import distributed as dist

    calls = []

    def fake_init(**kwargs):
        if not kwargs:
            raise ValueError("no cluster")
        calls.append(kwargs)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_initialized", False)
    for var in ("G2VEC_COORDINATOR", "G2VEC_PROCESS_ID",
                "G2VEC_NUM_PROCESSES"):
        monkeypatch.delenv(var, raising=False)
    dist.drain_pending_events()
    dist.initialize()
    assert calls and calls[0]["num_processes"] == 1
    events = dist.drain_pending_events()
    assert len(events) == 1
    assert events[0]["event"] == "single_process_fallback"
    assert "coordinator" in events[0]
    assert dist.drain_pending_events() == []    # drained means drained
    monkeypatch.setattr(dist, "_initialized", False)


def test_shutdown_makes_initialize_reset_safe(monkeypatch):
    import jax

    from g2vec_tpu.parallel import distributed as dist

    inits, downs = [], []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: inits.append(kw))
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: downs.append(1))
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("G2VEC_COORDINATOR", "10.0.0.1:1")
    monkeypatch.setenv("G2VEC_PROCESS_ID", "0")
    monkeypatch.setenv("G2VEC_NUM_PROCESSES", "2")
    dist.initialize()
    dist.initialize()                   # idempotent: one real init
    assert len(inits) == 1
    dist.shutdown()                     # runtime teardown resets the flag
    assert downs == [1]
    dist.initialize()                   # an in-process restart can rejoin
    assert len(inits) == 2
    dist.shutdown()
    monkeypatch.setattr(dist, "_initialized", False)
