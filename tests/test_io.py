"""L1/L6 tests: reader contracts and byte-identical writer formats.

Writer expectations are transcribed from the reference implementations
(G2Vec.py:127-131, 159-165, 203-215) and the manual.pdf output samples.
"""
import numpy as np
import pytest

from g2vec_tpu.io import (
    load_clinical,
    load_expression,
    load_network,
    write_biomarkers,
    write_lgroups,
    write_vectors,
)


@pytest.fixture()
def tsv_dir(tmp_path):
    (tmp_path / "expr.txt").write_text(
        "PATIENT\tS1\tS2\tS3\n"
        "GENEB\t1.5\t-0.25\t0.0\n"
        "GENEA\t2.0\t3.0\t4.0\n"
    )
    (tmp_path / "clin.txt").write_text(
        "PATIENT_BARCODE\tLABEL\nS1\t0\nS2\t1\nS3\t0\n")
    (tmp_path / "net.txt").write_text(
        "src\tdest\nGENEA\tGENEB\nGENEB\tGENEC\nGENEA\tGENEB\n")
    return tmp_path


def test_load_expression_transposes_to_samples_x_genes(tsv_dir):
    d = load_expression(str(tsv_dir / "expr.txt"), use_native=False)
    assert list(d.sample) == ["S1", "S2", "S3"]
    assert list(d.gene) == ["GENEB", "GENEA"]  # file order preserved here
    assert d.expr.shape == (3, 2)
    assert d.expr.dtype == np.float32
    np.testing.assert_allclose(d.expr[:, 0], [1.5, -0.25, 0.0])
    np.testing.assert_allclose(d.expr[1], [-0.25, 3.0])


def test_load_expression_tolerates_crlf_and_trailing_blank(tmp_path):
    p = tmp_path / "e.txt"
    p.write_text("PATIENT\tS1\r\nG1\t1.0\r\n\r\n")
    d = load_expression(str(p), use_native=False)
    assert d.expr.shape == (1, 1)


def test_load_expression_ragged_row_raises(tmp_path):
    p = tmp_path / "e.txt"
    p.write_text("PATIENT\tS1\tS2\nG1\t1.0\n")
    with pytest.raises(ValueError, match="G1"):
        load_expression(str(p), use_native=False)


def test_load_clinical(tsv_dir):
    c = load_clinical(str(tsv_dir / "clin.txt"))
    assert c == {"S1": 0, "S2": 1, "S3": 0}


def test_load_clinical_bad_label(tmp_path):
    p = tmp_path / "c.txt"
    p.write_text("P\tL\nS1\t2\n")
    with pytest.raises(ValueError, match="label"):
        load_clinical(str(p))


def test_load_network_keeps_direction_order_and_duplicates(tsv_dir):
    n = load_network(str(tsv_dir / "net.txt"))
    assert n.edges == [("GENEA", "GENEB"), ("GENEB", "GENEC"), ("GENEA", "GENEB")]
    assert n.genes == {"GENEA", "GENEB", "GENEC"}


def test_write_biomarkers_bytes(tmp_path):
    path = write_biomarkers(str(tmp_path / "res"), ["BRCA1", "TP53"])
    assert open(path).read() == "GeneSymbol\nBRCA1\nTP53\n"


def test_write_lgroups_bytes(tmp_path):
    idx = np.array([2, 0, 1], dtype=np.int32)
    path = write_lgroups(str(tmp_path / "res"), idx, ["A1", "B2", "C3"])
    assert open(path).read() == (
        "GeneSymbol\tLgroup(0:good,1:poor,2:other)\n"
        "A1\t2\nB2\t0\nC3\t1\n")


def test_write_vectors_bytes(tmp_path):
    vec = np.array([[0.1234567, -1.0], [2.0, 3.5]], dtype=np.float32)
    path = write_vectors(str(tmp_path / "res"), vec, ["A1", "B2"])
    assert open(path).read() == (
        "GeneSymbol\tV0\tV1\n"
        "A1\t0.123457\t-1.000000\n"
        "B2\t2.000000\t3.500000\n")


def test_writer_reader_roundtrip_on_synthetic(tmp_path, small_spec):
    from g2vec_tpu.data.synthetic import write_synthetic_tsv

    paths = write_synthetic_tsv(small_spec, str(tmp_path))
    d = load_expression(paths["expression"], use_native=False)
    c = load_clinical(paths["clinical"])
    n = load_network(paths["network"])
    assert d.expr.shape == (small_spec.n_samples, len(d.gene))
    assert set(d.sample) == set(c.keys())
    assert len(n.edges) > 0
