"""Worker for the TRUE 2-process distributed test (not pytest-collected).

Launched twice by tests/test_multiprocess.py with G2VEC_COORDINATOR /
G2VEC_PROCESS_ID / G2VEC_NUM_PROCESSES in the env — the same plumbing a real
multi-host fleet launch uses (parallel/distributed.py).

Scope note (the triage recorded for the seed failure of this test): the
pinned jaxlib's CPU backend cannot run cross-process XLA computations at
all (``Multiprocess computations aren't implemented on the CPU backend``),
so the original global-mesh SPMD phases (cross-process device_put, a
(2, 2) global-mesh train, per-process orbax shard files) are impossible
off-TPU and were retired. What a CPU fleet really runs — and what this
worker now exercises end to end — is the cpu_fleet() contract:

- device stages REPLICATED on a process-local mesh (every rank must land
  on bit-identical state; the parent asserts the cross-rank digests);
- the single-layout checkpoint written only by rank 0 into its PRIVATE
  dir, restored on rank 1 through the KV-transport coordinator broadcast
  (train/checkpoint.py) — exactly the silent-divergence hazard ADVICE.md
  round 1 flagged;
- the sharded (orbax) layout written by the coordinator into a SHARED
  dir and restored locally by every rank;
- the native walk work DIVIDED across ranks and allgathered over the
  coordination-service KV transport (sharded_native_path_set) —
  bit-identical to the single-host walker by global stream identities.

Prints one JSON line with cross-process-comparable digests; the parent test
asserts they are bit-identical between the two processes.
"""
import hashlib
import json
import os
import sys

import numpy as np


def _data(rng, n_paths=120, n_genes=40):
    """Planted-signal dataset (same shape as tests/test_checkpoint.py) so
    training converges instead of tripping the early stop."""
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        idx = rng.choice(half, size=5, replace=False) + (0 if lab == 0 else half)
        paths[i, idx] = 1
    return paths, labels


def _digest(arr) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def main() -> None:
    out_dir = sys.argv[1]          # PRIVATE per-process scratch dir
    from g2vec_tpu.parallel import distributed as dist
    from g2vec_tpu.resilience import fleet

    dist.initialize()
    import jax

    assert jax.process_count() == 2, jax.process_count()
    assert dist.cpu_fleet()
    # A dead/stalled sibling must fail THIS process fast, with the rank
    # named, instead of holding the test's port forever.
    fleet.configure(watchdog_deadline=120.0)

    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.train.trainer import train_cbow

    local_shape = fleet.plan_mesh(len(jax.local_devices()), prefer_model=1)
    assert local_shape == (2, 1), local_shape
    ctx = make_mesh_context(local_shape, devices=jax.local_devices())

    paths, labels = _data(np.random.default_rng(0))
    common = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=0, mesh_ctx=ctx)

    ref = train_cbow(paths, labels, max_epochs=12, **common)

    ckpt = os.path.join(out_dir, "ck")   # NOT shared across processes
    train_cbow(paths, labels, max_epochs=6, checkpoint_dir=ckpt,
               checkpoint_every=3, **common)
    resumed = train_cbow(paths, labels, max_epochs=12, checkpoint_dir=ckpt,
                         resume=True, checkpoint_every=3, **common)

    assert not ref.stopped_early and not resumed.stopped_early
    # Only the coordinator's private dir may contain the file: rank 1's
    # resume can only have succeeded through the KV coordinator broadcast.
    has_file = os.path.exists(os.path.join(ckpt, "cbow_state.npz"))
    assert has_file == (jax.process_index() == 0), (
        f"process {jax.process_index()} checkpoint-file presence: {has_file}")
    np.testing.assert_allclose(resumed.w_ih, ref.w_ih, rtol=1e-5, atol=1e-7)

    # fetch_global on the locally-sharded table (fully addressable here —
    # the cross-process branch needs cross-process XLA; its routing is
    # unit-tested in tests/test_distributed.py).
    w_full = dist.fetch_global(resumed.params.w_ih)

    # --- sharded (orbax OCDBT) layout: SHARED dir, coordinator-written
    # (cpu_fleet: ranks hold identical replicated state; orbax's own
    # multi-process path needs cross-process XLA), KV barrier ordering,
    # local restore + reshard on every rank ---
    shared_ckpt = sys.argv[2]
    common_sharded = dict(common, checkpoint_dir=shared_ckpt,
                          checkpoint_every=3, checkpoint_layout="sharded")
    train_cbow(paths, labels, max_epochs=6, **common_sharded)
    from g2vec_tpu.train.checkpoint import _latest_sharded_dir

    layout_dir = _latest_sharded_dir(shared_ckpt)
    names = os.listdir(layout_dir)
    assert any(n == "ocdbt.process_0" for n in names), names
    # Coordinator-only write: no per-process shard dir for rank 1.
    assert not any(n == "ocdbt.process_1" for n in names), names
    resumed_sh = train_cbow(paths, labels, max_epochs=12, resume=True,
                            **common_sharded)
    assert not resumed_sh.stopped_early
    np.testing.assert_allclose(resumed_sh.w_ih, ref.w_ih,
                               rtol=1e-5, atol=1e-7)

    # --- sharded walker over the process-LOCAL mesh (tables row-sharded
    # over 'model', walkers DP over 'data'): every rank replicates the walk
    # and must land on the identical path set (mesh invariance).
    from g2vec_tpu.ops.graph import neighbor_table
    from g2vec_tpu.ops.walker import generate_path_set

    wrng = np.random.default_rng(3)
    n = 24
    src = wrng.integers(0, n, 140).astype(np.int32)
    dst = wrng.integers(0, n, 140).astype(np.int32)
    wts = wrng.random(140).astype(np.float32) + 0.1
    table = neighbor_table(src, dst, wts, n)
    wkey = jax.random.key(17)
    local = generate_path_set(table, wkey, len_path=5, reps=2)  # no mesh
    sharded = generate_path_set(table, wkey, len_path=5, reps=2,
                                mesh_ctx=ctx, shard_tables=True)
    assert sharded == local, (
        f"local-mesh sharded walk diverged: {len(sharded)} vs "
        f"{len(local)} paths")
    walker_digest = hashlib.sha256(b"".join(sorted(sharded))).hexdigest()

    # --- sharded NATIVE walks: each process samples its shard of the
    # walker axis with the C++ sampler; the packed rows cross the process
    # boundary over the KV transport and the union must be bit-identical
    # to the single-host native result on every process. NO per-process
    # availability gate here — the sharded call's own collective agreement
    # check raises the SAME RuntimeError on every process when any host
    # lacks the toolchain, and we call it FIRST so the local single-host
    # call can never be reached on one process only.
    try:
        both = dist.sharded_native_path_set(src, dst, wts, n, len_path=5,
                                            reps=2, seed=9)
        from g2vec_tpu.ops.host_walker import generate_path_set_native

        single = generate_path_set_native(src, dst, wts, n, len_path=5,
                                          reps=2, seed=9)
        assert both == single, (
            f"sharded native walk diverged: {len(both)} vs {len(single)}")
        native_digest = hashlib.sha256(b"".join(sorted(both))).hexdigest()
    except RuntimeError:
        native_digest = "native-unavailable"

    print(json.dumps({
        "process": jax.process_index(),
        "n_global_devices": len(jax.devices()),
        "resumed_digest": _digest(resumed.w_ih),
        "sharded_fetch_digest": _digest(w_full),
        "sharded_layout_digest": _digest(resumed_sh.w_ih),
        "walker_digest": walker_digest,
        "native_walker_digest": native_digest,
        "acc_val": resumed.acc_val,
    }))


if __name__ == "__main__":
    main()
