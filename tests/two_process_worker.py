"""Worker for the TRUE 2-process distributed test (not pytest-collected).

Launched twice by tests/test_multiprocess.py with G2VEC_COORDINATOR /
G2VEC_PROCESS_ID / G2VEC_NUM_PROCESSES in the env — the same plumbing a real
multi-host fleet launch uses (parallel/distributed.py). Each process gets a
PRIVATE scratch dir: the checkpoint is written only by process 0 into ITS
dir, so the resume on process 1 can only succeed through the
coordinator-broadcast restore path (train/checkpoint.py) — exactly the
silent-divergence hazard ADVICE.md round 1 flagged.

Prints one JSON line with cross-process-comparable digests; the parent test
asserts they are bit-identical between the two processes.
"""
import hashlib
import json
import os
import sys

import numpy as np


def _data(rng, n_paths=120, n_genes=40):
    """Planted-signal dataset (same shape as tests/test_checkpoint.py) so
    training converges instead of tripping the early stop."""
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        idx = rng.choice(half, size=5, replace=False) + (0 if lab == 0 else half)
        paths[i, idx] = 1
    return paths, labels


def _digest(arr) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def main() -> None:
    out_dir = sys.argv[1]          # PRIVATE per-process scratch dir
    from g2vec_tpu.parallel import distributed as dist

    dist.initialize()
    import jax

    assert jax.process_count() == 2, jax.process_count()
    ctx = dist.make_global_mesh((2, 2))

    from g2vec_tpu.train.trainer import train_cbow

    paths, labels = _data(np.random.default_rng(0))
    common = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=0, mesh_ctx=ctx)

    ref = train_cbow(paths, labels, max_epochs=12, **common)

    ckpt = os.path.join(out_dir, "ck")   # NOT shared across processes
    train_cbow(paths, labels, max_epochs=6, checkpoint_dir=ckpt,
               checkpoint_every=3, **common)
    resumed = train_cbow(paths, labels, max_epochs=12, checkpoint_dir=ckpt,
                         resume=True, checkpoint_every=3, **common)

    assert not ref.stopped_early and not resumed.stopped_early
    # Only the coordinator's private dir may contain the file.
    has_file = os.path.exists(os.path.join(ckpt, "cbow_state.npz"))
    assert has_file == (jax.process_index() == 0), (
        f"process {jax.process_index()} checkpoint-file presence: {has_file}")
    np.testing.assert_allclose(resumed.w_ih, ref.w_ih, rtol=1e-5, atol=1e-7)

    # fetch_global's cross-process branch: the model-sharded embedding table
    # spans devices owned by BOTH processes; pull it whole on each.
    w_full = dist.fetch_global(resumed.params.w_ih)

    # --- sharded (orbax OCDBT) layout: SHARED dir, per-process shard
    # files, no full-state gather (VERDICT round-1 #7) ---
    shared_ckpt = sys.argv[2]
    common_sharded = dict(common, checkpoint_dir=shared_ckpt,
                          checkpoint_every=3, checkpoint_layout="sharded")
    train_cbow(paths, labels, max_epochs=6, **common_sharded)
    from g2vec_tpu.train.checkpoint import _latest_sharded_dir

    layout_dir = _latest_sharded_dir(shared_ckpt)
    names = os.listdir(layout_dir)
    assert any(n == "ocdbt.process_0" for n in names), names
    assert any(n == "ocdbt.process_1" for n in names), names
    resumed_sh = train_cbow(paths, labels, max_epochs=12, resume=True,
                            **common_sharded)
    assert not resumed_sh.stopped_early
    np.testing.assert_allclose(resumed_sh.w_ih, ref.w_ih,
                               rtol=1e-5, atol=1e-7)

    # --- sharded walker across the true 2-process mesh (VERDICT r2 #6):
    # tables row-sharded over 'model', walkers DP over 'data', and the
    # packed path rows span devices BOTH processes own — the
    # fetch_global packed-mask path crossing a real process boundary.
    from g2vec_tpu.ops.graph import neighbor_table
    from g2vec_tpu.ops.walker import generate_path_set

    wrng = np.random.default_rng(3)
    n = 24
    src = wrng.integers(0, n, 140).astype(np.int32)
    dst = wrng.integers(0, n, 140).astype(np.int32)
    wts = wrng.random(140).astype(np.float32) + 0.1
    table = neighbor_table(src, dst, wts, n)
    wkey = jax.random.key(17)
    local = generate_path_set(table, wkey, len_path=5, reps=2)  # no mesh
    sharded = generate_path_set(table, wkey, len_path=5, reps=2,
                                mesh_ctx=ctx, shard_tables=True)
    assert sharded == local, (
        f"cross-process sharded walk diverged: {len(sharded)} vs "
        f"{len(local)} paths")
    walker_digest = hashlib.sha256(b"".join(sorted(sharded))).hexdigest()

    # --- sharded NATIVE walks (round 4): each process samples its shard
    # of the walker axis with the C++ sampler, rows are allgathered; the
    # union must be bit-identical to the single-host native result on
    # every process. NO per-process availability gate here — the sharded
    # call's own collective agreement check raises the SAME RuntimeError
    # on every process when any host lacks the toolchain (a local gate
    # could desynchronize the collectives), and we call it FIRST so the
    # local single-host call can never be reached on one process only.
    try:
        both = dist.sharded_native_path_set(src, dst, wts, n, len_path=5,
                                            reps=2, seed=9)
        from g2vec_tpu.ops.host_walker import generate_path_set_native

        single = generate_path_set_native(src, dst, wts, n, len_path=5,
                                          reps=2, seed=9)
        assert both == single, (
            f"sharded native walk diverged: {len(both)} vs {len(single)}")
        native_digest = hashlib.sha256(b"".join(sorted(both))).hexdigest()
    except RuntimeError:
        native_digest = "native-unavailable"

    print(json.dumps({
        "process": jax.process_index(),
        "n_global_devices": len(jax.devices()),
        "resumed_digest": _digest(resumed.w_ih),
        "sharded_fetch_digest": _digest(w_full),
        "sharded_layout_digest": _digest(resumed_sh.w_ih),
        "walker_digest": walker_digest,
        "native_walker_digest": native_digest,
        "acc_val": resumed.acc_val,
    }))


if __name__ == "__main__":
    main()
