"""End-to-end kill-and-resume: the CLI supervisor SIGKILLs its child at a
chosen epoch via the fault plan (no Python cleanup — the shape of a real
TPU preemption), restarts it with --resume, and the final vectors must be
bit-identical to an uninterrupted seeded run. Slow: three full CLI
pipeline runs, each a fresh interpreter + jax import."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _cli(tsv_paths, result, ckpt, metrics=None, extra=()):
    args = [sys.executable, "-m", "g2vec_tpu",
            tsv_paths["expression"], tsv_paths["clinical"],
            tsv_paths["network"], result,
            "-p", "8", "-r", "2", "-s", "16", "-e", "30", "-l", "0.01",
            "-n", "5", "--seed", "0", "--compute-dtype", "float32",
            "--platform", "cpu",
            "--checkpoint-dir", ckpt, "--checkpoint-every", "3"]
    if metrics:
        args += ["--metrics-jsonl", metrics]
    return args + list(extra)


def test_sigkill_at_epoch_resumes_bit_identical(tsv_paths, tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("G2VEC_FAULT_PLAN", None)
    env.pop("G2VEC_FAULT_STATE", None)

    clean = subprocess.run(
        _cli(tsv_paths, str(tmp_path / "a"), str(tmp_path / "cka")),
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert clean.returncode == 0, clean.stderr[-1500:]

    mj = str(tmp_path / "m.jsonl")
    supervised = subprocess.run(
        _cli(tsv_paths, str(tmp_path / "b"), str(tmp_path / "ckb"),
             metrics=mj,
             extra=["--supervise", "--supervise-backoff", "0.01",
                    "--fault-plan", "stage=train,epoch=6,kind=sigkill"]),
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert supervised.returncode == 0, supervised.stderr[-1500:]
    assert "[supervisor] attempt 0 failed" in supervised.stderr

    # Final vectors bit-identical to the uninterrupted run.
    for suffix in ("_vectors.txt", "_lgroups.txt", "_biomarkers.txt"):
        with open(str(tmp_path / "a") + suffix, "rb") as fa, \
                open(str(tmp_path / "b") + suffix, "rb") as fb:
            assert fa.read() == fb.read(), suffix

    # The metrics stream carries the recovery story end to end: the first
    # attempt's records, the supervisor's retry/resume, the resumed
    # attempt's records (appended, not truncated), and the final done.
    with open(mj) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    names = [e["event"] for e in events]
    assert "retry" in names and "resume" in names
    assert names.count("done") == 1
    retry = next(e for e in events if e["event"] == "retry")
    assert retry["classified"] == "retryable"       # rc=-9: signal exit
    # The resumed attempt starts at the checkpoint, not epoch 0.
    idx = names.index("resume")
    resumed_epochs = [e["step"] for e in events[idx + 1:]
                      if e["event"] == "epoch"]
    assert resumed_epochs and resumed_epochs[0] > 0
