"""L4 trainer tests: learning on separable data, early-stop semantics
(previous-epoch weights, ref: G2Vec.py:276-283), and numeric parity of one
training step against a NumPy reimplementation of the same model."""
import numpy as np
import pytest

from g2vec_tpu.train import train_cbow


def _separable_paths(rng, n_paths=400, n_genes=60, flip=0.0):
    """Multi-hot paths: label-0 paths draw from the first half of genes,
    label-1 from the second half."""
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        lo = 0 if lab == 0 else half
        k = rng.integers(3, 10)
        idx = rng.choice(half, size=k, replace=False) + lo
        paths[i, idx] = 1
        if rng.random() < flip:
            labels[i] = 1 - labels[i]
    return paths, labels


def test_trainer_learns_separable_data(rng):
    paths, labels = _separable_paths(rng)
    res = train_cbow(paths, labels, hidden=16, learning_rate=0.05,
                     max_epochs=200, compute_dtype="float32", seed=1)
    assert res.acc_val >= 0.95
    assert res.w_ih.shape == (60, 16)
    assert res.w_ih.dtype == np.float32


def test_early_stop_returns_previous_epoch_weights(rng):
    # Noisy labels force a val-accuracy dip well before max_epochs.
    paths, labels = _separable_paths(rng, flip=0.25)
    res = train_cbow(paths, labels, hidden=8, learning_rate=0.05,
                     max_epochs=300, compute_dtype="float32", seed=3)
    assert res.stopped_early, "expected an early stop on noisy data"
    assert res.stop_epoch == len(res.history) - 2
    # Reported accuracies are the PREVIOUS epoch's (ref: G2Vec.py:278).
    assert res.acc_val == res.history[-2]["acc_val"]
    assert res.acc_tr == res.history[-2]["acc_tr"]
    # The returned W_ih equals what training for exactly stop_epoch+1 epochs
    # yields — i.e. the dip epoch's update was discarded.
    res2 = train_cbow(paths, labels, hidden=8, learning_rate=0.05,
                      max_epochs=res.stop_epoch + 1, compute_dtype="float32",
                      seed=3)
    np.testing.assert_array_equal(res.w_ih, res2.w_ih)


def test_on_epoch_callback_and_history(rng):
    paths, labels = _separable_paths(rng, n_paths=100, n_genes=20)
    seen = []
    res = train_cbow(paths, labels, hidden=4, learning_rate=0.05,
                     max_epochs=5, compute_dtype="float32", seed=0,
                     on_epoch=lambda e, av, at, s: seen.append((e, av, at)))
    assert len(seen) == len(res.history)
    assert [s[0] for s in seen] == [h["epoch"] for h in res.history]


def test_trainer_rejects_degenerate_split():
    paths = np.zeros((1, 4), dtype=np.int8)
    with pytest.raises(ValueError, match="at least 2 paths"):
        train_cbow(paths, np.zeros(1, np.int32), hidden=2,
                   learning_rate=0.01, max_epochs=1)


def test_one_step_matches_numpy_adam(rng):
    """One full-batch Adam step vs a NumPy transcription of the same math."""
    import jax
    import jax.numpy as jnp
    import optax

    from g2vec_tpu.models.cbow import forward, init_params

    n, g, h = 32, 12, 4
    x = (rng.random((n, g)) < 0.3).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32).reshape(-1, 1)
    params = init_params(jax.random.key(0), g, h)
    lr = 0.01

    # --- jax step ---
    def loss_fn(p):
        return jnp.mean(optax.sigmoid_binary_cross_entropy(
            forward(p, jnp.asarray(x), jnp.float32), jnp.asarray(y)))

    grads = jax.grad(loss_fn)(params)
    tx = optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8)
    updates, _ = tx.update(grads, tx.init(params), params)
    new_params = optax.apply_updates(params, updates)

    # --- numpy step ---
    w_ih = np.asarray(params.w_ih, np.float64)
    w_ho = np.asarray(params.w_ho, np.float64)
    logits = x @ w_ih @ w_ho
    p_sig = 1.0 / (1.0 + np.exp(-logits))
    dlogits = (p_sig - y) / n
    g_ho = (x @ w_ih).T @ dlogits
    g_ih = x.T @ (dlogits @ w_ho.T)
    # Adam step 1: mhat = g/(1-b1), vhat = g^2/(1-b2) -> update = -lr*mhat/(sqrt(vhat)+eps)
    for w, grad, ours in ((w_ih, g_ih, new_params.w_ih), (w_ho, g_ho, new_params.w_ho)):
        mhat = grad
        vhat = grad * grad
        ref = w - lr * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(np.asarray(ours), ref, rtol=2e-4, atol=2e-6)


def test_unknown_dtypes_rejected(rng):
    paths, labels = _separable_paths(rng, n_paths=8)
    with pytest.raises(ValueError, match="param_dtype"):
        train_cbow(paths, labels, hidden=4, learning_rate=0.01, max_epochs=1,
                   compute_dtype="float32", param_dtype="float16")
    with pytest.raises(ValueError, match="compute_dtype"):
        train_cbow(paths, labels, hidden=4, learning_rate=0.01, max_epochs=1,
                   compute_dtype="fp8")


def test_config_validates_param_dtype():
    from g2vec_tpu.config import G2VecConfig

    cfg = G2VecConfig(param_dtype="float16", epoch=1)
    with pytest.raises(ValueError, match="param_dtype"):
        cfg.validate()
    cfg2 = G2VecConfig(walker_hbm_budget=-1)
    with pytest.raises(ValueError, match="walker_hbm_budget"):
        cfg2.validate()


def test_history_acc_tr_matches_direct_eval(rng):
    """The eval-train fold reports epoch i's train accuracy from epoch
    i+1's grad forward (backfilled); every history row must still equal a
    direct evaluation at that epoch's post-update weights."""
    paths, labels = _separable_paths(rng, n_paths=120, n_genes=20)
    n_epochs = 8

    full = train_cbow(paths, labels, hidden=4, learning_rate=0.05,
                      max_epochs=n_epochs, compute_dtype="float32", seed=0)
    assert len(full.history) <= n_epochs

    # Reconstruct the trainer's own split (same seeded permutation).
    rng_np = np.random.default_rng(0)
    perm = rng_np.permutation(paths.shape[0])
    pivot = int(paths.shape[0] * 0.8)
    xtr = paths[perm[:pivot]].astype(np.float32)
    ytr = labels[perm[:pivot]].astype(np.float32).reshape(-1, 1)

    for k, row in enumerate(full.history):
        # Post-update weights after exactly k+1 epochs == a run capped
        # there; its snapshot (returned w_ih, genes sliced) is the
        # post-update table when no dip occurred.
        partial = train_cbow(paths, labels, hidden=4, learning_rate=0.05,
                             max_epochs=k + 1, compute_dtype="float32",
                             seed=0)
        if partial.stopped_early:
            break
        w_ho = np.asarray(partial.params.w_ho, np.float32)
        logits = (xtr @ partial.w_ih) @ w_ho
        acc = float(((logits > 0).astype(np.float32) == ytr).mean())
        np.testing.assert_allclose(row["acc_tr"], acc, atol=1e-6)


def test_history_invariant_to_chunk_size(rng, tmp_path):
    """The fold's riskiest paths are the chunk-boundary acc_tr handoff
    (body i==0 discards its grad-forward accuracy; the previous chunk's
    post-loop backfill must have recorded it) and the dip-epoch backfill.
    Chunked (checkpoint_every=3 => chunk 3) and unchunked runs must
    produce identical per-epoch history — including an early-stop run
    whose dip lands mid-chunk.

    float32: bitwise equal. bfloat16: the backfill's standalone eval
    forward and the chunk body's grad forward are distinct XLA programs
    that may round differently in low bits, so a borderline logit can
    flip one sample's prediction. Accuracies quantize at 1/n_rows
    (1/96 train, 1/24 val here), so the acc tolerance allows ONE flipped
    sample per split (atol 0.05) — a real handoff bug (wrong epoch's
    value) shifts accuracies by whole learning-curve steps, far above
    that. Loss is continuous: atol 1e-3. Stop bookkeeping must match
    exactly."""
    cases = [
        (_separable_paths(rng, n_paths=120, n_genes=20), 10, 0, "float32"),
        (_separable_paths(rng, flip=0.25), 300, 3, "float32"),  # early-stops
        (_separable_paths(rng, n_paths=120, n_genes=20), 10, 0, "bfloat16"),
    ]
    for (paths, labels), max_epochs, seed, dtype in cases:
        one = train_cbow(paths, labels, hidden=8, learning_rate=0.05,
                         max_epochs=max_epochs, compute_dtype=dtype,
                         seed=seed)
        ck = str(tmp_path / f"ck{seed}-{dtype}")
        many = train_cbow(paths, labels, hidden=8, learning_rate=0.05,
                          max_epochs=max_epochs, compute_dtype=dtype,
                          seed=seed, checkpoint_dir=ck, checkpoint_every=3)
        assert one.stopped_early == many.stopped_early
        assert one.stop_epoch == many.stop_epoch
        assert len(one.history) == len(many.history)
        exact = dtype == "float32"
        for ha, hb in zip(one.history, many.history):
            assert ha["epoch"] == hb["epoch"]
            if exact:
                np.testing.assert_array_equal(ha["acc_val"], hb["acc_val"])
                np.testing.assert_array_equal(ha["acc_tr"], hb["acc_tr"])
                np.testing.assert_array_equal(ha["loss"], hb["loss"])
            else:
                np.testing.assert_allclose(ha["acc_val"], hb["acc_val"], atol=0.05)
                np.testing.assert_allclose(ha["acc_tr"], hb["acc_tr"], atol=0.05)
                np.testing.assert_allclose(ha["loss"], hb["loss"], atol=1e-3)
        if exact:
            np.testing.assert_array_equal(one.w_ih, many.w_ih)
        else:
            np.testing.assert_allclose(one.w_ih, many.w_ih, atol=1e-3)
