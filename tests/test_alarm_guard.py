"""tools/watchdog.py bounds every profiler/bench stage (alarm_guard is a
shim over it); its contract — raise on overrun, leak nothing on
completion, nest cleanly, never depend on SIGALRM — must hold or a
battery stage inherits a stray deadline. The SIGALRM independence is the
point of the replacement: the old guard's signal handler was deferred
indefinitely by a blocked native call (the r5 kmeans-compile wedge)."""
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.alarm_guard import alarm  # noqa: E402
from tools.watchdog import WatchdogTimeout, watchdog  # noqa: E402


def _busy_wait(seconds):
    # Injected timeouts land at bytecode boundaries; a chunked wait gives
    # the watcher one every ~20ms (a single long time.sleep would defer
    # the raise to its end — exactly the blocked-native-call shape the
    # hard-mode test covers separately).
    t0 = time.monotonic()
    while time.monotonic() - t0 < seconds:
        time.sleep(0.02)


def test_raises_with_message_on_overrun():
    with pytest.raises(TimeoutError, match="too slow"):
        with alarm(1, "too slow"):
            _busy_wait(5)


def test_no_timeout_leaks_after_completion():
    with alarm(1, "unused"):
        pass
    # The watcher is cancelled: sleeping past the old deadline must not
    # raise a stale injected timeout.
    time.sleep(1.3)


def test_sigalrm_handler_untouched():
    # The replacement must not own the process-wide SIGALRM timer at all —
    # coexisting with code that does (bench child stages) is the contract.
    prev = signal.getsignal(signal.SIGALRM)
    with pytest.raises(TimeoutError):
        with alarm(1, "x"):
            _busy_wait(5)
    assert signal.getsignal(signal.SIGALRM) is prev


def test_nested_regions_inner_wins_outer_survives():
    with pytest.raises(TimeoutError, match="inner"):
        with alarm(30, "outer"):
            with alarm(1, "inner"):
                _busy_wait(5)


def test_outer_deadline_survives_clean_inner_region():
    # Each region owns its own watcher thread: an inner region that
    # completes must not disarm the outer bound.
    with pytest.raises(TimeoutError, match="outer"):
        with alarm(2, "outer"):
            with alarm(30, "inner"):
                pass            # completes instantly
            _busy_wait(10)      # outer must still fire (~2s)


def test_guards_non_main_threads():
    # SIGALRM could never do this: the guard must bound a worker thread
    # (the overlap scheduler's background tasks run there).
    caught = []

    def body():
        try:
            with watchdog(1, "worker overrun"):
                _busy_wait(5)
        except TimeoutError as e:
            caught.append(str(e))

    t = threading.Thread(target=body)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    assert caught == ["worker overrun"]


def test_timeout_is_watchdog_subclass():
    with pytest.raises(WatchdogTimeout):
        with watchdog(1, "typed"):
            _busy_wait(5)


def test_hard_mode_exits_124_on_wedged_native_call():
    # A body blocked in a native call never reaches a bytecode boundary,
    # so injection cannot land; hard=True must os._exit(124) the process
    # (the bounded-subprocess escape the r5 window needed). A subprocess
    # sleeping in C stands in for the wedged XLA compile.
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from tools.watchdog import watchdog\n"
        "import subprocess\n"
        "with watchdog(1, 'wedged', grace=1, hard=True):\n"
        "    # DEVNULL stdio: the orphaned grandchild must not hold the\n"
        "    # parent test's capture pipes open past the hard exit.\n"
        "    subprocess.run(['sleep', '15'], stdout=subprocess.DEVNULL,\n"
        "                   stderr=subprocess.DEVNULL)\n"
        "print('unreachable')\n" % REPO)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=30)
    assert proc.returncode == 124, (proc.returncode, proc.stderr[-400:])
    assert "wedged" in proc.stderr
    assert "unreachable" not in proc.stdout


def test_invalid_seconds_rejected():
    with pytest.raises(ValueError, match="seconds"):
        with watchdog(0, "zero"):
            pass
