"""tools/alarm_guard.py bounds every profiler stage; its contract —
raise on overrun, leak nothing on completion, restore the handler —
must hold or a battery stage inherits a stray alarm."""
import os
import signal
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.alarm_guard import alarm  # noqa: E402


def test_raises_with_message_on_overrun():
    with pytest.raises(TimeoutError, match="too slow"):
        with alarm(1, "too slow"):
            time.sleep(5)


def test_no_alarm_leaks_after_completion():
    prev = signal.getsignal(signal.SIGALRM)
    with alarm(1, "unused"):
        pass
    # The pending alarm is cancelled and the handler restored: sleeping
    # past the old deadline must not raise.
    time.sleep(1.2)
    assert signal.getsignal(signal.SIGALRM) is prev


def test_handler_restored_after_overrun():
    prev = signal.getsignal(signal.SIGALRM)
    with pytest.raises(TimeoutError):
        with alarm(1, "x"):
            time.sleep(5)
    assert signal.getsignal(signal.SIGALRM) is prev


def test_nested_regions_inner_wins_then_outer_restored():
    # The profilers use sequential regions, but nesting must at least
    # not corrupt the outer guard's handler bookkeeping.
    prev = signal.getsignal(signal.SIGALRM)
    with pytest.raises(TimeoutError, match="inner"):
        with alarm(30, "outer"):
            with alarm(1, "inner"):
                time.sleep(5)
    assert signal.getsignal(signal.SIGALRM) is prev


def test_outer_deadline_survives_clean_inner_region():
    # SIGALRM is one process-wide timer: an inner region that completes
    # must NOT disarm the outer bound — it re-arms the remaining time.
    with pytest.raises(TimeoutError, match="outer"):
        with alarm(2, "outer"):
            with alarm(30, "inner"):
                pass            # completes instantly
            time.sleep(10)      # outer must still fire (~2s)
