"""Worker for multi-process SHARDED streaming runs (not pytest-collected).

Launched R times by tests/test_shard.py and bench.py ``--_shard_scale``
with G2VEC_COORDINATOR / G2VEC_PROCESS_ID / G2VEC_NUM_PROCESSES in the
env — the same plumbing a real fleet launch uses. argv[1] is a JSON file
of G2VecConfig field overrides (the input paths, --graph-shards /
--embed-shards, the streaming knobs); the worker runs the full pipeline
and prints ONE JSON line: val-ACC, biomarkers, output files, path count,
and the process's peak RSS (ru_maxrss KB) — the number the scale-out
exists to bound.
"""
import json
import os
import resource
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    with open(sys.argv[1]) as f:
        overrides = json.load(f)

    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.pipeline import run

    cfg = G2VecConfig(**overrides)
    res = run(cfg, console=lambda s: None)
    print(json.dumps({
        "process": int(os.environ.get("G2VEC_PROCESS_ID", "0")),
        "acc_val": float(res.acc_val),
        "biomarkers": list(res.biomarkers),
        "n_paths": int(res.n_paths),
        "n_genes": int(res.n_genes),
        "n_edges": int(res.n_edges),
        "output_files": list(res.output_files),
        "rss_kb": int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss),
        "edge_stats": dict(res.edge_stats),
    }))


if __name__ == "__main__":
    main()
