"""L5 stats tests: t-scores vs a straight NumPy port of the reference
formulas (G2Vec.py:138-157), minmax guard, d-scores."""
import numpy as np
import pytest

from g2vec_tpu.ops.stats import dscores, minmax, tscores


def _ref_tstat(x, y):
    """Direct NumPy transcription of the reference formula semantics."""
    from math import sqrt

    s0, s1 = x.std(ddof=1), y.std(ddof=1)
    n0, n1 = len(x), len(y)
    d1 = sqrt(((n0 - 1) * s0 * s0 + (n1 - 1) * s1 * s1) / (n0 + n1 - 2))
    d2 = sqrt(1.0 / n0 + 1.0 / n1)
    if d1 > 0 and d2 > 0:
        return abs((x.mean() - y.mean()) / d1 / d2)
    return 0.0


def test_tscores_match_reference_formula(rng):
    g = rng.normal(size=(13, 7)).astype(np.float32)
    p = rng.normal(loc=0.5, size=(9, 7)).astype(np.float32)
    ours = np.asarray(tscores(g, p))
    expected = [_ref_tstat(g[:, i], p[:, i]) for i in range(7)]
    np.testing.assert_allclose(ours, expected, rtol=1e-5)


def test_tscores_constant_gene_is_zero(rng):
    g = np.ones((10, 3), dtype=np.float32)
    p = np.ones((8, 3), dtype=np.float32)
    np.testing.assert_array_equal(np.asarray(tscores(g, p)), 0.0)


def test_tscores_against_scipy(rng):
    scipy_stats = pytest.importorskip("scipy.stats")
    g = rng.normal(size=(20, 5)).astype(np.float32)
    p = rng.normal(loc=1.0, size=(15, 5)).astype(np.float32)
    ours = np.asarray(tscores(g, p))
    ref = np.abs(scipy_stats.ttest_ind(g, p, axis=0, equal_var=True).statistic)
    np.testing.assert_allclose(ours, ref, rtol=1e-4)


def test_minmax_basic_and_guard():
    s = np.array([2.0, 4.0, 3.0], dtype=np.float32)
    np.testing.assert_allclose(np.asarray(minmax(s)), [0.0, 1.0, 0.5], atol=1e-6)
    const = np.full(4, 7.0, dtype=np.float32)
    out = np.asarray(minmax(const))
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out, 0.0)


def test_dscores(rng):
    e = rng.normal(size=(6, 4)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(dscores(e)), np.linalg.norm(e, axis=1), rtol=1e-5)
