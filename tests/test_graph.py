"""L3 PCC adjacency tests vs a straightforward NumPy reference
(semantics of construct_adjMat/compute_PCC, G2Vec.py:354-391)."""
import numpy as np

from g2vec_tpu.ops.graph import build_adjacency, edge_weights


def _np_pcc(a: np.ndarray, b: np.ndarray) -> float:
    """Population-normalized Pearson r, 0.0 on zero std (ref: G2Vec.py:354-368)."""
    sa, sb = a.std(), b.std()
    if sa == 0.0 or sb == 0.0:
        return 0.0
    return float(np.mean((a - a.mean()) / sa * (b - b.mean()) / sb))


def test_edge_weights_match_numpy(rng):
    expr = rng.standard_normal((20, 8)).astype(np.float32)
    src = np.array([0, 1, 2, 3, 7], dtype=np.int32)
    dst = np.array([1, 0, 5, 4, 6], dtype=np.int32)
    w = np.asarray(edge_weights(expr, src, dst))
    for k in range(src.size):
        expected = abs(_np_pcc(expr[:, src[k]], expr[:, dst[k]]))
        np.testing.assert_allclose(w[k], expected, rtol=1e-5, atol=1e-6)


def test_degenerate_gene_gets_zero_weight(rng):
    expr = rng.standard_normal((10, 4)).astype(np.float32)
    expr[:, 2] = 3.14  # constant gene -> zero std -> PCC 0 everywhere
    src = np.array([2, 0], dtype=np.int32)
    dst = np.array([1, 2], dtype=np.int32)
    w = np.asarray(edge_weights(expr, src, dst))
    assert w[0] == 0.0 and w[1] == 0.0


def test_adjacency_directed_and_thresholded(rng):
    n = 6
    s = rng.standard_normal(30).astype(np.float32)
    expr = rng.standard_normal((30, n)).astype(np.float32) * 0.1
    expr[:, 0] += s   # genes 0 and 1 strongly correlated
    expr[:, 1] += s
    src = np.array([0, 2], dtype=np.int32)
    dst = np.array([1, 3], dtype=np.int32)
    adj = np.asarray(build_adjacency(expr, src, dst, n, threshold=0.5))
    assert adj[0, 1] > 0.5            # strong edge kept, weight = |PCC|
    assert adj[1, 0] == 0.0           # NOT symmetrized (ref: G2Vec.py:390)
    assert adj[2, 3] == 0.0           # weak edge dropped by strict '>'
    assert np.count_nonzero(adj) == 1


def test_strict_threshold_boundary(rng):
    # |PCC| == 1 edge with threshold 1.0-eps kept; with exactly |PCC| cut off.
    expr = np.zeros((8, 2), dtype=np.float32)
    expr[:, 0] = np.arange(8)
    expr[:, 1] = 2.0 * np.arange(8) + 1.0     # perfectly correlated
    src = np.array([0], dtype=np.int32)
    dst = np.array([1], dtype=np.int32)
    w = np.asarray(edge_weights(expr, src, dst))
    np.testing.assert_allclose(w[0], 1.0, rtol=1e-6)
    adj = np.asarray(build_adjacency(expr, src, dst, 2, threshold=1.0))
    assert adj[0, 1] == 0.0           # strict '>' (ref: G2Vec.py:389)
