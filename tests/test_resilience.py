"""Fault-matrix tests for the resilience subsystem (tier-1 subset).

Every seam the fault injector exposes is driven end to end here: stage
raise, train-loop crash, checkpoint corruption (manifest detection +
keep-previous fallback), and native-load failure (graceful degradation).
For each, the supervised run must complete with outputs BYTE-IDENTICAL to
an uninterrupted run at the same seed, and the metrics JSONL must carry
the supervisor's retry/resume events. The SIGKILL + child-process
supervisor path is the slow-marked e2e test (test_supervisor_e2e.py).
"""
import glob
import json
import os

import numpy as np
import pytest

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.resilience import faults
from g2vec_tpu.resilience.supervisor import (RetryPolicy, classify_child,
                                             classify_exception, supervise,
                                             _scrub_supervisor_argv)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Fault state is process-global: every test starts and ends clean."""
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _cfg(tsv_paths, tmp_path, **overrides):
    defaults = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out"),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        kmeans_iters=50, seed=0,
    )
    defaults.update(overrides)
    return G2VecConfig(**defaults)


_FAST = RetryPolicy(max_retries=3, backoff_base=0.0, backoff_max=0.0,
                    jitter=0.0)
_quiet = lambda s: None  # noqa: E731
_nosleep = lambda s: None  # noqa: E731


def _read_events(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _assert_outputs_identical(run_a, run_b):
    assert len(run_a.output_files) == len(run_b.output_files) == 3
    for fa, fb in zip(run_a.output_files, run_b.output_files):
        with open(fa, "rb") as a, open(fb, "rb") as b:
            assert a.read() == b.read(), f"{fa} differs from {fb}"


# ---------------------------------------------------------------- units

def test_plan_parsing_rejects_bad_specs():
    with pytest.raises(faults.FaultPlanError, match="seam"):
        faults.parse_plan("stage=nonsense")
    with pytest.raises(faults.FaultPlanError, match="kind"):
        faults.parse_plan("stage=train,kind=explode")
    with pytest.raises(faults.FaultPlanError, match="key"):
        faults.parse_plan("stage=train,when=now")
    with pytest.raises(faults.FaultPlanError, match="stage"):
        faults.parse_plan("kind=crash")
    with pytest.raises(faults.FaultPlanError, match="non-numeric"):
        faults.parse_plan("stage=train,epoch=soon")
    entries = faults.parse_plan(
        "stage=train,epoch=40,kind=crash; stage=save,kind=sigkill,times=2")
    assert [(e.stage, e.epoch, e.kind, e.times) for e in entries] == \
        [("train", 40, "crash", 1), ("save", None, "sigkill", 2)]
    # Config validation surfaces plan errors at parse time.
    with pytest.raises(ValueError, match="seam"):
        G2VecConfig(fault_plan="stage=nope").validate()


def test_fault_point_is_noop_without_plan():
    faults.fault_point("load")
    faults.fault_point("train", epoch=5)


def test_crash_fires_once_and_epoch_gates():
    faults.install_plan("stage=train,epoch=10,kind=crash")
    faults.fault_point("train", epoch=9)          # below the gate
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("train", epoch=10)
    faults.fault_point("train", epoch=11)         # already fired


def test_stall_and_fatal_kinds():
    faults.install_plan("stage=paths,kind=stall,seconds=0")
    with pytest.raises(faults.InjectedFault, match="stall"):
        faults.fault_point("paths")
    faults.install_plan("stage=paths,kind=fatal")
    faults._fired.clear()
    with pytest.raises(faults.InjectedFatal):
        faults.fault_point("paths")


def test_skip_defers_firing():
    faults.install_plan("stage=save,kind=crash,skip=2")
    faults.fault_point("save")
    faults.fault_point("save")
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("save")


def test_state_file_persists_fired_entries(tmp_path, monkeypatch):
    state = str(tmp_path / "fault-state.json")
    monkeypatch.setenv(faults.ENV_STATE, state)
    faults.install_plan("stage=load,kind=crash")
    with pytest.raises(faults.InjectedFault):
        faults.fault_point("load")
    # A "restarted process": fresh module state, same state file.
    faults._reset_for_tests()
    faults.install_plan("stage=load,kind=crash")
    faults.fault_point("load")                     # fired-state honored
    assert json.load(open(state)) == {"load:None:crash": 1}


def test_classification_table():
    assert classify_exception(faults.InjectedFault("x")) == "retryable"
    assert classify_exception(faults.InjectedFatal("x")) == "fatal"
    assert classify_exception(RuntimeError("preempted")) == "retryable"
    assert classify_exception(MemoryError()) == "retryable"
    assert classify_exception(OSError("io wobble")) == "retryable"
    assert classify_exception(ValueError("label must be 0 or 1")) == "fatal"
    assert classify_exception(
        ValueError("RESOURCE_EXHAUSTED: hbm oom")) == "retryable"
    assert classify_exception(FileNotFoundError("gone")) == "fatal"
    assert classify_exception(TypeError("bad arg")) == "fatal"
    # Child-process classification mirrors it from rc + stderr.
    assert classify_child(-9, "") == "retryable"           # SIGKILL
    assert classify_child(1, "ValueError: bad label") == "fatal"
    assert classify_child(1, "RuntimeError: preempted") == "retryable"
    assert classify_child(1, "InjectedFault: injected crash") == "retryable"
    assert classify_child(1, "") == "retryable"


def test_scrub_supervisor_argv():
    argv = ["e", "c", "n", "r", "--supervise", "--supervise-retries", "5",
            "--supervise-backoff=0.1", "--seed", "3"]
    assert _scrub_supervisor_argv(argv) == ["e", "c", "n", "r", "--seed", "3"]


def test_metrics_writer_append_mode(tmp_path):
    from g2vec_tpu.utils.metrics import MetricsWriter

    path = str(tmp_path / "m.jsonl")
    with MetricsWriter(path) as m:
        m.emit("a")
    with MetricsWriter(path, append=True) as m:
        m.emit("b")
    assert [e["event"] for e in _read_events(path)] == ["a", "b"]
    with MetricsWriter(path) as m:      # default mode truncates
        m.emit("c")
    assert [e["event"] for e in _read_events(path)] == ["c"]


# ------------------------------------------------- fault matrix (pipeline)

def test_supervised_recovers_from_stage_crash(tsv_paths, tmp_path):
    """Seam 1 — stage-boundary raise: retried, resumed, byte-identical."""
    from g2vec_tpu.pipeline import run

    clean = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "a")),
                console=_quiet)
    mj = str(tmp_path / "m.jsonl")
    cfg = _cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "b"),
               metrics_jsonl=mj, fault_plan="stage=paths,kind=crash")
    recovered = supervise(cfg, policy=_FAST, console=_quiet, sleep=_nosleep)
    _assert_outputs_identical(clean, recovered)
    events = [e["event"] for e in _read_events(mj)]
    assert "retry" in events and "resume" in events and "done" in events
    retry = next(e for e in _read_events(mj) if e["event"] == "retry")
    assert retry["classified"] == "retryable"
    assert "injected crash at seam=paths" in retry["error"]


def test_supervised_recovers_from_train_loop_crash(tsv_paths, tmp_path):
    """Seam 2 — crash mid-epoch-loop: the retry resumes from the last
    checkpoint (epochs before it are NOT redone) and the final outputs are
    byte-identical to an uninterrupted checkpointed run."""
    from g2vec_tpu.pipeline import run

    # learningRate=0.002 trains ~10 epochs before the early stop at this
    # scale (under the padding-invariant init, models/cbow.py) — enough
    # room for two checkpoint intervals before the crash.
    clean = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "a"),
                     learningRate=0.002, checkpoint_dir=str(tmp_path / "cka"),
                     checkpoint_every=3),
                console=_quiet)
    assert clean.train_history[-1]["epoch"] >= 7, "config trains too briefly"
    mj = str(tmp_path / "m.jsonl")
    cfg = _cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "b"),
               learningRate=0.002, checkpoint_dir=str(tmp_path / "ckb"),
               checkpoint_every=3, metrics_jsonl=mj,
               fault_plan="stage=train,epoch=6,kind=crash")
    recovered = supervise(cfg, policy=_FAST, console=_quiet, sleep=_nosleep)
    _assert_outputs_identical(clean, recovered)
    events = _read_events(mj)
    assert [e["event"] for e in events].count("retry") == 1
    # The resumed attempt's epoch records start at the checkpoint, not 0:
    # completed epochs are not redone. (seq restarts per attempt, so split
    # the stream at the resume event's file position, not by seq.)
    idx = events.index(next(e for e in events if e["event"] == "resume"))
    resumed_epochs = [e["step"] for e in events[idx + 1:]
                      if e["event"] == "epoch"]
    assert resumed_epochs and resumed_epochs[0] == 6   # ckpt at epoch 5


def test_supervised_survives_corrupt_latest_checkpoint(tsv_paths, tmp_path):
    """Seam 3 — corrupted checkpoint: the torn write is detected by
    manifest verification on resume, the previous numbered generation is
    used (with a warning), and the outputs still match bit-for-bit."""
    from g2vec_tpu.pipeline import run

    clean = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "a"),
                     learningRate=0.002, checkpoint_dir=str(tmp_path / "cka"),
                     checkpoint_every=3),
                console=_quiet)
    mj = str(tmp_path / "m.jsonl")
    # skip=1: the SECOND save (epoch 5) is silently corrupted, then the
    # crash at epoch 6 forces a resume that must detect it and fall back
    # to the good epoch-2 generation.
    cfg = _cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "b"),
               learningRate=0.002, checkpoint_dir=str(tmp_path / "ckb"),
               checkpoint_every=3, metrics_jsonl=mj,
               fault_plan="stage=checkpoint_finalize,kind=corrupt,skip=1;"
                          "stage=train,epoch=6,kind=crash")
    with pytest.warns(RuntimeWarning, match="integrity"):
        recovered = supervise(cfg, policy=_FAST, console=_quiet,
                              sleep=_nosleep)
    _assert_outputs_identical(clean, recovered)
    events = _read_events(mj)
    idx = events.index(next(e for e in events if e["event"] == "resume"))
    resumed_epochs = [e["step"] for e in events[idx + 1:]
                      if e["event"] == "epoch"]
    assert resumed_epochs and resumed_epochs[0] == 3   # prev ckpt: epoch 2


def test_checkpoint_write_fault_crashes_before_write(tmp_path):
    """The ``checkpoint_write`` seam fires BEFORE the savez: a crash
    there must leave no partial checkpoint behind (the atomic-write
    contract starts at the seam), and a prior good generation must
    survive untouched for the resume to use."""
    from g2vec_tpu.train import checkpoint as ck

    d = str(tmp_path)
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    opt = {"m": np.zeros((2, 3), np.float32)}
    ck.save_state(d, params, opt, params, 4, 0.5, 0.6)
    faults.install_plan("stage=checkpoint_write,kind=crash")
    try:
        with pytest.raises(faults.InjectedFault):
            ck.save_state(d, params, opt, params, 9, 0.7, 0.8)
    finally:
        faults._reset_for_tests()
    assert not glob.glob(os.path.join(d, "*.tmp*"))   # no torn write
    restored = ck.load_state(d, params, opt)
    assert restored[3] == 4                # the pre-crash generation


def test_corrupt_checkpoint_unit_fallback(tmp_path):
    """Unit twin of seam 3: latest corrupt -> .prev used with a warning;
    both corrupt -> one clear ValueError, never an opaque zip error."""
    from g2vec_tpu.train import checkpoint as ck

    d = str(tmp_path)
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    opt = {"m": np.zeros((2, 3), np.float32)}
    ck.save_state(d, params, opt, params, 4, 0.5, 0.6)
    ck.save_state(d, params, opt, params, 9, 0.7, 0.8)
    latest = os.path.join(d, ck.CKPT_NAME)
    faults._corrupt_file(latest)
    with pytest.warns(RuntimeWarning, match="integrity"):
        restored = ck.load_state(d, params, opt)
    assert restored[3] == 4                       # the .prev generation
    faults._corrupt_file(latest + ck.PREV_SUFFIX)
    with pytest.warns(RuntimeWarning):
        with pytest.raises(ValueError, match="no intact checkpoint"):
            ck.load_state(d, params, opt)


def test_native_load_fault_degrades_not_dies(tsv_paths, tmp_path):
    """Seam 4 — native-library load failure: the reader falls back to the
    Python parser and the auto walker resolves to the device backend; the
    run COMPLETES (degradation, not retry) with outputs identical to a
    run that pinned the degraded backends."""
    from g2vec_tpu.ops.backend import resolve_walker_backend
    from g2vec_tpu.pipeline import run

    pinned = run(_cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "a"),
                      use_native_io=False, walker_backend="device"),
                 console=_quiet)
    cfg = _cfg(tsv_paths, tmp_path, result_name=str(tmp_path / "b"),
               use_native_io=True, walker_backend="auto",
               fault_plan="stage=native_load,kind=crash;"
                          "stage=native_walker_load,kind=crash")
    faults.install_plan(cfg.fault_plan)
    assert resolve_walker_backend(cfg) == "device"   # degraded resolution
    faults._reset_for_tests()
    degraded = run(cfg, console=_quiet)
    assert degraded.walker_backend == "device"
    _assert_outputs_identical(pinned, degraded)


def test_supervisor_gives_up_on_fatal(tsv_paths, tmp_path):
    """A wrong-input failure must NOT be retried: one attempt, a gave_up
    event, and the original error."""
    mj = str(tmp_path / "m.jsonl")
    cfg = _cfg(tsv_paths, tmp_path, metrics_jsonl=mj,
               fault_plan="stage=preprocess,kind=fatal")
    attempts = []
    with pytest.raises(faults.InjectedFatal):
        supervise(cfg, policy=_FAST, console=attempts.append,
                  sleep=_nosleep)
    events = [e["event"] for e in _read_events(mj)]
    assert "gave_up" in events and "retry" not in events


def test_supervisor_exhausts_retry_budget(tsv_paths, tmp_path):
    """A fault that keeps firing (times=99) drains the budget and then
    re-raises with a gave_up event."""
    mj = str(tmp_path / "m.jsonl")
    cfg = _cfg(tsv_paths, tmp_path, metrics_jsonl=mj,
               fault_plan="stage=load,kind=crash,times=99")
    policy = RetryPolicy(max_retries=2, backoff_base=0.0, backoff_max=0.0,
                         jitter=0.0)
    with pytest.raises(faults.InjectedFault):
        supervise(cfg, policy=policy, console=_quiet, sleep=_nosleep)
    events = [e["event"] for e in _read_events(mj)]
    assert events.count("retry") == 2 and "gave_up" in events
