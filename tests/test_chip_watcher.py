"""The chip watcher's battery path has to work FIRST TRY when a tunnel
window finally opens — it has never fired on real hardware, so its
orchestration (stage spawning, artifact flushing, status transitions,
mid-battery abort on a dying tunnel) is pinned here with stubbed stages.
No jax anywhere; runs in milliseconds."""
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The battery's bench stage only counts as landed with all of these
# non-null (tools/chip_watcher.py battery()).
BENCH_REQUIRED = ("cbow_train_paths_per_sec_per_chip",
                  "packed_matmul_vs_xla_dense", "cbow_epoch_breakdown",
                  "cbow_train_xla_dense_sec_per_epoch",
                  "config2_train_paths_per_sec_per_chip",
                  "walker_restricted_walks_per_sec")
BENCH_OK_LINES = [{"metric": m, "value": 1.0} for m in BENCH_REQUIRED]


def _load_watcher(monkeypatch, tmp_path, round_name="rTEST"):
    """Import a fresh chip_watcher with REPO-relative paths redirected to
    tmp_path (module constants are computed at import time)."""
    monkeypatch.setenv("WATCHER_ROUND", round_name)
    monkeypatch.setenv("WATCHER_STATUS_PATH",
                       str(tmp_path / f"WATCHER_STATUS_{round_name}.json"))
    spec = importlib.util.spec_from_file_location(
        "chip_watcher_test", os.path.join(REPO, "tools", "chip_watcher.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # Artifacts land in tmp_path, not the real repo root.
    mod.REPO = str(tmp_path)
    return mod


def test_battery_runs_all_stages_and_writes_artifacts(tmp_path, monkeypatch):
    w = _load_watcher(monkeypatch, tmp_path)
    monkeypatch.setattr(w, "probe", lambda: {"platform": "tpu"})
    calls = []

    def fake_run_stage(name, cmd, timeout, out_path, env_extra=None):
        calls.append((name, timeout, env_extra))
        rec = {"stage": name, "rc": 0, "wall_seconds": 0.1,
               "lines": BENCH_OK_LINES if name == "bench"
               else [{"metric": f"{name}_ok", "value": 1}],
               "stderr_tail": ""}
        if out_path:
            with open(out_path, "w") as f:
                json.dump(rec, f)
        return rec

    monkeypatch.setattr(w, "run_stage", fake_run_stage)
    w.battery({"platform": "tpu"})

    names = [c[0] for c in calls]
    assert names == ["bench", "profile_walker", "profile_ops",
                     "acceptance_device", "scale_demo"]
    # The bench stage must carry the widened in-bench budgets (one run
    # covers every armed metric) and the device twin its walker pin.
    bench_env = calls[0][2]
    assert bench_env["G2VEC_BENCH_TOTAL_BUDGET"] == "860"
    assert int(bench_env["G2VEC_BENCH_CHILD_BUDGET"]) < int(
        bench_env["G2VEC_BENCH_TIMEOUT"])
    assert calls[3][2]["G2VEC_ACCEPT_WALKER"] == "device"
    # Every stage artifact flushed; round suffix respected.
    assert (tmp_path / "BENCH_LOCAL_rTEST.json").exists()
    assert (tmp_path / "PROFILE_WALKER_rTEST.json").exists()
    assert (tmp_path / "PROFILE_OPS_rTEST.json").exists()
    status = json.load(open(tmp_path / "WATCHER_STATUS_rTEST.json"))
    assert status["state"] == "done"
    assert [s["stage"] for s in status["stages"]] == names


def test_battery_aborts_when_tunnel_dies_mid_run(tmp_path, monkeypatch):
    w = _load_watcher(monkeypatch, tmp_path)
    # The initial alive-probe happens in main() BEFORE battery(); inside
    # the battery, probe() is only the between-stage re-check. One alive
    # answer then dead: the battery must run the next stage after the
    # alive re-probe, then stop burning timeouts and record why (the
    # one-shot shape also keeps this valid if battery() ever adds a
    # pre-stage check — some prefix of stages runs, then the abort).
    probes = iter([{"platform": "tpu"}])
    monkeypatch.setattr(w, "probe", lambda: next(probes, None))

    def fake_run_stage(name, cmd, timeout, out_path, env_extra=None):
        rec = {"stage": name, "rc": 0, "wall_seconds": 0.1, "lines": [],
               "stderr_tail": ""}
        if out_path:
            with open(out_path, "w") as f:
                json.dump(rec, f)
        return rec

    monkeypatch.setattr(w, "run_stage", fake_run_stage)
    w.battery({"platform": "tpu"})
    status = json.load(open(tmp_path / "WATCHER_STATUS_rTEST.json"))
    assert status["state"] == "aborted"
    stages = [s["stage"] for s in status["stages"]]
    # A prefix of the battery ran, then the abort — never the full list.
    assert stages[0] == "bench" and stages[-1] == "abort"
    assert "scale_demo" not in stages
    # Artifacts exist exactly for the stages that ran before the abort.
    assert (tmp_path / "BENCH_LOCAL_rTEST.json").exists()
    ran = set(stages)
    assert (tmp_path / "PROFILE_WALKER_rTEST.json").exists() \
        == ("profile_walker" in ran)
    assert not (tmp_path / "PROFILE_OPS_rTEST.json").exists()


def test_second_plan_reorders_and_isolates_the_bench_rerun(tmp_path,
                                                           monkeypatch):
    """WATCHER_PLAN=second: acceptance refresh first (so the bench's
    convergence line reads the fresh artifact), then the bench re-run
    (skip-accept, distinct artifact), then the unchanged tail."""
    monkeypatch.setenv("WATCHER_PLAN", "second")
    w = _load_watcher(monkeypatch, tmp_path)
    monkeypatch.setattr(w, "probe", lambda: {"platform": "tpu"})
    calls = []

    def fake_run_stage(name, cmd, timeout, out_path, env_extra=None):
        calls.append((name, out_path, env_extra))
        rec = {"stage": name, "rc": 0, "wall_seconds": 0.1,
               "lines": BENCH_OK_LINES if name == "bench" else [],
               "stderr_tail": ""}
        if out_path:
            with open(out_path, "w") as f:
                json.dump(rec, f)
        return rec

    monkeypatch.setattr(w, "run_stage", fake_run_stage)
    w.battery({"platform": "tpu"})
    names = [c[0] for c in calls]
    assert names == ["acceptance", "bench", "profile_walker", "profile_ops",
                     "acceptance_device", "scale_demo"]
    bench_path, bench_env = calls[1][1], calls[1][2]
    # The rerun must not clobber window #1's headline artifact...
    assert os.path.basename(bench_path) == "BENCH_LOCAL_rTESTb.json"
    # ...and must skip its in-bench acceptance so the budget reaches the
    # never-landed control/config2 lines.
    assert bench_env["G2VEC_BENCH_SKIP_ACCEPT"] == "1"
    assert bench_env["G2VEC_BENCH_TOTAL_BUDGET"] == "860"
    # The primary acceptance stage runs cold (wall comparable): no walker
    # pin, no compile cache.
    assert calls[0][2] is None
    status = json.load(open(tmp_path / "WATCHER_STATUS_rTEST.json"))
    assert status["state"] == "done"


def test_second_plan_incomplete_when_required_lines_null(tmp_path,
                                                         monkeypatch):
    """rc==0 with a budget-skipped (null) target line is NOT done: the
    status must say incomplete so the watch loop re-arms, and the next
    battery must re-run the bench stage despite SKIP_DONE."""
    monkeypatch.setenv("WATCHER_PLAN", "second")
    monkeypatch.setenv("WATCHER_SKIP_DONE", "1")
    w = _load_watcher(monkeypatch, tmp_path)
    monkeypatch.setattr(w, "probe", lambda: {"platform": "tpu"})
    calls = []

    def fake_run_stage(name, cmd, timeout, out_path, env_extra=None):
        calls.append(name)
        lines = [{"metric": "packed_matmul_vs_xla_dense", "value": None,
                  "skipped": "budget"}] if name == "bench" else []
        rec = {"stage": name, "rc": 0, "wall_seconds": 0.1, "lines": lines,
               "stderr_tail": ""}
        if out_path:
            with open(out_path, "w") as f:
                json.dump(rec, f)
        return rec

    monkeypatch.setattr(w, "run_stage", fake_run_stage)
    w.battery({"platform": "tpu"})
    status = json.load(open(tmp_path / "WATCHER_STATUS_rTEST.json"))
    assert status["state"] == "incomplete"
    assert status["unmet_required"] == ["BENCH_LOCAL_rTESTb.json"]
    # Second battery: every other stage skips (rc==0 on disk), the bench
    # with its null target line re-runs.
    calls.clear()
    w.battery({"platform": "tpu"})
    assert calls == ["bench"]


def test_skip_done_resumes_across_windows(tmp_path, monkeypatch):
    """WATCHER_SKIP_DONE=1: a stage whose rc==0 artifact is already on
    disk is not re-run (a dying window can't clobber landed evidence)."""
    monkeypatch.setenv("WATCHER_SKIP_DONE", "1")
    w = _load_watcher(monkeypatch, tmp_path)
    monkeypatch.setattr(w, "probe", lambda: {"platform": "tpu"})
    # Window #1 landed bench (rc=0, every required line non-null) and a
    # failed profile_walker (rc=-9).
    with open(tmp_path / "BENCH_LOCAL_rTEST.json", "w") as f:
        json.dump({"stage": "bench", "rc": 0, "lines": BENCH_OK_LINES}, f)
    with open(tmp_path / "PROFILE_WALKER_rTEST.json", "w") as f:
        json.dump({"stage": "profile_walker", "rc": -9, "lines": []}, f)
    calls = []

    def fake_run_stage(name, cmd, timeout, out_path, env_extra=None):
        calls.append(name)
        rec = {"stage": name, "rc": 0, "wall_seconds": 0.1, "lines": [],
               "stderr_tail": ""}
        if out_path:
            with open(out_path, "w") as f:
                json.dump(rec, f)
        return rec

    monkeypatch.setattr(w, "run_stage", fake_run_stage)
    w.battery({"platform": "tpu"})
    # bench skipped (rc==0 on disk); the failed walker stage re-runs.
    assert "bench" not in calls
    assert calls[0] == "profile_walker"
    status = json.load(open(tmp_path / "WATCHER_STATUS_rTEST.json"))
    recorded = {s["stage"]: s for s in status["stages"]}
    assert recorded["bench"].get("skipped")
    assert status["state"] == "done"


def test_run_stage_survives_timeout_and_parses_partial_lines(tmp_path,
                                                             monkeypatch):
    w = _load_watcher(monkeypatch, tmp_path)
    out = tmp_path / "stage.json"
    # A stage that prints one metric line then hangs past its timeout:
    # the record must keep the parsed line and mark the kill.
    rec = w.run_stage(
        "hang",
        [sys.executable, "-c",
         "import json,sys,time;"
         "print(json.dumps({'metric':'m','value':1}), flush=True);"
         "time.sleep(60)"],
        3, str(out))
    assert rec["rc"] == -9
    assert rec["lines"] == [{"metric": "m", "value": 1}]
    assert "killed at 3s" in rec["stderr_tail"]
    on_disk = json.load(open(out))
    assert on_disk["lines"] == rec["lines"]


def test_check_complete_predicate(tmp_path, monkeypatch):
    """The watch_loop re-arm predicate: done + all stages ok -> 0; any
    failed stage, non-done state, or missing status -> 1."""
    w = _load_watcher(monkeypatch, tmp_path)
    status = tmp_path / "WATCHER_STATUS_rTEST.json"
    assert w.check_complete() == 1                      # no status file
    status.write_text(json.dumps({"state": "done", "stages": [
        {"stage": "bench", "rc": 0},
        {"stage": "profile_walker", "skipped": "landed earlier"}]}))
    assert w.check_complete() == 0
    status.write_text(json.dumps({"state": "done", "stages": [
        {"stage": "bench", "rc": -9}]}))
    assert w.check_complete() == 1                      # failed stage
    status.write_text(json.dumps({"state": "incomplete", "stages": [
        {"stage": "bench", "rc": 0}]}))
    assert w.check_complete() == 1                      # unmet required
    status.write_text(json.dumps({"state": "probing"}))
    assert w.check_complete() == 1                      # never fired


def test_stage_done_ignores_relayed_lines(tmp_path, monkeypatch):
    """A bench record whose required lines are relays of an earlier
    window is NOT done — the metric was never re-measured."""
    w = _load_watcher(monkeypatch, tmp_path)
    art = tmp_path / "b.json"
    art.write_text(json.dumps({"rc": 0, "lines": [
        {"metric": "m1", "value": 7.9,
         "chip_window_relay": "BENCH_LOCAL_r05.json"}]}))
    assert not w._stage_done(str(art), ("m1",))
    art.write_text(json.dumps({"rc": 0, "lines": [
        {"metric": "m1", "value": 7.9}]}))
    assert w._stage_done(str(art), ("m1",))


def test_run_stage_rerun_salvages_previously_landed_lines(tmp_path,
                                                          monkeypatch):
    """A re-run that dies earlier than its predecessor must not regress
    the artifact: real values the previous run captured are carried over
    unless this run re-measured the same metric."""
    w = _load_watcher(monkeypatch, tmp_path)
    out = tmp_path / "stage.json"
    with open(out, "w") as f:
        json.dump({"stage": "bench", "rc": -9, "lines": [
            {"metric": "a", "value": 1.0},
            {"metric": "b", "value": 2.0},
            {"metric": "c", "value": None, "skipped": "budget"}]}, f)
    # The re-run lands a fresh (different) value for a, nothing for b/c.
    rec = w.run_stage(
        "bench",
        [sys.executable, "-c",
         "import json;print(json.dumps({'metric':'a','value':9.0}))"],
        30, str(out))
    assert rec["rc"] == 0
    by_metric = {d["metric"]: d["value"] for d in rec["lines"]}
    assert by_metric == {"a": 9.0, "b": 2.0}  # b salvaged, null c dropped
    assert rec["salvaged_lines"] == 1
    assert json.load(open(out))["lines"] == rec["lines"]
