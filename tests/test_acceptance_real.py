"""Acceptance on the REAL bundled network + clinical files.

Round-1 gap (VERDICT.md missing #3): the repo only ever tested on fully
synthetic ring+chord graphs whose degree distribution is nothing like the
real scale-free network. Here the full pipeline runs over
``/root/reference/ex_NETWORK.txt`` (298,799 edges, 9,904 genes — hubs of
degree 889) and ``ex_CLINICAL.txt`` (135 samples, 77/58) with a
statistically matched synthetic expression matrix
(g2vec_tpu/data/realistic.py), validating walker behavior (dead ends, hub
fan-out, neighbor-table padding) and accuracy at the reference's own
topology and CLI defaults (reps=10, lenPath=80). The committed artifact
from this config is REAL_ACCEPTANCE.json (n_paths=38,571, path genes
3,858, ACC[val]=0.92 vs the transcript's 45,402 / 3,773 / 0.8837 —
README.md:26-41). The ~15% path-count shortfall is a property of the
realistic.py expression calibration, NOT of walk behavior: round 2's
gumbel-max sampler produced 38,603 and round 3's inverse-CDF sampler
38,571 on the same inputs — two independent samplers agreeing to 0.1%
while both trailing the transcript means the synthetic |PCC| weight
distribution dedups slightly more walks than the (unpublished) real
expression did. Growing the planted modules does not close it cleanly:
n_active_per_group 1,940 -> 2,060 (+6.2%) moved n_paths only +3.6%
(38,571 -> 39,945) while pushing path genes +6.2% past their
near-exact match (3,858 -> 4,099 vs target 3,773) — the real modules
are denser per gene than a BFS ball of the same size, which is a
structural property of the missing expression file, not a spec knob. NOTE: fewer repetitions make the first-val-dip early
stop (reference quirk (c)) brittle — reps=2 stops at ACC~0.74 — so this
test pays the ~5 min for the real configuration; deselect with
``-m "not slow"``.
"""
import os

import numpy as np
import pytest

NET = "/root/reference/ex_NETWORK.txt"
CLIN = "/root/reference/ex_CLINICAL.txt"

needs_reference = pytest.mark.skipif(
    not (os.path.exists(NET) and os.path.exists(CLIN)),
    reason="reference data mount not present")


@pytest.mark.slow
@needs_reference
def test_real_network_pipeline(tmp_path):
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.data.realistic import write_real_expression_tsv
    from g2vec_tpu.pipeline import run

    expr_path = str(tmp_path / "real_EXPRESSION.txt")
    info = write_real_expression_tsv(NET, CLIN, expr_path)
    cfg = G2VecConfig(expression_file=expr_path, clinical_file=CLIN,
                      network_file=NET,
                      result_name=str(tmp_path / "real"),
                      seed=0)
    res = run(cfg, console=lambda s: None)

    # Transcript-scale invariants (README.md:26-32).
    assert res.n_samples == 135
    assert res.n_genes == 7523
    assert abs(res.n_edges - 216540) < 0.01 * 216540
    # Path genes ~ the planted active modules; the transcript's 3,773 is the
    # calibration target.
    assert 3200 <= res.n_path_genes <= 4500
    # Transcript: 45,402 paths at the same reps/lenPath.
    assert abs(res.n_paths - 45402) < 0.2 * 45402

    # The BASELINE north star: val-ACC >= 0.88 at the bundled-example scale.
    assert res.acc_val >= 0.88, res.acc_val

    # Biomarkers should be drawn from the planted modules (they carry both
    # the embedding-norm and the t-score signal).
    active = set(info["active_good"]) | set(info["active_poor"])
    hits = sum(1 for b in res.biomarkers if b in active)
    assert hits / len(res.biomarkers) > 0.9, f"{hits}/{len(res.biomarkers)}"

    # Output files exist and carry every gene.
    lg = open(res.output_files[1]).read().splitlines()
    assert len(lg) == 1 + res.n_genes
