"""Acceptance on the REAL bundled network + clinical files.

Round-1 gap (VERDICT.md missing #3): the repo only ever tested on fully
synthetic ring+chord graphs whose degree distribution is nothing like the
real scale-free network. Here the full pipeline runs over
``/root/reference/ex_NETWORK.txt`` (298,799 edges, 9,904 genes — hubs of
degree 889) and ``ex_CLINICAL.txt`` (135 samples, 77/58) with a
statistically matched synthetic expression matrix
(g2vec_tpu/data/realistic.py), validating walker behavior (dead ends, hub
fan-out, neighbor-table padding) and accuracy at the reference's own
topology and CLI defaults (reps=10, lenPath=80). The committed artifact
from this config is REAL_ACCEPTANCE.json (walker_backend=native — the
"auto" resolution on a single host, which cut its paths stage from 435 s
of XLA:CPU walking to ~5 s); the transcript's numbers are
45,402 paths / 3,773 path genes / ACC[val] 0.8837 (README.md:26-41).

Path-count calibration (VERDICT r2 weak #4, resolved round 3 with the
native-sampler surrogate in tools/calibrate_real.py; two independent
samplers — r2 gumbel-max, r3 inverse-CDF — agree on the counts to 0.1%,
so this is a data property, not walk behavior): with DISJOINT planted
modules the unique-path yield is structurally capped near
reps*(module genes) + singletons ~ 0.85 of the transcript, because
12.03 paths/gene at reps=10 is only reachable when the two groups'
active regions OVERLAP — a module correlated within BOTH groups adds
walks in both graphs and turns each group's dead-elsewhere genes into
surviving singletons. RealExampleSpec.n_shared plants exactly that, and
at n_active=1,500/n_shared=760 the stand-in hits 98.8% of the
transcript's paths at 99.8% of its path genes. But shared-module paths
are label-ambiguous by construction (their label is graph-of-origin,
their content nearly symmetric), and the measured tradeoff is linear:
ACC 0.92 at 0% shared walks, 0.80 at 31% — the transcript's own 0.8837
sits exactly where a ~15-25% ambiguous fraction lands, which is the
best available explanation of why the reference plateaus there. The
default spec (1,880/120, ~5% shared walks) takes the calibration gain
that keeps ACC ~ 0.90: n_paths ~ 40k (-12% vs -13% disjoint), path
genes ~ +2.5%, margin over the >= 0.88 north-star gate preserved.
The measured sweep (5 points, n_shared axis, native sampler + the
pipeline's exact training) is COMMITTED as CALIBRATION.json —
regenerate with ``python tools/calibrate_real.py --frontier``.

NOTE: fewer repetitions make the first-val-dip early stop (reference
quirk (c)) brittle — reps=2 stops at ACC~0.74 — so this test pays the
~8 min for the real configuration; deselect with ``-m "not slow"``.
"""
import os

import numpy as np
import pytest

NET = "/root/reference/ex_NETWORK.txt"
CLIN = "/root/reference/ex_CLINICAL.txt"

needs_reference = pytest.mark.skipif(
    not (os.path.exists(NET) and os.path.exists(CLIN)),
    reason="reference data mount not present")


def test_dense_region_prefers_connectivity():
    """Greedy max-connectivity growth picks the clique over the pendant
    chain a BFS ball would sweep up."""
    from g2vec_tpu.data.realistic import _bfs_region, _dense_region

    # Node 0 seeds; 1-2-3-4 form a clique with 0; 5-6-7 a chain off 0.
    edges = [(0, 1), (0, 2), (0, 3), (0, 4), (1, 2), (1, 3), (1, 4),
             (2, 3), (2, 4), (3, 4), (0, 5), (5, 6), (6, 7)]
    adj = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, []).append(a)
    allowed = np.ones(8, dtype=bool)
    dense = set(_dense_region(adj, [0], 5, allowed).tolist())
    assert dense == {0, 1, 2, 3, 4}, dense
    # BFS from 0 visits in queue order and may take 5 before the clique
    # closes; both must respect size and connectivity.
    bfs = set(_bfs_region(adj, [0], 5, allowed).tolist())
    assert len(bfs) == 5 and 0 in bfs


def test_shared_module_correlates_in_both_groups():
    """n_shared genes must carry > threshold |PCC| within EACH group (their
    edges survive both graphs) and no differential shift."""
    from g2vec_tpu.data.realistic import RealExampleSpec, make_real_expression

    if not (os.path.exists(NET) and os.path.exists(CLIN)):
        pytest.skip("reference data mount not present")
    spec = RealExampleSpec(n_active_per_group=50, n_shared=40)
    expression, info = make_real_expression(NET, CLIN, spec)
    # Reconstruct labels in expression sample order.
    from g2vec_tpu.io.readers import load_clinical
    clin = load_clinical(CLIN)
    labels = np.array([clin[s] for s in expression.sample])
    g2col = {g: j for j, g in enumerate(expression.gene)}
    shared = [g for g in info["active_shared"]][:10]
    cols = [g2col[g] for g in shared if g in g2col]
    for grp in (0, 1):
        x = expression.expr[labels == grp][:, cols]
        c = np.corrcoef(x.T)
        off = c[np.triu_indices_from(c, k=1)]
        assert np.abs(off).mean() > 0.5, (grp, np.abs(off).mean())
    # No differential shift on shared genes.
    mg = expression.expr[labels == 0][:, cols].mean()
    mp = expression.expr[labels == 1][:, cols].mean()
    assert abs(mg - mp) < 0.3, (mg, mp)


@needs_reference
@pytest.mark.parametrize("backend", [
    "auto",
    pytest.param("device", marks=pytest.mark.slow),
])
def test_real_network_pipeline(tmp_path, backend):
    """``auto`` (resolves to the native sampler single-host — the
    REAL_ACCEPTANCE.json config, ~25 s — the default full-scale gate) and
    ``device`` (the JAX walker's acceptance-scale coverage — ~7 min of
    XLA:CPU walking, so it is slow/opt-in: run with ``-m slow``; the chip
    watcher's acceptance_device battery stage covers the same
    configuration on real hardware). Per-backend PRNG families give
    slightly different path counts at the same seed, both inside the
    asserted bands."""
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.data.realistic import write_real_expression_tsv
    from g2vec_tpu.ops.backend import native_walker_available
    from g2vec_tpu.pipeline import run

    if backend == "auto" and not native_walker_available():
        pytest.skip("no C++ toolchain: 'auto' resolves to 'device', "
                    "identical to the other parametrization")

    expr_path = str(tmp_path / "real_EXPRESSION.txt")
    info = write_real_expression_tsv(NET, CLIN, expr_path)
    cfg = G2VecConfig(expression_file=expr_path, clinical_file=CLIN,
                      network_file=NET,
                      result_name=str(tmp_path / "real"),
                      seed=0, walker_backend=backend)
    res = run(cfg, console=lambda s: None)

    assert res.walker_backend == ("native" if backend == "auto" else "device")
    # Transcript-scale invariants (README.md:26-32).
    assert res.n_samples == 135
    assert res.n_genes == 7523
    assert abs(res.n_edges - 216540) < 0.01 * 216540
    # Path genes ~ the planted active modules; the transcript's 3,773 is the
    # calibration target.
    assert 3200 <= res.n_path_genes <= 4500
    # Transcript: 45,402 paths at the same reps/lenPath.
    assert abs(res.n_paths - 45402) < 0.2 * 45402

    # The BASELINE north star: val-ACC >= 0.88 at the bundled-example scale.
    assert res.acc_val >= 0.88, res.acc_val

    # Biomarkers should be drawn from the planted modules (they carry both
    # the embedding-norm and the t-score signal).
    active = set(info["active_good"]) | set(info["active_poor"])
    hits = sum(1 for b in res.biomarkers if b in active)
    assert hits / len(res.biomarkers) > 0.9, f"{hits}/{len(res.biomarkers)}"

    # Output files exist and carry every gene.
    lg = open(res.output_files[1]).read().splitlines()
    assert len(lg) == 1 + res.n_genes


def test_committed_calibration_frontier_matches_defaults():
    """CALIBRATION.json is the measured record behind the default
    RealExampleSpec; it must stay consistent with the shipped defaults
    (regenerate with tools/calibrate_real.py --frontier after changing
    the spec)."""
    import json

    from g2vec_tpu.data.realistic import RealExampleSpec

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "CALIBRATION.json")
    assert os.path.exists(path), "CALIBRATION.json missing at repo root"
    with open(path) as f:
        cal = json.load(f)
    default = next(p for p in cal["points"]
                   if p["point"] == cal["chosen_default"])
    spec = RealExampleSpec()
    assert default["spec"]["n_active_per_group"] == spec.n_active_per_group
    assert default["spec"]["n_shared"] == spec.n_shared
    # The default point must clear the north-star gate; the full-parity
    # point must demonstrate the tradeoff the docstring claims.
    assert default["acc_val"] >= 0.88
    parity = max(cal["points"], key=lambda p: p["vs_transcript_paths"])
    assert parity["vs_transcript_paths"] >= 0.95
    assert parity["acc_val"] < 0.88
