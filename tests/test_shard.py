"""Sharded million-node scale-out suite (parallel/shard.py, train/shard.py,
sharded streaming stages 3-7) — tier-1.

Contracts pinned here:

1. **Partitioning**: byte-aligned gene ranges tile ``[0, G)`` exactly,
   every shard has exactly one owner, ``subset_starts`` is even and
   chunk-exact.
2. **Chunked KV transport**: ``put/get_bytes_chunked`` round-trips at the
   chunk-size boundaries against a fake client (the segfaulting
   ``*_bytes`` KV entry points are documented in hostcomm.py — the
   string-value + base64 framing workaround stays pinned).
3. **Single-rank sharded == unsharded, BYTE-identical** — the
   refactor-safety contract: ``--graph-shards 1 --embed-shards 1`` at one
   process routes through the exact unsharded code paths.
4. **Multi-rank statistical parity** (the PR 7 contract: val-ACC band +
   biomarker overlap vs the unsharded run) on a TRUE 2-process fleet.
5. **Fault drills**: a rank sigkilled at the ``shard_exchange`` /
   ``embed_allreduce`` seams is NAMED by the survivor's
   PeerTimeoutError instead of wedging the fleet.
6. **Bounded per-rank RSS**: every sharded rank peaks well below the
   MEASURED unsharded run at the same scale (slow — the full scaling
   curve is BENCH_SHARD_SCALE.json, written on demand by
   `bench.py --_shard_scale`).
"""
import json
import os
import shutil
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.shard

HAVE_CXX = shutil.which("g++") is not None
needs_native = pytest.mark.skipif(not HAVE_CXX, reason="no C++ toolchain")

_WORKER = os.path.join(os.path.dirname(__file__), "shard_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# 1. ShardSpec partitioning (no jax, no processes)
# ---------------------------------------------------------------------------

def test_shard_spec_byte_ranges_tile_exactly():
    from g2vec_tpu.parallel.shard import ShardSpec

    for n_genes, n_ranks in ((64, 2), (100, 3), (1000, 4), (9999, 7),
                             (1 << 20, 4)):
        nb = (n_genes + 7) // 8
        covered = 0
        prev_hi = 0
        for r in range(n_ranks):
            spec = ShardSpec(rank=r, n_ranks=n_ranks, n_genes=n_genes,
                             embed_shards=n_ranks)
            blo, bhi = spec.byte_range()
            assert blo == prev_hi          # contiguous, no gaps/overlap
            prev_hi = bhi
            lo, hi = spec.gene_range()
            assert lo == blo * 8 and hi == min(bhi * 8, n_genes)
            assert spec.g_local == hi - lo
            covered += spec.g_local
        assert prev_hi == nb
        assert covered == n_genes          # gene ranges tile [0, G)


def test_shard_spec_slice_and_single_rank_passthrough():
    from g2vec_tpu.parallel.shard import ShardSpec

    rows = np.arange(3 * 13, dtype=np.uint8).reshape(3, 13)
    spec = ShardSpec(rank=1, n_ranks=2, n_genes=100, embed_shards=2)
    blo, bhi = spec.byte_range()
    np.testing.assert_array_equal(spec.slice_packed(rows),
                                  rows[:, blo:bhi])
    # Sharding off / one rank: the full range, always.
    off = ShardSpec(rank=0, n_ranks=1, n_genes=100, graph_shards=1,
                    embed_shards=1)
    assert off.byte_range() == (0, 13)
    assert off.gene_range() == (0, 100)
    assert not off.embed_split              # 1 rank => unsharded code paths


def test_shard_owner_covers_every_shard_once():
    from g2vec_tpu.parallel.shard import ShardSpec

    n_shards = 23
    for n_ranks, graph_shards in ((2, 2), (3, 5), (4, 4)):
        specs = [ShardSpec(rank=r, n_ranks=n_ranks, n_genes=512,
                           graph_shards=graph_shards)
                 for r in range(n_ranks)]
        for si in range(n_shards):
            owners = {s.shard_owner(si, n_shards) for s in specs}
            assert len(owners) == 1         # every rank agrees
            assert 0 <= owners.pop() < n_ranks
        owned = [sum(1 for si in range(n_shards)
                     if specs[r].shard_owner(si, n_shards) == r)
                 for r in range(n_ranks)]
        assert all(c > 0 for c in owned)    # work for every rank


def test_subset_starts_even_and_exact():
    from g2vec_tpu.parallel.shard import subset_starts

    assert subset_starts(1000, 0) is None          # off => full range
    assert subset_starts(1000, 1000) is None       # >= G => full range
    assert subset_starts(1000, 2000) is None
    s = subset_starts(1000, 100)
    assert s is not None and len(s) == 100
    assert s.dtype == np.int32
    assert len(np.unique(s)) == len(s)
    assert s[0] == 0 and s[-1] < 1000
    gaps = np.diff(s.astype(np.int64))
    assert gaps.max() - gaps.min() <= 1            # evenly spaced
    s7 = subset_starts(22, 7)
    assert len(s7) == 7 and s7.max() < 22


def test_shard_spec_validation_errors():
    from g2vec_tpu.parallel.shard import ShardSpec

    with pytest.raises(ValueError, match="rank"):
        ShardSpec(rank=2, n_ranks=2, n_genes=100)
    with pytest.raises(ValueError, match="embed_shards"):
        ShardSpec(rank=0, n_ranks=2, n_genes=100, embed_shards=3)
    with pytest.raises(ValueError, match="genes"):
        ShardSpec(rank=0, n_ranks=4, n_genes=16, embed_shards=4)


# ---------------------------------------------------------------------------
# 2. Chunked KV transport at the size boundaries (fake client, no cluster)
# ---------------------------------------------------------------------------

class _FakeKV:
    """String-API KV store double: same surface hostcomm touches. Gets of
    missing keys 'time out' immediately (the DEADLINE_EXCEEDED shape the
    real coordination service raises)."""

    def __init__(self):
        self.store = {}
        self.sets = []

    def key_value_set(self, key, value):
        assert isinstance(value, str)       # the *_bytes APIs segfault
        self.store[key] = value
        self.sets.append(key)

    def blocking_key_value_get(self, key, timeout_ms):
        if key not in self.store:
            raise RuntimeError(f"DEADLINE_EXCEEDED: key {key!r}")
        return self.store[key]


@pytest.mark.parametrize("size_delta", [-1, 0, 1])
def test_chunked_roundtrip_at_chunk_boundary(size_delta):
    from g2vec_tpu.parallel import hostcomm

    cb = 1024
    payload = (bytes(range(256)) * ((cb + 256 + 255) // 256))[:cb + size_delta]
    kv = _FakeKV()
    n = hostcomm.put_bytes_chunked("t/x", payload, client=kv,
                                   chunk_bytes=cb)
    assert n == (2 if size_delta == 1 else 1)
    # The count key is published LAST: a reader that sees it knows every
    # chunk is already present (no torn read window).
    assert kv.sets[-1] == "t/x/n"
    assert hostcomm.get_bytes_chunked("t/x", client=kv) == payload


def test_chunked_roundtrip_empty_and_multichunk():
    from g2vec_tpu.parallel import hostcomm

    kv = _FakeKV()
    hostcomm.put_bytes_chunked("t/empty", b"", client=kv, chunk_bytes=8)
    assert hostcomm.get_bytes_chunked("t/empty", client=kv) == b""
    big = os.urandom(5 * 1000 + 17)
    n = hostcomm.put_bytes_chunked("t/big", big, client=kv,
                                   chunk_bytes=1000)
    assert n == 6
    assert hostcomm.get_bytes_chunked("t/big", client=kv) == big


def test_chunked_get_timeout_names_owner():
    from g2vec_tpu.parallel import hostcomm
    from g2vec_tpu.resilience.fleet import PeerTimeoutError

    with pytest.raises(PeerTimeoutError, match=r"missing rank\(s\): \[3\]"):
        hostcomm.get_bytes_chunked("t/absent", client=_FakeKV(),
                                   deadline=0.01, owner=3)


# ---------------------------------------------------------------------------
# Shared pipeline fixtures/helpers (same dataset scale as test_stream.py)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="session")
def shard_tsv(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(
        n_good=30, n_poor=26, module_size=16, shared_module_size=6,
        n_background=24, n_expr_only=4, n_net_only=4, module_chords=3,
        background_edges=40, noise=0.25, shift=1.4, seed=7)
    return write_synthetic_tsv(
        spec, str(tmp_path_factory.mktemp("shard_data")))


def _cfg_dict(paths, out, **over):
    base = dict(
        expression_file=paths["expression"], clinical_file=paths["clinical"],
        network_file=paths["network"], result_name=out,
        lenPath=20, numRepetition=4, sizeHiddenlayer=32, epoch=8,
        numBiomarker=10, seed=11, compute_dtype="float32",
        walker_backend="native", train_mode="streaming", shard_paths=64)
    base.update(over)
    return base


def _run(paths, out, **over):
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.pipeline import run

    return run(G2VecConfig(**_cfg_dict(paths, out, **over)),
               console=lambda s: None)


def _read_files(result_name):
    out = {}
    for suffix in ("_biomarkers.txt", "_lgroups.txt", "_vectors.txt"):
        with open(result_name + suffix, "rb") as f:
            out[suffix] = f.read()
    return out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rank_env(port: int, process_id: int, n_ranks: int) -> dict:
    drop = ("PALLAS_AXON", "AXON_", "TPU_", "JAX_", "XLA_", "LIBTPU", "PJRT_")
    env = {k: v for k, v in os.environ.items() if not k.startswith(drop)}
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p.lower()]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + parts)
    env["JAX_PLATFORMS"] = "cpu"
    env["G2VEC_COORDINATOR"] = f"127.0.0.1:{port}"
    env["G2VEC_PROCESS_ID"] = str(process_id)
    env["G2VEC_NUM_PROCESSES"] = str(n_ranks)
    return env


def _launch_fleet(tmp_path, cfg_dict, n_ranks, timeout=420):
    """Run shard_worker.py on every rank; returns the Popen results as
    (returncode, last-stdout-line-or-None, stderr) triples."""
    cfg_path = tmp_path / "shard_cfg.json"
    cfg_path.write_text(json.dumps(cfg_dict))
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(cfg_path)],
        env=_rank_env(port, i, n_ranks), cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(n_ranks)]
    out = []
    try:
        for i, p in enumerate(procs):
            try:
                stdout, stderr = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail(f"rank {i} timed out after {timeout}s")
            lines = [ln for ln in stdout.strip().splitlines() if ln]
            out.append((p.returncode, lines[-1] if lines else None, stderr))
    finally:
        for q in procs:                     # a dead sibling must not wedge
            if q.poll() is None:
                q.kill()
    return out


# ---------------------------------------------------------------------------
# 3. Single-rank sharded mode is BYTE-identical to the unsharded path
# ---------------------------------------------------------------------------

@needs_native
def test_single_rank_sharded_byte_identical(shard_tsv, tmp_path):
    ref = _run(shard_tsv, str(tmp_path / "ref"))
    sharded = _run(shard_tsv, str(tmp_path / "sh"),
                   graph_shards=1, embed_shards=1)
    assert _read_files(str(tmp_path / "sh")) == _read_files(
        str(tmp_path / "ref"))
    assert sharded.acc_val == ref.acc_val
    assert sharded.n_paths == ref.n_paths
    # walk_starts >= G is exactly "no cap" — same bytes again.
    capped = _run(shard_tsv, str(tmp_path / "ws"),
                  graph_shards=1, embed_shards=1,
                  walk_starts=10 ** 6)
    assert _read_files(str(tmp_path / "ws")) == _read_files(
        str(tmp_path / "ref"))


@needs_native
def test_walk_starts_caps_volume_and_completes(shard_tsv, tmp_path):
    ref = _run(shard_tsv, str(tmp_path / "ref"))
    half = _run(shard_tsv, str(tmp_path / "half"),
                walk_starts=ref.n_genes // 2)
    assert half.n_paths < ref.n_paths           # genuinely fewer walks
    assert half.n_paths > 0
    assert len(half.biomarkers) == len(ref.biomarkers)   # still completes


# ---------------------------------------------------------------------------
# 4. TRUE 2-process run: statistical parity vs unsharded (PR 7 contract)
# ---------------------------------------------------------------------------

@needs_native
def test_two_rank_sharded_statistical_parity(shard_tsv, tmp_path):
    ref = _run(shard_tsv, str(tmp_path / "ref"), stream_patience=8)
    cfg = _cfg_dict(shard_tsv, str(tmp_path / "fleet"),
                    stream_patience=8, distributed=True,
                    graph_shards=2, embed_shards=2,
                    fleet_watchdog_deadline=120.0)
    results = _launch_fleet(tmp_path, cfg, n_ranks=2)
    parsed = []
    for i, (rc, line, stderr) in enumerate(results):
        assert rc == 0, f"rank {i} failed:\n{stderr[-3000:]}"
        parsed.append(json.loads(line))
    # Replicated decisions: both ranks computed identical selections.
    assert parsed[0]["biomarkers"] == parsed[1]["biomarkers"]
    assert parsed[0]["acc_val"] == pytest.approx(parsed[1]["acc_val"])
    assert parsed[0]["n_paths"] == parsed[1]["n_paths"]
    # Only the coordinator writes; the files exist and parse.
    writers = [p for p in parsed if p["output_files"]]
    assert len(writers) == 1 and writers[0]["process"] == 0
    files = _read_files(str(tmp_path / "fleet"))
    assert files["_vectors.txt"].count(b"\n") == ref.n_genes + 1
    # The PR 7 statistical contract vs the unsharded run.
    assert abs(parsed[0]["acc_val"] - ref.acc_val) <= 0.20
    a, b = set(ref.biomarkers), set(parsed[0]["biomarkers"])
    assert len(a & b) / max(len(a), 1) >= 0.6


# ---------------------------------------------------------------------------
# 5. Fault drills: the watchdog NAMES the rank that died mid-exchange
# ---------------------------------------------------------------------------

@needs_native
def test_shard_exchange_sigkill_names_dead_rank(shard_tsv, tmp_path):
    cfg = _cfg_dict(shard_tsv, str(tmp_path / "out"), distributed=True,
                    graph_shards=2, embed_shards=2,
                    fleet_watchdog_deadline=15.0,
                    fault_plan="process=1,stage=shard_exchange,kind=sigkill")
    results = _launch_fleet(tmp_path, cfg, n_ranks=2, timeout=300)
    assert results[1][0] == -9                  # rank 1 really sigkilled
    rc0, _, stderr0 = results[0]
    assert rc0 != 0
    assert "PeerTimeoutError" in stderr0
    assert "missing rank(s): [1]" in stderr0


@needs_native
def test_embed_allreduce_sigkill_names_dead_rank(shard_tsv, tmp_path):
    cfg = _cfg_dict(shard_tsv, str(tmp_path / "out"), distributed=True,
                    graph_shards=2, embed_shards=2,
                    fleet_watchdog_deadline=15.0,
                    fault_plan="process=1,stage=embed_allreduce,"
                               "kind=sigkill,epoch=3")
    results = _launch_fleet(tmp_path, cfg, n_ranks=2, timeout=300)
    assert results[1][0] == -9
    rc0, _, stderr0 = results[0]
    assert rc0 != 0
    assert "PeerTimeoutError" in stderr0
    assert "missing rank(s): [1]" in stderr0


# ---------------------------------------------------------------------------
# 6. Per-rank RSS below the MEASURED unsharded run at the same scale (slow)
# ---------------------------------------------------------------------------

@needs_native
@pytest.mark.slow
def test_sharded_rss_below_measured_unsharded_run(tmp_path):
    """262k genes, H=256: measure the plain single-host run's peak RSS,
    then the 2-rank sharded fleet's — every sharded rank must peak well
    below the measured unsharded peak (<= 0.9x). The analytic
    trainer-state bytes (4 x [G, H] f32) are NOT the bound: real peaks
    carry ~1 GB process overhead plus unpack/exchange transients, so
    the honest comparison is run-vs-run at the same scale (same framing
    as bench.py --_shard_scale, which writes BENCH_SHARD_SCALE.json
    on demand)."""
    from g2vec_tpu.data.synth import SynthGraphSpec, write_synth_graph_streamed

    n_genes, hidden = 262_144, 256
    spec = SynthGraphSpec(n_genes=n_genes, n_good=8, n_poor=8, seed=5)
    paths = write_synth_graph_streamed(spec, str(tmp_path / "big"))
    common = dict(sizeHiddenlayer=hidden, epoch=2, stream_patience=2,
                  lenPath=12, numRepetition=2, shard_paths=256,
                  walk_starts=2048, stream_eval_rows=256)
    plain_cfg = _cfg_dict(paths, str(tmp_path / "plain"),
                          graph_shards=0, embed_shards=0, **common)
    (rc_p, line_p, stderr_p), = _launch_fleet(
        tmp_path, plain_cfg, n_ranks=1, timeout=3600)
    assert rc_p == 0, f"plain run failed:\n{stderr_p[-3000:]}"
    plain_rss_kb = json.loads(line_p)["rss_kb"]

    cfg = _cfg_dict(paths, str(tmp_path / "out"), distributed=True,
                    graph_shards=2, embed_shards=2,
                    fleet_watchdog_deadline=1800.0, **common)
    results = _launch_fleet(tmp_path, cfg, n_ranks=2, timeout=3600)
    for i, (rc, line, stderr) in enumerate(results):
        assert rc == 0, f"rank {i} failed:\n{stderr[-3000:]}"
        rss_kb = json.loads(line)["rss_kb"]
        assert rss_kb <= 0.9 * plain_rss_kb, (
            f"rank {i} peak RSS {rss_kb} KB not well below the measured "
            f"unsharded peak {plain_rss_kb} KB at the same scale")


# ---------------------------------------------------------------------------
# Satellite: the --nodes-scaled streamed generator smoke
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("nodes", [400, 5000])
def test_make_synth_graph_streamed_smoke(tmp_path, nodes):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "make_synth_graph.py"),
         "--nodes", str(nodes), "--good", "4", "--poor", "4",
         "--stream", "--out", str(tmp_path), "--prefix", f"s{nodes}"],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-500:]
    summary = json.loads(proc.stdout)
    assert summary["streamed"] is True
    assert int(summary["n_genes"]) == nodes
    with open(summary["expression"]) as f:
        assert sum(1 for _ in f) == nodes + 1    # header + one row per gene
    with open(summary["network"]) as f:
        n_edges = sum(1 for _ in f) - 1
    assert n_edges == int(summary["n_edges"])
    assert n_edges >= nodes                      # connected + hubs


def test_streamed_generator_chunk_independent():
    from g2vec_tpu.data.synth import (iter_scale_free_edges,
                                      make_scale_free_edges)

    s1, d1 = make_scale_free_edges(500, 3, np.random.default_rng(5))
    chunks = list(iter_scale_free_edges(500, 3, np.random.default_rng(5),
                                        chunk_edges=37))
    np.testing.assert_array_equal(np.concatenate([c[0] for c in chunks]), s1)
    np.testing.assert_array_equal(np.concatenate([c[1] for c in chunks]), d1)
