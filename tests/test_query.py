"""Interactive query plane (PR 15): blocked top-k kernel exactness vs
the naive numpy reference, bundle publication/integrity (tamper + torn
drills), the byte-budgeted mmap LRU, the daemon's ``query`` op (cache,
token gating, lazy republish from the durable record), the bounded
``result`` op, and the router's failover read path.

The kernel-exactness tests use INTEGER-VALUED float32 embeddings: every
dot product is a sum of small integers, exact in float32 under any
summation order, so "blocked kernel == naive full sort" is a bitwise
assertion with no BLAS-ordering caveats. The daemon tests reuse the
in-process admit/step drive from test_serve.py; the lazy-republish and
auth drills fabricate durable records directly so they stay jax-free.
"""
import dataclasses
import json
import os
import socket
import threading

import numpy as np
import pytest

from g2vec_tpu.ops import knn
from g2vec_tpu.serve import inventory, protocol

pytestmark = pytest.mark.query

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _job(tsv_paths, tmp_path, name, **overrides):
    job = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out", name),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        walker_backend="device")
    job.update(overrides)
    return job


def _daemon(tmp_path, **opt_overrides):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=os.path.join(str(tmp_path), "serve.sock"),
        state_dir=os.path.join(str(tmp_path), "state"), **opt_overrides)
    return ServeDaemon(opts, console=lambda s: None)


def _plant_bundle(dest, g=30, h=8, seed=0, with_scores=True):
    """Write one real bundle from seeded arrays; returns what went in."""
    from g2vec_tpu.io.writers import write_inventory_bundle

    rng = np.random.default_rng(seed)
    emb = rng.standard_normal((g, h)).astype(np.float32)
    genes = [f"G{i:03d}" for i in range(g)]
    scores = (rng.standard_normal((2, g)).astype(np.float32)
              if with_scores else None)
    write_inventory_bundle(dest, emb, genes, scores, {"source": "test"})
    return emb, genes, scores


def _gen(dest):
    """Resolve a bundle root to its live generation directory (bundles
    are generational since the incremental-update plane; flat legacy
    bundles resolve to themselves)."""
    from g2vec_tpu.io.writers import read_generation

    return os.path.join(dest, read_generation(dest))


def _roundtrip(d, req):
    """One request over the daemon's real connection handler via a
    socketpair — exercises the auth gate and the op dispatch without a
    listener thread."""
    a, b = socket.socketpair()
    t = threading.Thread(target=d._handle_conn, args=(a,), daemon=True)
    t.start()
    f = b.makefile("rwb")
    try:
        protocol.write_event(f, req)
        ev = protocol.read_event(f)
    finally:
        f.close()
        b.close()
        t.join(timeout=30)
    return ev


# ---------------------------------------------------------------------------
# Kernel exactness: blocked top-k == naive full stable sort, bitwise
# ---------------------------------------------------------------------------

def _naive_cosine(emb, q, k, exclude=-1):
    """The unblocked full-sort reference the kernels are pinned to:
    one matmul, one stable descending sort (ties by ascending index)."""
    emb = np.asarray(emb, dtype=np.float32)
    q = np.asarray(q, dtype=np.float32)
    g = emb.shape[0]
    sims = emb @ q
    norms = np.sqrt((emb * emb).sum(axis=1))
    qn = np.float32(np.sqrt(np.dot(q, q)))
    denom = norms * qn
    ok = denom > 0
    sims = np.where(ok, sims / np.where(ok, denom, 1), np.float32(-2.0))
    if 0 <= exclude < g:
        sims[exclude] = -np.inf
    order = np.lexsort((np.arange(g), -sims))[:min(k, g)]
    return order, sims[order]


def _int_embeddings(g=257, h=8, seed=0):
    rng = np.random.default_rng(seed)
    emb = rng.integers(-5, 6, size=(g, h)).astype(np.float32)
    emb[7] = 0.0                  # zero-norm row: must rank last, no nan
    emb[100] = emb[3]             # exact duplicate: a forced tie
    emb[101] = emb[3]
    return emb


@pytest.mark.parametrize("k", [1, 5, 50, 257, 400])
@pytest.mark.parametrize("block_rows", [1, 13, 64, 8192])
def test_cosine_topk_exact_vs_naive(k, block_rows):
    emb = _int_embeddings()
    norms = knn.row_norms(emb, block_rows=block_rows)
    for exclude in (-1, 3):
        q = emb[3]
        idx, sims = knn.cosine_topk(emb, norms, q, k, exclude=exclude,
                                    block_rows=block_rows)
        ref_idx, ref_sims = _naive_cosine(emb, q, k, exclude=exclude)
        assert np.array_equal(idx, ref_idx), \
            f"k={k} block={block_rows} exclude={exclude}"
        assert np.array_equal(sims, ref_sims)
        assert not np.isnan(sims).any()


def test_cosine_topk_ties_break_by_ascending_index():
    emb = _int_embeddings()
    norms = knn.row_norms(emb)
    # Rows 3, 100, 101 are identical; excluding 3 leaves 100 and 101
    # tied at similarity 1.0 — the winner must be the lower index.
    idx, sims = knn.cosine_topk(emb, norms, emb[3], 2, exclude=3)
    assert idx[0] == 100 and idx[1] == 101
    assert sims[0] == sims[1]           # an exact tie, lower index first


def test_cosine_topk_zero_norm_scores_minus_two():
    emb = _int_embeddings()
    norms = knn.row_norms(emb)
    g = emb.shape[0]
    idx, sims = knn.cosine_topk(emb, norms, emb[3], g)
    assert sims[np.where(idx == 7)[0][0]] == np.float32(-2.0)
    # A zero query degrades every similarity to -2.0, never nan/inf.
    zidx, zsims = knn.cosine_topk(emb, norms, np.zeros(emb.shape[1]), 5)
    assert np.all(zsims == np.float32(-2.0))
    assert np.array_equal(zidx, np.arange(5))    # pure index tiebreak


def test_topk_scores_exact_vs_naive():
    rng = np.random.default_rng(1)
    scores = rng.integers(-50, 51, size=301).astype(np.float32)
    scores[10] = scores[200] = scores[20]         # forced 3-way tie
    for k in (1, 7, 301, 500):
        idx, vals = knn.topk_scores(scores, k)
        order = np.lexsort((np.arange(301), -scores))[:min(k, 301)]
        assert np.array_equal(idx, order)
        assert np.array_equal(vals, scores[order])


def test_row_norms_blocking_invariant():
    emb = _int_embeddings(g=103)
    outs = [knn.row_norms(emb, block_rows=b) for b in (1, 7, 64, 8192)]
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)


# ---------------------------------------------------------------------------
# Bundle integrity + catalog: roundtrip, tamper, torn, LRU byte budget
# ---------------------------------------------------------------------------

def test_bundle_roundtrip_preserves_arrays(tmp_path):
    dest = str(tmp_path / "inv" / "j1" / "v0")
    emb, genes, scores = _plant_bundle(dest)
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    b = cat.get("j1/v0")
    assert np.array_equal(np.asarray(b.embeddings), emb)
    assert np.array_equal(np.asarray(b.norms), knn.row_norms(emb))
    assert np.array_equal(np.asarray(b.scores), scores)
    assert b.genes == genes and b.gene_index["G003"] == 3
    assert b.meta["n_genes"] == len(genes) and b.meta["has_scores"]
    # Warm get is the same mapping, not a remap.
    assert cat.get("j1/v0") is b
    assert cat.stats()["cold_maps"] == 1


def test_tampered_bundle_is_refused(tmp_path):
    dest = str(tmp_path / "inv" / "j1" / "v0")
    _plant_bundle(dest)
    path = os.path.join(_gen(dest), "embeddings.npy")
    with open(path, "r+b") as f:             # same size, different bytes
        f.seek(os.path.getsize(path) - 3)
        orig = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([orig[0] ^ 0xFF]))
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    with pytest.raises(inventory.InventoryError) as ei:
        cat.get("j1/v0")
    assert ei.value.code == "tampered"
    # Truncation is caught by the cheaper size check first.
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 8)
    with pytest.raises(inventory.InventoryError) as ei:
        cat.get("j1/v0")
    assert ei.value.code == "tampered"
    assert cat.stats()["map_errors"] == 2


def test_torn_bundle_is_refused(tmp_path):
    from g2vec_tpu.io.writers import GENERATION_FILE

    dest = str(tmp_path / "inv" / "j1" / "v0")
    _plant_bundle(dest)
    gen = _gen(dest)
    os.unlink(os.path.join(gen, "genes.txt"))    # manifest names it
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    with pytest.raises(inventory.InventoryError) as ei:
        cat.get("j1/v0")
    assert ei.value.code == "torn"
    # Without a manifest or generation pointer the directory is not a
    # bundle at all: it never enters the catalog, so the failure mode
    # is not_found.
    os.unlink(os.path.join(gen, inventory.INVENTORY_MANIFEST))
    os.unlink(os.path.join(dest, GENERATION_FILE))
    with pytest.raises(inventory.InventoryError) as ei:
        cat.get("j1/v0")
    assert ei.value.code == "not_found"


def test_catalog_lru_respects_byte_budget(tmp_path):
    root = str(tmp_path / "inv")
    for i in range(4):
        _plant_bundle(os.path.join(root, f"j{i}", "v0"), seed=i)
    probe = inventory.InventoryCatalog([root], budget_bytes=1 << 30)
    size = probe.get("j0/v0").nbytes
    cat = inventory.InventoryCatalog([root], budget_bytes=2 * size)
    for i in range(4):
        cat.get(f"j{i}/v0")
    st = cat.stats()
    assert st["bytes_mapped"] <= 2 * size
    assert st["bundles_mapped"] == 2
    assert st["cold_maps"] == 4 and st["evictions"] == 2
    assert st["bundles_cataloged"] == 4      # eviction unmaps, not deletes
    # LRU order: j2/j3 survive, j0 remaps cold and evicts j2.
    cat.get("j3/v0")
    assert cat.stats()["cold_maps"] == 4
    cat.get("j0/v0")
    assert cat.stats()["cold_maps"] == 5
    # A budget smaller than one bundle still maps (exactly) one.
    tiny = inventory.InventoryCatalog([root], budget_bytes=1)
    tiny.get("j1/v0")
    assert tiny.stats()["bundles_mapped"] == 1


def test_resolve_bundle_key_matrix():
    known = {"ia/v0": "/x", "ia/v1": "/y", "ib/v0": "/z",
             "solo_inventory": "/s"}
    assert inventory.resolve_bundle_key(known, "ia", "v1") == ("ia/v1",
                                                              None)
    assert inventory.resolve_bundle_key(known, "ib", None) == ("ib/v0",
                                                               None)
    assert inventory.resolve_bundle_key(
        known, "solo_inventory", None) == ("solo_inventory", None)
    key, err = inventory.resolve_bundle_key(known, "ia", None)
    assert key is None and err["error"] == "ambiguous_variant"
    assert err["variants"] == ["v0", "v1"]
    key, err = inventory.resolve_bundle_key(known, "ia", "v9")
    assert key is None and err["error"] == "not_found"
    assert err["variants"] == ["v0", "v1"]
    key, err = inventory.resolve_bundle_key(known, "nope", None)
    assert key is None and err["error"] == "not_found"


def test_query_cache_lru_and_invalidation():
    qc = inventory.QueryCache(capacity=2)
    calls = []

    def make(v):
        def _c():
            calls.append(v)
            return {"v": v}
        return _c

    k1 = inventory.cache_key("b1", "neighbors", "G1", 5)
    assert qc.get_or_put(k1, make(1)) == ({"v": 1}, False)
    assert qc.get_or_put(k1, make(99)) == ({"v": 1}, True)
    assert calls == [1]
    qc.get_or_put(inventory.cache_key("b1", "neighbors", "G2", 5), make(2))
    qc.get_or_put(inventory.cache_key("b2", "meta", None, 0), make(3))
    # Capacity 2: k1 (the LRU entry) fell out.
    assert qc.get_or_put(k1, make(4)) == ({"v": 4}, False)
    st = qc.stats()
    assert st["hits"] == 1 and st["misses"] == 4 and st["entries"] == 2
    # Invalidation is bundle-scoped: b2 keys survive a b1 republish.
    qc.invalidate_bundle("b1")
    _, hit = qc.get_or_put(inventory.cache_key("b2", "meta", None, 0),
                           make(5))
    assert hit
    _, hit = qc.get_or_put(k1, make(6))
    assert not hit


def test_run_query_against_planted_bundle(tmp_path):
    dest = str(tmp_path / "inv" / "j1" / "v0")
    emb, genes, scores = _plant_bundle(dest, g=40, h=8)
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    r = inventory.run_query(cat, "neighbors", "j1/v0", gene="G005", k=3)
    # Plumbing check against the kernel itself (kernel-vs-naive
    # exactness is pinned above on integer-valued data, where bitwise
    # equality is summation-order-proof).
    ridx, rsims = knn.cosine_topk(emb, knn.row_norms(emb), emb[5], 3,
                                  exclude=5)
    assert r["neighbors"] == [genes[i] for i in ridx]
    assert r["sims"] == [float(s) for s in rsims]
    t = inventory.run_query(cat, "topk_biomarkers", "j1/v0", k=4)
    for row, group in enumerate(("good", "poor")):
        gidx, gsc = knn.topk_scores(scores[row], 4)
        assert t[group]["genes"] == [genes[i] for i in gidx]
        assert t[group]["scores"] == [float(s) for s in gsc]
    m = inventory.run_query(cat, "meta", "j1/v0")
    assert m["n_genes"] == 40 and m["hidden"] == 8
    # Structured refusals, not exceptions leaking numpy internals.
    for bad in [dict(q="frobnicate"), dict(q="neighbors"),
                dict(q="neighbors", gene="NOPE"),
                dict(q="neighbors", gene="G005", k=0),
                dict(q="neighbors", gene="G005", k=10001)]:
        with pytest.raises(inventory.InventoryError) as ei:
            inventory.run_query(cat, bad["q"], "j1/v0",
                                gene=bad.get("gene"), k=bad.get("k", 10))
        assert ei.value.code == "bad_query"
    # A scores-less bundle (the republish shape) refuses biomarkers.
    _plant_bundle(str(tmp_path / "inv" / "j2" / "v0"), with_scores=False)
    with pytest.raises(inventory.InventoryError) as ei:
        inventory.run_query(cat, "topk_biomarkers", "j2/v0", k=2)
    assert ei.value.code == "scores_unavailable"
    assert inventory.run_query(cat, "neighbors", "j2/v0", gene="G000",
                               k=2)["neighbors"]


# ---------------------------------------------------------------------------
# Daemon: publication on completion, the query op, cache, solo parity
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served(tsv_paths, tmp_path_factory):
    """One completed served job with a published bundle, shared by the
    read-only daemon tests below (none of them mutates the bundle)."""
    base = tmp_path_factory.mktemp("served")
    d = _daemon(base)
    sub = d.admit({"tenant": "alice",
                   "job": {**_job(tsv_paths, base, "q1"),
                           "variants": [{"name": "v0",
                                         "train_seed": 1}]}})
    assert sub["event"] == "accepted"
    assert d.step() == 1
    root = os.path.join(d.opts.state_dir, "inventory",
                        sub["job_id"], "v0")
    return {"d": d, "job_id": sub["job_id"],
            "key": f"{sub['job_id']}/v0",
            "root": root, "dir": _gen(root)}


def test_daemon_publishes_verified_bundle(served):
    from g2vec_tpu.io.writers import INVENTORY_ARRAYS, INVENTORY_MANIFEST

    for fn in INVENTORY_ARRAYS + (INVENTORY_MANIFEST, "meta.json"):
        assert os.path.exists(os.path.join(served["dir"], fn)), fn
    b = served["d"].catalog.get(served["key"])     # full sha256 verify
    assert b.meta["source"] == "serve"
    assert b.meta["job_id"] == served["job_id"]
    assert b.meta["variant"] == "v0" and b.meta["tenant"] == "alice"
    assert np.array_equal(np.asarray(b.norms),
                          knn.row_norms(np.asarray(b.embeddings)))


def test_daemon_query_ops_and_cache(served):
    d = served["d"]
    lst = d.handle_query({"q": "list"})
    assert lst["event"] == "query_result"
    assert any(e["bundle"] == served["key"] for e in lst["bundles"])

    meta = d.handle_query({"q": "meta", "job_id": served["job_id"],
                           "variant": "v0"})
    assert meta["event"] == "query_result" and meta["hidden"] == 16

    emb = np.load(os.path.join(served["dir"], "embeddings.npy"))
    norms = np.load(os.path.join(served["dir"], "norms.npy"))
    with open(os.path.join(served["dir"], "genes.txt")) as f:
        genes = [ln.rstrip("\n") for ln in f]
    gene = genes[0]
    n1 = d.handle_query({"q": "neighbors", "job_id": served["job_id"],
                         "gene": gene, "k": 4})    # variant auto-resolves
    assert n1["event"] == "query_result" and n1["bundle"] == served["key"]
    ridx, rsims = knn.cosine_topk(emb, norms, emb[0], 4, exclude=0)
    assert n1["neighbors"] == [genes[i] for i in ridx]
    assert n1["sims"] == [float(s) for s in rsims]

    # Identical query again: answered from the result cache.
    h0 = d.qcache.stats()["hits"]
    n2 = d.handle_query({"q": "neighbors", "job_id": served["job_id"],
                         "gene": gene, "k": 4})
    assert {k: v for k, v in n2.items()} == {k: v for k, v in n1.items()}
    assert d.qcache.stats()["hits"] == h0 + 1

    tk = d.handle_query({"q": "topk_biomarkers",
                         "job_id": served["job_id"], "k": 3})
    scores = np.load(os.path.join(served["dir"], "scores.npy"))
    for row, group in enumerate(("good", "poor")):
        gidx, gsc = knn.topk_scores(scores[row], 3)
        assert tk[group]["genes"] == [genes[i] for i in gidx]
        assert tk[group]["scores"] == [float(s) for s in gsc]

    st = d.status()["inventory"]
    assert st["bundles_cataloged"] >= 1 and st["bundles_mapped"] >= 1
    assert st["query_cache"]["hits"] >= 1

    for bad, want in [
            ({"q": "frobnicate"}, "bad_query"),
            ({"q": "neighbors"}, "bad_query"),
            ({"q": "neighbors", "job_id": "inope", "gene": gene},
             "not_found"),
            ({"q": "neighbors", "job_id": served["job_id"],
              "variant": "v9", "gene": gene}, "not_found"),
            ({"q": "neighbors", "job_id": served["job_id"],
              "gene": 7}, "bad_query"),
            ({"q": "neighbors", "job_id": served["job_id"],
              "gene": gene, "k": True}, "bad_query"),
            ({"q": "neighbors", "job_id": served["job_id"],
              "gene": "NOT_A_GENE"}, "bad_query")]:
        resp = d.handle_query(bad)
        assert resp["event"] == "error" and resp["error"] == want, bad


def test_solo_emit_inventory_bundle_is_byte_identical(served, tsv_paths,
                                                      tmp_path):
    """--emit-inventory on a solo run writes the SAME array bytes the
    daemon published for the equivalent lane — the PR 5 parity contract
    extended to the query plane's binary format."""
    from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
    from g2vec_tpu.config import config_from_job
    from g2vec_tpu.io.writers import INVENTORY_ARRAYS
    from g2vec_tpu.pipeline import run as solo_run

    os.makedirs(os.path.join(str(tmp_path), "out"), exist_ok=True)
    cfg = config_from_job(_job(tsv_paths, tmp_path, "solo1"))
    cfg = dataclasses.replace(cfg, emit_inventory=True)
    v = _variant_from_dict(0, {"name": "v0", "train_seed": 1}, cfg)
    lane = lane_config(cfg, v)
    solo_run(lane, console=lambda s: None)
    solo_dir = lane.result_name + "_inventory"
    assert os.path.isdir(solo_dir)
    for fn in INVENTORY_ARRAYS:
        with open(os.path.join(_gen(solo_dir), fn), "rb") as a, \
                open(os.path.join(served["dir"], fn), "rb") as b:
            assert a.read() == b.read(), \
                f"{fn}: solo bundle differs from served bundle"
    # And the solo bundle is addressable as a depth-1 catalog key.
    cat = inventory.InventoryCatalog([os.path.dirname(solo_dir)],
                                     budget_bytes=1 << 30)
    key = os.path.basename(solo_dir)
    assert inventory.run_query(cat, "meta", key)["n_genes"] > 0


# ---------------------------------------------------------------------------
# Lazy republish, token gating, bounded result op (all jax-free fakes)
# ---------------------------------------------------------------------------

def test_daemon_lazy_republish_from_durable_record(tmp_path):
    """A tampered bundle costs latency, never a wrong answer: the query
    triggers a rebuild from the durable record's _vectors.txt, answers
    neighbors/meta, and reports topk_biomarkers as scores_unavailable
    (the [2, G] matrix is not recoverable from text outputs)."""
    d = _daemon(tmp_path)
    jid = "i" + "a" * 12
    rng = np.random.default_rng(3)
    emb = rng.integers(-5, 6, size=(20, 8)).astype(np.float32)
    genes = [f"G{i:03d}" for i in range(20)]
    vec = os.path.join(str(tmp_path), "q_vectors.txt")
    with open(vec, "w") as f:
        f.write("GeneSymbol\t" + "\t".join(f"d{i}" for i in range(8))
                + "\n")
        for g, row in zip(genes, emb):
            f.write(g + "\t" + "\t".join(repr(float(x)) for x in row)
                    + "\n")
    with open(os.path.join(d.opts.state_dir, "results", f"{jid}.json"),
              "w") as f:
        json.dump({"event": "job_done", "job_id": jid, "status": "done",
                   "variants": {"v0": {"outputs": [vec]}}}, f)
    dest = os.path.join(d.opts.state_dir, "inventory", jid, "v0")
    _plant_bundle(dest)
    path = os.path.join(_gen(dest), "embeddings.npy")
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) - 3)
        orig = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([orig[0] ^ 0xFF]))

    resp = d.handle_query({"q": "neighbors", "job_id": jid,
                           "variant": "v0", "gene": "G000", "k": 3})
    assert resp["event"] == "query_result", resp
    want, _ = _naive_cosine(emb, emb[0], 3, exclude=0)
    assert resp["neighbors"] == [genes[i] for i in want]
    meta = d.handle_query({"q": "meta", "job_id": jid, "variant": "v0"})
    assert meta["meta"]["source"] == "republish"
    tk = d.handle_query({"q": "topk_biomarkers", "job_id": jid,
                         "variant": "v0", "k": 2})
    assert tk["event"] == "error"
    assert tk["error"] == "scores_unavailable"

    # No durable record to rebuild from: the corruption surfaces as-is.
    jid2 = "i" + "b" * 12
    dest2 = os.path.join(d.opts.state_dir, "inventory", jid2, "v0")
    _plant_bundle(dest2, seed=9)
    p2 = os.path.join(_gen(dest2), "norms.npy")
    with open(p2, "r+b") as f:
        f.truncate(os.path.getsize(p2) - 4)
    resp = d.handle_query({"q": "neighbors", "job_id": jid2,
                           "variant": "v0", "gene": "G000", "k": 2})
    assert resp["event"] == "error" and resp["error"] == "tampered"


def test_query_op_is_token_gated(tmp_path):
    d = _daemon(tmp_path, auth_token="sekret-42")
    resp = _roundtrip(d, {"op": "query", "q": "list"})
    assert resp["event"] == "rejected" and resp["error"] == "unauthorized"
    resp = _roundtrip(d, {"op": "query", "q": "list",
                          "auth_token": "wrong"})
    assert resp["event"] == "rejected"
    resp = _roundtrip(d, {"op": "query", "q": "list",
                          "auth_token": "sekret-42"})
    assert resp["event"] == "query_result" and resp["bundles"] == []
    # Health stays credential-free: the router's probes must not need
    # the secret.
    assert _roundtrip(d, {"op": "status"})["event"] == "status"


def test_result_op_is_bounded(tmp_path):
    rec = {"event": "job_done", "job_id": "i" + "c" * 12,
           "status": "done", "acc_val": 0.9,
           "outputs": ["x" * 2000], "variants": {"v": {"acc": 1}}}
    # The shared bounding primitive: selector + cap.
    out = protocol.bound_record(rec, ["status"], None, 1 << 20)
    assert out == {"event": "job_done", "job_id": rec["job_id"],
                   "status": "done"}
    out = protocol.bound_record(rec, "status", None, 1 << 20)
    assert out["error"] == "bad_fields"
    out = protocol.bound_record(rec, None, 256, 1 << 20)
    assert out["error"] == "oversized_result"
    assert out["bytes"] > 256 and out["max_bytes"] == 256
    assert "outputs" in out["fields_available"]
    # The server cap binds even a greedy client max_bytes.
    assert protocol.bound_record(rec, None, 1 << 20,
                                 256)["error"] == "oversized_result"

    # End to end over the connection handler, against a planted record.
    d = _daemon(tmp_path, max_result_bytes=300)
    with open(os.path.join(d.opts.state_dir, "results",
                           f"{rec['job_id']}.json"), "w") as f:
        json.dump(rec, f)
    resp = _roundtrip(d, {"op": "result", "job_id": rec["job_id"]})
    assert resp["error"] == "oversized_result"
    resp = _roundtrip(d, {"op": "result", "job_id": rec["job_id"],
                          "fields": ["status", "acc_val"]})
    assert resp == {"event": "job_done", "job_id": rec["job_id"],
                    "status": "done", "acc_val": 0.9}
    resp = _roundtrip(d, {"op": "result", "job_id": "i" + "d" * 12})
    assert resp["event"] == "pending"


# ---------------------------------------------------------------------------
# Router: failover reads from shared disk when the home replica is dead
# ---------------------------------------------------------------------------

def test_router_answers_query_for_dead_replica(tmp_path):
    """No replica process ever boots: every bundle owner is dead, so
    the router maps the bundle from the shared fleet directory and
    answers with the same inventory.run_query the daemon uses."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=2),
               console=lambda s: None)
    jid = "i" + "e" * 12
    dest = os.path.join(fleet_dir, "r0", "state", "inventory", jid, "v0")
    emb, genes, scores = _plant_bundle(dest, g=25, h=8, seed=5)
    assert r._bundle_owner(jid) == "r0"

    resp = r.handle_query({"q": "neighbors", "job_id": jid,
                           "gene": "G004", "k": 3})
    assert resp["event"] == "query_result"
    assert resp["served_by"] == "router"
    ridx, rsims = _naive_cosine(emb, emb[4], 3, exclude=4)
    assert resp["neighbors"] == [genes[i] for i in ridx]
    assert resp["sims"] == [float(s) for s in rsims]

    tk = r.handle_query({"q": "topk_biomarkers", "job_id": jid, "k": 2})
    assert tk["event"] == "query_result" and tk["served_by"] == "router"
    meta = r.handle_query({"q": "meta", "job_id": jid, "variant": "v0"})
    assert meta["n_genes"] == 25

    lst = r.handle_query({"q": "list"})
    ent = next(e for e in lst["bundles"] if e["bundle"] == f"{jid}/v0")
    assert ent["replica"] == "r0" and ent["replica_down"] is True

    resp = r.handle_query({"q": "neighbors", "job_id": "i" + "f" * 12,
                           "gene": "G000"})
    assert resp["event"] == "error" and resp["error"] == "not_found"
    # Ambiguity is the same structured refusal the daemon gives.
    _plant_bundle(os.path.join(fleet_dir, "r0", "state", "inventory",
                               jid, "v1"), g=25, h=8, seed=6)
    resp = r.handle_query({"q": "meta", "job_id": jid})
    assert resp["error"] == "ambiguous_variant"
    assert resp["variants"] == ["v0", "v1"]


def test_owner_and_resolve_caches_skip_rescans(tmp_path, monkeypatch):
    """The warm query path never walks directories: the router caches
    job->owner (placement is sticky, bundles never move), the daemon
    caches its scan_bundles view (it is the only writer of its root).
    Misses still rescan, so late-published bundles are found."""
    from g2vec_tpu.serve.daemon import ServeDaemon
    from g2vec_tpu.serve.router import Router, RouterOptions

    calls = {"n": 0}
    real_scan = inventory.scan_bundles

    def counting_scan(roots):
        calls["n"] += 1
        return real_scan(roots)

    monkeypatch.setattr(inventory, "scan_bundles", counting_scan)

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=3),
               console=lambda s: None)
    jid = "i" + "a" * 12
    _plant_bundle(os.path.join(fleet_dir, "r1", "state", "inventory",
                               jid, "v0"), g=10, h=4, seed=1)
    assert r._bundle_owner(jid) == "r1"
    first = calls["n"]
    assert first >= 2                 # walked r0 then found it on r1
    for _ in range(5):
        assert r._bundle_owner(jid) == "r1"
    assert calls["n"] == first        # every repeat was a dict hit
    # A genuinely unknown job still rescans (and stays uncached).
    assert r._bundle_owner("i" + "b" * 12) is None
    assert calls["n"] == first + 3

    d = _daemon(tmp_path)
    jid2 = "i" + "c" * 12
    _plant_bundle(os.path.join(str(tmp_path), "state", "inventory",
                               jid2, "v0"), g=10, h=4, seed=2)
    calls["n"] = 0
    assert d._resolve_bundle(jid2, None) == (f"{jid2}/v0", None)
    assert calls["n"] == 1            # cold: one rescan populated it
    for variant in (None, "v0"):
        assert d._resolve_bundle(jid2, variant) == (f"{jid2}/v0", None)
    assert calls["n"] == 1            # warm: zero directory walks
    # A bundle that appears after the cache was built is still found:
    # the miss rescans before erroring.
    jid3 = "i" + "d" * 12
    _plant_bundle(os.path.join(str(tmp_path), "state", "inventory",
                               jid3, "v0"), g=10, h=4, seed=3)
    assert d._resolve_bundle(jid3, None) == (f"{jid3}/v0", None)
    assert calls["n"] == 2
    # Publish-time reset keeps omitted-variant auto-resolve exact.
    d._inv_known = {}
    _plant_bundle(os.path.join(str(tmp_path), "state", "inventory",
                               jid2, "v1"), g=10, h=4, seed=4)
    key, err = d._resolve_bundle(jid2, None)
    assert key is None and err["error"] == "ambiguous_variant"
