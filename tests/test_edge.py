"""Edge-partitioned CSR suite (parallel/shard.py edge section, io/readers.py
range readers, data/synth.py partitioned emission, --edge-partition
pipeline wiring) — tier-1.

Contracts pinned here:

1. **Partitioning math**: ``edge_range`` tiles ``[0, G)`` exactly,
   ``owners_of`` agrees with it, ``build_partitioned_csr`` rejects rows
   outside the owned range.
2. **Engine byte identity**: multi-rank ``run_edge_walk`` — under BOTH
   boundary strategies (handoff batches, halo-replicated rows) —
   reproduces ``walk_shard``'s rows byte-for-byte; a single full-range
   rank is byte-identical with no exchange at all.
3. **Handoff edge cases**: a walk whose LAST step lands on a foreign
   gene terminates locally (no handoff); a handed-off walk that
   dead-ends immediately at the boundary gene resumes and finishes on
   the owner of that gene; a rank with nothing to send still publishes
   its (empty) round payload; zero cross-partition walks still cost
   exactly one all-pairs termination-barrier round.
4. **Range-filtered readers**: partitioned emission concat-equals the
   flat file, manifest sha256s verify (and corruption is caught), and
   the ``G2VEC_FORBID_FULL_NETWORK`` pin proves ``--edge-partition``
   runs never reach the unpartitioned reader.
5. **1-rank pipeline byte identity**: ``--edge-partition handoff|halo``
   at one process == plain streaming, byte-for-byte, under the pin.
6. **2-rank fleet**: handoff ≡ halo byte-identical to each other under
   the pin, within the PR 7 statistical band vs the unpartitioned run.
7. **Fault drills**: a rank sigkilled at the ``walk_handoff`` /
   ``halo_build`` seams is NAMED by the survivor's PeerTimeoutError.
"""
import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.edge

HAVE_CXX = shutil.which("g++") is not None
needs_native = pytest.mark.skipif(not HAVE_CXX, reason="no C++ toolchain")

_WORKER = os.path.join(os.path.dirname(__file__), "shard_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# 1. Partitioning math (no jax, no native, no processes)
# ---------------------------------------------------------------------------

def test_edge_range_tiles_exactly():
    from g2vec_tpu.parallel.shard import edge_bounds, edge_range, owners_of

    for n_genes, n_ranks in ((2, 2), (100, 3), (1000, 4), (9999, 7),
                             (1 << 20, 4)):
        bounds = edge_bounds(n_ranks, n_genes)
        prev_hi = 0
        for r in range(n_ranks):
            lo, hi = edge_range(r, n_ranks, n_genes)
            assert lo == prev_hi               # contiguous, no gaps/overlap
            assert bounds[r] == lo
            prev_hi = hi
        assert prev_hi == n_genes              # ranges tile [0, G)
        genes = np.arange(n_genes, dtype=np.int64)
        owners = owners_of(genes, bounds)
        for r in range(n_ranks):
            lo, hi = edge_range(r, n_ranks, n_genes)
            assert (owners[lo:hi] == r).all()  # owner lookup agrees
    with pytest.raises(ValueError, match="rank"):
        edge_range(2, 2, 100)


def test_build_partitioned_csr_guards_owned_range():
    from g2vec_tpu.parallel.shard import build_partitioned_csr

    src = np.array([2, 3], np.int32)
    dst = np.array([0, 5], np.int32)
    w = np.ones(2, np.float32)
    p = build_partitioned_csr(src, dst, w, 8, 2, 4)
    assert p.avail[2:4].all() and not p.avail[:2].any() \
        and not p.avail[4:].any()
    assert p.owned_edges == 2 and p.halo_edges == 0
    assert p.csr_bytes == (p.indptr.nbytes + p.indices.nbytes
                           + p.weights.nbytes + p.avail.nbytes)
    assert p.halo_bytes == 0 and p.halo_overhead_ratio == 0.0
    with pytest.raises(ValueError, match="owned range"):
        build_partitioned_csr(src, dst, w, 8, 3, 4)   # src 2 outside [3, 4)
    with pytest.raises(ValueError, match="outside"):
        build_partitioned_csr(src, np.array([0, 9], np.int32), w, 8, 2, 4)


# ---------------------------------------------------------------------------
# In-process fleet harness: ranks as threads over a local KV exchange
# ---------------------------------------------------------------------------

class _LocalExchange:
    """exchange_bytes stand-in: a dict + condvar, PeerTimeoutError naming
    the owner on deadline expiry (the real transport's shape)."""

    def __init__(self):
        self.store = {}
        self.cv = threading.Condition()

    def __call__(self, key, payload, owner, deadline=None, chunk_bytes=None):
        from g2vec_tpu.resilience.fleet import PeerTimeoutError

        if payload is not None:
            with self.cv:
                self.store[key] = payload
                self.cv.notify_all()
            return payload
        t_end = time.monotonic() + (deadline or 30.0)
        with self.cv:
            while key not in self.store:
                left = t_end - time.monotonic()
                if left <= 0:
                    raise PeerTimeoutError(
                        f"local get {key!r} timed out; missing rank(s): "
                        f"[{owner}]", collective=key, suspects=(owner,))
                self.cv.wait(left)
            return self.store[key]


def _partition(src, dst, w, n_genes, rank, n_ranks):
    from g2vec_tpu.parallel.shard import build_partitioned_csr, edge_range

    lo, hi = edge_range(rank, n_ranks, n_genes)
    m = (src >= lo) & (src < hi)
    return build_partitioned_csr(src[m], dst[m], w[m], n_genes, lo, hi)


def _build_halos(pcsrs, n_ranks):
    from g2vec_tpu.parallel.shard import build_halo_csr

    ex = _LocalExchange()
    out, errs = [None] * n_ranks, []

    def worker(r):
        try:
            out[r] = build_halo_csr(pcsrs[r], rank=r, n_ranks=n_ranks,
                                    group=0, exchange=ex, deadline=20.0)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(n_ranks)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    if errs:
        raise errs[0]
    return out


def _run_fleet(pcsrs, plan, si, seed, owner, n_ranks, *, starts=None,
               stats=None):
    from g2vec_tpu.parallel.shard import EdgeWalkStats, run_edge_walk

    ex = _LocalExchange()
    stats = stats if stats is not None else [EdgeWalkStats()] * n_ranks
    results, errs = [None] * n_ranks, []

    def worker(r):
        try:
            results[r] = run_edge_walk(
                pcsrs[r], plan, si, seed=seed, owner=owner, rank=r,
                n_ranks=n_ranks, starts=starts, n_threads=1, exchange=ex,
                deadline=30.0, key_prefix=f"t/{seed}", stats=stats[r])
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(n_ranks)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    if errs:
        raise errs[0]
    return results


def _rand_graph(n_genes, n_edges, seed):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_genes, n_edges).astype(np.int32)
    dst = rng.integers(0, n_genes, n_edges).astype(np.int32)
    keep = src != dst
    return src[keep], dst[keep], rng.random(int(keep.sum())).astype(
        np.float32)


# ---------------------------------------------------------------------------
# 2. Engine byte identity: handoff == halo == walk_shard
# ---------------------------------------------------------------------------

@needs_native
def test_engine_multirank_matches_walk_shard():
    from g2vec_tpu.ops.host_walker import edges_to_csr, plan_shards, \
        walk_shard

    n_genes, len_path = 200, 12
    src, dst, w = _rand_graph(n_genes, 2500, seed=7)
    plan = plan_shards(n_genes, 3, 64, len_path=len_path)
    csr = edges_to_csr(src, dst, w, n_genes)
    for n_ranks in (2, 3):
        pcsrs = [_partition(src, dst, w, n_genes, r, n_ranks)
                 for r in range(n_ranks)]
        halos = _build_halos(pcsrs, n_ranks)
        for h in halos:                        # halo accounting sanity
            assert h.halo_bytes == 8 * h.halo_edges
            assert h.avail[h.halo_genes].all()
            assert h.owned_edges == pcsrs[halos.index(h)].owned_edges
        for si in range(min(plan.n_shards, 3)):
            ref = walk_shard(src, dst, w, n_genes, plan, si, seed=11,
                             n_threads=1, csr=csr)
            owner = si % n_ranks
            res = _run_fleet(pcsrs, plan, si, 11, owner, n_ranks)
            hres = _run_fleet(halos, plan, si, 11, owner, n_ranks)
            for r in range(n_ranks):
                if r == owner:
                    assert res[r].tobytes() == ref.tobytes()
                    assert hres[r].tobytes() == ref.tobytes()
                else:                          # only the owner gets rows
                    assert res[r] is None and hres[r] is None


@needs_native
def test_engine_single_rank_identical_no_exchange():
    from g2vec_tpu.ops.host_walker import edges_to_csr, plan_shards, \
        walk_shard
    from g2vec_tpu.parallel.shard import build_partitioned_csr, run_edge_walk

    n_genes = 120
    src, dst, w = _rand_graph(n_genes, 1200, seed=3)
    plan = plan_shards(n_genes, 2, 64, len_path=10)
    csr = edges_to_csr(src, dst, w, n_genes)
    full = build_partitioned_csr(src, dst, w, n_genes, 0, n_genes)
    for si in range(min(plan.n_shards, 2)):
        ref = walk_shard(src, dst, w, n_genes, plan, si, seed=11,
                         n_threads=1, csr=csr)
        got = run_edge_walk(full, plan, si, seed=11, owner=0, rank=0,
                            n_ranks=1, n_threads=1)   # exchange never needed
        assert got.tobytes() == ref.tobytes()


# ---------------------------------------------------------------------------
# 3. Handoff edge cases (tiny deterministic graphs)
# ---------------------------------------------------------------------------

def _tiny(plan_starts, reps, len_path):
    from g2vec_tpu.ops.host_walker import plan_shards

    return plan_shards(plan_starts, reps, 1024, len_path=len_path)


@needs_native
def test_last_step_at_boundary_terminates_without_handoff():
    """A walk whose FINAL slot is filled by a foreign gene is done —
    pos==len_path wins over the availability check, so no state is ever
    shipped for it (and termination still costs one all-zero round)."""
    from g2vec_tpu.ops.host_walker import walk_shard
    from g2vec_tpu.parallel.shard import EdgeWalkStats

    n_genes = 2                                # rank 0 owns {0}, rank 1 {1}
    src = np.array([0, 1], np.int32)           # 0 -> 1, 1 -> 0
    dst = np.array([1, 0], np.int32)
    w = np.ones(2, np.float32)
    starts = np.array([0], np.int32)
    plan = _tiny(1, 2, len_path=2)             # path = [0, 1], full at 1
    pcsrs = [_partition(src, dst, w, n_genes, r, 2) for r in range(2)]
    ref = walk_shard(src, dst, w, n_genes, plan, 0, seed=5, n_threads=1,
                     starts=starts)
    stats = [EdgeWalkStats() for _ in range(2)]
    res = _run_fleet(pcsrs, plan, 0, 5, 0, 2, starts=starts, stats=stats)
    assert res[0].tobytes() == ref.tobytes()
    assert stats[0].states_sent == 0           # terminal step, no handoff
    assert stats[0].batches == 0
    assert stats[0].rounds == 1                # the termination barrier


@needs_native
def test_handoff_resumes_and_dead_ends_at_boundary_gene():
    """Mid-walk handoff with the handed gene a dead end: the receiving
    owner resumes, immediately dead-ends, and the finished row rides the
    next round's payload back to the shard owner. Rank 1 has nothing to
    send in round 0 — its EMPTY payload must still arrive (the empty
    exchange round) or the live-count barrier would wedge."""
    from g2vec_tpu.ops.host_walker import walk_shard
    from g2vec_tpu.parallel.shard import EdgeWalkStats

    n_genes = 2
    src = np.array([0], np.int32)              # 0 -> 1; gene 1 dead-ends
    dst = np.array([1], np.int32)
    w = np.ones(1, np.float32)
    starts = np.array([0], np.int32)
    plan = _tiny(1, 2, len_path=6)             # room left when it suspends
    pcsrs = [_partition(src, dst, w, n_genes, r, 2) for r in range(2)]
    ref = walk_shard(src, dst, w, n_genes, plan, 0, seed=5, n_threads=1,
                     starts=starts)
    stats = [EdgeWalkStats() for _ in range(2)]
    res = _run_fleet(pcsrs, plan, 0, 5, 0, 2, starts=starts, stats=stats)
    assert res[0].tobytes() == ref.tobytes()
    assert stats[0].states_sent == plan.group_rows(0)   # every rep crossed
    assert stats[0].batches == 1               # one destination batch
    assert stats[1].states_sent == 0           # rank 1 only finishes them
    assert stats[0].rounds >= 2                # suspend round + return round
    # Halo replication of gene 1's (empty) row finishes the same walks
    # locally in ONE round — and the rows stay byte-identical.
    halos = _build_halos(pcsrs, 2)
    hstats = [EdgeWalkStats() for _ in range(2)]
    hres = _run_fleet(halos, plan, 0, 5, 0, 2, starts=starts, stats=hstats)
    assert hres[0].tobytes() == ref.tobytes()
    assert hstats[0].states_sent == 0
    assert hstats[0].rounds == 1


@needs_native
def test_handoff_with_exactly_one_step_remaining():
    """Suspension with depth-1 remaining: the receiving rank takes one
    step, fills the last slot, and the walk is done."""
    from g2vec_tpu.ops.host_walker import walk_shard
    from g2vec_tpu.parallel.shard import EdgeWalkStats, edge_range

    n_genes = 3                                # rank 0 owns {0}, rank 1 {1,2}
    assert edge_range(0, 2, 3) == (0, 1) and edge_range(1, 2, 3) == (1, 3)
    src = np.array([0, 1, 2], np.int32)        # deterministic chain 0->1->2
    dst = np.array([1, 2, 0], np.int32)
    w = np.ones(3, np.float32)
    starts = np.array([0], np.int32)
    plan = _tiny(1, 2, len_path=3)             # suspend at 1 with ONE slot
    pcsrs = [_partition(src, dst, w, n_genes, r, 2) for r in range(2)]
    ref = walk_shard(src, dst, w, n_genes, plan, 0, seed=9, n_threads=1,
                     starts=starts)
    stats = [EdgeWalkStats() for _ in range(2)]
    res = _run_fleet(pcsrs, plan, 0, 9, 0, 2, starts=starts, stats=stats)
    assert res[0].tobytes() == ref.tobytes()
    assert stats[0].states_sent == plan.group_rows(0)
    assert ref[0].any()                        # rows are real multi-hot


@needs_native
def test_zero_cross_partition_walks_single_barrier_round():
    """Two disconnected per-rank components, all starts in the owner's
    range: nothing ever crosses, yet every rank still runs exactly one
    all-pairs round (the termination barrier) and agrees to stop."""
    from g2vec_tpu.ops.host_walker import walk_shard
    from g2vec_tpu.parallel.shard import EdgeWalkStats

    n_genes = 4                                # rank 0 owns {0,1}, rank 1 {2,3}
    src = np.array([0, 1, 2, 3], np.int32)     # two closed 2-cycles
    dst = np.array([1, 0, 3, 2], np.int32)
    w = np.ones(4, np.float32)
    starts = np.array([0, 1], np.int32)        # both in rank 0's range
    plan = _tiny(2, 2, len_path=5)
    pcsrs = [_partition(src, dst, w, n_genes, r, 2) for r in range(2)]
    ref = walk_shard(src, dst, w, n_genes, plan, 0, seed=13, n_threads=1,
                     starts=starts)
    stats = [EdgeWalkStats() for _ in range(2)]
    res = _run_fleet(pcsrs, plan, 0, 13, 0, 2, starts=starts, stats=stats)
    assert res[0].tobytes() == ref.tobytes()
    assert stats[0].states_sent == 0 and stats[1].states_sent == 0
    assert stats[0].rounds == 1 and stats[1].rounds == 1
    assert stats[0].peak_in_flight == 0


# ---------------------------------------------------------------------------
# 4. Range-filtered readers + partitioned emission
# ---------------------------------------------------------------------------

def _body(path):
    with open(path, "rb") as f:
        return f.read().split(b"\n", 1)[1]     # drop the header line


def test_partitioned_emission_concat_equals_flat(tmp_path):
    from g2vec_tpu.data.synth import SynthGraphSpec, write_synth_graph_streamed
    from g2vec_tpu.io.readers import (load_network_range,
                                      read_partition_manifest,
                                      scan_network_genes)

    spec = SynthGraphSpec(n_genes=1200, n_good=4, n_poor=4, seed=3)
    flat = write_synth_graph_streamed(spec, str(tmp_path / "flat"),
                                      prefix="f")["network"]
    man = write_synth_graph_streamed(spec, str(tmp_path / "part"),
                                     prefix="p", partitions=3)["network"]
    assert man.endswith(".manifest.json")
    m = read_partition_manifest(man)
    base = os.path.dirname(man)
    # Concatenated part bodies == the flat emission's body, byte-for-byte.
    concat = b"".join(_body(os.path.join(base, e["name"]))
                      for e in m["files"])
    assert concat == _body(flat)
    assert sum(e["n_edges"] for e in m["files"]) == concat.count(b"\n")
    # Bytes are chunk-size independent (the streamed-generator contract).
    man2 = write_synth_graph_streamed(spec, str(tmp_path / "part2"),
                                      prefix="p", partitions=3,
                                      edge_chunk=777)["network"]
    for e in m["files"]:
        with open(os.path.join(base, e["name"]), "rb") as a, \
                open(os.path.join(os.path.dirname(man2), e["name"]),
                     "rb") as b:
            assert a.read() == b.read()
    # Gene scans and range reads agree between flat file and manifest.
    genes = sorted(scan_network_genes(flat))
    assert scan_network_genes(man) == set(genes)
    g2i = {g: i for i, g in enumerate(genes)}
    for lo, hi in ((0, len(genes)), (0, len(genes) // 3),
                   (len(genes) // 3, len(genes))):
        fs, fd = load_network_range(flat, g2i, lo, hi)
        ms, md = load_network_range(man, g2i, lo, hi)
        np.testing.assert_array_equal(fs, ms)
        np.testing.assert_array_equal(fd, md)
        assert fs.size == 0 or (fs.min() >= lo and fs.max() < hi)


def test_partition_manifest_detects_corruption(tmp_path):
    from g2vec_tpu.data.synth import SynthGraphSpec, write_synth_graph_streamed
    from g2vec_tpu.io.readers import (load_network_range,
                                      read_partition_manifest,
                                      scan_network_genes)

    spec = SynthGraphSpec(n_genes=600, n_good=4, n_poor=4, seed=5)
    man = write_synth_graph_streamed(spec, str(tmp_path), prefix="c",
                                     partitions=2)["network"]
    genes = sorted(scan_network_genes(man))
    g2i = {g: i for i, g in enumerate(genes)}
    load_network_range(man, g2i, 0, len(genes))          # clean read works
    victim = os.path.join(os.path.dirname(man),
                          read_partition_manifest(man)["files"][0]["name"])
    with open(victim, "ab") as f:
        f.write(b"SGBOGUS\tSGBOGUS\n")
    with pytest.raises(ValueError, match="sha256"):
        load_network_range(man, g2i, 0, len(genes))


def test_forbid_full_network_pin(tmp_path, monkeypatch):
    """The acceptance pin: under G2VEC_FORBID_FULL_NETWORK the
    unpartitioned reader RAISES, while the streamed range path (what
    --edge-partition uses) keeps working."""
    from g2vec_tpu.io.readers import (FORBID_FULL_NETWORK_ENV, load_network,
                                      load_network_range, scan_network_genes)

    net = tmp_path / "net.txt"
    net.write_text("src\tdest\nSGA\tSGB\nSGB\tSGC\n")
    monkeypatch.setenv(FORBID_FULL_NETWORK_ENV, "1")
    with pytest.raises(RuntimeError, match="scan_network_genes"):
        load_network(str(net))
    assert scan_network_genes(str(net)) == {"SGA", "SGB", "SGC"}
    g2i = {"SGA": 0, "SGB": 1, "SGC": 2}
    src, dst = load_network_range(str(net), g2i, 0, 2)
    np.testing.assert_array_equal(src, [0, 1])
    np.testing.assert_array_equal(dst, [1, 2])


def test_make_synth_graph_partitions_cli(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "make_synth_graph.py"),
         "--nodes", "600", "--good", "4", "--poor", "4",
         "--partitions", "2", "--out", str(tmp_path), "--prefix", "cli"],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-500:]
    summary = json.loads(proc.stdout)
    assert summary["streamed"] is True         # --partitions implies --stream
    assert summary["network"].endswith(".manifest.json")
    from g2vec_tpu.io.readers import read_partition_manifest

    m = read_partition_manifest(summary["network"])
    assert m["partitions"] == 2 and len(m["files"]) == 2
    assert sum(e["n_edges"] for e in m["files"]) == int(summary["n_edges"])


# ---------------------------------------------------------------------------
# Shared pipeline fixtures/helpers (test_shard.py's dataset scale)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="session")
def edge_tsv(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(
        n_good=30, n_poor=26, module_size=16, shared_module_size=6,
        n_background=24, n_expr_only=4, n_net_only=4, module_chords=3,
        background_edges=40, noise=0.25, shift=1.4, seed=7)
    return write_synthetic_tsv(
        spec, str(tmp_path_factory.mktemp("edge_data")))


def _cfg_dict(paths, out, **over):
    base = dict(
        expression_file=paths["expression"], clinical_file=paths["clinical"],
        network_file=paths["network"], result_name=out,
        lenPath=20, numRepetition=4, sizeHiddenlayer=32, epoch=8,
        numBiomarker=10, seed=11, compute_dtype="float32",
        walker_backend="native", train_mode="streaming", shard_paths=64)
    base.update(over)
    return base


def _run(paths, out, **over):
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.pipeline import run

    return run(G2VecConfig(**_cfg_dict(paths, out, **over)),
               console=lambda s: None)


def _read_files(result_name):
    out = {}
    for suffix in ("_biomarkers.txt", "_lgroups.txt", "_vectors.txt"):
        with open(result_name + suffix, "rb") as f:
            out[suffix] = f.read()
    return out


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rank_env(port: int, process_id: int, n_ranks: int,
              extra: dict = None) -> dict:
    drop = ("PALLAS_AXON", "AXON_", "TPU_", "JAX_", "XLA_", "LIBTPU", "PJRT_")
    env = {k: v for k, v in os.environ.items() if not k.startswith(drop)}
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon" not in p.lower()]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + parts)
    env["JAX_PLATFORMS"] = "cpu"
    env["G2VEC_COORDINATOR"] = f"127.0.0.1:{port}"
    env["G2VEC_PROCESS_ID"] = str(process_id)
    env["G2VEC_NUM_PROCESSES"] = str(n_ranks)
    env.update(extra or {})
    return env


def _launch_fleet(tmp_path, cfg_dict, n_ranks, timeout=420, extra_env=None,
                  tag="edge_cfg"):
    cfg_path = tmp_path / f"{tag}.json"
    cfg_path.write_text(json.dumps(cfg_dict))
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(cfg_path)],
        env=_rank_env(port, i, n_ranks, extra_env), cwd=_REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for i in range(n_ranks)]
    out = []
    try:
        for i, p in enumerate(procs):
            try:
                stdout, stderr = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail(f"rank {i} timed out after {timeout}s")
            lines = [ln for ln in stdout.strip().splitlines() if ln]
            out.append((p.returncode, lines[-1] if lines else None, stderr))
    finally:
        for q in procs:                         # a dead sibling must not wedge
            if q.poll() is None:
                q.kill()
    return out


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------

def test_edge_partition_config_validation(edge_tsv, tmp_path):
    from g2vec_tpu.config import G2VecConfig

    def cfg(**over):
        c = G2VecConfig(**_cfg_dict(edge_tsv, str(tmp_path / "o"), **over))
        c.validate()
        return c

    cfg(edge_partition="handoff")              # the valid shapes construct
    cfg(edge_partition="halo")
    with pytest.raises(ValueError, match="edge_partition"):
        cfg(edge_partition="bogus")
    with pytest.raises(ValueError, match="streaming"):
        cfg(edge_partition="handoff", train_mode="full")
    with pytest.raises(ValueError, match="device"):
        cfg(edge_partition="handoff", walker_backend="device")
    with pytest.raises(ValueError, match="graph-shards"):
        cfg(edge_partition="handoff", distributed=True, num_processes=2)
    with pytest.raises(ValueError, match="checkpoint"):
        cfg(edge_partition="handoff", checkpoint_dir=str(tmp_path / "ck"))


# ---------------------------------------------------------------------------
# 5. 1-rank pipeline byte identity, under the forbidden-reader pin
# ---------------------------------------------------------------------------

@needs_native
def test_single_rank_edge_partition_byte_identical(edge_tsv, tmp_path,
                                                   monkeypatch):
    from g2vec_tpu.io.readers import FORBID_FULL_NETWORK_ENV

    ref = _run(edge_tsv, str(tmp_path / "ref"))
    # The pin: any touch of the unpartitioned reader now RAISES — an
    # --edge-partition run that completes proves it stayed range-filtered.
    monkeypatch.setenv(FORBID_FULL_NETWORK_ENV, "1")
    for mode in ("handoff", "halo"):
        res = _run(edge_tsv, str(tmp_path / mode), edge_partition=mode)
        assert _read_files(str(tmp_path / mode)) == _read_files(
            str(tmp_path / "ref")), f"1-rank {mode} != plain streaming"
        assert res.acc_val == ref.acc_val
        assert res.n_paths == ref.n_paths


# ---------------------------------------------------------------------------
# 6. TRUE 2-process fleets: handoff ≡ halo, PR 7 band vs unpartitioned
# ---------------------------------------------------------------------------

@needs_native
def test_two_rank_handoff_equals_halo_fleet(edge_tsv, tmp_path):
    from g2vec_tpu.io.readers import FORBID_FULL_NETWORK_ENV

    ref = _run(edge_tsv, str(tmp_path / "ref"), stream_patience=8)
    pin = {FORBID_FULL_NETWORK_ENV: "1"}
    parsed = {}
    for mode in ("handoff", "halo"):
        cfg = _cfg_dict(edge_tsv, str(tmp_path / mode),
                        stream_patience=8, distributed=True,
                        graph_shards=2, embed_shards=2,
                        edge_partition=mode, fleet_watchdog_deadline=120.0)
        results = _launch_fleet(tmp_path, cfg, n_ranks=2, extra_env=pin,
                                tag=mode)
        for i, (rc, line, stderr) in enumerate(results):
            assert rc == 0, f"{mode} rank {i} failed:\n{stderr[-3000:]}"
        parsed[mode] = json.loads(results[0][1])
    # The tentpole contract: the two boundary strategies are the SAME
    # run — byte-identical outputs, not just statistically close.
    assert _read_files(str(tmp_path / "handoff")) == _read_files(
        str(tmp_path / "halo"))
    assert parsed["handoff"]["acc_val"] == pytest.approx(
        parsed["halo"]["acc_val"])
    assert parsed["handoff"]["n_paths"] == parsed["halo"]["n_paths"]
    # And the PR 7 statistical band vs the unpartitioned streaming run.
    assert abs(parsed["handoff"]["acc_val"] - ref.acc_val) <= 0.20
    a = set(ref.biomarkers)
    b = set(parsed["handoff"]["biomarkers"])
    assert len(a & b) / max(len(a), 1) >= 0.6


# ---------------------------------------------------------------------------
# 7. Fault drills: the survivor NAMES the rank that died at the seam
# ---------------------------------------------------------------------------

@needs_native
def test_walk_handoff_sigkill_names_dead_rank(edge_tsv, tmp_path):
    cfg = _cfg_dict(edge_tsv, str(tmp_path / "out"), distributed=True,
                    graph_shards=2, embed_shards=2, edge_partition="handoff",
                    fleet_watchdog_deadline=15.0,
                    fault_plan="process=1,stage=walk_handoff,kind=sigkill")
    results = _launch_fleet(tmp_path, cfg, n_ranks=2, timeout=300)
    assert results[1][0] == -9                  # rank 1 really sigkilled
    rc0, _, stderr0 = results[0]
    assert rc0 != 0
    assert "PeerTimeoutError" in stderr0
    assert "missing rank(s): [1]" in stderr0


@needs_native
def test_halo_build_sigkill_names_dead_rank(edge_tsv, tmp_path):
    cfg = _cfg_dict(edge_tsv, str(tmp_path / "out"), distributed=True,
                    graph_shards=2, embed_shards=2, edge_partition="halo",
                    fleet_watchdog_deadline=15.0,
                    fault_plan="process=1,stage=halo_build,kind=sigkill")
    results = _launch_fleet(tmp_path, cfg, n_ranks=2, timeout=300)
    assert results[1][0] == -9
    rc0, _, stderr0 = results[0]
    assert rc0 != 0
    assert "PeerTimeoutError" in stderr0
    assert "missing rank(s): [1]" in stderr0
