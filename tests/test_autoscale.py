"""Elastic serve-fleet admission + scaling layer, in isolation.

Every test here is pure (clock-injected token buckets, a fed-by-hand
scaling policy, an in-process queue) or in-process (a Router object
with no booted replicas, a fake UNIX-socket server for the client shed
path) — no subprocesses, so the suite holds tier-1 cost. The full
elastic fleet under diurnal/burst load with SIGKILLs runs in
tools/chaos_soak.py --autoscale (bench.py --_autoscale_ab commits the
A/B evidence).
"""
import os
import socket
import threading

import pytest

pytestmark = pytest.mark.autoscale


# ---------------------------------------------------------------------------
# Token bucket
# ---------------------------------------------------------------------------

def test_token_bucket_burst_then_refill():
    from g2vec_tpu.resilience.lifecycle import TokenBucket

    b = TokenBucket(rate=1.0, burst=2.0)
    # Full at birth: the whole burst is admissible at t=0...
    assert b.take(0.0) and b.take(0.0)
    # ...and the third submission in the same instant is rate-limited.
    assert not b.take(0.0)
    # retry_after is the structured answer: one token at rate 1/s.
    assert b.retry_after(0.0) == pytest.approx(1.0)
    assert b.retry_after(0.5) == pytest.approx(0.5)
    # Fractional refill: at t=0.5 there is half a token — still no.
    assert not b.take(0.5)
    assert b.take(1.0)
    # Idle catch-up is capped at burst, not unbounded banking.
    assert b.take(100.0) and b.take(100.0)
    assert not b.take(100.0)


def test_token_bucket_retry_after_zero_when_available():
    from g2vec_tpu.resilience.lifecycle import TokenBucket

    b = TokenBucket(rate=2.0, burst=4.0)
    assert b.retry_after(0.0) == 0.0
    for _ in range(4):
        assert b.take(0.0)
    # rate 2/s -> half a second per token.
    assert b.retry_after(0.0) == pytest.approx(0.5)


def test_token_bucket_validates():
    from g2vec_tpu.resilience.lifecycle import TokenBucket

    with pytest.raises(ValueError):
        TokenBucket(rate=0.0, burst=1.0)
    with pytest.raises(ValueError):
        TokenBucket(rate=1.0, burst=0.0)


# ---------------------------------------------------------------------------
# Shed decision boundaries
# ---------------------------------------------------------------------------

def test_shed_decision_boundaries():
    from g2vec_tpu.resilience.lifecycle import shed_decision

    # Deadline exactly equal to the estimated wait -> ADMIT (shed only
    # on strict excess; the boundary job still has a chance).
    assert shed_decision(2.0, queued=4, service_time_s=0.5) is None
    # One more queued job tips it over: retry_after = the excess wait,
    # floored at one service time.
    ra = shed_decision(2.0, queued=5, service_time_s=0.5)
    assert ra == pytest.approx(0.5)
    ra = shed_decision(1.0, queued=5, service_time_s=0.5)
    assert ra == pytest.approx(1.5)
    # No deadline -> never shed, regardless of queue depth.
    assert shed_decision(None, queued=10 ** 6,
                         service_time_s=10.0) is None
    # No service-time evidence yet -> never shed (accept without proof).
    assert shed_decision(0.001, queued=10 ** 6,
                         service_time_s=None) is None
    # Empty queue admits even a tight deadline.
    assert shed_decision(0.0, queued=0, service_time_s=5.0) is None


# ---------------------------------------------------------------------------
# Scaling policy hysteresis
# ---------------------------------------------------------------------------

def test_policy_square_wave_never_flaps():
    """A queue-depth square wave flipping faster than the streak
    lengths must produce ZERO decisions — the whole point of streak
    counting."""
    from g2vec_tpu.resilience.lifecycle import ScalingPolicy

    p = ScalingPolicy(1, 3, up_ticks=2, down_ticks=6, cooldown_ticks=5)
    out = [p.observe(10 if t % 2 == 0 else 0, active=1)
           for t in range(40)]
    assert out == ["hold"] * 40
    assert p.decisions == 0


def test_policy_sustained_pressure_scales_up_then_cools():
    from g2vec_tpu.resilience.lifecycle import ScalingPolicy

    p = ScalingPolicy(1, 3, up_queue=4.0, up_ticks=2, cooldown_ticks=5)
    assert p.observe(10, active=1) == "hold"      # streak 1
    assert p.observe(10, active=1) == "up"        # streak 2 -> decide
    # Cooldown: sustained pressure during the hold changes nothing.
    for _ in range(5):
        assert p.observe(10, active=2) == "hold"
    # Pressure that PERSISTED through the whole cooldown has re-earned
    # its streak — the next tick may decide again immediately.
    assert p.observe(10, active=2) == "up"


def test_policy_scale_down_slow_and_bounded():
    from g2vec_tpu.resilience.lifecycle import ScalingPolicy

    p = ScalingPolicy(1, 3, down_queue=0.5, down_ticks=6,
                      cooldown_ticks=0)
    # At the floor, an idle fleet never scales below min_replicas.
    for _ in range(20):
        assert p.observe(0, active=1) == "hold"
    # Above the floor it takes down_ticks consecutive idle ticks.
    p2 = ScalingPolicy(1, 3, down_ticks=6, cooldown_ticks=0)
    out = [p2.observe(0, active=2) for _ in range(6)]
    assert out == ["hold"] * 5 + ["down"]


def test_policy_wait_signal_trips_up_at_modest_depth():
    from g2vec_tpu.resilience.lifecycle import ScalingPolicy

    p = ScalingPolicy(1, 3, up_queue=4.0, up_wait_s=8.0, up_ticks=2,
                      cooldown_ticks=0)
    # Pressure is under threshold (1 job/replica) but the estimated
    # wait says deadlines are dying: that alone must trip the up path.
    assert p.observe(1, active=1, est_wait_s=30.0) == "hold"
    assert p.observe(1, active=1, est_wait_s=30.0) == "up"


def test_policy_max_guard_and_victim_determinism():
    from g2vec_tpu.resilience.lifecycle import ScalingPolicy

    p = ScalingPolicy(1, 2, up_ticks=1, cooldown_ticks=0)
    assert p.observe(100, active=2) == "hold"     # already at max
    a = ScalingPolicy(1, 3, seed=7)
    b = ScalingPolicy(1, 3, seed=7)
    picks_a = [a.choose_victim(["r2", "r0", "r1"]) for _ in range(8)]
    picks_b = [b.choose_victim(["r0", "r1", "r2"]) for _ in range(8)]
    assert picks_a == picks_b                     # order-insensitive
    assert a.choose_victim([]) is None


def test_policy_validates():
    from g2vec_tpu.resilience.lifecycle import ScalingPolicy

    with pytest.raises(ValueError):
        ScalingPolicy(0, 2)
    with pytest.raises(ValueError):
        ScalingPolicy(3, 2)
    with pytest.raises(ValueError):
        ScalingPolicy(1, 2, up_queue=1.0, down_queue=1.0)


# ---------------------------------------------------------------------------
# Tenant quota grammar
# ---------------------------------------------------------------------------

def test_parse_tenant_quotas():
    from g2vec_tpu.serve.daemon import parse_tenant_quotas

    q = parse_tenant_quotas("gold:4:8:3;bulk:0.5:2;*:2:4:1")
    assert q["gold"].rate == 4.0 and q["gold"].burst == 8.0 \
        and q["gold"].weight == 3
    assert q["bulk"].weight == 1                  # weight defaults to 1
    assert "*" in q
    assert parse_tenant_quotas(None) == {}
    assert parse_tenant_quotas("") == {}
    for bad in ("gold", "gold:4", "gold:4:8:3:9", "gold:x:8",
                "gold:4:8:1.5", "gold:0:8", "gold:4:0", "gold:4:8:0",
                ":4:8", "gold:4:8;gold:2:2"):
        with pytest.raises(ValueError):
            parse_tenant_quotas(bad)


# ---------------------------------------------------------------------------
# Weighted-fair queue convergence
# ---------------------------------------------------------------------------

def _mk_job(job_id, tenant):
    from g2vec_tpu.serve.daemon import ServeJob

    return ServeJob(job_id=job_id, tenant=tenant, cfg=None, variants=[],
                    raw={}, submitted_at=0.0)


def test_fair_queue_weighted_convergence():
    """Two tenants in sustained contention: a tenant with weight 3 gets
    exactly 3 consecutive pops per rotation — over any window the
    service ratio converges to the weight ratio."""
    from g2vec_tpu.serve.daemon import _FairQueue

    q = _FairQueue(depth=64, aging_s=3600.0, weights={"a": 3, "b": 1})
    for i in range(24):
        q.push(_mk_job(f"a{i}", "a"))
    for i in range(8):
        q.push(_mk_job(f"b{i}", "b"))
    order = [q.pop(timeout=0).tenant for _ in range(32)]
    assert order[:16] == ["a", "a", "a", "b"] * 4
    assert order.count("a") == 24 and order.count("b") == 8


def test_fair_queue_unweighted_is_plain_round_robin():
    from g2vec_tpu.serve.daemon import _FairQueue

    q = _FairQueue(depth=64, aging_s=3600.0)
    for i in range(4):
        q.push(_mk_job(f"a{i}", "a"))
        q.push(_mk_job(f"b{i}", "b"))
    order = [q.pop(timeout=0).tenant for _ in range(8)]
    assert order == ["a", "b"] * 4


def test_fair_queue_star_default_weight():
    from g2vec_tpu.serve.daemon import _FairQueue

    q = _FairQueue(depth=64, aging_s=3600.0,
                   weights={"gold": 2, "*": 1})
    for i in range(6):
        q.push(_mk_job(f"g{i}", "gold"))
        q.push(_mk_job(f"u{i}", "unlisted"))
    order = [q.pop(timeout=0).tenant for _ in range(9)]
    assert order == ["gold", "gold", "unlisted"] * 3


# ---------------------------------------------------------------------------
# Client shed backoff (fake server — no daemon, no jax)
# ---------------------------------------------------------------------------

class _FakeServer:
    """Minimal JSONL server: scripted responses per submission, records
    every idem_key it sees."""

    def __init__(self, sock_path, script):
        self.sock_path = sock_path
        self.script = list(script)    # one entry per expected submit
        self.idem_keys = []
        self.tenants = []
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(sock_path)
        self._srv.listen(8)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        from g2vec_tpu.serve import protocol

        for events in self.script:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            f = conn.makefile("rwb")
            try:
                req = protocol.read_event(f)
                self.idem_keys.append(req.get("idem_key"))
                self.tenants.append(req.get("tenant"))
                for ev in events:
                    protocol.write_event(f, ev)
            finally:
                try:
                    f.close()
                    conn.close()
                except OSError:
                    pass

    def close(self):
        try:
            self._srv.close()
        except OSError:
            pass


def test_client_backs_off_shed_and_reuses_idem_key(tmp_path):
    from g2vec_tpu.serve import client

    shed = [{"event": "rejected", "error": "shed", "job_id": "i1",
             "tenant": "gold", "retry_after_s": 0.01,
             "est_wait_s": 9.9}]
    ok = [{"event": "accepted", "job_id": "i1"},
          {"event": "job_done", "job_id": "i1", "outputs": []}]
    path = os.path.join(str(tmp_path), "fake.sock")
    srv = _FakeServer(path, [shed, shed, ok])
    try:
        ev = client.submit_and_wait(path, {"j": 1}, tenant="gold",
                                    timeout=5.0, jitter=0.0,
                                    shed_retries=3)
    finally:
        srv.close()
    assert ev["event"] == "job_done"
    # Three submissions, ONE idempotency key — the shed retry must
    # never re-key (a re-keyed retry would run the job twice once the
    # fleet admits it).
    assert len(srv.idem_keys) == 3
    assert len(set(srv.idem_keys)) == 1 and srv.idem_keys[0]


def test_client_raises_structured_serve_shed(tmp_path):
    from g2vec_tpu.serve import client

    shed = [{"event": "rejected", "error": "tenant_quota",
             "job_id": "i2", "tenant": "bulk", "retry_after_s": 0.01}]
    path = os.path.join(str(tmp_path), "fake.sock")
    srv = _FakeServer(path, [shed] * 3)
    try:
        with pytest.raises(client.ServeShed) as ei:
            client.submit_and_wait(path, {"j": 1}, tenant="bulk",
                                   timeout=5.0, jitter=0.0,
                                   shed_retries=2)
    finally:
        srv.close()
    assert ei.value.tenant == "bulk"
    assert ei.value.job_id == "i2"
    assert ei.value.retry_after_s == pytest.approx(0.01)
    # All three attempts (1 + shed_retries) carried the same key.
    assert len(set(srv.idem_keys)) == 1


# ---------------------------------------------------------------------------
# Router aggregate status + elastic construction (no processes)
# ---------------------------------------------------------------------------

def test_router_elastic_state_and_aggregate_status(tmp_path):
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=1, min_replicas=1,
                             max_replicas=3, warm_spares=1),
               console=lambda s: None)
    # Fleet sized for the ceiling + warm headroom; only r0 active.
    assert r.fleet.names() == ["r0", "r1", "r2", "r3"]
    st = r.status()
    assert st["active"] == ["r0"]
    assert st["ring"] == ["r0"]
    assert st["warm_pool"] == [] and st["warm_pool_size"] == 0
    assert st["autoscale"]["elastic"] is True
    assert st["autoscale"]["min_replicas"] == 1
    assert st["autoscale"]["max_replicas"] == 3
    assert st["autoscale"]["warm_spares"] == 1
    assert st["last_scale_event"] is None
    assert st["scale_ups"] == 0 and st["scale_downs"] == 0
    assert st["fleet"] == {}          # no sweep has run yet
    roles = {n: rep["role"] for n, rep in st["replicas"].items()}
    assert roles == {"r0": "active", "r1": "cold", "r2": "cold",
                     "r3": "cold"}
    # Admin drain refuses non-active names instead of fencing a spec
    # the scale controller owns.
    resp = r.handle_drain_replica("r2")
    assert resp["event"] == "error" and "not active" in resp["error"]
    assert r.handle_drain_replica("nope")["event"] == "error"


def test_router_static_default_unchanged(tmp_path):
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=2), console=lambda s: None)
    st = r.status()
    assert r.fleet.names() == ["r0", "r1"]
    assert st["active"] == ["r0", "r1"]
    assert st["autoscale"]["elastic"] is False


def test_router_rejects_bad_elastic_bounds(tmp_path):
    from g2vec_tpu.serve.router import Router, RouterOptions

    with pytest.raises(ValueError):
        Router(RouterOptions(fleet_dir=str(tmp_path / "f1"),
                             replicas=2, min_replicas=3,
                             max_replicas=2), console=lambda s: None)
    with pytest.raises(ValueError):
        Router(RouterOptions(fleet_dir=str(tmp_path / "f2"),
                             replicas=1, warm_spares=-1),
               console=lambda s: None)


def test_sanitize_client_submit_strips_internal_fields():
    """The relay must never forward the fields that bypass admission:
    requeue/submitted_at (quota + shed + deadline-clock bypass) and the
    secrets. Everything a client legitimately controls passes through."""
    from g2vec_tpu.serve.router import sanitize_client_submit

    req = {"op": "submit", "job": {"epoch": 5}, "tenant": "gold",
           "priority": "batch", "deadline_s": 10.0, "idem_key": "k1",
           "auth_token": "fleet-secret", "requeue": True,
           "submitted_at": 1.0, "relay_token": "forged"}
    out = sanitize_client_submit(req)
    assert set(out) == {"op", "job", "tenant", "priority",
                        "deadline_s", "idem_key"}
    assert req["requeue"] is True         # input left untouched


def test_warmup_canary_uses_boot_scoped_idem_key(tmp_path):
    """The canary must carry the PROTOCOL idempotency field
    (``idem_key`` — a typo'd key is silently ignored and every re-warm
    re-runs the whole canary), stable within a boot so a re-warm of an
    already-warm process dedups to a re-ack, fresh across boots."""
    from g2vec_tpu.serve import protocol
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=1, min_replicas=1,
                             max_replicas=2, warm_spares=1,
                             auth_token="tok"),
               console=lambda s: None)
    r.fleet.replica("r1").boots = 3
    a = r._warmup_req("r1", {"epoch": 1})
    b = r._warmup_req("r1", {"epoch": 1})
    assert "idem_key" in a and "idempotency_key" not in a
    assert a["idem_key"] == b["idem_key"] == "warmup-r1-b3"
    assert a["auth_token"] == "tok" and a["tenant"] == "_warmup"
    # Every envelope key is protocol vocabulary — an off-vocabulary
    # key is exactly the silent-drop bug this test pins against.
    assert set(a) - {"job"} <= set(protocol.SUBMIT_KEYS)
    r.fleet.replica("r1").boots = 4
    assert r._warmup_req("r1", {"epoch": 1})["idem_key"] \
        == "warmup-r1-b4"


def test_router_scale_claim_and_probe_targets(tmp_path):
    """The pure halves of the scale machinery: capacity claims and the
    probe target set, driven without any processes."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=1, min_replicas=1,
                             max_replicas=2, warm_spares=1),
               console=lambda s: None)
    # Cold names are never probed (probing them would declare them
    # dead and launch processes that should not exist).
    assert r._probe_targets() == ["r0"]
    with r._hlock:
        r._warm.append("r1")
    assert r._probe_targets() == ["r0", "r1"]
    # A claim prefers the warm pool and empties it...
    name, capacity = r._claim_warm()
    assert (name, capacity) == ("r1", True)
    with r._hlock:
        r.ring.add(name)
        r._active.add(name)
    # ...and at the ceiling there is no capacity left to claim.
    assert r._claim_warm() == (None, False)
    # _next_cold skips active/warm/pending and claims the first cold.
    assert r._next_cold() == "r2"
    assert r._next_cold() is None     # r2 now pending, nothing cold left


# ---------------------------------------------------------------------------
# Join-key salting (flash-crowd spread onto the promoted spare)
# ---------------------------------------------------------------------------

def test_join_key_salting_bounded_spread(tmp_path):
    """A hot join key may spread to at most ``join_spread`` ring-chosen
    replicas, least-loaded first: a flash crowd on one key lands on the
    just-promoted spare instead of pinning the primary, while cold keys
    (and spread=1 fleets) reproduce the pre-salting placement exactly —
    that bound is what keeps the walk-cache affinity story alive."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    eligible = ["r0", "r1", "r2"]
    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=3, join_spread=2),
               console=lambda s: None)

    def owners(key):
        with r._hlock:
            return (r.ring.lookup(key, eligible=eligible),
                    r.ring.lookup(f"{key}#salt1", eligible=eligible))

    # A key whose salted alternate differs from its primary (most do;
    # the search keeps the test deterministic across ring tweaks).
    key = next(k for k in (f"hot{i}" for i in range(200))
               if owners(k)[0] != owners(k)[1])
    primary, alt = owners(key)

    # Calm fleet: the tie goes to the primary — byte-identical routing.
    assert r._pick_salted(key, eligible) == primary
    # Storm on the primary: the alternate absorbs the crowd, so the
    # pinning storm reaches 2 replicas.
    with r._hlock:
        r._fleet_stats = {"per_replica":
                          {primary: {"queued": 10, "running": 2}}}
    assert r._pick_salted(key, eligible) == alt
    # BOUNDED spread: with both candidates loaded, the idle third
    # replica must never win — it is not in the key's candidate set.
    third = next(n for n in eligible if n not in (primary, alt))
    with r._hlock:
        r._fleet_stats = {"per_replica": {
            primary: {"queued": 10, "running": 2},
            alt: {"queued": 10, "running": 2},
            third: {"queued": 0, "running": 0}}}
    assert r._pick_salted(key, eligible) == primary   # tie -> primary
    # In-flight assignments count BEFORE the next stats sweep lands:
    # the crowd spreads within one scale interval.
    with r._hlock:
        r._fleet_stats = {}
        r._assigned.update({f"j{i}": primary for i in range(3)})
    assert r._pick_salted(key, eligible) == alt
    # spread=1 routers ignore load entirely (legacy placement).
    r1 = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet1"),
                              replicas=3, join_spread=1),
                console=lambda s: None)
    with r1._hlock:
        r1._fleet_stats = {"per_replica":
                           {primary: {"queued": 100, "running": 9}}}
    assert r1._pick_salted(key, eligible) == primary
