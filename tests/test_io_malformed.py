"""Malformed-input contracts: every truncated/mangled file must surface as
the documented actionable ValueError — never an IndexError or a raw parse
crash — under BOTH the Python and native parsers (the native parser covers
the expression matrix; clinical/network are Python-only by design)."""
import shutil

import pytest

from g2vec_tpu.io.readers import load_clinical, load_expression, load_network

_HAS_GXX = shutil.which("g++") is not None
PARSERS = [pytest.param(False, id="python"),
           pytest.param(True, id="native",
                        marks=pytest.mark.skipif(
                            not _HAS_GXX,
                            reason="no C++ toolchain in this environment"))]


def _write_truncated_expression(tmp_path):
    """A kill-mid-write expression file: full rows, then a byte-truncated
    final row (what a dead writer or a torn copy leaves behind)."""
    full = ("PATIENT\tS1\tS2\tS3\n"
            "GENEA\t1.5\t-0.25\t0.0\n"
            "GENEB\t2.0\t3.0\t4.0\n")
    cut = full[:full.index("GENEB\t2.0\t3.0") + len("GENEB\t2.0\t3")]
    p = tmp_path / "truncated.txt"
    p.write_text(cut)
    return str(p)


@pytest.mark.parametrize("use_native", PARSERS)
def test_truncated_expression_row_raises_value_error(tmp_path, use_native):
    path = _write_truncated_expression(tmp_path)
    with pytest.raises(ValueError, match="GENEB"):
        load_expression(path, use_native=use_native)


@pytest.mark.parametrize("use_native", PARSERS)
def test_expression_header_only_raises_value_error(tmp_path, use_native):
    p = tmp_path / "header_only.txt"
    p.write_text("PATIENT\tS1\tS2\n")
    with pytest.raises(ValueError, match="at least one gene row"):
        load_expression(str(p), use_native=use_native)


@pytest.mark.parametrize("use_native", PARSERS)
def test_expression_empty_file_raises_value_error(tmp_path, use_native):
    p = tmp_path / "empty.txt"
    p.write_text("")
    with pytest.raises(ValueError):
        load_expression(str(p), use_native=use_native)


@pytest.mark.parametrize("use_native", PARSERS)
def test_expression_gene_name_only_row_raises_value_error(tmp_path,
                                                          use_native):
    # A row truncated right after the gene name (no values at all) — the
    # reference would IndexError on row[1:] mismatch downstream.
    p = tmp_path / "nameonly.txt"
    p.write_text("PATIENT\tS1\nGENEA\t1.0\nGENEB\n")
    with pytest.raises(ValueError, match="GENEB"):
        load_expression(str(p), use_native=use_native)


def test_clinical_non_integer_label_raises_value_error(tmp_path):
    p = tmp_path / "clin.txt"
    p.write_text("PATIENT_BARCODE\tLABEL\nS1\t0\nS2\tpoor\n")
    with pytest.raises(ValueError, match="label must be an integer"):
        load_clinical(str(p))
    # And a float label is just as malformed.
    p.write_text("PATIENT_BARCODE\tLABEL\nS1\t0.5\n")
    with pytest.raises(ValueError, match="label must be an integer"):
        load_clinical(str(p))


def test_clinical_missing_label_column_raises_value_error(tmp_path):
    p = tmp_path / "clin.txt"
    p.write_text("PATIENT_BARCODE\tLABEL\nS1\n")
    with pytest.raises(ValueError, match="sample"):
        load_clinical(str(p))


def test_network_single_column_row_raises_value_error(tmp_path):
    p = tmp_path / "net.txt"
    p.write_text("src\tdest\nGENEA\tGENEB\nGENEC\n")
    with pytest.raises(ValueError, match="src"):
        load_network(str(p))


def test_network_empty_file_raises_value_error(tmp_path):
    p = tmp_path / "net.txt"
    p.write_text("")
    with pytest.raises(ValueError, match="header"):
        load_network(str(p))
