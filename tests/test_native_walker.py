"""Native C++ walk sampler: reference walk invariants, determinism,
thread-count invariance, the packed-row output contract, and pipeline
integration via --walker-backend native."""
import shutil

import numpy as np
import pytest

g_plus_plus = shutil.which("g++")
pytestmark = pytest.mark.skipif(g_plus_plus is None,
                                reason="no C++ toolchain in this environment")


def _chain_plus_hub():
    """0->1->2->3 chain plus a hub 0->{4,5,6} with skewed weights."""
    src = np.array([0, 1, 2, 0, 0, 0], dtype=np.int32)
    dst = np.array([1, 2, 3, 4, 5, 6], dtype=np.int32)
    w = np.array([1.0, 1.0, 1.0, 0.5, 1.5, 2.0], dtype=np.float32)
    return src, dst, w, 7


def _raw_paths(src, dst, w, n, starts, len_path, seed, reps=1, n_threads=0):
    from g2vec_tpu.native.walker_bindings import walk_paths
    from g2vec_tpu.ops.host_walker import edges_to_csr

    indptr, indices, weights = edges_to_csr(src, dst, w, n)
    all_starts = np.tile(starts, reps).astype(np.int32)
    ids = np.arange(all_starts.size, dtype=np.uint64)
    return walk_paths(indptr, indices, weights, n, all_starts, ids,
                      len_path, seed, n_threads)


def test_walk_invariants():
    src, dst, w, n = _chain_plus_hub()
    edge_set = set(zip(src.tolist(), dst.tolist()))
    paths = _raw_paths(src, dst, w, n, np.arange(n, dtype=np.int32),
                       len_path=5, seed=7, reps=50)
    for row in paths:
        nodes = row[row >= 0]
        assert nodes.size >= 1
        assert len(set(nodes.tolist())) == nodes.size      # no revisit
        for a, b in zip(nodes[:-1], nodes[1:]):
            assert (int(a), int(b)) in edge_set            # real edges only
        # -1 padding is a strict suffix
        assert np.all(row[nodes.size:] == -1)
    # starts preserved in order
    np.testing.assert_array_equal(paths[:n, 0], np.arange(n))


def test_dead_end_and_length_cap():
    src, dst, w, n = _chain_plus_hub()
    paths = _raw_paths(src, dst, w, n, np.array([3], dtype=np.int32),
                       len_path=5, seed=0)
    np.testing.assert_array_equal(paths[0], [3, -1, -1, -1, -1])  # no out-edges
    long_chain = _raw_paths(src, dst, w, n, np.array([0], dtype=np.int32),
                            len_path=3, seed=1, reps=20)
    assert np.all((long_chain >= -1) & (long_chain < n))
    assert long_chain.shape == (20, 3)                      # capped


def test_deterministic_and_thread_invariant():
    src, dst, w, n = _chain_plus_hub()
    starts = np.arange(n, dtype=np.int32)
    a = _raw_paths(src, dst, w, n, starts, 5, seed=42, reps=64, n_threads=1)
    b = _raw_paths(src, dst, w, n, starts, 5, seed=42, reps=64, n_threads=4)
    np.testing.assert_array_equal(a, b)
    c = _raw_paths(src, dst, w, n, starts, 5, seed=43, reps=64)
    assert not np.array_equal(a, c)


def test_weighted_sampling_distribution():
    # From node 0 the hub edges carry weights 1(->1), .5(->4), 1.5(->5),
    # 2(->6): first-step frequencies must track w/sum(w) = .2/.1/.3/.4.
    src, dst, w, n = _chain_plus_hub()
    reps = 4000
    paths = _raw_paths(src, dst, w, n, np.array([0], dtype=np.int32),
                       len_path=2, seed=9, reps=reps)
    first = paths[:, 1]
    freq = {t: float((first == t).sum()) / reps for t in (1, 4, 5, 6)}
    total_w = 5.0
    for t, wt in ((1, 1.0), (4, 0.5), (5, 1.5), (6, 2.0)):
        assert abs(freq[t] - wt / total_w) < 0.03, (t, freq)


def test_packed_row_contract():
    from g2vec_tpu.ops.host_walker import generate_path_set_native

    src, dst, w, n = _chain_plus_hub()
    paths = generate_path_set_native(src, dst, w, n, len_path=4, reps=8,
                                     seed=0)
    assert paths and all(isinstance(p, bytes) and len(p) == (n + 7) // 8
                         for p in paths)
    rows = np.unpackbits(
        np.frombuffer(b"".join(sorted(paths)), dtype=np.uint8).reshape(
            len(paths), -1), axis=1)[:, :n]
    # every row is a non-empty node set; node 3's singleton path must exist
    assert rows.sum(axis=1).min() >= 1
    singleton_3 = np.zeros(n, dtype=np.uint8)
    singleton_3[3] = 1
    assert any(np.array_equal(r, singleton_3) for r in rows)


def test_pipeline_native_backend(tmp_path):
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.pipeline import run

    spec = SyntheticSpec(n_good=14, n_poor=10, module_size=10,
                         n_background=10, n_expr_only=2, n_net_only=2,
                         module_chords=2, background_edges=16, seed=3)
    files = write_synthetic_tsv(spec, str(tmp_path))
    cfg = G2VecConfig(expression_file=files["expression"],
                      clinical_file=files["clinical"],
                      network_file=files["network"],
                      result_name=str(tmp_path / "nat"),
                      lenPath=6, numRepetition=4, sizeHiddenlayer=16,
                      epoch=3, walker_backend="native", seed=0)
    res1 = run(cfg, console=lambda s: None)
    assert res1.n_paths >= 2
    # per-seed deterministic end to end
    cfg2 = G2VecConfig(**{**cfg.__dict__, "result_name": str(tmp_path / "nat2")})
    res2 = run(cfg2, console=lambda s: None)
    assert res2.n_paths == res1.n_paths
    assert (tmp_path / "nat_biomarkers.txt").read_text() \
        == (tmp_path / "nat2_biomarkers.txt").read_text()


def test_out_of_range_nodes_rejected():
    from g2vec_tpu.ops.host_walker import generate_path_set_native

    src, dst, w, n = _chain_plus_hub()
    with pytest.raises(ValueError, match="starts"):
        generate_path_set_native(src, dst, w, n, len_path=4, reps=1, seed=0,
                                 starts=np.array([n], dtype=np.int32))
    with pytest.raises(ValueError, match="dst"):
        generate_path_set_native(src, np.array([0, 1, 2, 4, 5, 99],
                                               dtype=np.int32),
                                 w, n, len_path=4, reps=1, seed=0)


def test_negative_seed_accepted():
    # The device backend accepts any int --seed (jax.random.key); the
    # native path masks to uint64 instead of letting NumPy 2 raise
    # OverflowError on negative values.
    from g2vec_tpu.ops.host_walker import generate_path_set_native

    src, dst, w, n = _chain_plus_hub()
    a = generate_path_set_native(src, dst, w, n, len_path=4, reps=2, seed=-1)
    b = generate_path_set_native(src, dst, w, n, len_path=4, reps=2, seed=-1)
    assert a == b and a


def test_config_validation():
    from g2vec_tpu.config import G2VecConfig

    base = dict(expression_file="e", clinical_file="c", network_file="n",
                result_name="r")
    with pytest.raises(ValueError, match="walker_backend"):
        G2VecConfig(**base, walker_backend="gpu").validate()
    # native + mesh/distributed is supported (host walks are upstream of
    # the sharded trainer; multi-process runs shard the walker axis).
    G2VecConfig(**base, walker_backend="native", mesh_shape=(2, 4)).validate()


def test_mismatched_weights_length_rejected():
    # The language boundary must catch a weights array shorter than the
    # edge list (the C++ reads weights[k] for k < indptr[-1]).
    from g2vec_tpu.native.walker_bindings import walk_paths
    from g2vec_tpu.ops.host_walker import edges_to_csr

    src, dst, w, n = _chain_plus_hub()
    indptr, indices, weights = edges_to_csr(src, dst, w, n)
    starts = np.arange(n, dtype=np.int32)
    ids = np.arange(n, dtype=np.uint64)
    with pytest.raises(ValueError, match="weights"):
        walk_paths(indptr, indices, weights[:-1], n, starts, ids, 4, 0)


def test_readonly_package_dir_builds_into_cache(tmp_path, monkeypatch):
    # Non-editable install into read-only site-packages: the on-demand
    # build must land in the per-user cache instead of failing forever.
    import os as _os
    import shutil as _shutil
    import g2vec_tpu.native._build as _build
    from g2vec_tpu.native import walker_bindings

    pkg = tmp_path / "ro_pkg"
    pkg.mkdir()
    src = pkg / "walker.cpp"
    _shutil.copyfile(walker_bindings._SRC, src)
    so = pkg / "_walker.so"
    cache_home = tmp_path / "cache"
    monkeypatch.setenv("XDG_CACHE_HOME", str(cache_home))
    # chmod is a no-op under root, so simulate the read-only directory at
    # the write probe itself (production raises OSError from the failed
    # create there — e.g. root-squash NFS).
    real_probe = _build._probe_writable

    def fake_probe(dirname):
        if _os.path.abspath(str(dirname)) == str(pkg):
            raise OSError(f"simulated read-only dir: {dirname}")
        return real_probe(dirname)

    monkeypatch.setattr(_build, "_probe_writable", fake_probe)
    lib = _build.build_and_load(str(src), str(so), ["-pthread"],
                                walker_bindings._configure)
    assert lib is not None
    assert not so.exists()
    cached = list((cache_home / "g2vec_tpu").glob("walker-*.so"))
    assert len(cached) == 1
    # Second call short-circuits on the memoized handle.
    assert _build.build_and_load(str(src), str(so), ["-pthread"],
                                 walker_bindings._configure) is lib


def test_broken_source_fails_without_cache_retry(tmp_path, monkeypatch):
    # A genuine compile error on a WRITABLE checkout must raise once,
    # against the package path — not re-run the failed compile into the
    # per-user cache and report the error against the cache path.
    import g2vec_tpu.native._build as _build

    src = tmp_path / "broken.cpp"
    src.write_text("this is not C++\n")
    so = tmp_path / "_broken.so"
    cache_home = tmp_path / "cache"
    monkeypatch.setenv("XDG_CACHE_HOME", str(cache_home))
    compiles = []
    real_compile = _build._compile

    def counting_compile(s, out, flags):
        compiles.append(out)
        return real_compile(s, out, flags)

    monkeypatch.setattr(_build, "_compile", counting_compile)
    with pytest.raises(RuntimeError, match="native build failed"):
        _build.build_and_load(str(src), str(so), [], lambda lib: None)
    assert compiles == [str(so)]  # one attempt, at the package path
    assert not (cache_home / "g2vec_tpu").exists() or not list(
        (cache_home / "g2vec_tpu").glob("broken-*.so"))


def test_packed_walk_matches_unpacked_packbits():
    # g2v_walk_packed must emit exactly np.packbits(one_hot(g2v_walk)):
    # same walks, same MSB-first byte layout.
    from g2vec_tpu.native.walker_bindings import walk_paths, walk_paths_packed
    from g2vec_tpu.ops.host_walker import edges_to_csr

    src, dst, w, n = _chain_plus_hub()
    indptr, indices, weights = edges_to_csr(src, dst, w, n)
    starts = np.tile(np.arange(n, dtype=np.int32), 20)
    ids = np.arange(starts.size, dtype=np.uint64)
    paths = walk_paths(indptr, indices, weights, n, starts, ids, 5, 11)
    packed = walk_paths_packed(indptr, indices, weights, n, starts, ids,
                               5, 11)
    rows = np.zeros((paths.shape[0], n), dtype=bool)
    real = paths >= 0
    rows[np.nonzero(real)[0], paths[real]] = True
    np.testing.assert_array_equal(packed, np.packbits(rows, axis=1))


def test_nonpositive_len_path_rejected():
    # A len_path < 1 would leave the np.empty output buffers unwritten
    # (the C++ early-returns); the boundary must raise instead.
    from g2vec_tpu.native.walker_bindings import walk_paths, walk_paths_packed
    from g2vec_tpu.ops.host_walker import edges_to_csr

    src, dst, w, n = _chain_plus_hub()
    indptr, indices, weights = edges_to_csr(src, dst, w, n)
    starts = np.arange(n, dtype=np.int32)
    ids = np.arange(n, dtype=np.uint64)
    for fn in (walk_paths, walk_paths_packed):
        with pytest.raises(ValueError, match="len_path"):
            fn(indptr, indices, weights, n, starts, ids, 0, 0)


def test_walker_axis_slices_reproduce_full_run():
    # Any partition of the flat (repetition x start) walker axis must
    # reproduce exactly the full run's rows for those walkers — streams
    # are keyed by global flat index (the multi-process sharding
    # contract, parallel/distributed.sharded_native_path_set).
    from g2vec_tpu.ops.host_walker import walk_packed_rows

    src, dst, w, n = _chain_plus_hub()
    kwargs = dict(len_path=5, reps=3, seed=21)
    full = walk_packed_rows(src, dst, w, n, **kwargs)
    total = n * 3
    cuts = [0, 5, 6, 14, total]
    pieces = [walk_packed_rows(src, dst, w, n, walker_lo=lo, walker_hi=hi,
                               **kwargs)
              for lo, hi in zip(cuts[:-1], cuts[1:])]
    np.testing.assert_array_equal(full, np.concatenate(pieces, axis=0))
    with pytest.raises(ValueError, match="walker range"):
        walk_packed_rows(src, dst, w, n, walker_lo=2, walker_hi=total + 1,
                         **kwargs)


def test_duplicate_edges_exceeding_n_genes_degree():
    # Duplicate edges are legal (multiset semantics) and can push one
    # row's degree past n_genes; each duplicate carries its own mass and
    # the compaction buffers must be sized by MAX ROW DEGREE, not
    # n_genes (a heap-overflow regression guard).
    n = 4
    reps = 6      # node 0 -> {1,2,3} repeated 6x: degree 18 > n_genes
    src = np.tile(np.array([0, 0, 0], dtype=np.int32), reps)
    dst = np.tile(np.array([1, 2, 3], dtype=np.int32), reps)
    w = np.tile(np.array([1.0, 2.0, 3.0], dtype=np.float32), reps)
    paths = _raw_paths(src, dst, w, n, np.array([0], dtype=np.int32),
                       len_path=4, seed=5, reps=200)
    # Walks are valid: start at 0, visit distinct real targets only.
    for row in paths:
        nodes = row[row >= 0]
        assert nodes[0] == 0
        assert set(nodes[1:].tolist()) <= {1, 2, 3}
        assert len(set(nodes.tolist())) == nodes.size
    # Duplicate mass keeps the 1:2:3 first-step ratio.
    first = paths[:, 1]
    freq = {t: (first == t).mean() for t in (1, 2, 3)}
    for t, expect in ((1, 1 / 6), (2, 2 / 6), (3, 3 / 6)):
        assert abs(freq[t] - expect) < 0.1, freq
