"""Acceptance test (SURVEY.md §4 item 6 / BASELINE.md target): the full
pipeline reaches val-ACC >= 0.88 on a dataset with the reference's label
balance and planted prognostic structure, and the biomarker list is
dominated by the planted module genes.

Runs at the 'medium' make_example scale (~940 common genes) so it finishes
in tens of seconds on CPU; the full-scale 'example' run is the TPU bench's
job. Distributional, not byte-golden: the reference is unseeded and its
bundled expression matrix is absent (BASELINE.md note).
"""
import os

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.data.make_example import SCALES
from g2vec_tpu.data.synthetic import write_synthetic_tsv


def test_pipeline_reaches_baseline_accuracy(tmp_path):
    # ~25 s: cheap enough to stay in the default suite (the full-scale
    # real-network gate is test_acceptance_real.py's auto variant).
    from g2vec_tpu.pipeline import run

    paths = write_synthetic_tsv(SCALES["medium"], str(tmp_path))
    cfg = G2VecConfig(
        expression_file=paths["expression"], clinical_file=paths["clinical"],
        network_file=paths["network"],
        result_name=os.path.join(str(tmp_path), "acc"),
        lenPath=40, numRepetition=10, sizeHiddenlayer=128, epoch=200,
        learningRate=0.005, numBiomarker=50, compute_dtype="bfloat16", seed=0)
    result = run(cfg, console=lambda s: None)

    assert result.n_samples == 135          # reference label balance
    assert result.acc_val >= 0.88, (
        f"val-ACC {result.acc_val:.4f} below the 0.88 acceptance bar")
    planted = sum(g.startswith(("GMOD", "PMOD")) for g in result.biomarkers)
    assert planted / len(result.biomarkers) >= 0.8
    for f in result.output_files:
        assert os.path.exists(f)
