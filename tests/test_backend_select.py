"""Walker-backend auto-resolution (ops/backend.py): host-walks-chip-trains.

The "auto" default must route walks to the native C++ sampler whenever it
is available (meshes change nothing; multi-process runs shard the walker
axis — the 2-process test covers the collective path), fall back to the
device walker without it, and honor explicit pins — all without the user
needing to know a flag exists (VERDICT r3 task 2)."""
import shutil

import pytest

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.ops.backend import (native_walker_available,
                                   resolve_walker_backend)

g_plus_plus = shutil.which("g++")


def _cfg(**overrides):
    base = dict(expression_file="e", clinical_file="c", network_file="n",
                result_name="r")
    base.update(overrides)
    return G2VecConfig(**base)


def test_default_is_auto():
    assert _cfg().walker_backend == "auto"
    _cfg().validate()  # auto is a valid value


def test_explicit_pins_are_honored():
    assert resolve_walker_backend(_cfg(walker_backend="device")) == "device"
    assert resolve_walker_backend(_cfg(walker_backend="native")) == "native"


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_auto_mesh_and_single_process_distributed_resolve_to_native():
    # Walks are upstream of the sharded trainer, so a mesh changes
    # nothing; a single-process --distributed run likewise. (The true
    # multi-process agreement path is covered by the 2-process test.)
    assert resolve_walker_backend(
        _cfg(walker_backend="auto", mesh_shape=(4, 2))) == "native"
    assert resolve_walker_backend(
        _cfg(walker_backend="auto", distributed=True)) == "native"


def test_auto_mesh_without_native_resolves_to_device(monkeypatch):
    import g2vec_tpu.ops.backend as backend

    monkeypatch.setattr(backend, "native_walker_available", lambda: False)
    assert backend.resolve_walker_backend(
        _cfg(walker_backend="auto", mesh_shape=(4, 2))) == "device"


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_auto_single_host_resolves_to_native():
    assert native_walker_available()
    assert resolve_walker_backend(_cfg(walker_backend="auto")) == "native"


def test_auto_without_native_falls_back_to_device(monkeypatch):
    import g2vec_tpu.ops.backend as backend

    monkeypatch.setattr(backend, "native_walker_available", lambda: False)
    assert backend.resolve_walker_backend(_cfg(walker_backend="auto")) \
        == "device"


def test_native_with_mesh_and_distributed_validates():
    # native walks are upstream of the sharded trainer (and shard across
    # processes under --distributed), so neither combination is an error
    # anymore.
    _cfg(walker_backend="native", mesh_shape=(2, 4)).validate()
    _cfg(walker_backend="native", distributed=True).validate()
    _cfg(walker_backend="auto", mesh_shape=(2, 4)).validate()


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_pipeline_default_routes_to_native(tmp_path):
    """End-to-end: a default-config single-host run reports the native
    sampler in its metrics stream and matches an explicitly pinned native
    run byte-for-byte (same resolved backend => same PRNG family)."""
    import json
    import os

    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.pipeline import run

    spec = SyntheticSpec(n_good=16, n_poor=14, module_size=8,
                         n_background=16, n_expr_only=2, n_net_only=2,
                         module_chords=2, background_edges=24, seed=3)
    paths = write_synthetic_tsv(spec, str(tmp_path))
    common = dict(
        expression_file=paths["expression"], clinical_file=paths["clinical"],
        network_file=paths["network"], lenPath=8, numRepetition=2,
        sizeHiddenlayer=16, epoch=10, compute_dtype="float32", seed=0)
    jl = str(tmp_path / "m.jsonl")
    r_auto = run(G2VecConfig(result_name=str(tmp_path / "auto"),
                             metrics_jsonl=jl, **common),
                 console=lambda s: None)
    r_nat = run(G2VecConfig(result_name=str(tmp_path / "nat"),
                            walker_backend="native", **common),
                console=lambda s: None)
    with open(jl) as f:
        paths_rec = [json.loads(ln) for ln in f
                     if json.loads(ln)["event"] == "paths"]
    assert paths_rec and paths_rec[0]["walker_backend"] == "native"
    assert r_auto.walker_backend == "native"
    assert r_nat.walker_backend == "native"
    for fa, fn in zip(r_auto.output_files, r_nat.output_files):
        with open(fa, "rb") as a, open(fn, "rb") as b:
            assert a.read() == b.read()
    assert os.path.exists(r_auto.output_files[0])


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_sharded_native_single_process_fallback():
    """With one process, sharded_native_path_set must return exactly the
    single-host set (no collectives involved)."""
    import numpy as np

    from g2vec_tpu.ops.host_walker import generate_path_set_native
    from g2vec_tpu.parallel.distributed import sharded_native_path_set

    rng = np.random.default_rng(2)
    n = 30
    src = rng.integers(0, n, 150).astype(np.int32)
    dst = rng.integers(0, n, 150).astype(np.int32)
    w = rng.random(150).astype(np.float32) + 0.1
    kwargs = dict(len_path=6, reps=3, seed=4)
    assert sharded_native_path_set(src, dst, w, n, **kwargs) \
        == generate_path_set_native(src, dst, w, n, **kwargs)
