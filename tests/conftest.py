"""Test environment: force JAX onto CPU with 8 virtual devices.

This must run before the first ``import jax`` anywhere in the test session —
pytest imports conftest.py first, and g2vec_tpu avoids importing jax at
package-import time, so setting env here is sufficient. This is the standard
JAX trick for exercising pjit/psum sharding in CI without a TPU pod
(SURVEY.md §4 item 5).
"""
import os

# Unconditional: the ambient environment may point JAX_PLATFORMS at a real
# TPU (e.g. the axon tunnel); tests must never grab it.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=8"])

# The env var alone is NOT enough: a TPU-tunnel sitecustomize may have
# already called jax.config.update("jax_platforms", ...) at interpreter
# startup, which takes precedence over the env var. Re-force the config
# explicitly or every jitted test silently dials the remote TPU (and blocks
# on its socket).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def small_spec():
    from g2vec_tpu.data.synthetic import SyntheticSpec

    return SyntheticSpec(
        n_good=24, n_poor=20, module_size=12, n_background=24,
        n_expr_only=4, n_net_only=4, module_chords=2,
        background_edges=40, seed=7,
    )


@pytest.fixture(scope="session")
def small_dataset(small_spec):
    from g2vec_tpu.data.synthetic import make_synthetic

    return make_synthetic(small_spec)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
