"""Test environment: force JAX onto CPU with 8 virtual devices.

This must run before the first ``import jax`` anywhere in the test session —
pytest imports conftest.py first, and g2vec_tpu avoids importing jax at
package-import time, so setting env here is sufficient. This is the standard
JAX trick for exercising pjit/psum sharding in CI without a TPU pod
(SURVEY.md §4 item 5).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def small_spec():
    from g2vec_tpu.data.synthetic import SyntheticSpec

    return SyntheticSpec(
        n_good=24, n_poor=20, module_size=12, n_background=24,
        n_expr_only=4, n_net_only=4, module_chords=2,
        background_edges=40, seed=7,
    )


@pytest.fixture(scope="session")
def small_dataset(small_spec):
    from g2vec_tpu.data.synthetic import make_synthetic

    return make_synthetic(small_spec)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
