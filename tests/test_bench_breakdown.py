"""The epoch-breakdown bench stage (bench._bench_epoch_breakdown) is
chip-gated in production; ``interpret=True`` runs its exact program
(Pallas packed matmul in interpreter mode) on CPU so the stage's shape
handling and the roofline arithmetic stay pinned between chip windows."""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench_mod():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    return bench


def test_breakdown_pieces_and_roofline(bench_mod):
    rng = np.random.default_rng(0)
    paths, labels = bench_mod.make_paths(rng, 96, 256)
    bd = bench_mod._bench_epoch_breakdown(paths, labels, 16, 0.01,
                                          interpret=True, superstep_k=4)
    for k in ("grad_update_ms", "eval_val_ms", "eval_tr_ms",
              "eval_tr_amortized_ms", "epoch_ms", "residual_ms",
              "fused_grad_eval_ms", "fused_eval_saved_ms"):
        assert isinstance(bd[k], float), k
    assert bd["grad_update_ms"] > 0 and bd["eval_val_ms"] > 0
    # PR-4 extended terms: the superstep A/B ran both arms, and the tile
    # attribution names a legal plan per shape/direction.
    ss = bd["superstep"]
    assert ss["k"] == 4
    for k in ("epoch_ms_k1", "epoch_ms_k", "residual_recovered_ms"):
        assert isinstance(ss[k], float), k
    assert ss["epoch_ms_k1"] > 0 and ss["epoch_ms_k"] > 0
    for shape in ("tr", "tr_val"):
        for d in ("fwd", "bwd"):
            tile = bd["kernel_tiles"][shape][d]
            assert tile["row_block"] > 0 and tile["blocks_per_group"] > 0
            assert tile["source"] in ("heuristic", "autotuned")

    rl = bd["roofline"]
    assert rl["hbm_peak_gbps"] == bench_mod._peak_hbm_bytes_per_sec() / 1e9
    # Min-traffic model at these shapes: padded rows/lanes from the
    # Pallas block sizes, packed X at 1 bit/gene.
    from g2vec_tpu.ops import packed_matmul as pm
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    g = pad_to_multiple(256, pm.LANE_BLOCK)
    m_tr = pad_to_multiple(int(96 * (1 - bench_mod.VAL_FRACTION)),
                           pm.ROW_BLOCK)
    m_val = pad_to_multiple(96 - int(96 * (1 - bench_mod.VAL_FRACTION)),
                            pm.ROW_BLOCK)
    hidden = 16
    assert rl["eval_val_min_bytes"] == m_val * g // 8 + g * hidden * 2
    assert rl["eval_tr_min_bytes"] == m_tr * g // 8 + g * hidden * 2
    expect_grad = (2 * (m_tr * g // 8) + 2 * (g * hidden * 2)
                   + 2 * (m_tr * hidden * 2) + 7 * g * hidden * 4)
    assert rl["grad_min_bytes"] == expect_grad
    # The bandwidth floor is epoch_min_bytes at peak bandwidth, in ms.
    assert rl["bandwidth_bound_epoch_ms_floor"] == pytest.approx(
        rl["epoch_min_bytes"] / bench_mod._peak_hbm_bytes_per_sec() * 1e3,
        abs=1e-3)
    # Implied bandwidths exist whenever the piece was timed.
    assert rl["grad_implied_gbps"] is not None
    # Fused-epoch floor: the standalone eval's W read is gone, so the
    # fused floor must sit strictly below shipping's (plus the amortized
    # boundary eval, which cannot flip the inequality at these shapes).
    assert rl["fused_epoch_min_bytes"] < rl["epoch_min_bytes"]
    assert rl["fused_bandwidth_bound_epoch_ms_floor"] <= \
        rl["bandwidth_bound_epoch_ms_floor"]
    assert rl["donate_double_buffer_bytes"] > 0
