"""L5 clustering tests: k-means recovery, L-group renumbering and the
compat tie-break (ref: G2Vec.py:167-200)."""
import numpy as np
import pytest

from g2vec_tpu.analysis import find_lgroups, select_biomarkers


@pytest.fixture(scope="module")
def key():
    import jax

    return jax.random.key(0)


def _blob_data(rng, sizes=(60, 15, 12), centers=((0, 0), (8, 8), (-8, 8))):
    pts = []
    for s, c in zip(sizes, centers):
        pts.append(rng.normal(scale=0.4, size=(s, 2)) + np.array(c))
    x = np.concatenate(pts).astype(np.float32)
    membership = np.repeat(np.arange(len(sizes)), sizes)
    return x, membership


def test_kmeans_recovers_separated_blobs(rng, key):
    from g2vec_tpu.ops.kmeans import kmeans

    x, member = _blob_data(rng)
    labels, centers, inertia = kmeans(x, 3, key)
    labels = np.asarray(labels)
    # Same-blob points share a label; different blobs get different labels.
    for b in range(3):
        blob_labels = labels[member == b]
        assert len(set(blob_labels.tolist())) == 1
    assert len({labels[member == b][0] for b in range(3)}) == 3
    assert float(inertia) < 100.0


def test_find_lgroups_vote_and_renumbering(rng, key):
    # blob 0 (largest, near origin) = "other"; blob 1 mostly good-freq genes;
    # blob 2 mostly poor-freq genes.
    x, member = _blob_data(rng)
    genes = np.array([f"G{i:03d}" for i in range(len(member))])
    freq = {}
    for i, b in enumerate(member):
        if b == 1:
            freq[genes[i]] = 0        # good-majority genes
        elif b == 2:
            freq[genes[i]] = 1        # poor-majority genes
    lg = find_lgroups(x, genes, freq, key=key)
    assert set(np.unique(lg)) == {0, 1, 2}
    assert np.all(lg[member == 0] == 2)     # largest cluster -> other
    assert np.all(lg[member == 1] == 0)     # good vote -> 0
    assert np.all(lg[member == 2] == 1)     # poor vote -> 1


def test_find_lgroups_compat_ignores_vote(rng, key):
    x, member = _blob_data(rng)
    genes = np.array([f"G{i:03d}" for i in range(len(member))])
    freq = {g: (0 if member[i] == 1 else 1) for i, g in enumerate(genes) if member[i] != 0}
    lg_fixed = find_lgroups(x, genes, freq, key=key)
    lg_compat = find_lgroups(x, genes, freq, key=key, compat_tiebreak=True)
    # Compat mode ignores the vote entirely: good/poor depend only on cluster
    # index order, so the two modes either agree or are exactly swapped.
    swapped = lg_compat.copy()
    swapped[lg_compat == 0] = 1
    swapped[lg_compat == 1] = 0
    assert np.array_equal(lg_fixed, lg_compat) or np.array_equal(lg_fixed, swapped)
    assert np.all(lg_compat[member == 0] == 2)  # "other" unaffected by the bug


class TestKmeansDegenerateInputs:
    """Regression pins for k-means on degenerate inputs (the batch
    engine's subsample lanes can legally shrink a group to a handful of
    rows). The CONTRACT (ops/kmeans.py): k-means++'s all-zero-D^2
    fallback seeds duplicate centers when N <= k or rows are identical;
    argmin ties resolve to the lowest duplicate index, the other
    duplicates stay empty and keep their center verbatim
    (_update_centers). These tests pin that behavior so any future
    empty-cluster 'fix' has to change them consciously."""

    def test_identical_rows_collapse_to_cluster_zero(self, key):
        from g2vec_tpu.ops.kmeans import kmeans

        x = np.ones((17, 4), dtype=np.float32) * 2.5
        labels, centers, inertia = kmeans(x, 3, key, n_init=3, iters=10)
        labels, centers = np.asarray(labels), np.asarray(centers)
        # All-zero D^2 -> every center is row 0's point; ties -> cluster 0.
        assert np.all(labels == 0)
        assert np.allclose(centers, 2.5)
        assert float(inertia) == 0.0

    def test_fewer_points_than_clusters(self, key):
        from g2vec_tpu.ops.kmeans import kmeans

        x = np.array([[0.0, 0.0], [10.0, 10.0]], dtype=np.float32)
        labels, centers, inertia = kmeans(x, 3, key, n_init=4, iters=10)
        labels = np.asarray(labels)
        # Both points are exact centers of their own cluster; the third
        # (duplicate-seeded) cluster is empty.
        assert labels.shape == (2,)
        assert set(labels.tolist()) <= {0, 1, 2}
        assert labels[0] != labels[1]
        assert float(inertia) == 0.0
        assert np.all(np.isfinite(np.asarray(centers)))

    def test_n_equals_k(self, key):
        from g2vec_tpu.ops.kmeans import kmeans

        x = np.array([[0.0, 0], [5.0, 0], [0, 5.0]], dtype=np.float32)
        labels, _, inertia = kmeans(x, 3, key, n_init=10, iters=25)
        labels = np.asarray(labels)
        # Perfect assignment is reachable and multi-restart finds it.
        assert len(set(labels.tolist())) == 3
        assert float(inertia) == 0.0

    def test_single_point(self, key):
        from g2vec_tpu.ops.kmeans import kmeans

        x = np.array([[1.0, 2.0, 3.0]], dtype=np.float32)
        labels, centers, inertia = kmeans(x, 3, key)
        assert np.asarray(labels).tolist() == [0]
        assert float(inertia) == 0.0
        # Empty duplicates froze on the only point.
        assert np.allclose(np.asarray(centers), x[0])

    def test_empty_input_rejected(self, key):
        from g2vec_tpu.ops.kmeans import kmeans

        with pytest.raises(ValueError, match="non-empty"):
            kmeans(np.zeros((0, 4), dtype=np.float32), 3, key)

    def test_degenerate_is_deterministic(self, key):
        from g2vec_tpu.ops.kmeans import kmeans

        x = np.ones((5, 3), dtype=np.float32)
        a = [np.asarray(v) for v in kmeans(x, 3, key, n_init=2, iters=5)]
        b = [np.asarray(v) for v in kmeans(x, 3, key, n_init=2, iters=5)]
        for va, vb in zip(a, b):
            assert np.array_equal(va, vb)

    def test_find_lgroups_survives_degenerate_embeddings(self, key):
        # All-identical embeddings: one giant cluster 0 (-> "other"), two
        # empty remaining clusters voted 0-0 -> deterministic good/poor
        # pick by index; every gene lands in "other".
        x = np.zeros((30, 4), dtype=np.float32)
        genes = np.array([f"G{i}" for i in range(30)])
        lg = find_lgroups(x, genes, {g: 0 for g in genes[:5]}, key=key)
        assert np.all(lg == 2)


def test_select_biomarkers_order_and_ties(rng):
    # 6 genes: 3 in good group, 3 in poor group; engineered scores.
    genes = np.array(["GB", "GA", "GC", "PZ", "PA", "PM"])
    lg = np.array([0, 0, 0, 1, 1, 1], dtype=np.int32)
    emb = np.zeros((6, 4), dtype=np.float32)
    emb[0] = 3.0   # GB largest d-score in good group
    emb[1] = 3.0   # GA ties GB -> stable sort keeps GB first
    emb[2] = 0.1
    emb[3] = 5.0
    emb[4] = 0.2
    emb[5] = 4.0
    n0, n1 = 10, 8
    # Identical expression for every gene -> all t-scores equal -> the minmax
    # guard zeroes them, so ranking is driven purely by d-scores.
    expr = np.tile(rng.normal(size=(n0 + n1, 1)).astype(np.float32), (1, 6))
    labels = np.array([0] * n0 + [1] * n1)
    bio, detail = select_biomarkers(emb, expr, labels, genes, lg,
                                    num_biomarker=2)
    # good group picks {GB, GA} (tie kept in gene order), poor picks {PZ, PM};
    # each block alphabetized then the whole list alphabetized.
    assert bio == sorted(sorted(["GB", "GA"]) + sorted(["PZ", "PM"]))
    assert set(detail) == {"good", "poor"}


def test_select_biomarkers_handles_fewer_genes_than_n(rng):
    genes = np.array(["A", "B"])
    lg = np.array([0, 1], dtype=np.int32)
    emb = rng.normal(size=(2, 3)).astype(np.float32)
    expr = rng.normal(size=(7, 2)).astype(np.float32)
    labels = np.array([0, 0, 0, 0, 1, 1, 1])
    bio, _ = select_biomarkers(emb, expr, labels, genes, lg, num_biomarker=50)
    assert bio == ["A", "B"]
