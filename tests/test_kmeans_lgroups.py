"""L5 clustering tests: k-means recovery, L-group renumbering and the
compat tie-break (ref: G2Vec.py:167-200)."""
import numpy as np
import pytest

from g2vec_tpu.analysis import find_lgroups, select_biomarkers


@pytest.fixture(scope="module")
def key():
    import jax

    return jax.random.key(0)


def _blob_data(rng, sizes=(60, 15, 12), centers=((0, 0), (8, 8), (-8, 8))):
    pts = []
    for s, c in zip(sizes, centers):
        pts.append(rng.normal(scale=0.4, size=(s, 2)) + np.array(c))
    x = np.concatenate(pts).astype(np.float32)
    membership = np.repeat(np.arange(len(sizes)), sizes)
    return x, membership


def test_kmeans_recovers_separated_blobs(rng, key):
    from g2vec_tpu.ops.kmeans import kmeans

    x, member = _blob_data(rng)
    labels, centers, inertia = kmeans(x, 3, key)
    labels = np.asarray(labels)
    # Same-blob points share a label; different blobs get different labels.
    for b in range(3):
        blob_labels = labels[member == b]
        assert len(set(blob_labels.tolist())) == 1
    assert len({labels[member == b][0] for b in range(3)}) == 3
    assert float(inertia) < 100.0


def test_find_lgroups_vote_and_renumbering(rng, key):
    # blob 0 (largest, near origin) = "other"; blob 1 mostly good-freq genes;
    # blob 2 mostly poor-freq genes.
    x, member = _blob_data(rng)
    genes = np.array([f"G{i:03d}" for i in range(len(member))])
    freq = {}
    for i, b in enumerate(member):
        if b == 1:
            freq[genes[i]] = 0        # good-majority genes
        elif b == 2:
            freq[genes[i]] = 1        # poor-majority genes
    lg = find_lgroups(x, genes, freq, key=key)
    assert set(np.unique(lg)) == {0, 1, 2}
    assert np.all(lg[member == 0] == 2)     # largest cluster -> other
    assert np.all(lg[member == 1] == 0)     # good vote -> 0
    assert np.all(lg[member == 2] == 1)     # poor vote -> 1


def test_find_lgroups_compat_ignores_vote(rng, key):
    x, member = _blob_data(rng)
    genes = np.array([f"G{i:03d}" for i in range(len(member))])
    freq = {g: (0 if member[i] == 1 else 1) for i, g in enumerate(genes) if member[i] != 0}
    lg_fixed = find_lgroups(x, genes, freq, key=key)
    lg_compat = find_lgroups(x, genes, freq, key=key, compat_tiebreak=True)
    # Compat mode ignores the vote entirely: good/poor depend only on cluster
    # index order, so the two modes either agree or are exactly swapped.
    swapped = lg_compat.copy()
    swapped[lg_compat == 0] = 1
    swapped[lg_compat == 1] = 0
    assert np.array_equal(lg_fixed, lg_compat) or np.array_equal(lg_fixed, swapped)
    assert np.all(lg_compat[member == 0] == 2)  # "other" unaffected by the bug


def test_select_biomarkers_order_and_ties(rng):
    # 6 genes: 3 in good group, 3 in poor group; engineered scores.
    genes = np.array(["GB", "GA", "GC", "PZ", "PA", "PM"])
    lg = np.array([0, 0, 0, 1, 1, 1], dtype=np.int32)
    emb = np.zeros((6, 4), dtype=np.float32)
    emb[0] = 3.0   # GB largest d-score in good group
    emb[1] = 3.0   # GA ties GB -> stable sort keeps GB first
    emb[2] = 0.1
    emb[3] = 5.0
    emb[4] = 0.2
    emb[5] = 4.0
    n0, n1 = 10, 8
    # Identical expression for every gene -> all t-scores equal -> the minmax
    # guard zeroes them, so ranking is driven purely by d-scores.
    expr = np.tile(rng.normal(size=(n0 + n1, 1)).astype(np.float32), (1, 6))
    labels = np.array([0] * n0 + [1] * n1)
    bio, detail = select_biomarkers(emb, expr, labels, genes, lg,
                                    num_biomarker=2)
    # good group picks {GB, GA} (tie kept in gene order), poor picks {PZ, PM};
    # each block alphabetized then the whole list alphabetized.
    assert bio == sorted(sorted(["GB", "GA"]) + sorted(["PZ", "PM"]))
    assert set(detail) == {"good", "poor"}


def test_select_biomarkers_handles_fewer_genes_than_n(rng):
    genes = np.array(["A", "B"])
    lg = np.array([0, 1], dtype=np.int32)
    emb = rng.normal(size=(2, 3)).astype(np.float32)
    expr = rng.normal(size=(7, 2)).astype(np.float32)
    labels = np.array([0, 0, 0, 0, 1, 1, 1])
    bio, _ = select_biomarkers(emb, expr, labels, genes, lg, num_biomarker=50)
    assert bio == ["A", "B"]
