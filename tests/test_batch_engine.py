"""Batch engine (batch/engine.py): per-lane BITWISE parity vs solo runs,
per-lane early stop, manifest validation, and walk share-vs-rewalk
accounting.

The engine's whole contract is that batching is a pure wall-clock
optimization: every lane's three output files must be byte-for-byte the
files ``pipeline.run(lane_config(cfg, v))`` writes solo (float32, same
backend). These tests hold it to that through every batching tier —
vmapped trainer buckets, vmapped k-means/scores, shared walk products,
subsample cohorts."""
import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from g2vec_tpu.config import G2VecConfig

pytestmark = pytest.mark.batch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _cfg(tsv_paths, tmp_path, **overrides):
    defaults = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "batch", "out"),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        kmeans_iters=50, seed=0, walker_backend="device",
    )
    defaults.update(overrides)
    return G2VecConfig(**defaults)


def _assert_lane_parity(cfg, res, tmp_path, sub=""):
    """Every lane's files == the solo twin's files, byte for byte."""
    from g2vec_tpu.batch.engine import lane_config
    from g2vec_tpu.pipeline import run as solo_run

    os.makedirs(os.path.join(str(tmp_path), f"solo{sub}"), exist_ok=True)
    for v, lane in zip(res.variants, res.lanes):
        solo_cfg = lane_config(dataclasses.replace(
            cfg, manifest=None, batch_seeds=0, cache_dir=None,
            metrics_jsonl=None,
            result_name=os.path.join(str(tmp_path), f"solo{sub}", "out")), v)
        sr = solo_run(solo_cfg, console=lambda s: None)
        assert len(lane.output_files) == len(sr.output_files) == 3
        for fa, fb in zip(lane.output_files, sr.output_files):
            with open(fa, "rb") as a, open(fb, "rb") as b:
                assert a.read() == b.read(), \
                    f"lane {v.name!r}: {fa} differs from solo {fb}"
        yield v, lane, sr


def test_seed_sweep_bitwise_parity_and_walk_sharing(tsv_paths, tmp_path):
    """The headline path: an amortized seed sweep — ONE walk product pair
    shared by every lane, one vmapped trainer bucket — and every lane
    byte-identical to its solo twin."""
    from g2vec_tpu.batch.engine import run_batch

    cfg = _cfg(tsv_paths, tmp_path, batch_seeds=4)
    res = run_batch(cfg, console=lambda s: None)
    assert len(res.lanes) == 4
    # Walk amortization: 8 lane-walks collapse to the 2 group products.
    assert res.walk_stats["walked"] == 2
    assert res.walk_stats["lane_shared"] == 6
    # One shape bucket, vmapped (same walks -> same n_paths for all).
    assert len(res.buckets) == 1
    assert res.buckets[0]["lanes"] == 4
    assert res.buckets[0]["mode"] == "vmap"
    solos = list(_assert_lane_parity(cfg, res, tmp_path))
    # The sweep actually varies: not all lanes produced identical vectors.
    vec_bytes = {open(lane.output_files[2], "rb").read()
                 for _, lane, _ in solos}
    assert len(vec_bytes) == 4


def test_per_lane_early_stop_matches_solo(tsv_paths, tmp_path):
    """Lanes stop at DIFFERENT epochs inside one vmapped bucket; each
    lane's stop epoch, accuracies, and history length are the solo
    run's."""
    from g2vec_tpu.batch.engine import run_batch

    cfg = _cfg(tsv_paths, tmp_path, batch_seeds=4)
    res = run_batch(cfg, console=lambda s: None)
    stops = []
    for v, lane, solo in _assert_lane_parity(cfg, res, tmp_path, sub="es"):
        assert len(lane.train_history) == len(solo.train_history)
        assert [h["acc_val"] for h in lane.train_history] \
            == [h["acc_val"] for h in solo.train_history]
        assert lane.acc_val == solo.acc_val
        stops.append(len(lane.train_history))
    # The point of per-lane masking: the bucket is NOT lockstep.
    assert len(set(stops)) > 1, f"want differing stop epochs, got {stops}"


def test_subsample_variants_parity_and_buckets(tsv_paths, tmp_path):
    """Patient-subsample lanes re-walk their own cohort (distinct
    products), may land in different shape buckets, and still match
    their solo twins byte-for-byte."""
    from g2vec_tpu.batch.engine import run_batch

    manifest = [
        {"name": "full", "train_seed": 1},
        {"name": "subA", "patient_subsample": 0.8, "subsample_seed": 3},
        {"name": "subB", "patient_subsample": 0.8, "subsample_seed": 9,
         "learningRate": 0.03},
    ]
    mpath = str(tmp_path / "manifest.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    cfg = _cfg(tsv_paths, tmp_path, manifest=mpath)
    res = run_batch(cfg, console=lambda s: None)
    # Each distinct cohort walked its own two group products.
    assert res.walk_stats["walked"] == 6
    assert sum(b["lanes"] for b in res.buckets) == 3
    list(_assert_lane_parity(cfg, res, tmp_path, sub="sub"))


def test_walk_cache_share_vs_rewalk_accounting(tsv_paths, tmp_path):
    """share-vs-rewalk over the three tiers: task dedup within a run,
    disk hits across runs, honest 'walked' when seeds force a rewalk."""
    from g2vec_tpu.batch.engine import run_batch

    cache = str(tmp_path / "cache")
    cfg = _cfg(tsv_paths, tmp_path, batch_seeds=3, cache_dir=cache)
    cold = run_batch(cfg, console=lambda s: None)
    assert cold.walk_stats == {"memo_hits": 0, "disk_hits": 0, "walked": 2,
                               "lane_shared": 4}
    warm = run_batch(cfg, console=lambda s: None)
    assert warm.walk_stats["walked"] == 0
    assert warm.walk_stats["disk_hits"] == 2
    for la, lb in zip(cold.lanes, warm.lanes):
        for fa, fb in zip(la.output_files, lb.output_files):
            with open(fa, "rb") as a, open(fb, "rb") as b:
                assert a.read() == b.read()
    # A walk-seed variant cannot share: it must rewalk BOTH its products.
    mpath = str(tmp_path / "rewalk.json")
    with open(mpath, "w") as f:
        json.dump([{"name": "base"}, {"name": "other", "seed": 5}], f)
    mixed = run_batch(
        _cfg(tsv_paths, tmp_path, manifest=mpath, cache_dir=cache,
             result_name=str(tmp_path / "rw" / "out")),
        console=lambda s: None)
    assert mixed.walk_stats["disk_hits"] == 2     # base lane, from run 1
    assert mixed.walk_stats["walked"] == 2        # seed-5 lane, fresh


def test_manifest_validation_errors(tsv_paths, tmp_path):
    from g2vec_tpu.batch.engine import ManifestError, load_manifest

    cfg = _cfg(tsv_paths, tmp_path)

    def write(doc):
        p = str(tmp_path / "m.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        return p

    with pytest.raises(ManifestError, match="unknown key.*learning_rate"):
        load_manifest(write([{"learning_rate": 0.1}]), cfg)
    with pytest.raises(ManifestError, match="variant 1.*train_seed"):
        load_manifest(write([{}, {"train_seed": -1}]), cfg)
    with pytest.raises(ManifestError, match="learningRate.*> 0"):
        load_manifest(write([{"learningRate": 0}]), cfg)
    with pytest.raises(ManifestError, match="patient_subsample"):
        load_manifest(write([{"patient_subsample": 1.5}]), cfg)
    with pytest.raises(ManifestError, match="non-empty JSON list"):
        load_manifest(write({"variants": []}), cfg)
    with pytest.raises(ManifestError, match="duplicate variant name"):
        load_manifest(write([{"name": "a"}, {"name": "a"}]), cfg)
    with pytest.raises(ManifestError, match="'name' must match"):
        load_manifest(write([{"name": "bad name!"}]), cfg)
    with pytest.raises(ManifestError, match="cannot read"):
        load_manifest(str(tmp_path / "missing.json"), cfg)
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("{not json")
    with pytest.raises(ManifestError, match="not valid JSON"):
        load_manifest(bad, cfg)


def test_batch_flags_config_validation(tsv_paths, tmp_path):
    with pytest.raises(ValueError, match="mutually exclusive"):
        _cfg(tsv_paths, tmp_path, manifest="m.json",
             batch_seeds=2).validate()
    with pytest.raises(ValueError, match="--lanes"):
        _cfg(tsv_paths, tmp_path, batch_seeds=2, lanes=0).validate()
    with pytest.raises(ValueError, match="does not compose"):
        _cfg(tsv_paths, tmp_path, batch_seeds=2, supervise=True).validate()
    with pytest.raises(ValueError, match="does not compose"):
        _cfg(tsv_paths, tmp_path, batch_seeds=2,
             checkpoint_dir="/tmp/x").validate()
    with pytest.raises(ValueError, match="patient_subsample"):
        _cfg(tsv_paths, tmp_path, patient_subsample=1.5).validate()


def test_cli_flags_reach_config():
    from g2vec_tpu.config import config_from_args

    cfg = config_from_args([
        "E", "C", "N", "R", "--seeds", "4", "--lanes", "3",
        "--train-seed", "9", "--kmeans-seed", "2",
        "--patient-subsample", "0.5", "--subsample-seed", "11"])
    assert (cfg.batch_seeds, cfg.lanes, cfg.train_seed, cfg.kmeans_seed,
            cfg.patient_subsample, cfg.subsample_seed) == (4, 3, 9, 2,
                                                           0.5, 11)


def test_lane_metrics_jsonl_parseable(tsv_paths, tmp_path):
    """B interleaving lanes in ONE JSONL stream stay per-run parseable
    through the lane field; the done event reports per-lane stop
    epochs."""
    from g2vec_tpu.batch.engine import run_batch

    mj = str(tmp_path / "metrics.jsonl")
    cfg = _cfg(tsv_paths, tmp_path, batch_seeds=3, metrics_jsonl=mj)
    res = run_batch(cfg, console=lambda s: None)
    with open(mj) as f:
        events = [json.loads(line) for line in f]
    assert events, "no metrics emitted"
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)
    tags = {v.tag() for v in res.variants}
    lane_events = [e for e in events if "lane" in e]
    assert {e["lane"] for e in lane_events} == tags
    for tag in tags:
        kinds = {e["event"] for e in lane_events if e["lane"] == tag}
        assert {"lane_variant", "paths", "epoch", "train_done",
                "done"} <= kinds
    done = [e for e in events if e["event"] == "done" and "lane" not in e]
    assert len(done) == 1
    assert set(done[0]["stop_epochs"]) == tags
    assert done[0]["runs_per_hour"] > 0
    # Per-lane stop epochs in the done event match the train_done events.
    for e in lane_events:
        if e["event"] == "train_done":
            assert done[0]["stop_epochs"][e["lane"]] == e["stop_epoch"]


def test_train_cbow_lanes_unit_parity():
    """Unit-level: the vmapped lane trainer is bitwise the solo trainer
    per lane — embeddings, history, early-stop decisions — across the
    fused/unfused, superstep, and donate modes."""
    from g2vec_tpu.train.trainer import (LaneTrainSpec, train_cbow,
                                         train_cbow_lanes)

    n_paths, n_genes, hidden = 50, 68, 16

    def make_lane(s):
        r = np.random.default_rng(100 + s)
        dense = r.random((n_paths, n_genes)) < 0.15
        labels = r.integers(0, 2, n_paths).astype(np.int32)
        return np.packbits(dense, axis=1), labels

    specs = [LaneTrainSpec(*make_lane(k), seed=seed)
             for k, seed in enumerate([3, 7, 11])]
    for modes in ({}, {"fused_eval": False, "epoch_superstep": 4,
                       "donate": False}):
        solo = [train_cbow(sp.paths, sp.labels, packed_genes=n_genes,
                           hidden=hidden, learning_rate=0.05,
                           max_epochs=40, compute_dtype="float32",
                           param_dtype="float32", seed=sp.seed, **modes)
                for sp in specs]
        results, emb = train_cbow_lanes(
            specs, packed_genes=n_genes, hidden=hidden, learning_rate=0.05,
            max_epochs=40, compute_dtype="float32", param_dtype="float32",
            **modes)
        assert np.asarray(emb).shape == (3, n_genes, hidden)
        for s, l in zip(solo, results):
            assert np.array_equal(s.w_ih, l.w_ih)
            assert s.stop_epoch == l.stop_epoch
            assert s.stopped_early == l.stopped_early
            assert [h["loss"] for h in s.history] \
                == [h["loss"] for h in l.history]


def test_masked_minmax_matches_gathered_minmax(rng):
    from g2vec_tpu.ops.stats import masked_minmax, minmax

    x = rng.normal(size=200).astype(np.float32)
    mask = rng.random(200) < 0.3
    got = np.asarray(masked_minmax(x, mask))[mask]
    want = np.asarray(minmax(x[mask]))
    assert np.array_equal(got, want)
    # Degenerate guards: constant subset and empty mask -> all new_min.
    const = np.full(8, 3.3, np.float32)
    assert np.all(np.asarray(masked_minmax(const, np.ones(8, bool))) == 0.0)
    assert np.all(np.asarray(
        masked_minmax(const, np.zeros(8, bool))) == 0.0)


def test_bench_batch_ab_smoke():
    """bench.py --_batch_ab at ultra-toy scale emits a real
    batch_runs_per_hour line whose on-the-spot bit-identity check
    passed (the A/B's honesty gate: a speedup that changed any lane's
    bytes would be reported as bit_identical=false)."""
    env = {**os.environ, "G2VEC_BENCH_BATCH_VARIANTS": "2",
           "G2VEC_BENCH_BATCH_REPS": "1", "G2VEC_BENCH_BATCH_EPOCHS": "5"}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--_batch_ab"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1
    line = lines[0]
    assert line["metric"] == "batch_runs_per_hour"
    assert line["value"] and line["value"] > 0
    assert line["bit_identical"] is True
    assert line["lanes"] == 2
    assert line["walk_stats"]["lane_shared"] == 2


def test_subsample_patients_stratified_and_deterministic(tsv_paths):
    from g2vec_tpu.io.readers import (load_clinical, load_expression)
    from g2vec_tpu.preprocess import match_labels, subsample_patients

    data = load_expression(tsv_paths["expression"], use_native=False)
    data.label = match_labels(load_clinical(tsv_paths["clinical"]),
                              data.sample)
    sub1 = subsample_patients(data, 0.5, seed=3)
    sub2 = subsample_patients(data, 0.5, seed=3)
    assert np.array_equal(sub1.expr, sub2.expr)
    assert np.array_equal(sub1.sample, sub2.sample)
    for cls in (0, 1):
        n_cls = int((data.label == cls).sum())
        want = min(n_cls, max(2, int(round(0.5 * n_cls))))
        assert int((sub1.label == cls).sum()) == want
    other = subsample_patients(data, 0.5, seed=4)
    assert not np.array_equal(sub1.sample, other.sample)
    with pytest.raises(ValueError, match="fraction"):
        subsample_patients(data, 0.0, seed=0)
