"""Sparse (neighbor-table) walker: same invariants as the dense walker,
table-construction correctness, and dense/sparse statistical agreement."""
import jax
import numpy as np

from g2vec_tpu.ops.graph import neighbor_table, thresholded_edges
from g2vec_tpu.ops.walker import (generate_path_set, random_walks,
                                  random_walks_sparse)


def _table_from_dense(adj):
    src, dst = np.nonzero(adj)
    return neighbor_table(src.astype(np.int32), dst.astype(np.int32),
                          adj[src, dst].astype(np.float32), adj.shape[0])


def _ring_adj(n, w=1.0):
    adj = np.zeros((n, n), dtype=np.float32)
    for i in range(n):
        adj[i, (i + 1) % n] = w
    return adj


def test_neighbor_table_shape_and_padding():
    adj = np.zeros((5, 5), dtype=np.float32)
    adj[0, 1] = 0.9
    adj[0, 2] = 0.8
    adj[0, 3] = 0.7
    adj[4, 0] = 0.6
    idx, w = _table_from_dense(adj)
    assert idx.shape == w.shape == (5, 4)        # max degree 3 -> pow2 4
    row0 = {(int(i), float(x)) for i, x in zip(idx[0], w[0]) if x > 0}
    assert row0 == {(1, np.float32(0.9)), (2, np.float32(0.8)),
                    (3, np.float32(0.7))}
    assert (w[1] == 0).all() and (w[2] == 0).all()  # no out-edges -> all pad
    assert float(w[4, 0]) == np.float32(0.6)


def test_thresholded_edges_dedups_duplicates(rng):
    # The same directed edge listed twice must appear once (a duplicate
    # neighbor-list entry would double its sampling probability).
    n = 30
    s = rng.standard_normal(n).astype(np.float32)
    expr = (rng.standard_normal((n, 4)) * 0.05).astype(np.float32)
    expr[:, 0] += s
    expr[:, 1] += s
    src = np.array([0, 0, 2], dtype=np.int32)
    dst = np.array([1, 1, 3], dtype=np.int32)
    s_k, d_k, w_k = thresholded_edges(expr, src, dst, threshold=0.5)
    assert list(zip(s_k.tolist(), d_k.tolist())) == [(0, 1)]
    assert w_k[0] > 0.5


def test_sparse_walk_invariants_ring():
    n = 10
    idx, w = _table_from_dense(_ring_adj(n))
    starts = np.arange(n, dtype=np.int32)
    for len_path in (1, 4, 10):
        visited = np.asarray(random_walks_sparse(
            idx, w, starts, jax.random.key(0), len_path))
        assert (visited.sum(axis=1) == min(len_path, n)).all()


def test_sparse_dead_end_and_no_revisit():
    adj = np.zeros((4, 4), dtype=np.float32)
    adj[0, 1] = adj[1, 0] = 1.0          # 2-cycle: must stop after 2 nodes
    adj[2, 3] = 1.0                      # chain into dead end
    idx, w = _table_from_dense(adj)
    visited = np.asarray(random_walks_sparse(
        idx, w, np.array([0, 2], np.int32), jax.random.key(1), len_path=50))
    assert visited[0].sum() == 2
    assert visited[1].tolist() == [False, False, True, True]


def test_sparse_weighted_sampling_prefers_heavy_edge():
    adj = np.zeros((3, 3), dtype=np.float32)
    adj[0, 1], adj[0, 2] = 9.0, 1.0
    idx, w = _table_from_dense(adj)
    starts = np.zeros(4000, dtype=np.int32)
    visited = np.asarray(random_walks_sparse(
        idx, w, starts, jax.random.key(3), len_path=2))
    frac = visited[:, 1].mean()
    assert 0.86 < frac < 0.94, frac


def test_sparse_matches_dense_on_deterministic_graph():
    # On a graph with exactly one choice per step the two walkers must
    # produce the SAME path sets (randomness never enters).
    n = 12
    adj = _ring_adj(n)
    table = _table_from_dense(adj)
    dense = generate_path_set(adj, jax.random.key(7), len_path=5, reps=2)
    sparse = generate_path_set(table, jax.random.key(7), len_path=5, reps=2)
    assert dense == sparse


def test_sparse_batching_invariance(rng):
    n = 10
    adj = (rng.random((n, n)) * (rng.random((n, n)) < 0.4)).astype(np.float32)
    np.fill_diagonal(adj, 0.0)
    table = _table_from_dense(adj)
    full = generate_path_set(table, jax.random.key(5), len_path=4, reps=2)
    batched = generate_path_set(table, jax.random.key(5), len_path=4, reps=2,
                                walker_batch=3)
    assert full == batched


def test_sparse_dense_distributional_agreement(rng):
    # Same stochastic graph, many walks: visit frequencies per gene should
    # agree between implementations (their inverse-CDF slot orders differ —
    # gene ids vs neighbor-list position — so compare statistics, not sets).
    n = 8
    adj = (rng.random((n, n)) * (rng.random((n, n)) < 0.5)).astype(np.float32)
    np.fill_diagonal(adj, 0.0)
    table = _table_from_dense(adj)
    starts = np.repeat(np.arange(n, dtype=np.int32), 300)
    vd = np.asarray(random_walks(adj, starts, jax.random.key(0), 4))
    vs = np.asarray(random_walks_sparse(table[0], table[1], starts,
                                        jax.random.key(1), 4))
    fd = vd.mean(axis=0)
    fs = vs.mean(axis=0)
    np.testing.assert_allclose(fd, fs, atol=0.05)


def test_prefix_segmented_scan_matches_single_scan(rng):
    """The segmented no-revisit compare (ops/walker._SCAN_SEGMENTS,
    overridable via the n_segments parameter) drops only compares against
    -1 sentinel slots, so path lists must be BIT-IDENTICAL to a
    single-scan run — on a random weighted graph whose walks include dead
    ends and early stops, at several path lengths (including ones that
    don't divide evenly into segments)."""
    import g2vec_tpu.ops.walker as W

    n = 40
    adj = (rng.random((n, n)) < 0.15).astype(np.float32)
    adj *= rng.random((n, n)).astype(np.float32)
    np.fill_diagonal(adj, 0.0)
    nbr_idx, nbr_w = _table_from_dense(adj)
    starts = np.arange(n, dtype=np.int32)
    key = jax.random.key(5)

    for len_path in (1, 2, 7, 16):
        runs = {}
        for segs in (1, 3, 4, None):      # None = the module default
            runs[segs] = np.asarray(W._sparse_path_list(
                jax.numpy.asarray(nbr_idx), jax.numpy.asarray(nbr_w),
                jax.numpy.asarray(starts), key, len_path,
                n_segments=segs))
        np.testing.assert_array_equal(runs[1], runs[4])
        np.testing.assert_array_equal(runs[1], runs[3])
        np.testing.assert_array_equal(runs[1], runs[None])
