"""Streaming minibatch trainer suite (train/stream.py) — tier-1.

Five contracts, each pinned here:

1. **Ring**: bounded backpressure, slow-producer waits, producer-failure
   propagation, consumer-cancel unblocking — the four no-deadlock edges.
2. **Determinism**: shard contents are bit-identical to the full-range
   walker call, and the whole streaming trajectory (histories AND output
   bytes) is invariant to ``--sampler-threads`` and ring depth.
3. **Statistical parity vs full-batch**: val-ACC within the pinned band
   and top-N biomarker overlap above the pinned floor on the bundled-
   scale synthetic (the full-batch path stays the bitwise-golden
   reference; streaming's contract is this band).
4. **Bounded memory + overlap**: at a synthetic scale whose total path
   volume is many times the ring bound, peak in-flight path bytes stay
   at O(shard x depth) and training starts while sampling runs
   (backpressure caps the shards emitted before the first update).
5. **Fault seams**: ``shard_ring``/``prefetch`` faults terminate cleanly
   (stall/crash -> the injected error, never a wedged ring); a corrupted
   spool shard is detected at replay, deterministically re-walked, and
   the run's outputs are byte-identical to the unfaulted run's.
"""
import os
import shutil
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.stream

HAVE_CXX = shutil.which("g++") is not None
needs_native = pytest.mark.skipif(not HAVE_CXX, reason="no C++ toolchain")


# ---------------------------------------------------------------------------
# 1. ShardRing unit tests (no jax, no native code)
# ---------------------------------------------------------------------------

def _shard(i, rows=4, nb=8):
    from g2vec_tpu.train.stream import Shard

    return Shard(i, np.full((rows, nb), i % 251, np.uint8),
                 np.zeros(rows, np.int32))


def test_ring_backpressure_bounds_producer():
    from g2vec_tpu.train.stream import ShardRing

    ring = ShardRing(2)

    def produce():
        for i in range(7):
            assert ring.put(_shard(i))
        ring.finish()

    t = threading.Thread(target=produce)
    t.start()
    time.sleep(0.3)                 # let the producer hit the full ring
    got = []
    while True:
        s = ring.get()
        if s is None:
            break
        got.append(s.index)
        time.sleep(0.02)            # slow consumer
    t.join(timeout=5)
    assert not t.is_alive()
    assert got == list(range(7))            # emission order preserved
    assert ring.occupancy_hw <= 2           # never more than depth resident
    assert ring.peak_bytes <= 2 * _shard(0).nbytes
    assert ring.wait_put_s > 0.1            # the producer really blocked


def test_ring_slow_producer_consumer_waits():
    from g2vec_tpu.train.stream import ShardRing

    ring = ShardRing(4)

    def produce():
        for i in range(3):
            time.sleep(0.05)
            ring.put(_shard(i))
        ring.finish()

    t = threading.Thread(target=produce)
    t.start()
    got = [ring.get().index for _ in range(3)]
    assert ring.get() is None               # drained + finished
    t.join(timeout=5)
    assert got == [0, 1, 2]
    assert ring.wait_get_s > 0.05           # the consumer really waited


def test_ring_producer_failure_raises_at_get():
    from g2vec_tpu.train.stream import ShardRing

    ring = ShardRing(2)
    boom = RuntimeError("sampler died")
    ring.fail(boom)
    with pytest.raises(RuntimeError, match="sampler died"):
        ring.get()
    # Idempotent: every later get re-raises too (no deadlock, no None).
    with pytest.raises(RuntimeError):
        ring.get()


def test_ring_cancel_unblocks_blocked_producer():
    from g2vec_tpu.train.stream import ShardRing

    ring = ShardRing(1)
    assert ring.put(_shard(0))
    outcome = {}

    def produce():
        outcome["second_put"] = ring.put(_shard(1))   # blocks: ring full

    t = threading.Thread(target=produce)
    t.start()
    time.sleep(0.2)
    assert t.is_alive()                     # genuinely parked on the ring
    ring.cancel()
    t.join(timeout=5)
    assert not t.is_alive()
    assert outcome["second_put"] is False   # told to stop, not wedged


# ---------------------------------------------------------------------------
# 2. Shard plan + walker-range determinism
# ---------------------------------------------------------------------------

def test_shard_plan_partitions_start_axis():
    from g2vec_tpu.ops.host_walker import plan_shards

    plan = plan_shards(101, 3, 24, len_path=10)     # 24/(2*3) = 4 starts
    assert plan.starts_per_shard == 4
    covered = []
    total_rows = 0
    for s in range(plan.n_shards):
        lo, hi = plan.start_range(s)
        covered.extend(range(lo, hi))
        total_rows += 2 * plan.group_rows(s)
    assert covered == list(range(101))              # exact partition
    assert total_rows == 2 * plan.n_walkers         # both groups, all reps
    assert plan.rows_per_shard == 2 * 4 * 3


def test_shard_plan_auto_and_validation():
    from g2vec_tpu.ops.host_walker import plan_shards

    auto = plan_shards(100_000, 10, 0, len_path=80)
    assert auto.starts_per_shard * 2 * 10 <= 4096
    with pytest.raises(ValueError):
        plan_shards(100, 10, -1, len_path=80)


@needs_native
def test_walk_shard_bitwise_matches_full_range(small_dataset):
    """Every shard's rows are byte-for-byte the full-range call's rows for
    the same global walker indices — the determinism the spool re-walk
    and the thread/depth invariance both rest on."""
    from g2vec_tpu.ops.host_walker import (edges_to_csr, plan_shards,
                                           walk_packed_rows, walk_shard)

    rng = np.random.default_rng(0)
    G = 37
    src = rng.integers(0, G, 120).astype(np.int64)
    dst = rng.integers(0, G, 120).astype(np.int64)
    w = rng.random(120).astype(np.float32) + 0.1
    reps = 3
    full = walk_packed_rows(src, dst, w, G, len_path=9, reps=reps, seed=5)
    plan = plan_shards(G, reps, 10, len_path=9)
    csr = edges_to_csr(src, dst, w, G)
    for s in range(plan.n_shards):
        lo, hi = plan.start_range(s)
        expect = np.concatenate(
            [full[r * G + lo:r * G + hi] for r in range(reps)])
        got = walk_shard(src, dst, w, G, plan, s, seed=5, csr=csr,
                         n_threads=2)
        np.testing.assert_array_equal(got, expect)


# ---------------------------------------------------------------------------
# Pipeline-level fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def stream_tsv(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(
        n_good=30, n_poor=26, module_size=16, shared_module_size=6,
        n_background=24, n_expr_only=4, n_net_only=4, module_chords=3,
        background_edges=40, noise=0.25, shift=1.4, seed=7)
    return write_synthetic_tsv(
        spec, str(tmp_path_factory.mktemp("stream_data")))


def _cfg(paths, out, **over):
    from g2vec_tpu.config import G2VecConfig

    base = dict(
        expression_file=paths["expression"], clinical_file=paths["clinical"],
        network_file=paths["network"], result_name=out,
        lenPath=20, numRepetition=4, sizeHiddenlayer=32, epoch=40,
        numBiomarker=10, seed=11, compute_dtype="float32",
        walker_backend="native", train_mode="streaming", shard_paths=64)
    base.update(over)
    return G2VecConfig(**base)


def _run(cfg):
    from g2vec_tpu.pipeline import run

    return run(cfg, console=lambda s: None)


def _read_outputs(res):
    return [open(p, "rb").read() for p in res.output_files]


# ---------------------------------------------------------------------------
# 3. Determinism across threads and ring depth; 4. parity band
# ---------------------------------------------------------------------------

@needs_native
def test_streaming_invariant_to_threads_and_depth(stream_tsv, tmp_path):
    ref = _run(_cfg(stream_tsv, str(tmp_path / "a"),
                    sampler_threads=1, prefetch_depth=1, epoch=8))
    ref_bytes = _read_outputs(ref)
    for tag, threads, depth in (("b", 3, 1), ("c", 1, 4), ("d", 2, 3)):
        res = _run(_cfg(stream_tsv, str(tmp_path / tag),
                        sampler_threads=threads, prefetch_depth=depth,
                        epoch=8))
        assert _read_outputs(res) == ref_bytes, (tag, threads, depth)

        def strip(hist):
            return [{k: v for k, v in h.items() if k != "secs"}
                    for h in hist]

        assert strip(res.train_history) == strip(ref.train_history), (tag,)


@needs_native
def test_streaming_parity_band_vs_full_batch(stream_tsv, tmp_path):
    """The statistical contract: same config, streaming vs full-batch —
    val-ACC within the pinned band, top-N biomarker overlap above the
    pinned floor. (Both numbers measured with margin: at this seed the
    modes land within ~0.12 ACC and >= 0.85 overlap.)"""
    full = _run(_cfg(stream_tsv, str(tmp_path / "full"),
                     train_mode="full"))
    stream = _run(_cfg(stream_tsv, str(tmp_path / "stream"),
                       stream_patience=8))
    assert abs(stream.acc_val - full.acc_val) <= 0.20
    a, b = set(full.biomarkers), set(stream.biomarkers)
    assert len(a & b) / max(len(a), 1) >= 0.6
    # The streamed per-shard filter approximates the global integrate:
    # kept rows within ~15% of the full-batch path count at this scale.
    assert abs(stream.n_paths - full.n_paths) / full.n_paths <= 0.3


# ---------------------------------------------------------------------------
# 4. Bounded memory + sampling/training overlap
# ---------------------------------------------------------------------------

@needs_native
def test_streaming_memory_bounded_and_overlapped(tmp_path):
    """At a scale where the full-batch path matrix would be many times
    the ring bound, the in-flight path bytes stay O(shard x depth) and
    backpressure caps how far sampling runs ahead of training."""
    from g2vec_tpu.data.synth import SynthGraphSpec, write_synth_graph

    spec = SynthGraphSpec(n_genes=1500, attach=2, n_good=10, n_poor=10,
                          seed=3)
    paths = write_synth_graph(spec, str(tmp_path / "big"))
    depth = 2
    cfg = _cfg(paths, str(tmp_path / "res"), lenPath=12, numRepetition=4,
               shard_paths=128, prefetch_depth=depth, epoch=2,
               stream_patience=2, sizeHiddenlayer=16)
    res = _run(cfg)
    st = res.stream_stats
    nb = (res.n_genes + 7) // 8
    shard_bytes = st["shard_rows"] * (nb + 4)       # x rows + int32 labels
    total_path_bytes = st["rows_sampled"] * nb      # full-batch would hold
    assert st["n_shards"] >= 40                     # genuinely many shards
    assert st["ring_occupancy_hw"] <= depth
    assert st["ring_peak_bytes"] <= depth * shard_bytes
    # The bound is real: materializing every sampled row (what full-batch
    # does before epoch 0) would need >10x the ring's peak.
    assert total_path_bytes > 10 * st["ring_peak_bytes"]
    # Overlap: backpressure means at most (device double-buffer + ring
    # depth + 1) shards existed when the first update retired — training
    # began while the other ~90% of sampling still ran.
    assert st["shards_at_first_update"] <= depth + 4
    assert st["shards_at_first_update"] < st["n_shards"] // 2
    assert st["time_to_first_update_ms"] / 1e3 < st["sampling_wall_s"]


# ---------------------------------------------------------------------------
# 5. Fault seams: stall/crash terminate cleanly; corrupt -> re-walk
# ---------------------------------------------------------------------------

@needs_native
def test_shard_ring_stall_fault_fails_clean(stream_tsv, tmp_path):
    from g2vec_tpu.resilience.faults import InjectedFault, _reset_for_tests

    _reset_for_tests()
    cfg = _cfg(stream_tsv, str(tmp_path / "r"), epoch=6,
               fault_plan="stage=shard_ring,kind=stall,seconds=0.05")
    t0 = time.time()
    with pytest.raises(InjectedFault):
        _run(cfg)
    assert time.time() - t0 < 60        # died promptly, no wedged ring
    _reset_for_tests()


@needs_native
def test_prefetch_crash_fault_fails_clean(stream_tsv, tmp_path):
    from g2vec_tpu.resilience.faults import InjectedFault, _reset_for_tests

    _reset_for_tests()
    cfg = _cfg(stream_tsv, str(tmp_path / "r"), epoch=6,
               fault_plan="stage=prefetch,kind=crash,epoch=2")
    with pytest.raises(InjectedFault):
        _run(cfg)
    _reset_for_tests()


@needs_native
def test_spool_corrupt_rewalks_and_matches_unfaulted(stream_tsv, tmp_path):
    """kind=corrupt tears a spooled shard AFTER emission: epoch 0 trains
    on the good in-memory copy, the epoch-1 replay catches the sha256
    mismatch, re-walks the shard (deterministic => identical bytes), and
    the run completes with outputs byte-identical to the unfaulted run."""
    from g2vec_tpu.resilience.faults import _reset_for_tests

    _reset_for_tests()
    clean = _run(_cfg(stream_tsv, str(tmp_path / "clean"), epoch=6,
                      shard_paths=32, stream_patience=6))
    assert clean.stream_stats["rewalks"] == 0
    _reset_for_tests()
    with pytest.warns(RuntimeWarning, match="re-walking"):
        faulted = _run(_cfg(
            stream_tsv, str(tmp_path / "faulted"), epoch=6,
            shard_paths=32, stream_patience=6,
            fault_plan="stage=shard_ring,kind=corrupt,epoch=1"))
    assert faulted.stream_stats["rewalks"] == 1
    assert _read_outputs(faulted) == _read_outputs(clean)
    _reset_for_tests()


# ---------------------------------------------------------------------------
# Config plumbing + synth generator + engine integration
# ---------------------------------------------------------------------------

def test_streaming_config_validation(stream_tsv):
    from g2vec_tpu.config import SERVE_JOB_KEYS, G2VecConfig

    def cfg(**over):
        c = _cfg(stream_tsv, "x", **over)
        c.validate()
        return c

    cfg()                                            # baseline valid
    cfg(checkpoint_dir="/tmp/ck")                    # durable cursor (PR 9)
    cfg(checkpoint_dir="/tmp/ck", resume=True)
    with pytest.raises(ValueError, match="streaming"):
        cfg(mesh_shape=(2, 1))
    with pytest.raises(ValueError, match="checkpoint-dir"):
        cfg(resume=True)                             # cursor needs a home
    with pytest.raises(ValueError, match="single"):
        cfg(checkpoint_dir="/tmp/ck", checkpoint_layout="sharded")
    # Device backend STREAMS now (bit-exact sampler, PR 20) — and the
    # fused device feed has its own composition gates.
    cfg(walker_backend="device")
    cfg(walker_backend="device", device_feed=True)
    with pytest.raises(ValueError, match="streaming"):
        G2VecConfig(device_feed=True, walker_backend="device").validate()
    with pytest.raises(ValueError, match="walker-backend device"):
        cfg(device_feed=True)                        # native cannot fuse
    with pytest.raises(ValueError, match="graph-shards"):
        cfg(walker_backend="device", device_feed=True, graph_shards=2)
    with pytest.raises(ValueError, match="shard_paths"):
        cfg(shard_paths=2)
    with pytest.raises(ValueError, match="prefetch_depth"):
        cfg(prefetch_depth=0)
    with pytest.raises(ValueError, match="stream_patience"):
        cfg(stream_patience=0)
    with pytest.raises(ValueError, match="train_mode"):
        G2VecConfig(train_mode="sideways").validate()
    for key in ("train_mode", "shard_paths", "prefetch_depth",
                "stream_patience", "device_feed"):
        assert key in SERVE_JOB_KEYS                 # serve jobs may stream


def test_synth_graph_deterministic_and_loadable(tmp_path):
    from g2vec_tpu.data.synth import (SynthGraphSpec, make_scale_free_edges,
                                      make_synth_graph, write_synth_graph)
    from g2vec_tpu.io.readers import (load_clinical, load_expression,
                                      load_network)

    spec = SynthGraphSpec(n_genes=200, n_good=6, n_poor=6, seed=9)
    g1 = make_synth_graph(spec)
    g2 = make_synth_graph(spec)
    np.testing.assert_array_equal(g1[3], g2[3])      # expr deterministic
    np.testing.assert_array_equal(g1[4][0], g2[4][0])
    src, dst = make_scale_free_edges(200, 3, np.random.default_rng(0))
    assert src.min() >= 0 and dst.max() < 200
    deg = np.bincount(np.concatenate([src, dst]), minlength=200)
    assert deg.min() >= 1                            # one component seeded
    assert deg.max() >= 5 * max(np.median(deg), 1)   # heavy-tailed hubs

    paths = write_synth_graph(spec, str(tmp_path), prefix="t")
    data = load_expression(paths["expression"], use_native=False)
    clin = load_clinical(paths["clinical"])
    net = load_network(paths["network"])
    assert data.expr.shape == (12, 200)
    assert len(clin) == 12
    assert len(net.edges) == int(paths["n_edges"])


def test_make_synth_graph_cli_smoke(tmp_path):
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "make_synth_graph.py"),
         "--genes", "60", "--good", "4", "--poor", "4",
         "--out", str(tmp_path), "--prefix", "cli"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-400:]
    assert os.path.exists(tmp_path / "cli_EXPRESSION.txt")
    proc2 = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "make_synth_graph.py"),
         "--genes", "10", "--attach", "20", "--out", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc2.returncode == 2                     # loud validation


@needs_native
def test_engine_streaming_lanes_and_status(stream_tsv, tmp_path):
    """Streaming jobs are first-class under the batch engine (and so
    under serve): lanes run the solo streaming pipeline, metrics carry
    per-lane stream events, and the engine status surfaces the stream
    totals the daemon's /status republishes."""
    import json

    from g2vec_tpu.batch.engine import ResidentEngine, plan_variants

    mj = str(tmp_path / "m.jsonl")
    cfg = _cfg(stream_tsv, str(tmp_path / "m"), epoch=6, batch_seeds=2,
               shard_paths=32, metrics_jsonl=mj)
    with ResidentEngine() as engine:
        br = engine.execute(cfg, plan_variants(cfg),
                            console=lambda s: None)
        status = engine.status()
    assert len(br.lanes) == 2
    assert all(b["mode"] == "stream-solo" for b in br.buckets)
    for r in br.lanes:
        for p in r.output_files:
            assert os.path.exists(p)
    events = [json.loads(l) for l in open(mj)]
    stream_events = [e for e in events if e["event"] == "stream"]
    assert len(stream_events) == 2
    assert all("lane" in e and e["shards_emitted"] > 0
               for e in stream_events)
    assert status["stream"]["runs"] >= 2             # /status currency
    assert status["stream"]["shards_emitted"] > 0


# ---------------------------------------------------------------------------
# 6. Durable checkpoint/resume (PR 9): mid-epoch cursor, byte-identical
# ---------------------------------------------------------------------------

def test_spool_write_error_is_structured(tmp_path, monkeypatch):
    """ENOSPC / short-write during shard spooling surfaces as
    SpoolWriteError naming the shard and path — a clean job failure, not
    a half-written spool file silently poisoning the epoch-1 replay."""
    import errno

    from g2vec_tpu.train import stream as st

    arr = np.arange(65536, dtype=np.uint32).reshape(1024, 64)
    dest = str(tmp_path / "shard_000.npy")

    def boom(path, a):
        raise OSError(errno.ENOSPC, "No space left on device")

    monkeypatch.setattr(st.np, "save", boom)
    with pytest.raises(st.SpoolWriteError, match="shard 3") as ei:
        st._spool_write(3, dest, arr)
    assert ei.value.errno == errno.ENOSPC
    assert not os.path.exists(dest)          # no poisoned partial file
    monkeypatch.undo()

    real_save = np.save

    def short(path, a):
        real_save(path, a[: len(a) // 2])    # silent truncation

    monkeypatch.setattr(st.np, "save", short)
    with pytest.raises(st.SpoolWriteError, match="short write"):
        st._spool_write(0, dest, arr)


@needs_native
def test_stream_checkpoint_resume_byte_identical(stream_tsv, tmp_path):
    """The tentpole drill, in-process: a streaming run dies at the
    stream_ckpt seam mid-run; a --resume run picks the cursor up from
    the durable spool and finishes with outputs BYTE-IDENTICAL to an
    uninterrupted run — and a second --resume is a completed-run no-op
    that rewrites the same bytes."""
    from g2vec_tpu.resilience.faults import InjectedFault, _reset_for_tests

    _reset_for_tests()
    clean = _run(_cfg(stream_tsv, str(tmp_path / "clean"), epoch=6,
                      shard_paths=32, stream_patience=6))
    clean_bytes = _read_outputs(clean)

    ck = str(tmp_path / "ck")
    cfg_kw = dict(epoch=6, shard_paths=32, stream_patience=6,
                  checkpoint_dir=ck, checkpoint_every=1)
    with pytest.raises(InjectedFault):
        _run(_cfg(stream_tsv, str(tmp_path / "dur"),
                  fault_plan="stage=stream_ckpt,kind=crash,epoch=1",
                  **cfg_kw))
    _reset_for_tests()
    assert os.path.exists(os.path.join(ck, "stream_state.npz"))

    resumed = _run(_cfg(stream_tsv, str(tmp_path / "dur"),
                        resume=True, **cfg_kw))
    assert resumed.stream_stats["resumed"] == 1
    assert resumed.stream_stats["checkpoints"] > 0
    assert _read_outputs(resumed) == clean_bytes

    again = _run(_cfg(stream_tsv, str(tmp_path / "dur"),
                      resume=True, **cfg_kw))
    assert again.stream_stats["resumed"] == 1        # done short-circuit:
    assert again.stream_stats["shards_emitted"] == 0  # no training, no walks
    assert again.stream_stats["checkpoints"] == 0
    assert _read_outputs(again) == clean_bytes
    _reset_for_tests()


@needs_native
def test_stream_resume_from_every_epoch_boundary(stream_tsv, tmp_path):
    """Whichever epoch the death lands in, resume converges to the same
    bytes (the cursor is (epoch, shard), not just epoch)."""
    from g2vec_tpu.resilience.faults import InjectedFault, _reset_for_tests

    _reset_for_tests()
    clean = _run(_cfg(stream_tsv, str(tmp_path / "clean"), epoch=5,
                      shard_paths=32, stream_patience=6))
    clean_bytes = _read_outputs(clean)
    for ep in (0, 2):
        ck = str(tmp_path / f"ck{ep}")
        out = str(tmp_path / f"dur{ep}")
        kw = dict(epoch=5, shard_paths=32, stream_patience=6,
                  checkpoint_dir=ck, checkpoint_every=2)
        with pytest.raises(InjectedFault):
            _run(_cfg(stream_tsv, out,
                      fault_plan=f"stage=stream_ckpt,kind=crash,epoch={ep}",
                      **kw))
        _reset_for_tests()
        resumed = _run(_cfg(stream_tsv, out, resume=True, **kw))
        assert _read_outputs(resumed) == clean_bytes, ep
    _reset_for_tests()
