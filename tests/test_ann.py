"""Approximate-NN query plane (PR 18): the IVF index (ops/ann.py) —
build determinism, structural validation, the nprobe>=nlist bitwise
contract, the recall@k >= 0.95 contract at pruning scale — plus the
serve-side integration: indexed bundle publication/verification,
approx/exact cache-key separation, index tamper/torn/corrupt drills
(always exact fallback, never a wrong answer), tamper-then-republish
keeping the approx plane, and the federated ``fquery`` op on the
daemon and the router (dead-owner disk reads with attribution).

Bitwise assertions use INTEGER-VALUED float32 embeddings throughout
(dot products are sums of small integers, exact in float32 under any
summation order — the same trick as tests/test_query.py), so "approx
rescore == exact kernel on shared rows" carries no BLAS caveats.
"""
import json
import os
import socket
import threading

import numpy as np
import pytest

from g2vec_tpu.ops import ann, knn
from g2vec_tpu.resilience import faults
from g2vec_tpu.serve import inventory, protocol

pytestmark = pytest.mark.ann


# ---------------------------------------------------------------------------
# Shared fixtures/helpers (test_query.py idioms)
# ---------------------------------------------------------------------------

def _int_embeddings(g=257, h=8, seed=0):
    rng = np.random.default_rng(seed)
    emb = rng.integers(-5, 6, size=(g, h)).astype(np.float32)
    if g > 7:
        emb[7] = 0.0              # zero-norm row: scores -2.0, no nan
    if g > 101:
        emb[100] = emb[3]         # exact duplicates: forced ties
        emb[101] = emb[3]
    return emb


def _clustered_int_embeddings(g, h, n_clusters, seed=0):
    """Integer-valued clustered rows: well-separated integer centers
    plus small integer noise, so IVF pruning is meaningful AND every
    dot product stays exact in float32."""
    rng = np.random.default_rng(seed)
    centers = rng.integers(-30, 31, size=(n_clusters, h))
    which = rng.integers(0, n_clusters, size=g)
    noise = rng.integers(-2, 3, size=(g, h))
    return (centers[which] + noise).astype(np.float32)


def _naive_cosine(emb, q, k, exclude=-1):
    emb = np.asarray(emb, dtype=np.float32)
    q = np.asarray(q, dtype=np.float32)
    g = emb.shape[0]
    sims = emb @ q
    norms = np.sqrt((emb * emb).sum(axis=1))
    qn = np.float32(np.sqrt(np.dot(q, q)))
    denom = norms * qn
    ok = denom > 0
    sims = np.where(ok, sims / np.where(ok, denom, 1), np.float32(-2.0))
    if 0 <= exclude < g:
        sims[exclude] = -np.inf
    order = np.lexsort((np.arange(g), -sims))[:min(k, g)]
    return order, sims[order]


def _plant_bundle(dest, g=48, h=8, seed=0, with_scores=True,
                  ann_nlist=0, clustered=False):
    """Write one real bundle (optionally indexed); returns what went in."""
    from g2vec_tpu.io.writers import write_inventory_bundle

    rng = np.random.default_rng(seed)
    if clustered:
        emb = _clustered_int_embeddings(g, h, max(4, g // 12), seed=seed)
    else:
        emb = rng.integers(-5, 6, size=(g, h)).astype(np.float32)
    genes = [f"G{i:03d}" for i in range(g)]
    scores = (rng.standard_normal((2, g)).astype(np.float32)
              if with_scores else None)
    write_inventory_bundle(dest, emb, genes, scores, {"source": "test"},
                           ann_nlist=ann_nlist)
    return emb, genes, scores


def _gen(dest):
    """Resolve a bundle root to its live generation directory."""
    from g2vec_tpu.io.writers import read_generation

    return os.path.join(dest, read_generation(dest))


def _daemon(tmp_path, **opt_overrides):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=os.path.join(str(tmp_path), "serve.sock"),
        state_dir=os.path.join(str(tmp_path), "state"), **opt_overrides)
    return ServeDaemon(opts, console=lambda s: None)


def _roundtrip(d, req):
    a, b = socket.socketpair()
    t = threading.Thread(target=d._handle_conn, args=(a,), daemon=True)
    t.start()
    f = b.makefile("rwb")
    try:
        protocol.write_event(f, req)
        ev = protocol.read_event(f)
    finally:
        f.close()
        b.close()
        t.join(timeout=30)
    return ev


def _flip_byte(path, from_end=3):
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) - from_end)
        orig = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([orig[0] ^ 0xFF]))


# ---------------------------------------------------------------------------
# resolve_nlist / build structure / determinism
# ---------------------------------------------------------------------------

def test_resolve_nlist_contract():
    assert ann.resolve_nlist(10**6, -1) == 0          # disabled
    assert ann.resolve_nlist(0, 0) == 0               # nothing to index
    assert ann.resolve_nlist(100, 8) == 8             # explicit
    assert ann.resolve_nlist(5, 8) == 5               # clamped to rows
    assert ann.resolve_nlist(ann.ANN_AUTO_MIN_ROWS - 1, 0) == 0
    auto = ann.resolve_nlist(ann.ANN_AUTO_MIN_ROWS, 0)
    assert auto == int(round(ann.ANN_AUTO_MIN_ROWS ** 0.5))
    assert ann.resolve_nlist(10**6, 0) == 1000        # sqrt scaling


def test_build_ivf_structure_and_postings_invariants():
    emb = _int_embeddings(g=300)
    cents, postings, offsets = ann.build_ivf(emb, 12)
    assert cents.shape == (12, 8) and cents.dtype == np.float32
    assert postings.shape == (300,) and postings.dtype == np.int32
    assert offsets.shape == (13,) and offsets.dtype == np.int64
    # offsets partition [0, G]; postings are a permutation of rows.
    assert offsets[0] == 0 and offsets[-1] == 300
    assert np.all(np.diff(offsets) >= 0)
    assert np.array_equal(np.sort(postings), np.arange(300))
    # Within each list, ids ascend — the order the subset kernel's tie
    # rule depends on.
    for li in range(12):
        lst = postings[offsets[li]:offsets[li + 1]]
        assert np.all(np.diff(lst) > 0) or lst.size <= 1


def test_build_ivf_is_deterministic():
    emb = _int_embeddings(g=300, seed=4)
    a = ann.build_ivf(emb, 10)
    b = ann.build_ivf(emb.copy(), 10)
    for x, y in zip(a, b):
        assert x.tobytes() == y.tobytes()
    # Seeded builds are deterministic too, and a shape-mismatched seed
    # silently falls back to the row seeding (same bytes as unseeded).
    seed_c = np.random.default_rng(9).integers(
        -5, 6, size=(3, 8)).astype(np.float32)
    s1 = ann.build_ivf(emb, 10, seed_centroids=seed_c)
    s2 = ann.build_ivf(emb, 10, seed_centroids=seed_c.copy())
    for x, y in zip(s1, s2):
        assert x.tobytes() == y.tobytes()
    bad_seed = np.ones((3, 5), dtype=np.float32)      # hidden mismatch
    s3 = ann.build_ivf(emb, 10, seed_centroids=bad_seed)
    for x, y in zip(a, s3):
        assert x.tobytes() == y.tobytes()


def test_build_ivf_rejects_bad_inputs():
    emb = _int_embeddings(g=20)
    for bad_nlist in (0, -1, 21):
        with pytest.raises(ValueError):
            ann.build_ivf(emb, bad_nlist)
    with pytest.raises(ValueError):
        ann.build_ivf(np.empty((0, 8), dtype=np.float32), 1)
    with pytest.raises(ValueError):
        ann.build_ivf(np.ones(8, dtype=np.float32), 1)


def test_ivf_index_refuses_structural_corruption():
    emb = _int_embeddings(g=50)
    cents, postings, offsets = ann.build_ivf(emb, 5)
    ann.IVFIndex(cents, postings, offsets, n_rows=50, hidden=8)  # sane
    bad_off = offsets.copy()
    bad_off[2], bad_off[3] = bad_off[3] + 1, bad_off[2]   # non-monotone
    with pytest.raises(ValueError):
        ann.IVFIndex(cents, postings, bad_off, n_rows=50, hidden=8)
    bad_post = postings.copy()
    bad_post[0] = 50                                      # out of range
    with pytest.raises(ValueError):
        ann.IVFIndex(cents, bad_post, offsets, n_rows=50, hidden=8)
    with pytest.raises(ValueError):
        ann.IVFIndex(cents, postings[:-1], offsets, n_rows=50, hidden=8)
    with pytest.raises(ValueError):
        ann.IVFIndex(cents, postings, offsets, n_rows=50, hidden=16)
    with pytest.raises(ValueError):
        ann.IVFIndex(cents, postings, offsets[:-1], n_rows=50, hidden=8)


# ---------------------------------------------------------------------------
# Kernel exactness: subset kernel, nprobe>=nlist, edge cases
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 5, 257, 400])
@pytest.mark.parametrize("block_rows", [1, 13, 8192])
def test_subset_kernel_on_full_rows_is_bitwise_exact(k, block_rows):
    emb = _int_embeddings()
    norms = knn.row_norms(emb)
    rows = np.arange(emb.shape[0], dtype=np.int64)
    for exclude in (-1, 3):
        idx, sims = knn.cosine_topk_subset(emb, norms, rows, emb[3], k,
                                           exclude=exclude,
                                           block_rows=block_rows)
        ref_idx, ref_sims = knn.cosine_topk(emb, norms, emb[3], k,
                                            exclude=exclude)
        assert np.array_equal(idx, ref_idx)
        assert np.array_equal(sims, ref_sims)


def test_subset_kernel_restricted_rows_match_masked_naive():
    emb = _int_embeddings()
    norms = knn.row_norms(emb)
    rows = np.arange(0, emb.shape[0], 3, dtype=np.int64)  # every 3rd row
    idx, sims = knn.cosine_topk_subset(emb, norms, rows, emb[3], 7,
                                       exclude=3)
    ref_idx, ref_sims = _naive_cosine(emb[rows], emb[3], 7,
                                      exclude=int(np.searchsorted(rows, 3)))
    assert np.array_equal(idx, rows[ref_idx])
    assert np.array_equal(sims, ref_sims.astype(np.float32))
    assert set(idx.tolist()) <= set(rows.tolist())


def test_nprobe_ge_nlist_is_bitwise_equal_to_exact():
    emb = _int_embeddings(g=300, seed=2)
    norms = knn.row_norms(emb)
    cents, postings, offsets = ann.build_ivf(emb, 8)
    index = ann.IVFIndex(cents, postings, offsets, n_rows=300, hidden=8)
    for nprobe in (8, 9, 10000):
        for exclude in (-1, 3):
            idx, sims, ncand = ann.ivf_topk(emb, norms, index, emb[3],
                                            10, nprobe=nprobe,
                                            exclude=exclude)
            assert ncand == 300       # full coverage, no pruning
            ref_idx, ref_sims = knn.cosine_topk(emb, norms, emb[3], 10,
                                                exclude=exclude)
            assert np.array_equal(idx, ref_idx)
            assert np.array_equal(sims, ref_sims)


def test_k_exceeding_candidates_and_g():
    emb = _int_embeddings(g=60, seed=5)
    norms = knn.row_norms(emb)
    cents, postings, offsets = ann.build_ivf(emb, 6)
    index = ann.IVFIndex(cents, postings, offsets, n_rows=60, hidden=8)
    # k > G with full probe: every row comes back, descending.
    idx, sims, ncand = ann.ivf_topk(emb, norms, index, emb[0], 500,
                                    nprobe=6)
    assert ncand == 60 and idx.shape == (60,)
    assert np.all(np.diff(sims) <= 0)
    # k > candidate count with a narrow probe: all candidates, no more.
    idx, sims, ncand = ann.ivf_topk(emb, norms, index, emb[0], 500,
                                    nprobe=1)
    assert idx.shape == (ncand,) and 0 < ncand < 60


def test_empty_posting_lists_yield_empty_result_not_crash():
    # Hand-built index: every row lives in list 1, list 0 is empty. A
    # query sitting on centroid 0 with nprobe=1 probes only the empty
    # list — the contract is an EMPTY result, never an exception (the
    # serve layer then surfaces whatever its caller does with zero
    # neighbors; correctness is preserved because nothing is invented).
    g = 12
    emb = np.eye(g, 4, dtype=np.float32) + 1.0
    norms = knn.row_norms(emb)
    cents = np.array([[1.0, 0, 0, 0], [0, 1, 0, 0]], dtype=np.float32)
    postings = np.arange(g, dtype=np.int32)
    offsets = np.array([0, 0, g], dtype=np.int64)
    index = ann.IVFIndex(cents, postings, offsets, n_rows=g, hidden=4)
    q = np.array([100.0, 0, 0, 0], dtype=np.float32)  # sits on list 0
    idx, sims, ncand = ann.ivf_topk(emb, norms, index, q, 3, nprobe=1)
    assert ncand == 0 and idx.size == 0 and sims.size == 0
    # Probing both lists recovers everything.
    idx, sims, ncand = ann.ivf_topk(emb, norms, index, q, 3, nprobe=2)
    assert ncand == g and idx.size == 3


def test_duplicate_rows_tie_by_ascending_index_in_approx_path():
    emb = _int_embeddings()            # rows 3, 100, 101 identical
    norms = knn.row_norms(emb)
    cents, postings, offsets = ann.build_ivf(emb, 4)
    index = ann.IVFIndex(cents, postings, offsets, n_rows=emb.shape[0],
                         hidden=8)
    # Duplicates land in the same list (identical vectors assign
    # identically), so even nprobe=1 sees all three; excluding row 3
    # must surface 100 before 101 — the exact kernel's tie rule.
    idx, sims, _ = ann.ivf_topk(emb, norms, index, emb[3], 2,
                                nprobe=1, exclude=3)
    assert idx[0] == 100 and idx[1] == 101
    assert sims[0] == sims[1]


def test_posting_major_topk_bitwise_vs_gather():
    """The posting-major contiguous candidate storage is a pure layout
    change: for every (query, nprobe) the streamed slab path returns
    the gather path's answer bitwise — same ids, same float32 sims,
    same candidate count — including the nprobe>=nlist delegation."""
    emb = _clustered_int_embeddings(160, 8, 8, seed=11)
    norms = knn.row_norms(emb)
    cen, post, off = ann.build_ivf(emb, 8)
    gather = ann.IVFIndex(cen, post, off, emb.shape[0], emb.shape[1])
    pm = ann.IVFIndex(cen, post, off, emb.shape[0], emb.shape[1],
                      pvecs=np.ascontiguousarray(emb[post]))
    for qi in (0, 3, 17, 59, 121):
        for nprobe in (1, 2, 3, 8):
            gi, gs, gc = ann.ivf_topk(emb, norms, gather, emb[qi], 5,
                                      nprobe=nprobe, exclude=qi,
                                      posting_major=False)
            pi, ps, pc = ann.ivf_topk(emb, norms, pm, emb[qi], 5,
                                      nprobe=nprobe, exclude=qi,
                                      posting_major=True)
            assert np.array_equal(gi, pi), (qi, nprobe)
            assert np.array_equal(gs, ps), (qi, nprobe)
            assert gc == pc
    # auto mode streams iff the index carries the copy; forcing
    # posting-major without one is a loud error, not a silent gather.
    ai, _, _ = ann.ivf_topk(emb, norms, pm, emb[0], 5, nprobe=2,
                            exclude=0)
    bi, _, _ = ann.ivf_topk(emb, norms, gather, emb[0], 5, nprobe=2,
                            exclude=0)
    assert np.array_equal(ai, bi)
    with pytest.raises(ValueError, match="posting-major"):
        ann.ivf_topk(emb, norms, gather, emb[0], 3, posting_major=True)


def test_topk_biomarkers_shortlist_matches_exact(tmp_path):
    """The ann_scores shortlist serves approx topk_biomarkers with
    answers IDENTICAL to the exact kernel (top-k is a prefix of the
    build-time top-M), and a torn shortlist degrades to exact with the
    same attribution contract as the neighbors path."""
    dest = str(tmp_path / "inv" / "j1" / "v0")
    _plant_bundle(dest, g=64, h=8, seed=5, ann_nlist=4, clustered=True)
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    b = cat.get("j1/v0")
    assert b.ann_scores is not None and b.ann_scores.shape == (2, 64)
    approx = inventory.run_query(cat, "topk_biomarkers", "j1/v0", k=5,
                                 mode="approx")
    assert approx["recall_mode"] == "approx"
    assert approx["shortlist_m"] == 64
    exact = inventory.run_query(cat, "topk_biomarkers", "j1/v0", k=5,
                                mode="exact")
    assert exact["recall_mode"] == "exact"
    for group in ("good", "poor"):
        assert approx[group] == exact[group]
    # Torn shortlist (lenient tier): the approx request falls back to
    # the exact scan, answer unchanged, refusal attributed.
    os.unlink(os.path.join(_gen(dest), "ann_scores.npy"))
    cat.invalidate("j1/v0")
    again = inventory.run_query(cat, "topk_biomarkers", "j1/v0", k=5,
                                mode="approx")
    assert again["recall_mode"] == "exact_fallback"
    assert again["ann_warning"]["code"] == "torn"
    for group in ("good", "poor"):
        assert again[group] == exact[group]


def test_lloyd_update_parity_with_jax_kmeans():
    """ops/ann's numpy Lloyd step mirrors ops.kmeans._update_centers —
    including the empty-cluster freeze — up to f64-accumulate-then-cast
    rounding (the jax side sums in f32, so parity is allclose, not
    bitwise; the freeze itself IS bitwise)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from g2vec_tpu.ops.kmeans import _update_centers

    rng = np.random.default_rng(11)
    x = rng.integers(-5, 6, size=(120, 6)).astype(np.float32)
    centers = rng.integers(-5, 6, size=(7, 6)).astype(np.float32)
    centers[5] = 1000.0            # guaranteed-empty cluster
    assign = ann._assign(ann._normalize_rows(x), centers)
    xn = ann._normalize_rows(x)
    ours = ann.lloyd_update(xn, centers, assign)
    onehot = jax.nn.one_hot(jnp.asarray(assign), 7, dtype=jnp.float32)
    theirs = np.asarray(_update_centers(onehot, jnp.asarray(xn),
                                        jnp.asarray(centers)))
    assert np.allclose(ours, theirs, rtol=1e-5, atol=1e-6)
    # Empty cluster 5: frozen VERBATIM on both sides.
    assert np.array_equal(ours[5], centers[5])
    assert np.array_equal(theirs[5], centers[5])


# ---------------------------------------------------------------------------
# The recall contract, at a scale where pruning actually prunes
# ---------------------------------------------------------------------------

def test_recall_contract_at_pruning_scale():
    """The headline contract: nlist=32/nprobe=4 over 6000 clustered
    rows scans <G candidates per query yet keeps recall@10 >= 0.95,
    and every id the approx path returns carries the EXACT kernel's
    similarity for that id, bitwise."""
    g, h, k, nprobe = 6000, 32, 10, 4
    emb = _clustered_int_embeddings(g, h, 40, seed=3)
    norms = knn.row_norms(emb)
    cents, postings, offsets = ann.build_ivf(emb, 32)
    index = ann.IVFIndex(cents, postings, offsets, n_rows=g, hidden=h)
    rng = np.random.default_rng(17)
    queries = rng.choice(g, size=50, replace=False)
    hits = total = 0
    for gi in queries:
        gi = int(gi)
        idx, sims, ncand = ann.ivf_topk(emb, norms, index, emb[gi], k,
                                        nprobe=nprobe, exclude=gi)
        assert 0 < ncand < g       # pruning really happened
        ref_idx, ref_sims = knn.cosine_topk(emb, norms, emb[gi], k,
                                            exclude=gi)
        exact = {int(i): float(s) for i, s in zip(ref_idx, ref_sims)}
        for i, s in zip(idx, sims):
            if int(i) in exact:    # shared ids: bitwise-identical score
                assert float(s) == exact[int(i)]
        hits += len(set(idx.tolist()) & set(ref_idx.tolist()))
        total += k
    recall = hits / total
    assert recall >= 0.95, f"recall@{k}={recall:.3f} < 0.95"


# ---------------------------------------------------------------------------
# Serve integration: cache keys, indexed bundles, tamper/corrupt drills
# ---------------------------------------------------------------------------

def test_cache_key_separates_mode_and_nprobe():
    base = inventory.cache_key("j/v0", "neighbors", "G001", 10)
    keys = {base,
            inventory.cache_key("j/v0", "neighbors", "G001", 10,
                                mode="approx"),
            inventory.cache_key("j/v0", "neighbors", "G001", 10,
                                mode="approx", nprobe=4),
            inventory.cache_key("j/v0", "neighbors", "G001", 10,
                                mode="approx", nprobe=8),
            inventory.cache_key("j/v0", "neighbors", "G001", 10,
                                mode="exact", nprobe=0)}
    assert len(keys) == 4       # exact/0 == the default key, rest differ
    assert inventory.cache_key("j/v0", "neighbors", "G001", 10,
                               mode="exact", nprobe=0) == base


def test_indexed_bundle_roundtrip_and_mode_attribution(tmp_path):
    from g2vec_tpu.io.writers import INVENTORY_MANIFEST

    dest = str(tmp_path / "inv" / "j1" / "v0")
    emb, genes, _ = _plant_bundle(dest, g=96, h=8, seed=1, ann_nlist=8,
                                  clustered=True)
    with open(os.path.join(_gen(dest), INVENTORY_MANIFEST)) as f:
        man = json.load(f)["files"]
    for fn in ann.ANN_FILES:
        assert fn in man and \
            os.path.exists(os.path.join(_gen(dest), fn)), fn
    with open(os.path.join(_gen(dest), "meta.json")) as f:
        meta = json.load(f)
    assert meta["ann"]["format"] == ann.ANN_FORMAT
    assert meta["ann"]["nlist"] == 8 and meta["ann"]["build_ms"] >= 0

    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    b = cat.get("j1/v0")
    assert b.ann is not None and b.ann.nlist == 8 and b.ann_error is None
    ent = next(e for e in cat.listing() if e["bundle"] == "j1/v0")
    assert ent["ann"] is True

    approx = inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[5],
                                 k=6, mode="approx", nprobe=2)
    assert approx["recall_mode"] == "approx" and approx["mode"] == "approx"
    assert approx["nprobe"] == 2 and approx["nlist"] == 8
    assert 0 < approx["candidates"] < 96
    exact = inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[5],
                                k=6, mode="exact")
    assert exact["recall_mode"] == "exact"
    ref_idx, ref_sims = _naive_cosine(emb, emb[5], 6, exclude=5)
    assert exact["neighbors"] == [genes[i] for i in ref_idx]
    # Full-width probe: approx answers == exact answers, values and all.
    full = inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[5],
                               k=6, mode="approx", nprobe=8)
    assert full["neighbors"] == exact["neighbors"]
    assert full["sims"] == exact["sims"]
    # Unindexed bundle: mode=approx silently serves exact, no warning.
    _plant_bundle(str(tmp_path / "inv" / "j2" / "v0"), g=20, seed=2)
    plain = inventory.run_query(cat, "neighbors", "j2/v0", gene="G000",
                                k=3, mode="approx")
    assert plain["recall_mode"] == "exact" and "ann_warning" not in plain
    with pytest.raises(inventory.InventoryError) as ei:
        inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[0],
                            mode="blended")
    assert ei.value.code == "bad_query"
    with pytest.raises(inventory.InventoryError):
        inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[0],
                            nprobe=-1)


def test_tampered_or_torn_index_falls_back_to_exact(tmp_path):
    dest = str(tmp_path / "inv" / "j1" / "v0")
    emb, genes, _ = _plant_bundle(dest, g=64, h=8, seed=6, ann_nlist=4)
    _flip_byte(os.path.join(_gen(dest), "ann_postings.npy"))
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    b = cat.get("j1/v0")                 # maps: core arrays verify fine
    assert b.ann is None and b.ann_error["code"] == "tampered"
    resp = inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[2],
                               k=5, mode="approx")
    assert resp["recall_mode"] == "exact_fallback"
    assert resp["ann_warning"]["code"] == "tampered"
    ref_idx, _ = _naive_cosine(emb, emb[2], 5, exclude=2)
    assert resp["neighbors"] == [genes[i] for i in ref_idx]  # right answer
    # Torn index (file deleted): same degradation, code "torn".
    dest2 = str(tmp_path / "inv" / "j2" / "v0")
    _plant_bundle(dest2, g=64, h=8, seed=7, ann_nlist=4)
    os.unlink(os.path.join(_gen(dest2), "ann_offsets.npy"))
    b2 = cat.get("j2/v0")
    assert b2.ann is None and b2.ann_error["code"] == "torn"
    r2 = inventory.run_query(cat, "neighbors", "j2/v0", gene="G001",
                             k=3, mode="approx")
    assert r2["recall_mode"] == "exact_fallback"
    # mode=exact on the same bundle: clean, no warning attached.
    r3 = inventory.run_query(cat, "neighbors", "j2/v0", gene="G001",
                             k=3, mode="exact")
    assert r3["recall_mode"] == "exact" and "ann_warning" not in r3
    # Core arrays stay strict: the two-tier gate never loosened them.
    dest3 = str(tmp_path / "inv" / "j3" / "v0")
    _plant_bundle(dest3, g=32, h=8, seed=8, ann_nlist=4)
    _flip_byte(os.path.join(_gen(dest3), "embeddings.npy"))
    with pytest.raises(inventory.InventoryError) as ei:
        cat.get("j3/v0")
    assert ei.value.code == "tampered"


def test_ann_build_fault_seam_corrupt_drill(tmp_path):
    """kind=corrupt at the ann_build seam models post-manifest bitrot
    of the staged index: publication succeeds, the manifest hash then
    refuses the index at map time, and queries degrade to exact with
    the structured warning — a corrupted index can never mis-answer."""
    assert "ann_build" in faults.SEAMS
    faults.install_plan("stage=ann_build,kind=corrupt")
    try:
        dest = str(tmp_path / "inv" / "j1" / "v0")
        emb, genes, _ = _plant_bundle(dest, g=64, h=8, seed=9,
                                      ann_nlist=4)
    finally:
        faults.install_plan(None)
    cat = inventory.InventoryCatalog([str(tmp_path / "inv")],
                                     budget_bytes=1 << 30)
    b = cat.get("j1/v0")
    assert b.ann is None and b.ann_error["code"] == "tampered"
    resp = inventory.run_query(cat, "neighbors", "j1/v0", gene=genes[0],
                               k=4, mode="approx")
    assert resp["recall_mode"] == "exact_fallback"
    ref_idx, _ = _naive_cosine(emb, emb[0], 4, exclude=0)
    assert resp["neighbors"] == [genes[i] for i in ref_idx]


# ---------------------------------------------------------------------------
# Daemon: mode plumbing, cache separation, republish, fquery
# ---------------------------------------------------------------------------

def test_daemon_query_modes_and_cache_separation(tmp_path):
    d = _daemon(tmp_path, ann_nlist=4)
    jid = "i" + "a" * 12
    dest = os.path.join(d.opts.state_dir, "inventory", jid, "v0")
    emb, genes, _ = _plant_bundle(dest, g=48, h=8, seed=1, ann_nlist=4,
                                  clustered=True)
    base = {"q": "neighbors", "job_id": jid, "gene": genes[3], "k": 5}
    ap = d.handle_query(dict(base))                    # default: approx
    assert ap["event"] == "query_result"
    assert ap["recall_mode"] == "approx" and ap["nlist"] == 4
    ex = d.handle_query(dict(base, mode="exact"))
    assert ex["recall_mode"] == "exact"
    ref_idx, ref_sims = _naive_cosine(emb, emb[3], 5, exclude=3)
    assert ex["neighbors"] == [genes[i] for i in ref_idx]
    # Distinct cache entries per (mode, nprobe): repeating each exact
    # request hits, switching mode/nprobe misses.
    h0 = d.qcache.stats()["hits"]
    assert d.handle_query(dict(base))["recall_mode"] == "approx"
    assert d.qcache.stats()["hits"] == h0 + 1
    n2 = d.handle_query(dict(base, nprobe=2))
    assert n2["nprobe"] == 2
    assert d.qcache.stats()["hits"] == h0 + 1          # a miss, cached new
    assert d.handle_query(dict(base, mode="exact"))["recall_mode"] == \
        "exact"
    assert d.qcache.stats()["hits"] == h0 + 2
    for bad in [dict(base, mode="blended"), dict(base, nprobe=-2),
                dict(base, nprobe=True)]:
        resp = d.handle_query(bad)
        assert resp["event"] == "error" and resp["error"] == "bad_query"


def test_daemon_republish_rebuilds_ann_index(tmp_path):
    """Tamper-then-republish: the rebuilt bundle carries a fresh index
    (daemon ann_nlist applies to republication too), so the approx
    plane survives the round trip — mode=approx serves recall_mode
    approx again, not a permanent exact_fallback."""
    d = _daemon(tmp_path, ann_nlist=4)
    jid = "i" + "b" * 12
    rng = np.random.default_rng(3)
    emb = rng.integers(-5, 6, size=(20, 8)).astype(np.float32)
    genes = [f"G{i:03d}" for i in range(20)]
    vec = os.path.join(str(tmp_path), "q_vectors.txt")
    with open(vec, "w") as f:
        f.write("GeneSymbol\t" + "\t".join(f"d{i}" for i in range(8))
                + "\n")
        for g, row in zip(genes, emb):
            f.write(g + "\t" + "\t".join(repr(float(x)) for x in row)
                    + "\n")
    with open(os.path.join(d.opts.state_dir, "results", f"{jid}.json"),
              "w") as f:
        json.dump({"event": "job_done", "job_id": jid, "status": "done",
                   "variants": {"v0": {"outputs": [vec]}}}, f)
    dest = os.path.join(d.opts.state_dir, "inventory", jid, "v0")
    _plant_bundle(dest, g=20, h=8, seed=3, ann_nlist=4)
    _flip_byte(os.path.join(_gen(dest), "embeddings.npy"))  # core tamper

    resp = d.handle_query({"q": "neighbors", "job_id": jid,
                           "variant": "v0", "gene": "G000", "k": 3})
    assert resp["event"] == "query_result", resp
    assert resp["recall_mode"] == "approx"             # index rebuilt
    want, _ = _naive_cosine(emb, emb[0], 3, exclude=0)
    full = d.handle_query({"q": "neighbors", "job_id": jid,
                           "variant": "v0", "gene": "G000", "k": 3,
                           "nprobe": 4})               # nprobe == nlist
    assert full["neighbors"] == [genes[i] for i in want]
    meta = d.handle_query({"q": "meta", "job_id": jid, "variant": "v0"})
    assert meta["meta"]["source"] == "republish"
    assert meta["meta"]["ann"]["nlist"] == 4


def test_daemon_fquery_gene_rank_and_bundle_overlap(tmp_path):
    d = _daemon(tmp_path)
    planted = {}
    for jid, seed in [("i" + "c" * 12, 1), ("i" + "d" * 12, 2)]:
        dest = os.path.join(d.opts.state_dir, "inventory", jid, "v0")
        planted[jid] = _plant_bundle(dest, g=30, h=8, seed=seed,
                                     ann_nlist=4)
    # A scores-less bundle and a bundle missing the gene, for
    # per-bundle attribution.
    jid3 = "i" + "e" * 12
    _plant_bundle(os.path.join(d.opts.state_dir, "inventory", jid3,
                               "v0"), g=30, h=8, seed=3,
                  with_scores=False)
    jid4 = "i" + "f" * 12
    from g2vec_tpu.io.writers import write_inventory_bundle
    write_inventory_bundle(
        os.path.join(d.opts.state_dir, "inventory", jid4, "v0"),
        np.ones((5, 8), dtype=np.float32),
        [f"X{i}" for i in range(5)], None, {"source": "test"})

    fr = d.handle_fquery({"fq": "gene_rank", "gene": "G005", "k": 10})
    assert fr["event"] == "fquery_result" and fr["ref_genes"] is None
    by_bundle = {p["bundle"]: p for p in fr["bundles"]}
    assert len(by_bundle) == 4
    for jid in planted:
        p = by_bundle[f"{jid}/v0"]
        scores = planted[jid][2]
        for row, group in enumerate(("good", "poor")):
            s = scores[row]
            want = int(1 + np.count_nonzero(s > s[5]))
            assert p[group]["rank"] == want
            assert p[group]["in_top_k"] == (want <= 10)
    assert by_bundle[f"{jid3}/v0"]["error"] == "scores_unavailable"
    assert by_bundle[f"{jid4}/v0"]["present"] is False
    # Ranked bundles sort before errored/absent ones, best rank first.
    ranked = [p for p in fr["bundles"] if "good" in p]
    assert ranked == sorted(
        ranked, key=lambda p: min(p["good"]["rank"], p["poor"]["rank"]))
    assert fr["bundles"][-2:] == sorted(
        fr["bundles"][-2:], key=lambda p: p["bundle"])

    # bundle_overlap with the reference derived from a named bundle:
    # the reference bundle overlaps itself fully.
    jref = "i" + "c" * 12
    ov = d.handle_fquery({"fq": "bundle_overlap", "gene": "G005",
                          "k": 5, "job_id": jref})
    assert ov["event"] == "fquery_result"
    assert len(ov["ref_genes"]) == 5
    parts = {p["bundle"]: p for p in ov["bundles"]}
    assert parts[f"{jref}/v0"]["overlap"] == 1.0
    assert parts[f"{jref}/v0"]["recall_mode"] in ("approx", "exact")
    assert parts[f"{jid4}/v0"]["present"] is False
    # Sorted by overlap descending (scored bundles first).
    scored = [p["overlap"] for p in ov["bundles"]
              if p.get("overlap") is not None]
    assert scored == sorted(scored, reverse=True)
    # Without ref_genes or a reference job: structured refusal.
    bad = d.handle_fquery({"fq": "bundle_overlap", "gene": "G005"})
    assert bad["event"] == "error" and bad["error"] == "bad_query"
    assert d.handle_fquery({"fq": "nope", "gene": "G005"})["event"] == \
        "error"


def test_fquery_op_is_token_gated_on_the_wire(tmp_path):
    d = _daemon(tmp_path, auth_token="sekret-43")
    resp = _roundtrip(d, {"op": "fquery", "fq": "gene_rank",
                          "gene": "G000"})
    assert resp["event"] == "rejected" and resp["error"] == "unauthorized"
    resp = _roundtrip(d, {"op": "fquery", "fq": "gene_rank",
                          "gene": "G000", "auth_token": "sekret-43"})
    assert resp["event"] == "fquery_result" and resp["bundles"] == []


# ---------------------------------------------------------------------------
# Router: federated scatter-gather with dead-owner disk reads
# ---------------------------------------------------------------------------

def test_router_fquery_answers_dead_replicas_from_disk(tmp_path):
    """No replica process ever boots: every bundle owner is dead, so
    the router answers the whole federated query from the shared fleet
    directory, attributing each partial served_by=router +
    replica_down=True — the read plane's failover contract extended to
    fquery."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=2),
               console=lambda s: None)
    jid_a, jid_b = "i" + "a" * 12, "i" + "b" * 12
    dest_a = os.path.join(fleet_dir, "r0", "state", "inventory", jid_a,
                          "v0")
    dest_b = os.path.join(fleet_dir, "r1", "state", "inventory", jid_b,
                          "v0")
    emb_a, genes, scores_a = _plant_bundle(dest_a, g=30, h=8, seed=1,
                                           ann_nlist=4)
    _plant_bundle(dest_b, g=30, h=8, seed=2)

    fr = r.handle_fquery({"fq": "gene_rank", "gene": "G007", "k": 10})
    assert fr["event"] == "fquery_result"
    parts = {p["bundle"]: p for p in fr["bundles"]}
    assert set(parts) == {f"{jid_a}/v0", f"{jid_b}/v0"}
    for p in parts.values():
        assert p["served_by"] == "router" and p["replica_down"] is True
        assert p["good"]["rank"] >= 1 and p["poor"]["rank"] >= 1
    s = scores_a[0]
    assert parts[f"{jid_a}/v0"]["good"]["rank"] == \
        int(1 + np.count_nonzero(s > s[7]))

    # bundle_overlap: the reference resolves through the routed read
    # (also a disk read here), then every bundle scores against it.
    ov = r.handle_fquery({"fq": "bundle_overlap", "gene": "G007",
                          "k": 5, "job_id": jid_a})
    assert ov["event"] == "fquery_result" and len(ov["ref_genes"]) == 5
    parts = {p["bundle"]: p for p in ov["bundles"]}
    assert parts[f"{jid_a}/v0"]["overlap"] == 1.0
    assert parts[f"{jid_a}/v0"]["recall_mode"] in ("approx", "exact")
    assert parts[f"{jid_b}/v0"]["recall_mode"] == "exact"  # no index
    assert all(p["replica_down"] for p in parts.values())
    # Merge order: overlap descending, ties/absent by bundle key.
    ovs = [p.get("overlap") for p in ov["bundles"]]
    assert ovs == sorted(ovs, key=lambda v: (-1e9 if v is None else -v))

    bad = r.handle_fquery({"fq": "bundle_overlap", "gene": "G007",
                           "job_id": "i" + "z" * 12})
    assert bad["event"] == "error" and bad["error"] == "not_found"
    assert r.handle_fquery({"fq": "gene_rank", "gene": ""})["event"] == \
        "error"
