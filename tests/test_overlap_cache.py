"""Overlapped execution + persistent caches (ISSUE 3): the scheduler's
failure/drain contract, the walk-artifact cache's verify-before-trust
matrix (hit / miss / tampered / stale), the sampler pool's N-thread
bit-identity, and the pipeline-level warm-cache rerun that skips stage 3.

The scheduler drain test is the tier-1 smoke gate wired into
tools/watch_loop.sh: a foreground stage failure must propagate the
ORIGINAL exception and leave no thread waiting (no deadlock)."""
import json
import os
import shutil
import sys
import threading
import time
import warnings

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from g2vec_tpu.cache import (NATIVE_FAMILY, MANIFEST_SUFFIX, WalkCache,
                             resolve_cache_tiers, walk_cache_key)
from g2vec_tpu.parallel.overlap import OverlapScheduler, TaskCancelled
from g2vec_tpu.resilience import faults

g_plus_plus = shutil.which("g++")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


# ---- overlap scheduler ------------------------------------------------------


def test_scheduler_runs_tasks_and_respects_deps():
    order = []
    with OverlapScheduler(max_workers=2) as sched:
        sched.submit("a", lambda: order.append("a") or 1)
        sched.submit("b", lambda: order.append("b") or 2, deps=["a"])
        assert sched.result("b") == 2
        assert sched.result("a") == 1
    assert order == ["a", "b"]


def test_scheduler_result_reraises_task_exception():
    with OverlapScheduler() as sched:
        sched.submit("boom", lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            sched.result("boom")


def test_scheduler_cancels_dependents_of_failed_task():
    ran = []
    with OverlapScheduler() as sched:
        sched.submit("boom", lambda: 1 / 0)
        sched.submit("child", lambda: ran.append(True), deps=["boom"])
        with pytest.raises(TaskCancelled, match="dependency 'boom' failed"):
            sched.result("child")
    assert ran == []        # never started


def test_scheduler_drain_propagates_first_real_failure():
    # The no-deadlock smoke gate (tools/watch_loop.sh): one task fails,
    # its dependent is cancelled, a slow independent task still runs —
    # drain must join EVERYTHING promptly and re-raise the original
    # exception, not a TaskCancelled shadow of it.
    slow_done = threading.Event()

    def slow():
        time.sleep(0.2)
        slow_done.set()

    sched = OverlapScheduler(max_workers=4)
    sched.submit("boom", lambda: (_ for _ in ()).throw(KeyError("orig")))
    sched.submit("child", lambda: None, deps=["boom"])
    sched.submit("slow", slow)
    t0 = time.monotonic()
    with pytest.raises(KeyError, match="orig"):
        sched.drain()
    assert time.monotonic() - t0 < 10          # no deadlock
    assert slow_done.is_set()                  # independent task completed
    sched.close()                              # idempotent after drain


def test_scheduler_close_never_raises():
    sched = OverlapScheduler()
    sched.submit("boom", lambda: 1 / 0)
    sched.close()           # the finally-path contract: swallow, drain


def test_scheduler_rejects_bad_submissions():
    with OverlapScheduler() as sched:
        sched.submit("a", lambda: None)
        with pytest.raises(ValueError, match="duplicate"):
            sched.submit("a", lambda: None)
        with pytest.raises(ValueError, match="unsubmitted"):
            sched.submit("b", lambda: None, deps=["nope"])
        assert sched.has("a") and not sched.has("b")


def test_scheduler_saved_seconds_accounting():
    with OverlapScheduler() as sched:
        sched.submit("bg", lambda: time.sleep(0.15))
        time.sleep(0.25)            # foreground "work" the task hid under
        sched.result("bg")
    saved = sched.saved_seconds()
    # The task ran ~0.15s and the join waited ~0s: nearly all of its run
    # time is saved. The claim under test is the accounting identity
    # (saved = duration - waited), NOT the sleep's punctuality — on a
    # loaded host sleep() overshoots arbitrarily, so bound saved by the
    # task's actual measured duration instead of the nominal 0.15.
    task = sched._tasks["bg"]
    assert saved["bg"] >= 0.05
    assert saved["bg"] == pytest.approx(task.duration - task.waited,
                                        abs=1e-3)   # saved_seconds rounds
    assert task.waited < task.duration / 2, (
        "join should not have blocked: the task finished under the "
        "foreground sleep")


# ---- walk-artifact cache ----------------------------------------------------


def _toy_edges():
    src = np.array([0, 1, 2], dtype=np.int32)
    dst = np.array([1, 2, 3], dtype=np.int32)
    w = np.array([1.0, 1.0, 1.0], dtype=np.float32)
    return src, dst, w, 4


def _toy_key(seed=0):
    src, dst, w, n = _toy_edges()
    return walk_cache_key(src, dst, w, n, len_path=5, reps=2, seed=seed,
                          family=NATIVE_FAMILY)


def _toy_path_set(n=4):
    rows = np.packbits(np.eye(n, dtype=np.uint8), axis=1)
    return {r.tobytes() for r in rows}


def test_cache_key_tracks_every_input():
    src, dst, w, n = _toy_edges()
    base = _toy_key()
    assert base == _toy_key()                      # deterministic
    assert base != _toy_key(seed=1)                # params in the key
    assert base != walk_cache_key(src, dst, w + 1, n, len_path=5, reps=2,
                                  seed=0, family=NATIVE_FAMILY)
    # PRNG family tags must never alias (the two samplers draw from
    # different stream families).
    assert base != walk_cache_key(src, dst, w, n, len_path=5, reps=2,
                                  seed=0, family="device-jaxrandom-v1")


def test_cache_store_load_roundtrip(tmp_path):
    cache = WalkCache(str(tmp_path / "walks"))
    key = _toy_key()
    assert cache.load(key) is None                 # cold miss
    ps = _toy_path_set()
    art = cache.store(key, ps, 4, meta={"group": "g"})
    assert os.path.exists(art) and os.path.exists(art + MANIFEST_SUFFIX)
    with warnings.catch_warnings():
        warnings.simplefilter("error")             # a hit must be silent
        assert cache.load(key) == ps
    # The manifest records provenance the next session can audit.
    manifest = json.loads(open(art + MANIFEST_SUFFIX).read())
    assert manifest["key"] == key and manifest["group"] == "g"
    assert manifest["n_rows"] == len(ps)


def test_cache_empty_path_set_roundtrip(tmp_path):
    cache = WalkCache(str(tmp_path))
    key = _toy_key()
    cache.store(key, set(), 4)
    assert cache.load(key) == set()


def test_cache_tampered_artifact_verified_and_recomputed(tmp_path):
    # The acceptance drill: bytes flipped AFTER the manifest recorded the
    # good hash -> sha mismatch -> warning + miss; the recompute's store
    # overwrites the bad entry and the next load is a clean hit.
    cache = WalkCache(str(tmp_path))
    key = _toy_key()
    ps = _toy_path_set()
    art = cache.store(key, ps, 4)
    with open(art, "r+b") as f:
        f.seek(8)
        byte = f.read(1)
        f.seek(8)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.warns(RuntimeWarning, match="sha256 verification"):
        assert cache.load(key) is None
    cache.store(key, ps, 4)                        # the recompute
    assert cache.load(key) == ps


def test_cache_fault_plan_corrupt_seam(tmp_path):
    # kind=corrupt at the walk_cache seam models the same bitrot through
    # the production fault grammar — store "succeeds", load must refuse.
    faults.install_plan("stage=walk_cache,kind=corrupt")
    cache = WalkCache(str(tmp_path))
    key = _toy_key()
    cache.store(key, _toy_path_set(), 4)
    with pytest.warns(RuntimeWarning, match="corrupt or torn"):
        assert cache.load(key) is None


def test_cache_missing_or_mangled_manifest_is_a_miss(tmp_path):
    cache = WalkCache(str(tmp_path))
    key = _toy_key()
    ps = _toy_path_set()
    art = cache.store(key, ps, 4)
    os.remove(art + MANIFEST_SUFFIX)               # manifest-less artifact
    assert cache.load(key) is None
    cache.store(key, ps, 4)
    with open(art + MANIFEST_SUFFIX, "w") as f:
        f.write("{not json")
    with pytest.warns(RuntimeWarning, match="unreadable"):
        assert cache.load(key) is None


def test_cache_stale_schema_or_foreign_key_is_a_miss(tmp_path):
    import g2vec_tpu.utils.integrity as integrity

    cache = WalkCache(str(tmp_path))
    key = _toy_key()
    art = cache.store(key, _toy_path_set(), 4)
    man_path = art + MANIFEST_SUFFIX
    manifest = json.loads(open(man_path).read())
    for bad in ({**manifest, "schema": 0},
                {**manifest, "key": "f" * 64}):    # truncated-key collision
        integrity.write_json_atomic(man_path, bad)
        with pytest.warns(RuntimeWarning, match="stale"):
            assert cache.load(key) is None


def test_resolve_cache_tiers_semantics(tmp_path):
    root = str(tmp_path / "c")
    xla, walks = resolve_cache_tiers(root, None)
    assert xla == os.path.join(root, "xla")
    assert walks is not None and walks.directory == os.path.join(root, "walks")
    # --compilation-cache is the narrower flag: it wins the xla tier.
    xla, walks = resolve_cache_tiers(root, "/elsewhere/xla")
    assert xla == "/elsewhere/xla" and walks is not None
    # --no-walk-cache keeps the compile tier only.
    xla, walks = resolve_cache_tiers(root, None, walk_cache_enabled=False)
    assert xla and walks is None
    # No --cache-dir: legacy behavior, xla tier only if explicitly set.
    assert resolve_cache_tiers(None, None) == (None, None)


# ---- sampler thread resolution + bit-identity -------------------------------


def test_resolve_sampler_threads(monkeypatch):
    from g2vec_tpu.ops.host_walker import resolve_sampler_threads

    assert resolve_sampler_threads(3) == 3         # explicit wins
    monkeypatch.setenv("G2VEC_SAMPLER_THREADS", "5")
    assert resolve_sampler_threads(0) == 5         # env override for auto
    assert resolve_sampler_threads(2) == 2
    monkeypatch.setenv("G2VEC_SAMPLER_THREADS", "nope")
    with pytest.raises(ValueError, match="G2VEC_SAMPLER_THREADS"):
        resolve_sampler_threads(0)
    monkeypatch.delenv("G2VEC_SAMPLER_THREADS")
    assert resolve_sampler_threads(0) >= 1         # auto = all cores
    with pytest.raises(ValueError, match=">= 0"):
        resolve_sampler_threads(-1)


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_pool_sharded_rows_bit_identical_to_single_thread():
    # The determinism contract on a workload LARGE enough to engage the
    # Python range pool (n_walkers > RANGE_CHUNK): streams are keyed by
    # global walker index and every range writes a disjoint row slice,
    # so any thread count reproduces the 1-thread bytes exactly.
    from g2vec_tpu.ops.host_walker import RANGE_CHUNK, walk_packed_rows

    src, dst, w, n = _toy_edges()
    reps = RANGE_CHUNK // n + 2                    # push past one chunk
    kwargs = dict(len_path=5, reps=reps, seed=17)
    rows1 = walk_packed_rows(src, dst, w, n, n_threads=1, **kwargs)
    assert rows1.shape[0] == n * reps > RANGE_CHUNK
    for threads in (2, 4, 7):
        rows_t = walk_packed_rows(src, dst, w, n, n_threads=threads,
                                  **kwargs)
        np.testing.assert_array_equal(rows1, rows_t)


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_path_set_thread_invariant_on_example_network():
    from g2vec_tpu.ops.host_walker import generate_path_set_native

    src, dst, w, n = _toy_edges()
    a = generate_path_set_native(src, dst, w, n, len_path=5, reps=600,
                                 seed=3, n_threads=1)
    b = generate_path_set_native(src, dst, w, n, len_path=5, reps=600,
                                 seed=3, n_threads=4)
    assert a == b and a


# ---- pipeline integration ---------------------------------------------------


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=14, n_poor=10, module_size=10,
                         n_background=10, n_expr_only=2, n_net_only=2,
                         module_chords=2, background_edges=16, seed=3)
    out = tmp_path_factory.mktemp("syn_overlap")
    return write_synthetic_tsv(spec, str(out))


def _cfg(tsv_paths, tmp_path, **overrides):
    from g2vec_tpu.config import G2VecConfig

    os.makedirs(str(tmp_path), exist_ok=True)
    defaults = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out"),
        lenPath=6, numRepetition=4, sizeHiddenlayer=16, epoch=3,
        compute_dtype="float32", seed=0,
    )
    defaults.update(overrides)
    return G2VecConfig(**defaults)


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_pipeline_warm_cache_rerun_skips_walks(tsv_paths, tmp_path):
    # Cold run populates the artifact tier; the warm rerun must serve
    # BOTH groups from it (stage 3 sampled nothing) and produce byte-
    # identical outputs. Then a tampered artifact forces a verified
    # recompute — the cache can be fast, never wrong.
    from g2vec_tpu.pipeline import run

    cache_dir = str(tmp_path / "cache")
    cold = run(_cfg(tsv_paths, tmp_path / "a", walker_backend="native",
                    cache_dir=cache_dir), console=lambda s: None)
    assert cold.walk_cache_hits == []
    assert cold.sampler_threads >= 1
    lines = []
    warm = run(_cfg(tsv_paths, tmp_path / "b", walker_backend="native",
                    cache_dir=cache_dir), console=lines.append)
    assert sorted(warm.walk_cache_hits) == ["g", "p"]
    assert warm.n_paths == cold.n_paths
    assert any("verified walk artifact hit" in ln for ln in lines)
    assert (tmp_path / "a" / "out_biomarkers.txt").read_text() \
        == (tmp_path / "b" / "out_biomarkers.txt").read_text()
    # Tamper with every cached artifact: the next run must detect the
    # sha mismatch, warn, recompute, and still match the cold outputs.
    walks_dir = os.path.join(cache_dir, "walks")
    for name in os.listdir(walks_dir):
        if name.endswith(".npz"):
            with open(os.path.join(walks_dir, name), "r+b") as f:
                f.seek(10)
                f.write(b"\xff\xff")
    with pytest.warns(RuntimeWarning, match="sha256 verification"):
        redo = run(_cfg(tsv_paths, tmp_path / "c", walker_backend="native",
                        cache_dir=cache_dir), console=lambda s: None)
    assert redo.walk_cache_hits == []
    assert (tmp_path / "a" / "out_biomarkers.txt").read_text() \
        == (tmp_path / "c" / "out_biomarkers.txt").read_text()


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_pipeline_overlap_matches_sequential(tsv_paths, tmp_path):
    # --no-overlap is an attribution/debug switch: the transcript moves,
    # the bytes must not.
    from g2vec_tpu.pipeline import run

    res_seq = run(_cfg(tsv_paths, tmp_path / "seq", walker_backend="native",
                       overlap=False), console=lambda s: None)
    res_ovl = run(_cfg(tsv_paths, tmp_path / "ovl", walker_backend="native",
                       overlap=True), console=lambda s: None)
    assert res_seq.n_paths == res_ovl.n_paths
    assert (tmp_path / "seq" / "out_biomarkers.txt").read_text() \
        == (tmp_path / "ovl" / "out_biomarkers.txt").read_text()
    np.testing.assert_array_equal(res_seq.embeddings, res_ovl.embeddings)


@pytest.mark.skipif(g_plus_plus is None, reason="no C++ toolchain")
def test_pipeline_stage_failure_drains_overlap(tsv_paths, tmp_path):
    # A foreground stage failure with background tasks in flight: the
    # ORIGINAL injected fault must propagate (not a scheduler artifact)
    # and the run must end promptly — the outer finally drains the
    # scheduler instead of deadlocking on it.
    from g2vec_tpu.pipeline import run

    faults.install_plan("stage=train,kind=crash")
    t0 = time.monotonic()
    with pytest.raises(faults.InjectedFault):
        run(_cfg(tsv_paths, tmp_path, walker_backend="native"),
            console=lambda s: None)
    assert time.monotonic() - t0 < 120
    # The scheduler left no stray non-daemon workers holding the process.
    stray = [t for t in threading.enumerate()
             if t.name.startswith("g2v-overlap") and not t.daemon]
    assert all(not t.is_alive() for t in stray)


def test_pipeline_done_event_carries_attribution(tsv_paths, tmp_path):
    # The done metrics event must say HOW stage_seconds were achieved:
    # backend, pool width, per-task overlap savings, cache hits.
    from g2vec_tpu.pipeline import run

    metrics_path = str(tmp_path / "m.jsonl")
    run(_cfg(tsv_paths, tmp_path, metrics_jsonl=metrics_path),
        console=lambda s: None)
    events = [json.loads(ln) for ln in open(metrics_path)]
    done = [e for e in events if e["event"] == "done"]
    assert len(done) == 1
    for field in ("walker_backend", "sampler_threads", "overlap_saved_s",
                  "walk_cache_hits", "stage_extras"):
        assert field in done[0], field
    paths_ev = [e for e in events if e["event"] == "paths"]
    assert paths_ev and "sampler_threads" in paths_ev[0]
    assert done[0]["stage_extras"].get("paths", {}).get("walker_backend") \
        == done[0]["walker_backend"]
