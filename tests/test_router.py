"""Replicated-fleet front door (serve/router.py + daemon TCP mode):
consistent-hash placement, the replica health machine, idempotency-key
exactly-once admission, listener hardening (auth, deadlines, size
bounds), and the SIGKILL-a-replica-mid-streaming-job failover drill.

The fast tests here are pure-unit (ring, health table) or in-process
single-daemon (idem dedup across incarnations, the TCP listener) — no
replica subprocesses, so they hold tier-1 cost. The full fleet drill
(router + N daemon children + mid-job SIGKILL + byte parity) boots real
processes and is ``slow``; the router chaos soak (tools/chaos_soak.py
--replicas) storms the same machinery at scale.
"""
import json
import os
import shutil
import signal
import socket
import threading
import time

import pytest

from g2vec_tpu.resilience import faults

pytestmark = pytest.mark.router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _job(tsv_paths, tmp_path, name, **overrides):
    job = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out", name),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        walker_backend="device")
    job.update(overrides)
    return job


def _daemon(tmp_path, **opt_overrides):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opt_overrides.setdefault(
        "socket_path", os.path.join(str(tmp_path), "serve.sock"))
    opt_overrides.setdefault(
        "state_dir", os.path.join(str(tmp_path), "state"))
    opts = ServeOptions(**opt_overrides)
    return ServeDaemon(opts, console=lambda s: None)


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------

def test_hash_ring_minimal_movement_and_affinity():
    from g2vec_tpu.serve.router import HashRing

    ring = HashRing(vnodes=64)
    for name in ("r0", "r1", "r2"):
        ring.add(name)
    keys = [f"jobkey-{i}" for i in range(1000)]
    before = {k: ring.lookup(k) for k in keys}
    # Same key -> same owner, always (placement is a pure function).
    assert all(ring.lookup(k) == before[k] for k in keys)

    # Adding a 4th replica moves ~1/4 of keys, never between survivors.
    ring.add("r3")
    after = {k: ring.lookup(k) for k in keys}
    moved = [k for k in keys if after[k] != before[k]]
    assert all(after[k] == "r3" for k in moved)
    assert len(moved) < 450        # ~250 expected; far from rehash-all

    # Removing it restores the original owner for every key.
    ring.remove("r3")
    assert all(ring.lookup(k) == before[k] for k in keys)

    # Health overlay: an ineligible owner's keys fall to the clockwise
    # successor without disturbing other keys' owners.
    degraded = {k: ring.lookup(k, eligible=["r0", "r1"]) for k in keys}
    assert all(degraded[k] == before[k] for k in keys
               if before[k] != "r2")
    assert all(degraded[k] in ("r0", "r1") for k in keys)
    assert ring.lookup("anything", eligible=[]) is None


def test_router_join_key_affinity(tsv_paths, tmp_path):
    """Shape-compatible jobs (differing only in join-excluded fields:
    seeds, result paths) hash to the SAME replica, so they can still
    join one warm batch there."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=3), console=lambda s: None)
    a = {"job": _job(tsv_paths, tmp_path, "a", train_seed=1)}
    b = {"job": _job(tsv_paths, tmp_path, "b", train_seed=99,
                     seed=5, kmeans_seed=7)}
    incompat = {"job": _job(tsv_paths, tmp_path, "c",
                            sizeHiddenlayer=32)}
    assert r.pick_replica(a) == r.pick_replica(b)
    assert r.pick_replica(a) in ("r0", "r1", "r2")
    # A bad job raises at router admission (same ValueError contract as
    # the daemon), never a silent misroute.
    with pytest.raises((ValueError, TypeError)):
        r.pick_replica({"job": "nope"})
    # Different shape may land elsewhere — but must be deterministic.
    assert r.pick_replica(incompat) == r.pick_replica(incompat)


# ---------------------------------------------------------------------------
# Replica health state machine
# ---------------------------------------------------------------------------

def test_replica_health_transition_matrix():
    from g2vec_tpu.resilience.lifecycle import REPLICA_STATES, ReplicaHealth

    h = ReplicaHealth("r0", suspect_after=1, dead_after=3,
                      rejoin_after=2)
    assert h.state == "healthy" and h.in_ring

    # healthy --fail--> suspect (still in the ring: one missed probe is
    # usually GC or a long compile, not death).
    assert h.on_probe(False, now=1.0) == ("healthy", "suspect")
    assert h.in_ring
    # suspect --ok--> healthy (full recovery resets the fail count).
    assert h.on_probe(True, 0, now=2.0) == ("suspect", "healthy")
    assert h.fails == 0

    # dead_after consecutive failures declare dead -> out of the ring.
    assert h.on_probe(False, now=3.0) == ("healthy", "suspect")
    assert h.on_probe(False, now=4.0) is None
    assert h.on_probe(False, now=5.0) == ("suspect", "dead")
    assert not h.in_ring

    # dead --ok--> rejoining; NOT healthy until rejoin_after consecutive
    # OKs AND an empty journal (the stale-journal drain gate).
    assert h.on_probe(True, 4, now=6.0) == ("dead", "rejoining")
    assert not h.in_ring
    assert h.on_probe(True, 2, now=7.0) is None      # journal not drained
    assert h.on_probe(True, 0, now=8.0) == ("rejoining", "healthy")
    assert h.in_ring

    # rejoining flaps straight back to dead on any failed probe.
    h2 = ReplicaHealth("r1", dead_after=2)
    h2.on_probe(False, now=1.0)
    h2.on_probe(False, now=2.0)
    assert h2.state == "dead"
    h2.on_probe(True, 0, now=3.0)
    assert h2.state == "rejoining"
    assert h2.on_probe(False, now=4.0) == ("rejoining", "dead")

    # Out-of-band death observation (fence, refused forward).
    h3 = ReplicaHealth("r2")
    assert h3.force_dead(now=1.0) == ("healthy", "dead")
    assert h3.force_dead(now=2.0) is None     # idempotent

    # Probe backoff: flat while healthy, exponential (capped) when not.
    h4 = ReplicaHealth("r3")
    assert h4.probe_interval(0.5) == 0.5
    h4.on_probe(False, now=1.0)
    h4.on_probe(False, now=2.0)
    h4.on_probe(False, now=3.0)
    assert h4.probe_interval(0.5) == 0.5 * 4.0
    for _ in range(10):
        h4.on_probe(False, now=4.0)
    assert h4.probe_interval(0.5) == 0.5 * 8.0      # capped

    assert tuple(REPLICA_STATES) == ("healthy", "suspect", "dead",
                                     "rejoining")


# ---------------------------------------------------------------------------
# Idempotency keys: exactly-once admission
# ---------------------------------------------------------------------------

def test_idem_key_dedup_within_and_across_incarnations(
        tsv_paths, tmp_path):
    from g2vec_tpu.serve.daemon import idem_job_id

    d = _daemon(tmp_path)
    try:
        payload = {"tenant": "a", "idem_key": "k-123",
                   "job": _job(tsv_paths, tmp_path, "a1")}
        ack = d.admit(dict(payload))
        assert ack["event"] == "accepted"
        assert ack["job_id"] == idem_job_id("k-123")
        # Same key again: deduped ack names the ORIGINAL job, and
        # nothing new is journaled or queued.
        again = d.admit(dict(payload))
        assert again["event"] == "accepted"
        assert again.get("deduped") is True
        assert again["job_id"] == ack["job_id"]
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1
    finally:
        d.close()

    # A NEW daemon on the same state dir rebuilds the idem table from
    # the journal — the duplicate is refused across incarnations too.
    d2 = _daemon(tmp_path)
    try:
        again = d2.admit(dict(payload))
        assert again.get("deduped") is True
        assert again["job_id"] == ack["job_id"]
    finally:
        d2.close()


def test_idem_key_closes_kill_between_accept_and_journal_window(
        tsv_paths, tmp_path, monkeypatch):
    """The nastiest ack window: a replica acks a submit, then dies
    BEFORE the journal write hits disk. The client saw 'accepted'; no
    durable trace exists. Because the job_id is derived from the idem
    key, the client's safe resubmission (same key) recreates the exact
    same job — same id, same journal path, same result record name —
    so downstream there is still exactly one of everything."""
    from g2vec_tpu.serve.daemon import ServeDaemon, idem_job_id

    d = _daemon(tmp_path)
    monkeypatch.setattr(ServeDaemon, "_journal",
                        lambda self, job: None)     # die-before-journal
    payload = {"tenant": "a", "idem_key": "k-window",
               "job": _job(tsv_paths, tmp_path, "w1")}
    try:
        ack = d.admit(dict(payload))
        assert ack["event"] == "accepted"
        assert os.listdir(os.path.join(d.opts.state_dir, "jobs")) == []
    finally:
        d.close()
    monkeypatch.undo()

    d2 = _daemon(tmp_path)
    try:
        # Nothing durable survived, so this is NOT a dedup — it is a
        # fresh admission that lands on the identical job_id.
        ack2 = d2.admit(dict(payload))
        assert ack2["event"] == "accepted"
        assert ack2.get("deduped") is None
        assert ack2["job_id"] == ack["job_id"] == idem_job_id("k-window")
        # And NOW the same key dedups (journal exists).
        ack3 = d2.admit(dict(payload))
        assert ack3.get("deduped") is True
    finally:
        d2.close()


def test_concurrent_same_key_submits_admit_exactly_once(
        tsv_paths, tmp_path):
    """The dedup check and the table insert are one atomic step: N
    threads (per-connection handlers) racing the same idem_key must
    yield exactly ONE real admission — the rest get deduped acks — and
    one journal entry."""
    d = _daemon(tmp_path)
    n = 8
    barrier = threading.Barrier(n)
    results = [None] * n

    def hammer(i):
        payload = {"tenant": "a", "idem_key": "k-race",
                   "job": _job(tsv_paths, tmp_path, "race")}
        barrier.wait()
        results[i] = d.admit(payload)

    try:
        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(r is not None and r["event"] == "accepted"
                   for r in results), results
        assert len({r["job_id"] for r in results}) == 1
        real = [r for r in results if not r.get("deduped")]
        assert len(real) == 1, f"{len(real)} non-deduped admissions"
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1
    finally:
        d.close()


def test_journal_never_persists_auth_token(tsv_paths, tmp_path):
    """The shared secret is needed only at admission; the journal record
    (plaintext, default file perms, resent verbatim on failover) must
    not carry it."""
    d = _daemon(tmp_path, auth_token="sekrit-token")
    try:
        ack = d.admit({"op": "submit", "auth_token": "sekrit-token",
                       "tenant": "a", "idem_key": "k-tok",
                       "job": _job(tsv_paths, tmp_path, "tok")})
        assert ack["event"] == "accepted"
        jpath = os.path.join(d.opts.state_dir, "jobs",
                             f"{ack['job_id']}.json")
        with open(jpath) as f:
            text = f.read()
        assert "sekrit-token" not in text
        assert "auth_token" not in json.loads(text)["payload"]
    finally:
        d.close()


def test_keyless_resubmit_preserves_explicit_job_id(tsv_paths, tmp_path):
    """A keyless journal entry migrated by the router keeps its job_id
    (cursors and the client's poll handle stay attached): the daemon
    honors an explicit payload job_id, dedups a repeat of it against
    its journal, and rejects ids that could escape the state dir."""
    d = _daemon(tmp_path)
    try:
        payload = {"tenant": "a", "job_id": "j0007-deadbeef",
                   "job": _job(tsv_paths, tmp_path, "kl")}
        ack = d.admit(dict(payload))
        assert ack["event"] == "accepted"
        assert ack["job_id"] == "j0007-deadbeef"
        # A router retrying the same migration (crash between the
        # survivor's ack and the dead journal's unlink) dedups.
        again = d.admit(dict(payload))
        assert again.get("deduped") is True
        assert again["job_id"] == "j0007-deadbeef"
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1
        for bad in ("../escape", ".hidden", "a/b", "", "x" * 200, 7):
            rej = d.admit({"job_id": bad,
                           "job": _job(tsv_paths, tmp_path, "kl2")})
            assert rej["event"] == "rejected", bad
            assert "job_id" in rej["detail"]
        # idem_key still wins over an explicit id (derivation rules).
        both = d.admit({"idem_key": "k-boss", "job_id": "jignored-00",
                        "job": _job(tsv_paths, tmp_path, "kl3")})
        from g2vec_tpu.serve.daemon import idem_job_id
        assert both["job_id"] == idem_job_id("k-boss")
    finally:
        d.close()


def test_bad_idem_keys_reject_at_admission(tsv_paths, tmp_path):
    d = _daemon(tmp_path)
    try:
        for bad in ("", "x" * 200, 7):
            rej = d.admit({"idem_key": bad,
                           "job": _job(tsv_paths, tmp_path, "x")})
            assert rej["event"] == "rejected"
            assert "idem_key" in rej["detail"]
    finally:
        d.close()


# ---------------------------------------------------------------------------
# Sticky routing + drain/failover serialization
# ---------------------------------------------------------------------------

def _drain_events(f):
    f.seek(0)
    return [json.loads(line) for line in f.read().splitlines()]


def test_sticky_deadline_rejects_instead_of_ring_placing(
        tsv_paths, tmp_path):
    """A key whose journal entry sits on an unrecovered replica must
    NEVER fall through to a fresh ring placement when the sticky wait
    expires — the survivor's idem table has not seen the key and would
    run the job twice. The submit is refused with retry_later."""
    import io

    from g2vec_tpu.serve import protocol
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=2,
                             sticky_deadline_s=0.6),
               console=lambda s: None)
    jid = protocol.idem_job_id("k-stuck")
    jdir = os.path.join(fleet_dir, "r0", "state", "jobs")
    os.makedirs(jdir)
    with open(os.path.join(jdir, f"{jid}.json"), "w") as fh:
        json.dump({"job_id": jid, "submitted_at": 1.0,
                   "payload": {"idem_key": "k-stuck"}}, fh)

    f = io.BytesIO()
    r._relay_submit(f, {"op": "submit", "idem_key": "k-stuck",
                        "job": _job(tsv_paths, tmp_path, "stuck")})
    evs = _drain_events(f)
    assert evs[-1]["event"] == "rejected"
    assert evs[-1]["error"] == "retry_later"
    assert evs[-1]["job_id"] == jid
    assert "r0" in evs[-1]["detail"]
    # The entry never moved and nothing was placed elsewhere.
    assert os.listdir(jdir) == [f"{jid}.json"]

    # A FRESH key still takes the ring-placement path (and, with no
    # replica processes alive, gets the no_replicas refusal — not
    # retry_later).
    f2 = io.BytesIO()
    r._relay_submit(f2, {"op": "submit", "idem_key": "k-fresh",
                         "job": _job(tsv_paths, tmp_path, "fresh")})
    evs2 = _drain_events(f2)
    assert evs2[-1]["event"] == "rejected"
    assert evs2[-1]["error"] == "no_replicas"


def test_admin_drain_suppresses_failover(tmp_path):
    """While drain_replica owns a replica, a probe-loop death
    declaration must not fire the journal-migrating failover (the
    maintenance contract is re-queue on OWN relaunch), and a second
    concurrent drain of the same replica is refused."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=2), console=lambda s: None)
    with r._hlock:
        r._admin_draining.add("r0")
    try:
        assert r._failover("r0") == 0          # suppressed, no fence
        resp = r.handle_drain_replica("r0")
        assert resp["event"] == "error"
        assert "already draining" in resp["error"]
        # The untouched replica still fails over normally (no journal,
        # nothing to migrate, relaunch skipped via relaunch=False).
        assert r._failover("r1", relaunch=False) == 0
    finally:
        with r._hlock:
            r._admin_draining.discard("r0")


# ---------------------------------------------------------------------------
# TCP front door + listener hardening
# ---------------------------------------------------------------------------

def test_tcp_listener_status_auth_and_bounds(tsv_paths, tmp_path):
    from g2vec_tpu.serve import client, protocol

    d = _daemon(tmp_path, listen="127.0.0.1:0", auth_token="sekrit",
                read_deadline_s=1.0, max_request_bytes=4096)
    th = threading.Thread(target=d.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 30
        while d.tcp_addr is None and time.time() < deadline:
            time.sleep(0.05)
        assert d.tcp_addr is not None
        addr = f"{d.tcp_addr[0]}:{d.tcp_addr[1]}"
        # Discovery file matches the bound ephemeral port; pidfile (the
        # fence target of last resort) names this process.
        with open(os.path.join(d.opts.state_dir, "tcp_addr")) as f:
            assert f.read().strip() == addr
        with open(os.path.join(d.opts.state_dir, "serve.pid")) as f:
            assert int(f.read()) == os.getpid()

        # status over TCP: open (no token), carries the new fields.
        st = client.status(addr)
        assert st["event"] == "status"
        assert st["listen"] == addr
        assert st["journal_depth"] == 0
        assert isinstance(st["last_heartbeat_age_s"], float)

        # ping over TCP; plain HTTP GET /status on the same port.
        assert client.ping(addr)["event"] == "pong"
        s = protocol.dial(addr, timeout=5.0)
        s.sendall(b"GET /status HTTP/1.0\r\n\r\n")
        http = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            http += chunk
        s.close()
        assert http.startswith(b"HTTP/1.0 200")
        assert b"journal_depth" in http

        # Mutating op without the token: rejected at admission, nothing
        # journaled.
        evs = client.submit_job(addr, _job(tsv_paths, tmp_path, "t1"))
        assert evs[-1]["event"] == "rejected"
        assert evs[-1]["error"] == "unauthorized"
        assert os.listdir(os.path.join(d.opts.state_dir, "jobs")) == []

        # Wrong token: same refusal. Cancel is gated too.
        evs = client.submit_job(addr, _job(tsv_paths, tmp_path, "t2"),
                                auth_token="wrong")
        assert evs[-1]["error"] == "unauthorized"
        bad = next(client.request(addr, {"op": "cancel", "job_id": "x",
                                         "auth_token": "nope"}))
        assert bad["error"] == "unauthorized"

        # Oversized request line: structured refusal, not an OOM.
        s = protocol.dial(addr, timeout=5.0)
        s.sendall(b"{" + b"x" * 8192)
        f = s.makefile("rb")
        ev = json.loads(f.readline())
        assert ev["error"] == "oversized_request"
        s.close()

        # Read deadline: a silent client is disconnected, not parked on
        # an acceptor thread forever. The same deadline now guards the
        # UNIX listener (opts apply to both).
        s = protocol.dial(addr, timeout=10.0)
        t0 = time.time()
        assert s.recv(1) == b""          # server closes on timeout
        assert time.time() - t0 < 8.0
        s.close()

        # result op: pending for an unknown id (the poll path clients
        # use after failover), journaled=False.
        pend = next(client.request(addr, {"op": "result",
                                          "job_id": "nope"}))
        assert pend["event"] == "pending"
        assert pend["journaled"] is False
    finally:
        d._stop.set()
        th.join(timeout=15)
        d.close()


# ---------------------------------------------------------------------------
# Fleet e2e: SIGKILL a replica mid-streaming-job, byte parity
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.skipif(not shutil.which("g++"),
                    reason="streaming drill needs the native toolchain")
def test_router_failover_mid_streaming_job_byte_identical(
        tsv_paths, tmp_path):
    """Boot a 2-replica fleet behind an in-process router, start a
    streaming job, SIGKILL the replica running it, and require: the
    client's submit stream still ends in the job's terminal record, a
    ``failover`` metrics event names the migration, exactly one
    terminal job_state event exists fleet-wide, and the outputs are
    byte-identical to a solo uninterrupted run."""
    from g2vec_tpu.serve import client
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(
        fleet_dir=fleet_dir, replicas=2, listen="127.0.0.1:0",
        probe_interval=0.3, probe_deadline=2.0,
        serve_argv=("--platform", "cpu",
                    "--cache-dir", str(tmp_path / "cache"))),
        console=lambda s: None)
    th = threading.Thread(target=r.serve_forever, daemon=True)
    th.start()
    result_holder = {}
    try:
        deadline = time.time() + 300
        while r.tcp_addr is None and time.time() < deadline:
            time.sleep(0.1)
        assert r.tcp_addr is not None, "router never bound"
        addr = f"{r.tcp_addr[0]}:{r.tcp_addr[1]}"

        job = _job(tsv_paths, tmp_path, "stream1", epoch=400,
                   train_mode="streaming", walker_backend="native",
                   shard_paths=16, checkpoint_every=1)

        def submit():
            result_holder["rec"] = client.submit_and_wait(
                addr, job, timeout=600, poll_deadline_s=600,
                idem_key="drill-1")

        sub = threading.Thread(target=submit, daemon=True)
        sub.start()

        # Wait until some replica journals the job, then kill that one.
        victim = None
        deadline = time.time() + 240
        while victim is None and time.time() < deadline:
            for name in r.fleet.names():
                jdir = os.path.join(fleet_dir, name, "state", "jobs")
                if os.path.isdir(jdir) and os.listdir(jdir):
                    victim = name
                    break
            time.sleep(0.1)
        assert victim is not None, "job never journaled on any replica"
        # Kill the instant the first checkpoint lands: the job is
        # provably mid-training (a fixed sleep races a warm cache — the
        # job can finish inside it and no failover ever happens).
        from g2vec_tpu.serve import protocol as _proto
        jid = _proto.idem_job_id("drill-1")
        ckpt_dir = os.path.join(fleet_dir, victim, "state", "ckpt")
        deadline = time.time() + 240
        while time.time() < deadline:
            if os.path.isdir(ckpt_dir) and any(
                    jid in e for e in os.listdir(ckpt_dir)):
                break
            time.sleep(0.05)
        else:
            pytest.fail("job never checkpointed on the victim")
        res_path = os.path.join(fleet_dir, victim, "state", "results",
                                f"{jid}.json")
        assert not os.path.exists(res_path), \
            "job finished before the kill could land — enlarge the job"
        pid = r.fleet.replica(victim).pid
        os.kill(pid, signal.SIGKILL)

        sub.join(timeout=600)
        assert not sub.is_alive(), "client never got a terminal record"
        rec = result_holder["rec"]
        assert rec["event"] == "job_done", rec
        job_id = rec["job_id"]

        # Failover event names the migration.
        evs = []
        with open(os.path.join(fleet_dir, "router-metrics.jsonl")) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("event") == "failover":
                    evs.append(ev)
        assert any(ev["job_id"] == job_id and ev["from_replica"] == victim
                   and ev["to_replica"] != victim and
                   ev["latency_s"] >= 0 for ev in evs), evs

        # Exactly one terminal job_state event fleet-wide.
        terminal = 0
        for name in r.fleet.names():
            mpath = os.path.join(fleet_dir, name, "metrics.jsonl")
            if not os.path.exists(mpath):
                continue
            with open(mpath) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "job_state" \
                            and ev.get("job_id") == job_id \
                            and ev.get("state") == "done":
                        terminal += 1
        assert terminal == 1, f"{terminal} terminal events"

        # Byte parity vs a solo uninterrupted twin.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        cfg = config_from_job(
            {**job, "result_name": os.path.join(str(tmp_path), "out",
                                                "solo1")})
        v = _variant_from_dict(0, {"name": "v"}, cfg)
        sres = solo_run(lane_config(cfg, v), console=lambda s: None)
        outs = rec["variants"]["v"]["outputs"]
        assert len(outs) == len(sres.output_files) > 0
        for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
            with open(fa, "rb") as a, open(fb, "rb") as b:
                assert a.read() == b.read(), f"{fa} != {fb}"
    finally:
        r._stop.set()
        th.join(timeout=120)

# ---------------------------------------------------------------------------
# Leadership lease + fencing epochs (partition-tolerant control plane)
# ---------------------------------------------------------------------------

def test_leader_lease_acquire_renew_release_handoff(tmp_path):
    from g2vec_tpu.serve import leader

    fleet = str(tmp_path)
    a = leader.LeaderLease(fleet, ttl_s=5.0, holder="A", settle_s=0.01)
    b = leader.LeaderLease(fleet, ttl_s=5.0, holder="B", settle_s=0.01)
    assert a.acquire() and a.held and a.epoch == 1
    # A fresh foreign lease refuses a second claimant outright.
    assert not b.acquire() and not b.held
    assert a.renew()
    # Re-acquire while holding is idempotent (same epoch, no bump).
    assert a.acquire() and a.epoch == 1
    # Clean release hands over WITHOUT waiting out the ttl, epoch +1.
    a.release()
    assert not a.held
    assert b.acquire() and b.held and b.epoch == 2


def test_leader_lease_expiry_takeover_keeps_zombie_epoch(tmp_path):
    """After a ttl takeover the old holder must become a ZOMBIE that
    keeps its stale epoch: renew/bump fail, held drops, but .epoch
    stays — its stamped commands are what daemons reject."""
    from g2vec_tpu.serve import leader

    fleet = str(tmp_path)
    a = leader.LeaderLease(fleet, ttl_s=0.2, holder="A", settle_s=0.01)
    b = leader.LeaderLease(fleet, ttl_s=0.2, holder="B", settle_s=0.01)
    assert a.acquire() and a.epoch == 1
    time.sleep(0.35)                         # let A's lease expire
    assert b.acquire() and b.epoch == 2      # takeover bumps the epoch
    assert a.renew() is False
    assert not a.held
    assert a.epoch == 1                      # KEPT, not zeroed
    assert a.bump() == 0                     # no fencing rights
    assert b.bump() == 3                     # the real leader fences on


def test_leader_lease_torn_write_keeps_epochs_monotone(tmp_path):
    """A half-written lease file must not grant leadership OR reset the
    epoch sequence: the epoch-hint sidecar keeps claims monotone."""
    from g2vec_tpu.serve import leader

    fleet = str(tmp_path)
    a = leader.LeaderLease(fleet, ttl_s=5.0, holder="A", settle_s=0.01)
    assert a.acquire() and a.bump() == 2
    # Tear the lease file mid-write (no atomic rename).
    with open(os.path.join(fleet, leader.LEASE_FILE), "w") as fh:
        fh.write('{"epoch": 99, "hol')
    st, expired = a.peek()
    assert st is None and expired            # torn = absent = expired
    b = leader.LeaderLease(fleet, ttl_s=5.0, holder="B", settle_s=0.01)
    assert b.acquire()
    assert b.epoch == 3                      # hint (2) + 1, monotone


def test_leader_lease_stale_mtime_backstop(tmp_path):
    """A writer with a future-skewed clock cannot publish an
    unexpirable lease: either stale clock (recorded renewed_at OR the
    file mtime) expires it."""
    import json as _json

    from g2vec_tpu.serve import leader

    fleet = str(tmp_path)
    path = os.path.join(fleet, leader.LEASE_FILE)
    # Future renewed_at (skewed writer) but an honest, old mtime.
    with open(path, "w") as fh:
        _json.dump({"epoch": 7, "holder": "skewed",
                    "renewed_at": time.time() + 1e6, "ttl_s": 0.2}, fh)
    old = time.time() - 60
    os.utime(path, (old, old))
    st, expired = leader.LeaderLease(fleet, ttl_s=0.2,
                                     holder="B").peek()
    assert st is not None and st.epoch == 7
    assert expired                           # mtime backstop fired
    b = leader.LeaderLease(fleet, ttl_s=5.0, holder="B",
                           settle_s=0.01)
    assert b.acquire() and b.epoch == 8      # monotone over the corpse
    # The inverse skew (ancient renewed_at, fresh mtime) expires too.
    with open(path, "w") as fh:
        _json.dump({"epoch": 8, "holder": "B",
                    "renewed_at": time.time() - 60, "ttl_s": 0.2}, fh)
    st2, expired2 = b.peek()
    assert st2 is not None and expired2
    # And a genuinely fresh lease does NOT expire.
    with open(path, "w") as fh:
        _json.dump({"epoch": 8, "holder": "B",
                    "renewed_at": time.time(), "ttl_s": 60.0}, fh)
    _, expired3 = b.peek()
    assert not expired3


def test_leader_lease_concurrent_acquire_single_winner(tmp_path):
    """N routers racing one expired lease: claim-then-confirm leaves at
    most one confirmed holder per settle window, and one renew() round
    collapses any window straggler to EXACTLY one leader."""
    from g2vec_tpu.serve import leader

    fleet = str(tmp_path)
    leases = [leader.LeaderLease(fleet, ttl_s=5.0, holder=f"h{i}",
                                 settle_s=0.05) for i in range(4)]
    barrier = threading.Barrier(len(leases))
    got = [False] * len(leases)

    def race(i):
        barrier.wait()
        got[i] = leases[i].acquire()

    threads = [threading.Thread(target=race, args=(i,))
               for i in range(len(leases))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert any(got), "nobody acquired an uncontested expired lease"
    survivors = [ls for ls in leases if ls.held and ls.renew()]
    assert len(survivors) == 1, [ls.holder for ls in leases if ls.held]
    # Every loser saw the winner's claim and reports not-held.
    winner = survivors[0]
    for ls in leases:
        if ls is not winner:
            assert not ls.held


def test_daemon_stale_epoch_reject_matrix(tmp_path):
    """The daemon-side fencing gate: absent/0/non-int epochs are inert
    (single-router PR 16 contract), >= watermark advances and persists,
    lower rejects with the structured stale_epoch event — across
    daemon incarnations too."""
    from g2vec_tpu.serve import leader

    d = _daemon(tmp_path)
    try:
        for inert in ({}, {"router_epoch": 0}, {"router_epoch": -3},
                      {"router_epoch": "5"}, {"router_epoch": True},
                      {"router_epoch": 2.5}):
            assert d._observe_epoch(dict(inert, op="submit")) is None
        # Watermark never moved for any of those.
        assert leader.read_epoch_file(
            os.path.join(d.opts.state_dir,
                         leader.ROUTER_EPOCH_FILE)) == 0
        # A real epoch advances + persists.
        assert d._observe_epoch({"op": "submit", "router_epoch": 3}) \
            is None
        assert leader.read_epoch_file(
            os.path.join(d.opts.state_dir,
                         leader.ROUTER_EPOCH_FILE)) == 3
        # Equal passes (the same leader keeps commanding).
        assert d._observe_epoch({"op": "cancel", "router_epoch": 3}) \
            is None
        # Lower rejects, structured, for every mutating op.
        for op in ("submit", "cancel", "drain", "shutdown"):
            rej = d._observe_epoch({"op": op, "router_epoch": 2})
            assert rej is not None and rej["event"] == "rejected"
            assert rej["error"] == "stale_epoch"
            assert rej["got_epoch"] == 2 and rej["seen_epoch"] == 3
    finally:
        d.close()
    # The watermark is durable: a relaunched daemon still rejects.
    d2 = _daemon(tmp_path)
    try:
        rej = d2._observe_epoch({"op": "drain", "router_epoch": 1})
        assert rej is not None and rej["error"] == "stale_epoch"
        assert rej["seen_epoch"] == 3
        assert d2._observe_epoch({"op": "drain", "router_epoch": 4}) \
            is None
    finally:
        d2.close()


def test_stale_epoch_gate_over_tcp_mutators_only(tmp_path):
    """Wire-level matrix: every mutating op with a stale epoch gets the
    structured reject BEFORE dispatch; reads (status/ping/result) stay
    open no matter what epoch they carry — reads ARE degraded mode."""
    from g2vec_tpu.serve import client

    d = _daemon(tmp_path, listen="127.0.0.1:0")
    th = threading.Thread(target=d.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 30
        while d.tcp_addr is None and time.time() < deadline:
            time.sleep(0.05)
        assert d.tcp_addr is not None
        addr = f"{d.tcp_addr[0]}:{d.tcp_addr[1]}"
        # Prime the watermark at 5 (the op itself may fail — the epoch
        # observation happens before dispatch).
        ev = next(client.request(addr, {"op": "cancel", "job_id": "x",
                                        "router_epoch": 5}))
        assert ev.get("error") != "stale_epoch"
        for req in ({"op": "cancel", "job_id": "x", "router_epoch": 4},
                    {"op": "drain", "router_epoch": 1},
                    {"op": "shutdown", "router_epoch": 2},
                    {"op": "submit", "router_epoch": 3, "job": {}}):
            ev = next(client.request(addr, req))
            assert ev["event"] == "rejected", req
            assert ev["error"] == "stale_epoch", req
            assert ev["seen_epoch"] == 5
        # Reads never fence (and report the watermark).
        st = next(client.request(addr, {"op": "status",
                                        "router_epoch": 1}))
        assert st["event"] == "status"
        assert st["router_epoch"] == 5 and st["fenced"] is False
        assert next(client.request(addr, {"op": "ping"}))["event"] \
            == "pong"
        pend = next(client.request(addr, {"op": "result",
                                          "job_id": "nope"}))
        assert pend["event"] == "pending"
    finally:
        d._stop.set()
        th.join(timeout=15)
        d.close()


def test_fence_marker_quarantines_daemon(tsv_paths, tmp_path):
    """A fence marker in the state dir self-quarantines the daemon:
    admission closes with a structured 'fenced' reject, the scheduler
    refuses to start batches, everything stays journaled, status
    reports the quarantine, and the marker's epoch advances the
    stale-epoch watermark."""
    from g2vec_tpu.serve import leader

    d = _daemon(tmp_path)
    try:
        ack = d.admit({"tenant": "a", "idem_key": "k-parked",
                       "job": _job(tsv_paths, tmp_path, "q1")})
        assert ack["event"] == "accepted"
        leader.write_fence_marker(d.opts.state_dir, 9)
        rej = d.admit({"tenant": "a", "idem_key": "k-after-fence",
                       "job": _job(tsv_paths, tmp_path, "q2")})
        assert rej["event"] == "rejected" and rej["error"] == "fenced"
        # The scheduler parks instead of popping the queue.
        assert d.step(timeout=0.05) == 0
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1       # parked job journaled
        rdir = os.path.join(d.opts.state_dir, "results")
        assert not os.path.isdir(rdir) or os.listdir(rdir) == []
        st = d.status()
        assert st["fenced"] is True and st["router_epoch"] == 9
        # The marker's epoch is now the watermark: older leaders are
        # stale even though they never spoke to this daemon again.
        rej2 = d._observe_epoch({"op": "submit", "router_epoch": 8})
        assert rej2 is not None and rej2["error"] == "stale_epoch"
        # The successor's relaunch path clears the marker.
        leader.clear_fence_marker(d.opts.state_dir)
        assert leader.read_fence_marker(d.opts.state_dir) is None
    finally:
        d.close()


def test_unverified_death_fences_before_migration(tmp_path):
    """An UNREACHABLE (non-local, SIGKILL-unverifiable) replica gets a
    fence marker before its journal is touched, and is never
    relaunched; a local replica's failover writes no marker."""
    from g2vec_tpu.serve import leader
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=2,
                             remote_replicas=True),
               console=lambda s: None)
    spec = r.fleet.replica("r0")
    assert not spec.local
    os.makedirs(spec.state_dir, exist_ok=True)
    assert r._failover("r0") == 0            # no journal: nothing moves
    # Marker written with epoch 0 (no lease machinery): presence alone
    # quarantines, and no local relaunch was attempted.
    assert leader.read_fence_marker(spec.state_dir) == 0
    assert spec.pid is None

    # Local replicas keep the PR 16 behavior: no marker.
    r2 = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet2"),
                              replicas=2), console=lambda s: None)
    spec2 = r2.fleet.replica("r0")
    os.makedirs(spec2.state_dir, exist_ok=True)
    assert r2._failover("r0", relaunch=False) == 0
    assert leader.read_fence_marker(spec2.state_dir) is None


def test_fence_epoch_bumps_with_lease_and_zombie_never_migrates(
        tmp_path):
    """With leased leadership, fencing an unreachable replica bumps the
    epoch first; a router that LOST the lease refuses to fence or
    migrate at all (it is the zombie) and keeps stamping its stale
    epoch."""
    from g2vec_tpu.serve import leader
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=2,
                             remote_replicas=True, lease_ttl_s=5.0),
               console=lambda s: None)
    assert r._lease is not None
    assert r._lease.acquire() and r.router_epoch == 1
    for name in ("r0", "r1"):
        os.makedirs(r.fleet.replica(name).state_dir, exist_ok=True)
    assert r._failover("r0", relaunch=False) == 0
    assert leader.read_fence_marker(
        r.fleet.replica("r0").state_dir) == 2      # bumped before fence
    assert r.router_epoch == 2
    # Leadership moves (usurper steals after the lease file vanishes).
    os.unlink(os.path.join(fleet_dir, leader.LEASE_FILE))
    usurper = leader.LeaderLease(fleet_dir, ttl_s=5.0, holder="U",
                                 settle_s=0.01)
    assert usurper.acquire() and usurper.epoch == 3
    # The zombie must NOT fence r1 or touch its journal.
    assert r._failover("r1", relaunch=False) == 0
    assert leader.read_fence_marker(
        r.fleet.replica("r1").state_dir) is None
    assert r.router_epoch == 2                  # stale stamp, kept


def test_client_address_rotation_and_degraded_mode(tsv_paths, tmp_path):
    """submit_and_wait / poll_result_net rotate through an address list
    (dead router first, live endpoint second); the degraded_* helpers
    reach the fleet via published tcp_addr files when no router
    answers."""
    from g2vec_tpu.serve import client, protocol

    fleet_dir = tmp_path / "fleet"
    state = fleet_dir / "r0" / "state"
    d = _daemon(tmp_path, listen="127.0.0.1:0",
                state_dir=str(state))
    th = threading.Thread(target=d.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 30
        while d.tcp_addr is None and time.time() < deadline:
            time.sleep(0.05)
        assert d.tcp_addr is not None
        addr = f"{d.tcp_addr[0]}:{d.tcp_addr[1]}"
        dead = "127.0.0.1:9"                  # discard port: refused
        # Plant a durable record; poll via a rotating address list.
        jid = protocol.idem_job_id("k-rotate")
        os.makedirs(os.path.join(str(state), "results"), exist_ok=True)
        with open(os.path.join(str(state), "results",
                               f"{jid}.json"), "w") as fh:
            json.dump({"event": "job_done", "job_id": jid}, fh)
        rec = client.poll_result_net([dead, addr], jid,
                                     deadline_s=60, interval=0.05,
                                     jitter=0.01)
        assert rec["job_id"] == jid and rec["event"] == "job_done"
        # submit_and_wait rotates off the dead router too (the live
        # daemon's structured reject proves the second hop answered).
        d.opts.auth_token = "gate"
        ev = client.submit_and_wait(
            [dead, addr], _job(tsv_paths, tmp_path, "rot"),
            retries=2, backoff=0.05, jitter=0.01, timeout=30)
        assert ev["event"] == "rejected"
        assert ev["error"] == "unauthorized"
        d.opts.auth_token = None

        # Degraded mode: the fleet's own published addresses.
        assert client.fleet_addrs(str(fleet_dir)) == [addr]
        assert client.router_addrs(str(fleet_dir)) == []
        rec2 = client.degraded_result(str(fleet_dir), jid)
        assert rec2["event"] == "job_done" and rec2["degraded"] is True
        pend = client.degraded_result(str(fleet_dir), "i" + "0" * 12)
        assert pend["event"] == "pending" and pend["degraded"] is True
        st = client.degraded_status(str(fleet_dir))
        assert st["degraded"] is True
        assert st["replicas"][addr]["event"] == "status"
        # A keyed degraded submit whose job already finished dedups
        # client-side off the durable record — reconciliation IS the
        # idem key.
        evs = client.degraded_submit(str(fleet_dir),
                                     _job(tsv_paths, tmp_path, "deg"),
                                     idem_key="k-rotate")
        assert evs[0]["event"] == "accepted"
        assert evs[0]["deduped"] is True and evs[0]["job_id"] == jid
        assert evs[1]["event"] == "job_done"
        # No replicas at all: structured refusal / lost-connection.
        empty = str(tmp_path / "nowhere")
        os.makedirs(empty, exist_ok=True)
        none = client.degraded_result(empty, "x")
        assert none["error"] == "no_replicas"
        with pytest.raises(client.ServeConnectionLost):
            client.degraded_submit(empty,
                                   _job(tsv_paths, tmp_path, "none"),
                                   idem_key="k-none")
    finally:
        d._stop.set()
        th.join(timeout=15)
        d.close()


def test_probe_keeps_fenced_replica_out_of_the_ring(tmp_path):
    """A fenced replica answers status (reads stay open) but must read
    as probe-DEAD: it rejects every admission, so rejoining the ring
    would bounce its whole key range. Only a verified restart (which
    clears the marker) lifts that."""
    from g2vec_tpu.serve import leader
    from g2vec_tpu.serve.router import Router, RouterOptions

    d = _daemon(tmp_path, listen="127.0.0.1:0")
    th = threading.Thread(target=d.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 30
        while d.tcp_addr is None and time.time() < deadline:
            time.sleep(0.05)
        assert d.tcp_addr is not None
        r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                                 replicas=1, remote_replicas=True),
                   console=lambda s: None)
        r.fleet.replica("r0").addr = \
            f"{d.tcp_addr[0]}:{d.tcp_addr[1]}"
        ok, _ = r.probe("r0")
        assert ok
        leader.write_fence_marker(d.opts.state_dir, 4)
        ok2, _ = r.probe("r0")
        assert not ok2
        leader.clear_fence_marker(d.opts.state_dir)
        ok3, _ = r.probe("r0")
        assert ok3
    finally:
        d._stop.set()
        th.join(timeout=15)
        d.close()


def test_replica_health_asymmetric_partition():
    """The health table under a one-way partition: status replies stop
    arriving while the replica keeps WORKING (journal non-empty the
    whole time). It must walk healthy -> suspect -> dead on the probe
    count alone; when replies return, the rejoin gate must hold it out
    of the ring until its journal drains, and one mid-rejoin probe loss
    drops it straight back to dead."""
    from g2vec_tpu.resilience.lifecycle import ReplicaHealth

    h = ReplicaHealth("r0", suspect_after=1, dead_after=3,
                      rejoin_after=2)
    assert h.on_probe(True, journal_depth=2, now=1.0) is None
    assert h.in_ring
    # Replies blackholed: the probe sees silence, not the live worker.
    assert h.on_probe(False, now=2.0) == ("healthy", "suspect")
    assert h.in_ring                      # suspect still routes
    assert h.on_probe(False, now=3.0) is None
    assert h.on_probe(False, now=4.0) == ("suspect", "dead")
    assert not h.in_ring
    # Probes back off for the corpse instead of storming it.
    assert h.probe_interval(0.5) > 0.5
    # Partition heals — but the replica still holds journaled work the
    # router migrated off it; it must NOT rejoin with a stale journal.
    assert h.on_probe(True, journal_depth=2, now=5.0) \
        == ("dead", "rejoining")
    assert not h.in_ring
    assert h.on_probe(True, journal_depth=2, now=6.0) is None
    assert not h.in_ring                  # gate holds: journal not empty
    # One more blip mid-rejoin: straight back to dead, no credit kept.
    assert h.on_probe(False, now=7.0) == ("rejoining", "dead")
    # Full recovery: replies AND an empty journal, rejoin_after times.
    assert h.on_probe(True, journal_depth=0, now=8.0) \
        == ("dead", "rejoining")
    assert h.on_probe(True, journal_depth=0, now=9.0) \
        == ("rejoining", "healthy")
    assert h.in_ring
