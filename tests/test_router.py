"""Replicated-fleet front door (serve/router.py + daemon TCP mode):
consistent-hash placement, the replica health machine, idempotency-key
exactly-once admission, listener hardening (auth, deadlines, size
bounds), and the SIGKILL-a-replica-mid-streaming-job failover drill.

The fast tests here are pure-unit (ring, health table) or in-process
single-daemon (idem dedup across incarnations, the TCP listener) — no
replica subprocesses, so they hold tier-1 cost. The full fleet drill
(router + N daemon children + mid-job SIGKILL + byte parity) boots real
processes and is ``slow``; the router chaos soak (tools/chaos_soak.py
--replicas) storms the same machinery at scale.
"""
import json
import os
import shutil
import signal
import socket
import threading
import time

import pytest

from g2vec_tpu.resilience import faults

pytestmark = pytest.mark.router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _job(tsv_paths, tmp_path, name, **overrides):
    job = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out", name),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        walker_backend="device")
    job.update(overrides)
    return job


def _daemon(tmp_path, **opt_overrides):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=os.path.join(str(tmp_path), "serve.sock"),
        state_dir=os.path.join(str(tmp_path), "state"), **opt_overrides)
    return ServeDaemon(opts, console=lambda s: None)


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------

def test_hash_ring_minimal_movement_and_affinity():
    from g2vec_tpu.serve.router import HashRing

    ring = HashRing(vnodes=64)
    for name in ("r0", "r1", "r2"):
        ring.add(name)
    keys = [f"jobkey-{i}" for i in range(1000)]
    before = {k: ring.lookup(k) for k in keys}
    # Same key -> same owner, always (placement is a pure function).
    assert all(ring.lookup(k) == before[k] for k in keys)

    # Adding a 4th replica moves ~1/4 of keys, never between survivors.
    ring.add("r3")
    after = {k: ring.lookup(k) for k in keys}
    moved = [k for k in keys if after[k] != before[k]]
    assert all(after[k] == "r3" for k in moved)
    assert len(moved) < 450        # ~250 expected; far from rehash-all

    # Removing it restores the original owner for every key.
    ring.remove("r3")
    assert all(ring.lookup(k) == before[k] for k in keys)

    # Health overlay: an ineligible owner's keys fall to the clockwise
    # successor without disturbing other keys' owners.
    degraded = {k: ring.lookup(k, eligible=["r0", "r1"]) for k in keys}
    assert all(degraded[k] == before[k] for k in keys
               if before[k] != "r2")
    assert all(degraded[k] in ("r0", "r1") for k in keys)
    assert ring.lookup("anything", eligible=[]) is None


def test_router_join_key_affinity(tsv_paths, tmp_path):
    """Shape-compatible jobs (differing only in join-excluded fields:
    seeds, result paths) hash to the SAME replica, so they can still
    join one warm batch there."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=3), console=lambda s: None)
    a = {"job": _job(tsv_paths, tmp_path, "a", train_seed=1)}
    b = {"job": _job(tsv_paths, tmp_path, "b", train_seed=99,
                     seed=5, kmeans_seed=7)}
    incompat = {"job": _job(tsv_paths, tmp_path, "c",
                            sizeHiddenlayer=32)}
    assert r.pick_replica(a) == r.pick_replica(b)
    assert r.pick_replica(a) in ("r0", "r1", "r2")
    # A bad job raises at router admission (same ValueError contract as
    # the daemon), never a silent misroute.
    with pytest.raises((ValueError, TypeError)):
        r.pick_replica({"job": "nope"})
    # Different shape may land elsewhere — but must be deterministic.
    assert r.pick_replica(incompat) == r.pick_replica(incompat)


# ---------------------------------------------------------------------------
# Replica health state machine
# ---------------------------------------------------------------------------

def test_replica_health_transition_matrix():
    from g2vec_tpu.resilience.lifecycle import REPLICA_STATES, ReplicaHealth

    h = ReplicaHealth("r0", suspect_after=1, dead_after=3,
                      rejoin_after=2)
    assert h.state == "healthy" and h.in_ring

    # healthy --fail--> suspect (still in the ring: one missed probe is
    # usually GC or a long compile, not death).
    assert h.on_probe(False, now=1.0) == ("healthy", "suspect")
    assert h.in_ring
    # suspect --ok--> healthy (full recovery resets the fail count).
    assert h.on_probe(True, 0, now=2.0) == ("suspect", "healthy")
    assert h.fails == 0

    # dead_after consecutive failures declare dead -> out of the ring.
    assert h.on_probe(False, now=3.0) == ("healthy", "suspect")
    assert h.on_probe(False, now=4.0) is None
    assert h.on_probe(False, now=5.0) == ("suspect", "dead")
    assert not h.in_ring

    # dead --ok--> rejoining; NOT healthy until rejoin_after consecutive
    # OKs AND an empty journal (the stale-journal drain gate).
    assert h.on_probe(True, 4, now=6.0) == ("dead", "rejoining")
    assert not h.in_ring
    assert h.on_probe(True, 2, now=7.0) is None      # journal not drained
    assert h.on_probe(True, 0, now=8.0) == ("rejoining", "healthy")
    assert h.in_ring

    # rejoining flaps straight back to dead on any failed probe.
    h2 = ReplicaHealth("r1", dead_after=2)
    h2.on_probe(False, now=1.0)
    h2.on_probe(False, now=2.0)
    assert h2.state == "dead"
    h2.on_probe(True, 0, now=3.0)
    assert h2.state == "rejoining"
    assert h2.on_probe(False, now=4.0) == ("rejoining", "dead")

    # Out-of-band death observation (fence, refused forward).
    h3 = ReplicaHealth("r2")
    assert h3.force_dead(now=1.0) == ("healthy", "dead")
    assert h3.force_dead(now=2.0) is None     # idempotent

    # Probe backoff: flat while healthy, exponential (capped) when not.
    h4 = ReplicaHealth("r3")
    assert h4.probe_interval(0.5) == 0.5
    h4.on_probe(False, now=1.0)
    h4.on_probe(False, now=2.0)
    h4.on_probe(False, now=3.0)
    assert h4.probe_interval(0.5) == 0.5 * 4.0
    for _ in range(10):
        h4.on_probe(False, now=4.0)
    assert h4.probe_interval(0.5) == 0.5 * 8.0      # capped

    assert tuple(REPLICA_STATES) == ("healthy", "suspect", "dead",
                                     "rejoining")


# ---------------------------------------------------------------------------
# Idempotency keys: exactly-once admission
# ---------------------------------------------------------------------------

def test_idem_key_dedup_within_and_across_incarnations(
        tsv_paths, tmp_path):
    from g2vec_tpu.serve.daemon import idem_job_id

    d = _daemon(tmp_path)
    try:
        payload = {"tenant": "a", "idem_key": "k-123",
                   "job": _job(tsv_paths, tmp_path, "a1")}
        ack = d.admit(dict(payload))
        assert ack["event"] == "accepted"
        assert ack["job_id"] == idem_job_id("k-123")
        # Same key again: deduped ack names the ORIGINAL job, and
        # nothing new is journaled or queued.
        again = d.admit(dict(payload))
        assert again["event"] == "accepted"
        assert again.get("deduped") is True
        assert again["job_id"] == ack["job_id"]
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1
    finally:
        d.close()

    # A NEW daemon on the same state dir rebuilds the idem table from
    # the journal — the duplicate is refused across incarnations too.
    d2 = _daemon(tmp_path)
    try:
        again = d2.admit(dict(payload))
        assert again.get("deduped") is True
        assert again["job_id"] == ack["job_id"]
    finally:
        d2.close()


def test_idem_key_closes_kill_between_accept_and_journal_window(
        tsv_paths, tmp_path, monkeypatch):
    """The nastiest ack window: a replica acks a submit, then dies
    BEFORE the journal write hits disk. The client saw 'accepted'; no
    durable trace exists. Because the job_id is derived from the idem
    key, the client's safe resubmission (same key) recreates the exact
    same job — same id, same journal path, same result record name —
    so downstream there is still exactly one of everything."""
    from g2vec_tpu.serve.daemon import ServeDaemon, idem_job_id

    d = _daemon(tmp_path)
    monkeypatch.setattr(ServeDaemon, "_journal",
                        lambda self, job: None)     # die-before-journal
    payload = {"tenant": "a", "idem_key": "k-window",
               "job": _job(tsv_paths, tmp_path, "w1")}
    try:
        ack = d.admit(dict(payload))
        assert ack["event"] == "accepted"
        assert os.listdir(os.path.join(d.opts.state_dir, "jobs")) == []
    finally:
        d.close()
    monkeypatch.undo()

    d2 = _daemon(tmp_path)
    try:
        # Nothing durable survived, so this is NOT a dedup — it is a
        # fresh admission that lands on the identical job_id.
        ack2 = d2.admit(dict(payload))
        assert ack2["event"] == "accepted"
        assert ack2.get("deduped") is None
        assert ack2["job_id"] == ack["job_id"] == idem_job_id("k-window")
        # And NOW the same key dedups (journal exists).
        ack3 = d2.admit(dict(payload))
        assert ack3.get("deduped") is True
    finally:
        d2.close()


def test_concurrent_same_key_submits_admit_exactly_once(
        tsv_paths, tmp_path):
    """The dedup check and the table insert are one atomic step: N
    threads (per-connection handlers) racing the same idem_key must
    yield exactly ONE real admission — the rest get deduped acks — and
    one journal entry."""
    d = _daemon(tmp_path)
    n = 8
    barrier = threading.Barrier(n)
    results = [None] * n

    def hammer(i):
        payload = {"tenant": "a", "idem_key": "k-race",
                   "job": _job(tsv_paths, tmp_path, "race")}
        barrier.wait()
        results[i] = d.admit(payload)

    try:
        threads = [threading.Thread(target=hammer, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert all(r is not None and r["event"] == "accepted"
                   for r in results), results
        assert len({r["job_id"] for r in results}) == 1
        real = [r for r in results if not r.get("deduped")]
        assert len(real) == 1, f"{len(real)} non-deduped admissions"
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1
    finally:
        d.close()


def test_journal_never_persists_auth_token(tsv_paths, tmp_path):
    """The shared secret is needed only at admission; the journal record
    (plaintext, default file perms, resent verbatim on failover) must
    not carry it."""
    d = _daemon(tmp_path, auth_token="sekrit-token")
    try:
        ack = d.admit({"op": "submit", "auth_token": "sekrit-token",
                       "tenant": "a", "idem_key": "k-tok",
                       "job": _job(tsv_paths, tmp_path, "tok")})
        assert ack["event"] == "accepted"
        jpath = os.path.join(d.opts.state_dir, "jobs",
                             f"{ack['job_id']}.json")
        with open(jpath) as f:
            text = f.read()
        assert "sekrit-token" not in text
        assert "auth_token" not in json.loads(text)["payload"]
    finally:
        d.close()


def test_keyless_resubmit_preserves_explicit_job_id(tsv_paths, tmp_path):
    """A keyless journal entry migrated by the router keeps its job_id
    (cursors and the client's poll handle stay attached): the daemon
    honors an explicit payload job_id, dedups a repeat of it against
    its journal, and rejects ids that could escape the state dir."""
    d = _daemon(tmp_path)
    try:
        payload = {"tenant": "a", "job_id": "j0007-deadbeef",
                   "job": _job(tsv_paths, tmp_path, "kl")}
        ack = d.admit(dict(payload))
        assert ack["event"] == "accepted"
        assert ack["job_id"] == "j0007-deadbeef"
        # A router retrying the same migration (crash between the
        # survivor's ack and the dead journal's unlink) dedups.
        again = d.admit(dict(payload))
        assert again.get("deduped") is True
        assert again["job_id"] == "j0007-deadbeef"
        jdir = os.path.join(d.opts.state_dir, "jobs")
        assert len(os.listdir(jdir)) == 1
        for bad in ("../escape", ".hidden", "a/b", "", "x" * 200, 7):
            rej = d.admit({"job_id": bad,
                           "job": _job(tsv_paths, tmp_path, "kl2")})
            assert rej["event"] == "rejected", bad
            assert "job_id" in rej["detail"]
        # idem_key still wins over an explicit id (derivation rules).
        both = d.admit({"idem_key": "k-boss", "job_id": "jignored-00",
                        "job": _job(tsv_paths, tmp_path, "kl3")})
        from g2vec_tpu.serve.daemon import idem_job_id
        assert both["job_id"] == idem_job_id("k-boss")
    finally:
        d.close()


def test_bad_idem_keys_reject_at_admission(tsv_paths, tmp_path):
    d = _daemon(tmp_path)
    try:
        for bad in ("", "x" * 200, 7):
            rej = d.admit({"idem_key": bad,
                           "job": _job(tsv_paths, tmp_path, "x")})
            assert rej["event"] == "rejected"
            assert "idem_key" in rej["detail"]
    finally:
        d.close()


# ---------------------------------------------------------------------------
# Sticky routing + drain/failover serialization
# ---------------------------------------------------------------------------

def _drain_events(f):
    f.seek(0)
    return [json.loads(line) for line in f.read().splitlines()]


def test_sticky_deadline_rejects_instead_of_ring_placing(
        tsv_paths, tmp_path):
    """A key whose journal entry sits on an unrecovered replica must
    NEVER fall through to a fresh ring placement when the sticky wait
    expires — the survivor's idem table has not seen the key and would
    run the job twice. The submit is refused with retry_later."""
    import io

    from g2vec_tpu.serve import protocol
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(fleet_dir=fleet_dir, replicas=2,
                             sticky_deadline_s=0.6),
               console=lambda s: None)
    jid = protocol.idem_job_id("k-stuck")
    jdir = os.path.join(fleet_dir, "r0", "state", "jobs")
    os.makedirs(jdir)
    with open(os.path.join(jdir, f"{jid}.json"), "w") as fh:
        json.dump({"job_id": jid, "submitted_at": 1.0,
                   "payload": {"idem_key": "k-stuck"}}, fh)

    f = io.BytesIO()
    r._relay_submit(f, {"op": "submit", "idem_key": "k-stuck",
                        "job": _job(tsv_paths, tmp_path, "stuck")})
    evs = _drain_events(f)
    assert evs[-1]["event"] == "rejected"
    assert evs[-1]["error"] == "retry_later"
    assert evs[-1]["job_id"] == jid
    assert "r0" in evs[-1]["detail"]
    # The entry never moved and nothing was placed elsewhere.
    assert os.listdir(jdir) == [f"{jid}.json"]

    # A FRESH key still takes the ring-placement path (and, with no
    # replica processes alive, gets the no_replicas refusal — not
    # retry_later).
    f2 = io.BytesIO()
    r._relay_submit(f2, {"op": "submit", "idem_key": "k-fresh",
                         "job": _job(tsv_paths, tmp_path, "fresh")})
    evs2 = _drain_events(f2)
    assert evs2[-1]["event"] == "rejected"
    assert evs2[-1]["error"] == "no_replicas"


def test_admin_drain_suppresses_failover(tmp_path):
    """While drain_replica owns a replica, a probe-loop death
    declaration must not fire the journal-migrating failover (the
    maintenance contract is re-queue on OWN relaunch), and a second
    concurrent drain of the same replica is refused."""
    from g2vec_tpu.serve.router import Router, RouterOptions

    r = Router(RouterOptions(fleet_dir=str(tmp_path / "fleet"),
                             replicas=2), console=lambda s: None)
    with r._hlock:
        r._admin_draining.add("r0")
    try:
        assert r._failover("r0") == 0          # suppressed, no fence
        resp = r.handle_drain_replica("r0")
        assert resp["event"] == "error"
        assert "already draining" in resp["error"]
        # The untouched replica still fails over normally (no journal,
        # nothing to migrate, relaunch skipped via relaunch=False).
        assert r._failover("r1", relaunch=False) == 0
    finally:
        with r._hlock:
            r._admin_draining.discard("r0")


# ---------------------------------------------------------------------------
# TCP front door + listener hardening
# ---------------------------------------------------------------------------

def test_tcp_listener_status_auth_and_bounds(tsv_paths, tmp_path):
    from g2vec_tpu.serve import client, protocol

    d = _daemon(tmp_path, listen="127.0.0.1:0", auth_token="sekrit",
                read_deadline_s=1.0, max_request_bytes=4096)
    th = threading.Thread(target=d.serve_forever, daemon=True)
    th.start()
    try:
        deadline = time.time() + 30
        while d.tcp_addr is None and time.time() < deadline:
            time.sleep(0.05)
        assert d.tcp_addr is not None
        addr = f"{d.tcp_addr[0]}:{d.tcp_addr[1]}"
        # Discovery file matches the bound ephemeral port; pidfile (the
        # fence target of last resort) names this process.
        with open(os.path.join(d.opts.state_dir, "tcp_addr")) as f:
            assert f.read().strip() == addr
        with open(os.path.join(d.opts.state_dir, "serve.pid")) as f:
            assert int(f.read()) == os.getpid()

        # status over TCP: open (no token), carries the new fields.
        st = client.status(addr)
        assert st["event"] == "status"
        assert st["listen"] == addr
        assert st["journal_depth"] == 0
        assert isinstance(st["last_heartbeat_age_s"], float)

        # ping over TCP; plain HTTP GET /status on the same port.
        assert client.ping(addr)["event"] == "pong"
        s = protocol.dial(addr, timeout=5.0)
        s.sendall(b"GET /status HTTP/1.0\r\n\r\n")
        http = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            http += chunk
        s.close()
        assert http.startswith(b"HTTP/1.0 200")
        assert b"journal_depth" in http

        # Mutating op without the token: rejected at admission, nothing
        # journaled.
        evs = client.submit_job(addr, _job(tsv_paths, tmp_path, "t1"))
        assert evs[-1]["event"] == "rejected"
        assert evs[-1]["error"] == "unauthorized"
        assert os.listdir(os.path.join(d.opts.state_dir, "jobs")) == []

        # Wrong token: same refusal. Cancel is gated too.
        evs = client.submit_job(addr, _job(tsv_paths, tmp_path, "t2"),
                                auth_token="wrong")
        assert evs[-1]["error"] == "unauthorized"
        bad = next(client.request(addr, {"op": "cancel", "job_id": "x",
                                         "auth_token": "nope"}))
        assert bad["error"] == "unauthorized"

        # Oversized request line: structured refusal, not an OOM.
        s = protocol.dial(addr, timeout=5.0)
        s.sendall(b"{" + b"x" * 8192)
        f = s.makefile("rb")
        ev = json.loads(f.readline())
        assert ev["error"] == "oversized_request"
        s.close()

        # Read deadline: a silent client is disconnected, not parked on
        # an acceptor thread forever. The same deadline now guards the
        # UNIX listener (opts apply to both).
        s = protocol.dial(addr, timeout=10.0)
        t0 = time.time()
        assert s.recv(1) == b""          # server closes on timeout
        assert time.time() - t0 < 8.0
        s.close()

        # result op: pending for an unknown id (the poll path clients
        # use after failover), journaled=False.
        pend = next(client.request(addr, {"op": "result",
                                          "job_id": "nope"}))
        assert pend["event"] == "pending"
        assert pend["journaled"] is False
    finally:
        d._stop.set()
        th.join(timeout=15)
        d.close()


# ---------------------------------------------------------------------------
# Fleet e2e: SIGKILL a replica mid-streaming-job, byte parity
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.skipif(not shutil.which("g++"),
                    reason="streaming drill needs the native toolchain")
def test_router_failover_mid_streaming_job_byte_identical(
        tsv_paths, tmp_path):
    """Boot a 2-replica fleet behind an in-process router, start a
    streaming job, SIGKILL the replica running it, and require: the
    client's submit stream still ends in the job's terminal record, a
    ``failover`` metrics event names the migration, exactly one
    terminal job_state event exists fleet-wide, and the outputs are
    byte-identical to a solo uninterrupted run."""
    from g2vec_tpu.serve import client
    from g2vec_tpu.serve.router import Router, RouterOptions

    fleet_dir = str(tmp_path / "fleet")
    r = Router(RouterOptions(
        fleet_dir=fleet_dir, replicas=2, listen="127.0.0.1:0",
        probe_interval=0.3, probe_deadline=2.0,
        serve_argv=("--platform", "cpu",
                    "--cache-dir", str(tmp_path / "cache"))),
        console=lambda s: None)
    th = threading.Thread(target=r.serve_forever, daemon=True)
    th.start()
    result_holder = {}
    try:
        deadline = time.time() + 300
        while r.tcp_addr is None and time.time() < deadline:
            time.sleep(0.1)
        assert r.tcp_addr is not None, "router never bound"
        addr = f"{r.tcp_addr[0]}:{r.tcp_addr[1]}"

        job = _job(tsv_paths, tmp_path, "stream1", epoch=400,
                   train_mode="streaming", walker_backend="native",
                   shard_paths=16, checkpoint_every=1)

        def submit():
            result_holder["rec"] = client.submit_and_wait(
                addr, job, timeout=600, poll_deadline_s=600,
                idem_key="drill-1")

        sub = threading.Thread(target=submit, daemon=True)
        sub.start()

        # Wait until some replica journals the job, then kill that one.
        victim = None
        deadline = time.time() + 240
        while victim is None and time.time() < deadline:
            for name in r.fleet.names():
                jdir = os.path.join(fleet_dir, name, "state", "jobs")
                if os.path.isdir(jdir) and os.listdir(jdir):
                    victim = name
                    break
            time.sleep(0.1)
        assert victim is not None, "job never journaled on any replica"
        # Kill the instant the first checkpoint lands: the job is
        # provably mid-training (a fixed sleep races a warm cache — the
        # job can finish inside it and no failover ever happens).
        from g2vec_tpu.serve import protocol as _proto
        jid = _proto.idem_job_id("drill-1")
        ckpt_dir = os.path.join(fleet_dir, victim, "state", "ckpt")
        deadline = time.time() + 240
        while time.time() < deadline:
            if os.path.isdir(ckpt_dir) and any(
                    jid in e for e in os.listdir(ckpt_dir)):
                break
            time.sleep(0.05)
        else:
            pytest.fail("job never checkpointed on the victim")
        res_path = os.path.join(fleet_dir, victim, "state", "results",
                                f"{jid}.json")
        assert not os.path.exists(res_path), \
            "job finished before the kill could land — enlarge the job"
        pid = r.fleet.replica(victim).pid
        os.kill(pid, signal.SIGKILL)

        sub.join(timeout=600)
        assert not sub.is_alive(), "client never got a terminal record"
        rec = result_holder["rec"]
        assert rec["event"] == "job_done", rec
        job_id = rec["job_id"]

        # Failover event names the migration.
        evs = []
        with open(os.path.join(fleet_dir, "router-metrics.jsonl")) as f:
            for line in f:
                ev = json.loads(line)
                if ev.get("event") == "failover":
                    evs.append(ev)
        assert any(ev["job_id"] == job_id and ev["from_replica"] == victim
                   and ev["to_replica"] != victim and
                   ev["latency_s"] >= 0 for ev in evs), evs

        # Exactly one terminal job_state event fleet-wide.
        terminal = 0
        for name in r.fleet.names():
            mpath = os.path.join(fleet_dir, name, "metrics.jsonl")
            if not os.path.exists(mpath):
                continue
            with open(mpath) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if ev.get("event") == "job_state" \
                            and ev.get("job_id") == job_id \
                            and ev.get("state") == "done":
                        terminal += 1
        assert terminal == 1, f"{terminal} terminal events"

        # Byte parity vs a solo uninterrupted twin.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        cfg = config_from_job(
            {**job, "result_name": os.path.join(str(tmp_path), "out",
                                                "solo1")})
        v = _variant_from_dict(0, {"name": "v"}, cfg)
        sres = solo_run(lane_config(cfg, v), console=lambda s: None)
        outs = rec["variants"]["v"]["outputs"]
        assert len(outs) == len(sres.output_files) > 0
        for fa, fb in zip(sorted(outs), sorted(sres.output_files)):
            with open(fa, "rb") as a, open(fb, "rb") as b:
                assert a.read() == b.read(), f"{fa} != {fb}"
    finally:
        r._stop.set()
        th.join(timeout=120)
