"""Native TSV parser: byte-level parity with the Python reader, error
behavior, and the transparent-fallback contract."""
import os
import shutil

import numpy as np
import pytest

from g2vec_tpu.io.readers import load_expression

g_plus_plus = shutil.which("g++")
pytestmark = pytest.mark.skipif(g_plus_plus is None,
                                reason="no C++ toolchain in this environment")


@pytest.fixture(scope="module")
def expr_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("native") / "expr.txt"
    rng = np.random.default_rng(3)
    samples = [f"S{i}" for i in range(7)]
    with open(p, "w") as f:
        f.write("PATIENT\t" + "\t".join(samples) + "\r\n")   # CRLF on purpose
        for j in range(11):
            vals = "\t".join("%.6f" % v for v in rng.normal(size=7))
            f.write(f"GENE{j:03d}\t{vals}\n")
    return str(p)


def test_native_matches_python_reader(expr_file):
    native = load_expression(expr_file, use_native=True)
    python = load_expression(expr_file, use_native=False)
    np.testing.assert_array_equal(native.sample, python.sample)
    np.testing.assert_array_equal(native.gene, python.gene)
    np.testing.assert_allclose(native.expr, python.expr, rtol=0, atol=0)
    assert native.expr.dtype == np.float32
    assert native.expr.shape == (7, 11)


def test_native_crlf_trailing_blank_line_parity(tmp_path):
    # Windows-produced file with a trailing blank CRLF line: both parsers
    # must accept it identically (the blank-line skip runs after \r strip).
    p = tmp_path / "crlf.txt"
    p.write_bytes(b"PATIENT\tS1\r\nG1\t1.0\r\n\r\n")
    native = load_expression(str(p), use_native=True)
    python = load_expression(str(p), use_native=False)
    np.testing.assert_array_equal(native.gene, python.gene)
    np.testing.assert_array_equal(native.expr, python.expr)
    assert native.expr.shape == (1, 1)


def test_native_rejects_ragged_rows(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("PATIENT\tS1\tS2\nG1\t1.0\n")
    with pytest.raises(ValueError, match="1 values, expected 2"):
        load_expression(str(p), use_native=True)


def test_native_rejects_garbage_floats(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("PATIENT\tS1\nG1\t1.5x\n")
    with pytest.raises(ValueError, match="non-numeric"):
        load_expression(str(p), use_native=True)


def test_native_missing_file_raises(tmp_path):
    with pytest.raises(ValueError, match="No such file"):
        from g2vec_tpu.native import bindings

        bindings.read_expression(str(tmp_path / "nope.txt"))


def test_native_large_roundtrip(tmp_path):
    # A bigger matrix to catch indexing/transpose bugs the tiny case misses.
    rng = np.random.default_rng(0)
    s, g = 23, 57
    expr = rng.normal(size=(s, g)).astype(np.float32)
    p = tmp_path / "big.txt"
    with open(p, "w") as f:
        f.write("PATIENT\t" + "\t".join(f"S{i}" for i in range(s)) + "\n")
        for j in range(g):
            f.write(f"G{j}\t" + "\t".join("%.6f" % v for v in expr[:, j]) + "\n")
    d = load_expression(str(p), use_native=True)
    np.testing.assert_allclose(d.expr, np.loadtxt(
        str(p), skiprows=1, usecols=range(1, s + 1), dtype=np.float32).T,
        rtol=1e-6)
    assert d.gene[0] == "G0" and d.sample[-1] == f"S{s-1}"
