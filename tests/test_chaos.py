"""Chaos soak harness (tools/chaos_soak.py): seeded fault storm vs the
serve daemon with exactly-once terminal accounting.

The storm itself is slow (daemon relaunches, real SIGKILLs) so the soak
e2e is opt-in via ``-m chaos`` (also marked slow — tier-1 stays fast);
the parser/accounting units run everywhere.
"""
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SOAK = os.path.join(REPO, "tools", "chaos_soak.py")


def test_parser_env_fallbacks(monkeypatch):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_soak
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("G2V_CHAOS_JOBS", "7")
    monkeypatch.setenv("G2V_CHAOS_SEED", "3")
    opts = chaos_soak.build_parser().parse_args([])
    assert (opts.jobs, opts.seed) == (7, 3)
    # Explicit flags beat the env.
    opts = chaos_soak.build_parser().parse_args(["--jobs", "2"])
    assert opts.jobs == 2
    assert opts.budget_s > 0 and opts.mean_arrival > 0


@pytest.mark.slow
def test_chaos_soak_small_storm_accounts_every_job(tmp_path):
    """A shrunk storm (jobs, ops, budget from env) must still satisfy
    the full acceptance: exit 0, every acknowledged job in exactly one
    terminal state, zero lost/duplicated, drains exit 0, sampled byte
    parity intact."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": "6", "G2V_CHAOS_OPS": "3",
           "G2V_CHAOS_EVERY": "4", "G2V_CHAOS_VERIFY": "2",
           "G2V_CHAOS_BUDGET": "300"}
    out = os.path.join(str(tmp_path), "summary.json")
    proc = subprocess.run(
        [sys.executable, SOAK, "--seed", "1", "--json", out],
        capture_output=True, text=True, env=env, timeout=420)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-1200:]
    with open(out) as f:
        summary = json.load(f)
    assert summary["ok"] is True
    assert summary["accepted"] == 6
    assert summary["lost"] == [] and summary["duplicated"] == []
    assert summary["unsubmitted"] == 0
    assert summary["journal_leftover"] == []
    assert sum(summary["terminal_by_status"].values()) == 6
    assert set(summary["terminal_by_status"]) <= {
        "done", "cancelled", "deadline_exceeded"}
    assert all(rc == 0 for rc in summary["drain_exit_codes"])
    assert summary["byte_identical"] == summary["byte_checked"]


def test_partition_flags_env_fallbacks(monkeypatch):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_soak
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("G2V_CHAOS_PARTITION", "1")
    monkeypatch.setenv("G2V_CHAOS_TAKEOVERS", "2")
    monkeypatch.setenv("G2V_CHAOS_LEASE_TTL", "0.8")
    opts = chaos_soak.build_parser().parse_args([])
    assert opts.partition is True
    assert (opts.takeovers, opts.lease_ttl) == (2, 0.8)
    monkeypatch.delenv("G2V_CHAOS_PARTITION")
    opts = chaos_soak.build_parser().parse_args(["--partition"])
    assert opts.partition is True and opts.takeovers == 2


def test_relay_blackholes_each_direction_independently():
    """The partition injector itself: bytes flow both ways when healed,
    die in exactly the direction that was dropped, and connections
    still ACCEPT while partitioned (a partition is silence, not a
    refused dial)."""
    import socket
    import threading

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from chaos_soak import _Relay
    finally:
        sys.path.pop(0)

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)

    def echo_loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            def pump(c):
                try:
                    while True:
                        d = c.recv(4096)
                        if not d:
                            return
                        c.sendall(d)
                except OSError:
                    pass
                finally:
                    c.close()
            threading.Thread(target=pump, args=(conn,),
                             daemon=True).start()

    threading.Thread(target=echo_loop, daemon=True).start()
    relay = _Relay("127.0.0.1:%d" % srv.getsockname()[1])
    try:
        host, port = relay.addr.rsplit(":", 1)

        def rt(payload: bytes, timeout: float):
            c = socket.create_connection((host, int(port)), timeout=5)
            c.settimeout(timeout)
            try:
                c.sendall(payload)
                return c.recv(4096)
            finally:
                c.close()

        assert rt(b"ping", 5.0) == b"ping"           # healed: echo
        relay.partition(to_replica=False, to_client=True)
        with pytest.raises(OSError):                  # replies die
            rt(b"lost", 2.0)
        relay.heal()
        assert rt(b"again", 5.0) == b"again"
        relay.partition()                             # both directions
        with pytest.raises(OSError):
            rt(b"void", 2.0)
        # Still ACCEPTS while partitioned — the SYN is the kernel's.
        c = socket.create_connection((host, int(port)), timeout=5)
        c.close()
        relay.heal()
        assert rt(b"healed", 5.0) == b"healed"
    finally:
        relay.close()
        srv.close()


@pytest.mark.slow
@pytest.mark.partition
def test_partition_drill_small_storm(tmp_path):
    """A shrunk control-plane drill must pass the full partition
    acceptance: false-dead fence + quarantine, zombie epoch rejection,
    takeover chain, degraded-mode drills, exactly-once fleet-wide."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": "6", "G2V_CHAOS_STREAM_FRAC": "0",
           "G2V_CHAOS_VERIFY": "1", "G2V_CHAOS_TAKEOVERS": "1",
           "G2V_CHAOS_BUDGET": "420"}
    out = os.path.join(str(tmp_path), "summary.json")
    proc = subprocess.run(
        [sys.executable, SOAK, "--partition", "--seed", "2",
         "--json", out],
        capture_output=True, text=True, env=env, timeout=540)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-1500:]
    with open(out) as f:
        summary = json.load(f)
    assert summary["ok"] is True
    assert summary["mode"] == "partition"
    assert summary["fence_epoch"] >= 1
    assert summary["quarantine_to_park_s"] is not None
    assert summary["fenced_replica_violations"] == []
    assert summary["fenced_stays_out"] is True
    assert summary["stale_probe_rejects"] \
        == summary["stale_probe_targets"] > 0
    assert summary["zombie_rejects"] >= 1
    assert summary["takeovers"] >= 2      # SIGSTOP + 1 SIGKILL round
    assert summary["degraded_submits"] >= 1
    assert summary["lost"] == [] and summary["duplicated"] == []
    assert summary["journal_leftover"] == []
