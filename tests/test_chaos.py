"""Chaos soak harness (tools/chaos_soak.py): seeded fault storm vs the
serve daemon with exactly-once terminal accounting.

The storm itself is slow (daemon relaunches, real SIGKILLs) so the soak
e2e is opt-in via ``-m chaos`` (also marked slow — tier-1 stays fast);
the parser/accounting units run everywhere.
"""
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SOAK = os.path.join(REPO, "tools", "chaos_soak.py")


def test_parser_env_fallbacks(monkeypatch):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import chaos_soak
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("G2V_CHAOS_JOBS", "7")
    monkeypatch.setenv("G2V_CHAOS_SEED", "3")
    opts = chaos_soak.build_parser().parse_args([])
    assert (opts.jobs, opts.seed) == (7, 3)
    # Explicit flags beat the env.
    opts = chaos_soak.build_parser().parse_args(["--jobs", "2"])
    assert opts.jobs == 2
    assert opts.budget_s > 0 and opts.mean_arrival > 0


@pytest.mark.slow
def test_chaos_soak_small_storm_accounts_every_job(tmp_path):
    """A shrunk storm (jobs, ops, budget from env) must still satisfy
    the full acceptance: exit 0, every acknowledged job in exactly one
    terminal state, zero lost/duplicated, drains exit 0, sampled byte
    parity intact."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "G2V_CHAOS_JOBS": "6", "G2V_CHAOS_OPS": "3",
           "G2V_CHAOS_EVERY": "4", "G2V_CHAOS_VERIFY": "2",
           "G2V_CHAOS_BUDGET": "300"}
    out = os.path.join(str(tmp_path), "summary.json")
    proc = subprocess.run(
        [sys.executable, SOAK, "--seed", "1", "--json", out],
        capture_output=True, text=True, env=env, timeout=420)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-1200:]
    with open(out) as f:
        summary = json.load(f)
    assert summary["ok"] is True
    assert summary["accepted"] == 6
    assert summary["lost"] == [] and summary["duplicated"] == []
    assert summary["unsubmitted"] == 0
    assert summary["journal_leftover"] == []
    assert sum(summary["terminal_by_status"].values()) == 6
    assert set(summary["terminal_by_status"]) <= {
        "done", "cancelled", "deadline_exceeded"}
    assert all(rc == 0 for rc in summary["drain_exit_codes"])
    assert summary["byte_identical"] == summary["byte_checked"]
