"""Checkpoint/resume tests (capability the reference lacks — SURVEY.md §5)."""
import numpy as np
import pytest

from g2vec_tpu.train import train_cbow


def _data(rng, n_paths=120, n_genes=40, flip=0.0):
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        idx = rng.choice(half, size=5, replace=False) + (0 if lab == 0 else half)
        paths[i, idx] = 1
        if rng.random() < flip:
            labels[i] = 1 - labels[i]
    return paths, labels


def test_resume_matches_uninterrupted_run(rng, tmp_path):
    paths, labels = _data(rng)
    kwargs = dict(hidden=8, learning_rate=0.05, compute_dtype="float32", seed=0)

    full = train_cbow(paths, labels, max_epochs=12, **kwargs)

    # Interrupted run: checkpoint every 3 epochs, stop at 6, resume to 12.
    ckpt = str(tmp_path / "ck")
    train_cbow(paths, labels, max_epochs=6, checkpoint_dir=ckpt,
               checkpoint_every=3, **kwargs)
    resumed = train_cbow(paths, labels, max_epochs=12, checkpoint_dir=ckpt,
                         resume=True, checkpoint_every=3, **kwargs)

    assert not full.stopped_early and not resumed.stopped_early
    np.testing.assert_allclose(resumed.w_ih, full.w_ih, rtol=1e-5, atol=1e-7)
    assert resumed.acc_val == pytest.approx(full.acc_val)


def test_resume_of_finished_run_returns_without_training(rng, tmp_path):
    # Noisy data forces an early stop; resuming afterwards must NOT step
    # further (that would re-apply the dip epoch's update).
    paths, labels = _data(rng, flip=0.3)
    ckpt = str(tmp_path / "ck")
    kwargs = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=3, max_epochs=200, checkpoint_dir=ckpt)
    first = train_cbow(paths, labels, **kwargs)
    assert first.stopped_early
    again = train_cbow(paths, labels, resume=True, **kwargs)
    assert again.stopped_early
    assert again.stop_epoch == first.stop_epoch
    assert again.history == []          # no epochs were run
    np.testing.assert_array_equal(again.w_ih, first.w_ih)
    assert again.acc_val == first.acc_val


def test_bfloat16_params_roundtrip(rng, tmp_path):
    # np.savez stores ml_dtypes bfloat16 as raw void bytes; load_state must
    # reinterpret them (it once surfaced '|V2' arrays that crashed epoch 1).
    paths, labels = _data(rng)
    ckpt = str(tmp_path / "ck")
    kwargs = dict(hidden=8, learning_rate=0.05, compute_dtype="bfloat16",
                  param_dtype="bfloat16", seed=0, checkpoint_dir=ckpt,
                  checkpoint_every=2)
    train_cbow(paths, labels, max_epochs=4, **kwargs)
    resumed = train_cbow(paths, labels, max_epochs=8, resume=True, **kwargs)
    assert np.isfinite(resumed.w_ih).all()
    assert len(resumed.history) == 4          # epochs 4..7 actually ran


def test_resume_rejects_shape_mismatch(rng, tmp_path):
    paths, labels = _data(rng)
    ckpt = str(tmp_path / "ck")
    train_cbow(paths, labels, hidden=8, learning_rate=0.05, max_epochs=3,
               compute_dtype="float32", seed=0, checkpoint_dir=ckpt)
    with pytest.raises(ValueError, match="shape"):
        train_cbow(paths, labels, hidden=16, learning_rate=0.05, max_epochs=3,
                   compute_dtype="float32", seed=0, checkpoint_dir=ckpt,
                   resume=True)


def test_sharded_layout_resume_matches_uninterrupted(rng, tmp_path):
    """Orbax OCDBT layout under a (4, 2) DP x TP mesh: per-shard save +
    sharding-preserving restore, bit-compatible with an uninterrupted run
    (VERDICT round-1 #7 — no full-state gather on save)."""
    import os

    from g2vec_tpu.parallel.mesh import make_mesh_context

    paths, labels = _data(rng)
    ctx = make_mesh_context((4, 2))
    kwargs = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=0, mesh_ctx=ctx)

    full = train_cbow(paths, labels, max_epochs=12, **kwargs)

    ckpt = str(tmp_path / "ck")
    common = dict(checkpoint_dir=ckpt, checkpoint_every=3,
                  checkpoint_layout="sharded", **kwargs)
    train_cbow(paths, labels, max_epochs=6, **common)
    # The orbax OCDBT layout is on disk (per-process shard files) at the
    # dir the LATEST pointer names.
    from g2vec_tpu.train.checkpoint import _latest_sharded_dir

    layout_dir = _latest_sharded_dir(ckpt)
    assert layout_dir is not None and os.path.isdir(layout_dir)
    assert any(n.startswith("ocdbt.process_") for n in os.listdir(layout_dir))
    resumed = train_cbow(paths, labels, max_epochs=12, resume=True, **common)

    assert not full.stopped_early and not resumed.stopped_early
    np.testing.assert_allclose(resumed.w_ih, full.w_ih, rtol=1e-5, atol=1e-7)
    assert resumed.acc_val == pytest.approx(full.acc_val)


def test_sharded_layout_terminal_state(rng, tmp_path):
    """Early-stopped sharded checkpoints are terminal on resume, exactly
    like the single layout."""
    paths, labels = _data(rng, flip=0.3)
    ckpt = str(tmp_path / "ck")
    kwargs = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=3, max_epochs=200, checkpoint_dir=ckpt,
                  checkpoint_layout="sharded")
    first = train_cbow(paths, labels, **kwargs)
    assert first.stopped_early
    again = train_cbow(paths, labels, resume=True, **kwargs)
    assert again.stopped_early
    assert again.stop_epoch == first.stop_epoch
    assert again.history == []
    np.testing.assert_array_equal(again.w_ih, first.w_ih)


def test_sharded_layout_shape_mismatch_and_cross_layout(rng, tmp_path):
    paths, labels = _data(rng)
    ckpt = str(tmp_path / "ck")
    kwargs = dict(learning_rate=0.05, compute_dtype="float32", seed=0,
                  max_epochs=3, checkpoint_dir=ckpt)
    train_cbow(paths, labels, hidden=8, checkpoint_layout="sharded", **kwargs)
    # Same clear error as the single layout on a config change.
    with pytest.raises(ValueError, match="shape"):
        train_cbow(paths, labels, hidden=16, checkpoint_layout="sharded",
                   resume=True, **kwargs)
    # Resuming with the WRONG layout must fail loudly, not retrain.
    with pytest.raises(ValueError, match="checkpoint-layout"):
        train_cbow(paths, labels, hidden=8, checkpoint_layout="single",
                   resume=True, **kwargs)


def test_sharded_layout_keeps_previous_until_commit(rng, tmp_path):
    """Each save lands in a fresh numbered dir + atomic LATEST flip; the
    newest AND one previous generation are kept (the previous is the
    corruption fallback — resilience subsystem), anything older is pruned,
    and LATEST points at the newest."""
    import os

    paths, labels = _data(rng)
    ckpt = str(tmp_path / "ck")
    kwargs = dict(hidden=8, learning_rate=0.05, compute_dtype="float32",
                  seed=0, checkpoint_dir=ckpt, checkpoint_every=2,
                  checkpoint_layout="sharded")
    train_cbow(paths, labels, max_epochs=6, **kwargs)
    dirs = sorted(n for n in os.listdir(ckpt)
                  if n.startswith("cbow_state_ocdbt.")
                  and os.path.isdir(os.path.join(ckpt, n)))
    assert 1 <= len(dirs) <= 2, dirs             # newest + one fallback
    newest = max(dirs, key=lambda n: int(n.rsplit(".", 1)[1]))
    with open(os.path.join(ckpt, "cbow_state_ocdbt.LATEST")) as f:
        assert f.read().strip() == newest
    # Every kept generation carries its integrity manifest.
    for n in dirs:
        assert os.path.exists(os.path.join(ckpt, n + ".manifest.json")), n
