"""Committed byte-golden end-to-end fixtures, one per walker backend.

Round-1 gap (VERDICT.md missing #5): run-vs-run determinism tests cannot
catch a silent behavior-changing regression that shifts both runs together.
Here the full pipeline runs on a tiny committed-spec synthetic dataset with
a fixed seed and the three output files are compared BYTE-FOR-BYTE against
fixtures committed under tests/golden/ (format spec:
G2Vec.py:127-131,159-165,203-215). Any numerics drift in any stage —
graph, walker, trainer, k-means, scoring, writers — breaks the bytes.

Both samplers carry their own golden — and since PR 20 the two fixture
sets are BYTE-IDENTICAL: the device backend emulates the native
sampler's splitmix64 streams bit-exactly (ops/device_walker.py), so one
shared byte contract covers both engines. Keeping separate fixture
files preserves the per-backend drift attribution (a diff names the
engine that moved; round 4 moved the native sampler's bit-packing into
C++ — a change that was only provably walk-preserving because the
streams are pinned; this fixture makes that proof automatic for the
next such change, on either engine).

Regenerate intentionally with:
    G2VEC_REGEN_GOLDEN=1 python -m pytest tests/test_golden_e2e.py
and review the diff before committing.
"""
import os
import shutil

import pytest

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
SUFFIXES = ("biomarkers", "lgroups", "vectors")


def _run_pipeline(tmp_path, backend):
    from g2vec_tpu.config import G2VecConfig
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv
    from g2vec_tpu.pipeline import run

    spec = SyntheticSpec(
        n_good=24, n_poor=20, module_size=12, n_background=24,
        n_expr_only=4, n_net_only=4, module_chords=2,
        background_edges=40, seed=7,
    )
    paths = write_synthetic_tsv(spec, str(tmp_path))
    cfg = G2VecConfig(
        expression_file=paths["expression"],
        clinical_file=paths["clinical"],
        network_file=paths["network"],
        result_name=str(tmp_path / "golden"),
        lenPath=20, numRepetition=3, sizeHiddenlayer=16,
        epoch=30, numBiomarker=10, seed=11,
        # Pinned explicitly: each backend's PRNG family is its own byte
        # contract ("auto" would pick whatever this host supports).
        walker_backend=backend,
    )
    res = run(cfg, console=lambda s: None)
    return {s: f for s, f in zip(SUFFIXES, res.output_files)}


@pytest.mark.parametrize("backend", [
    "device",
    pytest.param("native", marks=pytest.mark.skipif(
        shutil.which("g++") is None, reason="no C++ toolchain")),
])
def test_outputs_match_committed_golden(tmp_path, backend):
    outputs = _run_pipeline(tmp_path, backend)
    prefix = "golden" if backend == "device" else f"golden_{backend}"
    if os.environ.get("G2VEC_REGEN_GOLDEN") == "1":
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        for suffix, path in outputs.items():
            with open(path, "rb") as f:
                data = f.read()
            with open(os.path.join(GOLDEN_DIR,
                                   f"{prefix}_{suffix}.txt"), "wb") as f:
                f.write(data)
        pytest.skip("golden fixtures regenerated — review and commit the diff")
    for suffix, path in outputs.items():
        golden = os.path.join(GOLDEN_DIR, f"{prefix}_{suffix}.txt")
        assert os.path.exists(golden), (
            f"missing fixture {golden}; regenerate with G2VEC_REGEN_GOLDEN=1")
        with open(path, "rb") as got, open(golden, "rb") as want:
            got_b, want_b = got.read(), want.read()
        assert got_b == want_b, (
            f"{suffix} output drifted from the committed {backend} golden "
            f"fixture ({len(got_b)} vs {len(want_b)} bytes) — if the change "
            "is intentional, regenerate with G2VEC_REGEN_GOLDEN=1 and commit")
