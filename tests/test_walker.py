"""L3 random-walk property tests (invariants of generate_pathSet,
G2Vec.py:324-352: no revisits, length cap, positive-weight transitions,
dead-end stop) plus integration/vote semantics (G2Vec.py:288-322)."""
import jax
import numpy as np
import pytest

from g2vec_tpu.ops.walker import (count_gene_freq, generate_path_set,
                                  integrate_path_sets, random_walks,
                                  unpack_paths)


def _ring_adj(n, w=1.0):
    """Directed ring 0->1->...->n-1->0."""
    adj = np.zeros((n, n), dtype=np.float32)
    for i in range(n):
        adj[i, (i + 1) % n] = w
    return adj


def test_walk_respects_length_cap_and_no_revisit():
    n = 10
    adj = _ring_adj(n)
    starts = np.arange(n, dtype=np.int32)
    for len_path in (1, 3, 10):
        visited = np.asarray(random_walks(adj, starts, jax.random.key(0), len_path))
        sizes = visited.sum(axis=1)
        # On a ring every walker moves deterministically until the cap.
        assert (sizes == min(len_path, n)).all()
        assert visited.dtype == np.bool_


def test_dead_end_stops_walk():
    # 0 -> 1 -> 2, nothing out of 2.
    adj = np.zeros((4, 4), dtype=np.float32)
    adj[0, 1] = adj[1, 2] = 1.0
    visited = np.asarray(random_walks(adj, np.array([0], np.int32),
                                      jax.random.key(0), len_path=50))
    assert visited[0].sum() == 3
    assert visited[0, :3].all() and not visited[0, 3]


def test_no_revisit_blocks_return_edge():
    # 0 <-> 1 both directions: walker must stop after 0,1 (can't go back).
    adj = np.zeros((3, 3), dtype=np.float32)
    adj[0, 1] = adj[1, 0] = 1.0
    visited = np.asarray(random_walks(adj, np.array([0], np.int32),
                                      jax.random.key(1), len_path=50))
    assert visited[0].sum() == 2


def test_transitions_only_on_positive_weights(rng):
    # Random sparse graph: every visited node other than the start must be
    # reachable via an edge chain of positive weights. Weak check: the set of
    # genes visited from src is a subset of nodes reachable from src.
    n = 12
    adj = (rng.random((n, n)) < 0.2).astype(np.float32)
    np.fill_diagonal(adj, 0.0)
    # reachability closure
    reach = adj > 0
    for _ in range(n):
        reach = reach | (reach.astype(np.int32) @ reach.astype(np.int32) > 0)
    visited = np.asarray(random_walks(adj, np.arange(n, dtype=np.int32),
                                      jax.random.key(2), len_path=6))
    for s in range(n):
        others = np.flatnonzero(visited[s])
        for g in others:
            if g != s:
                assert reach[s, g], f"walker from {s} visited unreachable {g}"


def test_weighted_sampling_prefers_heavy_edge():
    # From 0: edge to 1 with weight 9, edge to 2 with weight 1 -> ~90/10.
    adj = np.zeros((3, 3), dtype=np.float32)
    adj[0, 1], adj[0, 2] = 9.0, 1.0
    starts = np.zeros(4000, dtype=np.int32)
    visited = np.asarray(random_walks(adj, starts, jax.random.key(3), len_path=2))
    frac_to_1 = visited[:, 1].mean()
    assert 0.86 < frac_to_1 < 0.94, frac_to_1


def test_generate_path_set_dedups():
    # Deterministic ring: every start yields a distinct rotation-invariant
    # node SET; with len_path=n all walks visit all nodes -> one unique path.
    n = 6
    adj = _ring_adj(n)
    paths = generate_path_set(adj, jax.random.key(0), len_path=n, reps=3)
    assert len(paths) == 1
    arr = unpack_paths(sorted(paths), n)
    assert (arr == 1).all()
    # With len_path=2 there are exactly n distinct 2-node sets.
    paths2 = generate_path_set(adj, jax.random.key(0), len_path=2, reps=2)
    assert len(paths2) == n


def test_walker_batching_equivalence(rng):
    # STOCHASTIC graph: batch size must not change which uniform stream each
    # walker draws (per-walker keys are bound to global walker identity).
    n = 10
    adj = rng.random((n, n)).astype(np.float32)
    np.fill_diagonal(adj, 0.0)
    full = generate_path_set(adj, jax.random.key(5), len_path=4, reps=2)
    batched = generate_path_set(adj, jax.random.key(5), len_path=4, reps=2,
                                walker_batch=3)
    assert full == batched


def test_integrate_drops_common_paths():
    n = 5
    a = np.zeros(n, np.uint8); a[[0, 1]] = 1
    b = np.zeros(n, np.uint8); b[[2, 3]] = 1
    c = np.zeros(n, np.uint8); c[[1, 4]] = 1
    pa, pb, pc = (np.packbits(x).tobytes() for x in (a, b, c))
    good = {pa, pb}
    poor = {pb, pc}
    paths, labels = integrate_path_sets(good, poor, n)
    assert paths.shape == (2, n)
    # pb was common -> dropped from both (ref: G2Vec.py:313-315)
    assert labels.tolist() == [0, 1]
    np.testing.assert_array_equal(paths[0], a)
    np.testing.assert_array_equal(paths[1], c)


def test_count_gene_freq_majority_and_ties():
    genes = ["A", "B", "C", "D"]
    paths = np.array([
        [1, 1, 0, 0],   # good
        [1, 0, 1, 0],   # good
        [1, 0, 1, 0],   # poor
    ], dtype=np.int32)
    labels = np.array([0, 0, 1], dtype=np.int32)
    freq = count_gene_freq(paths, labels, genes)
    assert freq["A"] == 0        # 2 good vs 1 poor
    assert freq["B"] == 0        # 1 good vs 0 poor
    assert freq["C"] == 2        # 1 vs 1 tie
    assert "D" not in freq       # in no path (ref: G2Vec.py:292-297)


def test_single_node_paths_when_no_edges():
    n = 4
    adj = np.zeros((n, n), dtype=np.float32)
    paths = generate_path_set(adj, jax.random.key(0), len_path=10, reps=1)
    assert len(paths) == n       # each start is its own singleton path
    arr = unpack_paths(sorted(paths), n)
    assert (arr.sum(axis=1) == 1).all()


def test_integrate_packed_matches_dense(rng):
    n = 20
    rows = [(rng.random(n) < 0.3).astype(np.uint8) for _ in range(12)]
    good = {np.packbits(r).tobytes() for r in rows[:8]}
    poor = {np.packbits(r).tobytes() for r in rows[5:]}   # overlap -> dropped
    dense, lab_d = integrate_path_sets(good, poor, n)
    packed, lab_p = integrate_path_sets(good, poor, n, packed=True)
    assert np.array_equal(lab_d, lab_p)
    assert packed.dtype == np.uint8 and packed.shape[1] == (n + 7) // 8
    assert np.array_equal(np.unpackbits(packed, axis=1)[:, :n], dense)


def test_count_gene_freq_packed_matches_dense(rng):
    n = 37
    genes = [f"G{i}" for i in range(n)]
    dense = (rng.random((50, n)) < 0.2).astype(np.uint8)
    labels = (rng.random(50) < 0.5).astype(np.int32)
    packed = np.packbits(dense, axis=1)
    assert count_gene_freq(packed, labels, genes, packed=True) == \
        count_gene_freq(dense, labels, genes)
    with pytest.raises(ValueError, match="inconsistent"):
        count_gene_freq(packed, labels, genes + ["EXTRA"] * 30, packed=True)


def test_trainer_accepts_packed_paths(rng):
    from g2vec_tpu.train.trainer import train_cbow

    n_paths, n_genes = 64, 90
    dense = (rng.random((n_paths, n_genes)) < 0.2).astype(np.int8)
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    packed = np.packbits(dense != 0, axis=1)
    common = dict(hidden=16, learning_rate=0.01, max_epochs=3,
                  compute_dtype="float32", seed=1)
    res_d = train_cbow(dense, labels, **common)
    res_p = train_cbow(packed, labels, packed_genes=n_genes, **common)
    np.testing.assert_allclose(res_p.w_ih, res_d.w_ih, atol=1e-6)

    with pytest.raises(ValueError, match="packed_genes"):
        train_cbow(packed, labels, packed_genes=n_genes + 99, **common)


def test_path_set_invariant_to_mesh(rng):
    """Sharded walkers (4x1 mesh) produce the exact same path set as a
    single device for the same seed — including when walker counts don't
    divide the data axis (pad walkers are dropped)."""
    from g2vec_tpu.ops.graph import neighbor_table
    from g2vec_tpu.parallel.mesh import make_mesh_context

    n = 30
    src = rng.integers(0, n, 200).astype(np.int32)
    dst = rng.integers(0, n, 200).astype(np.int32)
    w = rng.random(200).astype(np.float32) + 0.1
    table = neighbor_table(src, dst, w, n)
    key = jax.random.key(11)
    kwargs = dict(len_path=6, reps=2, starts=np.arange(n, dtype=np.int32))
    base = generate_path_set(table, key, **kwargs)
    meshed = generate_path_set(table, key, mesh_ctx=make_mesh_context((4, 1)),
                               **kwargs)
    assert base == meshed
    batched = generate_path_set(table, key, walker_batch=7,
                                mesh_ctx=make_mesh_context((4, 1)), **kwargs)
    assert base == batched
    # 2x2 mesh with FORCED table sharding (auto would replicate this tiny
    # table): rows shard over 'model' (n=30 pads to 32) and the
    # ownership-psum gather must reconstruct the exact same candidate
    # rows — the path set is bit-identical to single-device.
    sharded = generate_path_set(table, key, mesh_ctx=make_mesh_context((2, 2)),
                                shard_tables=True, **kwargs)
    assert base == sharded
    sharded_b = generate_path_set(table, key, walker_batch=7, shard_tables=True,
                                  mesh_ctx=make_mesh_context((2, 2)), **kwargs)
    assert base == sharded_b
    # Auto policy on a small table: replicated, still identical.
    auto = generate_path_set(table, key, mesh_ctx=make_mesh_context((2, 2)),
                             **kwargs)
    assert base == auto


def test_auto_walker_batch_model_respects_budget():
    from g2vec_tpu.ops.walker import auto_walker_batch, walker_working_set

    # 45k-gene scale (BASELINE configs #3-#5): the chosen batch must fit the
    # stated budget (which governs MARGINAL walker state; the transition
    # tables are launch-invariant and deliberately outside it) and still
    # make progress in a handful of launches.
    g, d, L = 45000, 8192, 80
    total = 10 * g
    budget = 4 * 1024**3
    batch = auto_walker_batch(g, d, L, total, dense=False, hbm_budget=budget)
    per = walker_working_set(g, d, L, dense=False)
    assert batch * per <= budget
    assert total // batch <= 64, (
        f"a 45k-gene walk should take a few launches, not {total // batch}")
    # A bundled-scale walk fits in ONE launch under the default budget.
    assert auto_walker_batch(9904, 1024, 80, 99040, dense=False) == 99040
    # A budget smaller than one walker still yields a working batch of 1.
    assert auto_walker_batch(g, d, L, total, dense=False, hbm_budget=1) == 1


def test_path_set_invariant_to_hbm_budget(rng):
    # Tiny budget -> many small launches; result must equal one big launch.
    from g2vec_tpu.ops.graph import neighbor_table

    n = 16
    src = rng.integers(0, n, 80).astype(np.int32)
    dst = rng.integers(0, n, 80).astype(np.int32)
    w = rng.random(80).astype(np.float32) + 0.1
    table = neighbor_table(src, dst, w, n)
    key = jax.random.key(9)
    full = generate_path_set(table, key, len_path=5, reps=3)
    tiny = generate_path_set(table, key, len_path=5, reps=3,
                             walker_hbm_budget=walker_budget_for(table, n, 5))
    assert full == tiny


def walker_budget_for(table, n, walkers):
    """Budget covering ~``walkers`` walkers of marginal state, so the run
    splits into ceil(total/walkers) launches."""
    from g2vec_tpu.ops.walker import walker_working_set

    return walkers * walker_working_set(n, table[0].shape[1], 5, dense=False)


def test_packbits_rows_matches_numpy(rng):
    from g2vec_tpu.ops.walker import _packbits_rows

    for n in (8, 13, 64, 9904):
        rows = rng.random((7, n)) < 0.3
        got = np.asarray(_packbits_rows(jax.numpy.asarray(rows)))
        np.testing.assert_array_equal(got, np.packbits(rows, axis=1))


def test_sample_slots_is_exactly_categorical():
    # Inverse-CDF on a dense u grid: the selected-slot frequencies must
    # equal the normalized weights to grid resolution, and zero-weight
    # slots (leading, interior, trailing/padding) must NEVER be chosen.
    import jax.numpy as jnp

    from g2vec_tpu.ops.walker import _sample_slots

    w_row = np.array([0.0, 2.0, 0.0, 3.0, 5.0, 0.0, 0.0], dtype=np.float32)
    n = 20000
    u = (np.arange(n) + 0.5) / n
    w = jnp.asarray(np.tile(w_row, (n, 1)))
    slot, total = _sample_slots(w, jnp.asarray(u, jnp.float32))
    slot = np.asarray(slot)
    np.testing.assert_allclose(np.asarray(total), w_row.sum(), rtol=1e-6)
    counts = np.bincount(slot, minlength=7)
    assert counts[0] == counts[2] == counts[5] == counts[6] == 0
    np.testing.assert_allclose(counts[[1, 3, 4]] / n,
                               w_row[[1, 3, 4]] / w_row.sum(), atol=1e-3)
    # All-zero weights (dead end): total must be 0 so the caller freezes.
    _, total0 = _sample_slots(jnp.zeros((4, 7)), jnp.asarray(u[:4], jnp.float32))
    assert (np.asarray(total0) == 0).all()


def test_visited_from_path_list_ignores_sentinels():
    import jax.numpy as jnp

    from g2vec_tpu.ops.walker import _visited_from_path_list

    path = jnp.asarray(np.array([[3, 1, -1, -1], [0, 2, 2, -1]], np.int32))
    visited = np.asarray(_visited_from_path_list(path, 5))
    np.testing.assert_array_equal(visited, [
        [False, True, False, True, False],
        [True, False, True, False, False]])


def test_packed_from_path_list_matches_bool_route(rng):
    import jax.numpy as jnp

    from g2vec_tpu.ops.walker import (_packbits_rows, _packed_from_path_list,
                                      _visited_from_path_list)

    for n in (9, 16, 40):
        # Unique nodes per row (the walk's no-revisit guarantee), -1 padded.
        rows = []
        for _ in range(6):
            k = rng.integers(1, min(n, 7))
            ids = rng.choice(n, size=k, replace=False).astype(np.int32)
            rows.append(np.pad(ids, (0, 7 - k), constant_values=-1))
        path = jnp.asarray(np.stack(rows))
        direct = np.asarray(_packed_from_path_list(path, n))
        via_bool = np.asarray(_packbits_rows(_visited_from_path_list(path, n)))
        np.testing.assert_array_equal(direct, via_bool)
