"""Pallas packed-matmul kernel: layout, parity, and trainer integration.

On CPU the kernel runs in pallas interpret mode (same program, emulated),
so these tests exercise the real kernel logic without a TPU; the TPU
compile path is covered by the benchmark run.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from g2vec_tpu.ops import packed_matmul as pm


def test_pack_unpack_roundtrip(rng):
    x = (rng.random((64, 2048)) < 0.1).astype(np.uint8)
    packed = pm.pack_blockwise(x)
    assert packed.shape == (64, 256)
    assert np.array_equal(pm.unpack_blockwise(packed), x)


def test_pack_rejects_unaligned():
    with pytest.raises(ValueError):
        pm.pack_blockwise(np.zeros((4, 1000), dtype=np.uint8))


def test_fwd_matches_dense(rng):
    m, g, h = 512, 2048, 128
    x = (rng.random((m, g)) < 0.05).astype(np.uint8)
    w = jnp.asarray((rng.standard_normal((g, h)) * 0.1).astype(np.float32))
    p = jnp.asarray(pm.pack_blockwise(x))
    out = np.asarray(pm.packed_matmul(p, w, True))
    ref = np.asarray(
        (jnp.asarray(x, jnp.bfloat16) @ w.astype(jnp.bfloat16)
         ).astype(jnp.float32))
    # Kernel keeps an f32 accumulator; the reference rounds through bf16
    # once more — tolerance covers that single-rounding difference.
    np.testing.assert_allclose(out, ref, atol=0.05)


def test_grad_matches_dense(rng):
    m, g, h = 512, 1024, 128
    x = (rng.random((m, g)) < 0.05).astype(np.uint8)
    w = jnp.asarray((rng.standard_normal((g, h)) * 0.1).astype(np.float32))
    p = jnp.asarray(pm.pack_blockwise(x))
    xd = jnp.asarray(x, jnp.bfloat16)

    def loss_packed(w):
        return jnp.sum(jnp.tanh(pm.packed_matmul(p, w, True)))

    def loss_dense(w):
        o = jax.lax.dot_general(xd, w.astype(jnp.bfloat16),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return jnp.sum(jnp.tanh(o))

    gp = np.asarray(jax.grad(loss_packed)(w))
    gd = np.asarray(jax.grad(loss_dense)(w))
    scale = np.max(np.abs(gd)) + 1e-12
    assert np.max(np.abs(gp - gd)) / scale < 0.02


def test_row_padding_helper():
    p = np.ones((700, 128), np.uint8)
    padded = pm.pad_rows_packed(p)
    assert padded.shape == (1024, 128)
    assert np.array_equal(padded[:700], p)
    assert not padded[700:].any()


def test_availability_gate():
    # CPU backend -> no pallas (interpret is opt-in for tests).
    assert not pm.packed_matmul_available(512, 2048, 128, backend="cpu")
    # Misaligned hidden or gene dims -> no.
    assert not pm.packed_matmul_available(512, 2048, 96, backend="tpu")
    assert not pm.packed_matmul_available(512, 2000, 128, backend="tpu")
    # Within budget -> yes.
    assert pm.packed_matmul_available(512, 8192, 128, backend="tpu")
    # The gene axis tiles (round-2 fix): BASELINE configs #3-#5 shapes that
    # the old whole-[G,H] accumulator rejected are now in.
    assert pm.packed_matmul_available(45056, 16384, 1024, backend="tpu")
    assert pm.packed_matmul_available(512, 65536, 128, backend="tpu")
    # A minimum grid step's working set must still fit: h=2048 exceeds it.
    assert not pm.packed_matmul_available(512, 32768, 2048, backend="tpu")


def test_blocks_per_group_divides_evenly():
    # h=1024 -> one lane slab per gene block (the resident tile + streamed
    # tiles + slab temp fill the step budget).
    assert pm._blocks_per_group(4096, 1024) == 1
    # Small h -> several slabs per block, and the count divides evenly.
    assert pm._blocks_per_group(8192, 128) == 8
    assert pm._blocks_per_group(16384, 128) == 8
    # Budget never violated for the chosen block, at either h regime.
    for g, h in [(4096, 1024), (8192, 128), (16384, 128), (65536, 512)]:
        gb = pm._blocks_per_group(g, h) * pm.LANE_BLOCK
        assert pm._vmem_step_bytes(gb, h, pm._row_block(h)) <= pm._VMEM_STEP_BUDGET


@pytest.mark.parametrize("m,g,h", [
    (1024, 4096, 1024),    # h=1024: 4 row tiles x 4 one-slab gene blocks
    (512, 16384, 128),     # h=128: 2 gene blocks of 8 slabs each
])
def test_fwd_matches_dense_multi_gene_block(rng, m, g, h):
    """Shapes that force the 2-D grid — the BASELINE #3-#5 regime the old
    whole-table-resident kernel refused."""
    x = (rng.random((m, g)) < 0.02).astype(np.uint8)
    w = jnp.asarray((rng.standard_normal((g, h)) * 0.1).astype(np.float32))
    p = jnp.asarray(pm.pack_blockwise(x))
    out = np.asarray(pm.packed_matmul(p, w, True))
    ref = np.asarray(
        (jnp.asarray(x, jnp.bfloat16) @ w.astype(jnp.bfloat16)
         ).astype(jnp.float32))
    np.testing.assert_allclose(out, ref, atol=0.05)


def test_grad_matches_dense_multi_gene_block(rng):
    m, g, h = 1024, 4096, 1024
    x = (rng.random((m, g)) < 0.02).astype(np.uint8)
    w = jnp.asarray((rng.standard_normal((g, h)) * 0.1).astype(np.float32))
    p = jnp.asarray(pm.pack_blockwise(x))
    xd = jnp.asarray(x, jnp.bfloat16)

    def loss_packed(w):
        return jnp.sum(jnp.tanh(pm.packed_matmul(p, w, True)))

    def loss_dense(w):
        o = jax.lax.dot_general(xd, w.astype(jnp.bfloat16),
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return jnp.sum(jnp.tanh(o))

    gp = np.asarray(jax.grad(loss_packed)(w))
    gd = np.asarray(jax.grad(loss_dense)(w))
    scale = np.max(np.abs(gd)) + 1e-12
    assert np.max(np.abs(gp - gd)) / scale < 0.02


def test_trainer_pallas_parity(rng):
    """Full trainer: pallas (interpret) vs XLA path track each other."""
    from g2vec_tpu.train.trainer import train_cbow

    n_paths, n_genes = 96, 700
    paths = (rng.random((n_paths, n_genes)) < 0.15).astype(np.int8)
    # Planted signal so accuracy moves off 0.5.
    labels = (paths[:, :40].sum(axis=1) > paths[:, 40:80].sum(axis=1)
              ).astype(np.int32)
    common = dict(hidden=128, learning_rate=0.01, max_epochs=6,
                  compute_dtype="bfloat16", seed=3)
    res_p = train_cbow(paths, labels, use_pallas=True, **common)
    res_x = train_cbow(paths, labels, use_pallas=False, **common)
    # Packed input + pallas is the production TPU combination the pipeline
    # drives (packed_genes routes through the chunked blockwise repack).
    packed_in = np.packbits(paths != 0, axis=1)
    res_pp = train_cbow(packed_in, labels, use_pallas=True,
                        packed_genes=n_genes, **common)
    np.testing.assert_array_equal(res_pp.w_ih, res_p.w_ih)
    assert res_p.w_ih.shape == res_x.w_ih.shape == (n_genes, 128)
    # Same seed, same split, same math up to bf16 rounding order: the
    # trajectories must agree closely for the first few epochs.
    for hp, hx in zip(res_p.history, res_x.history):
        assert abs(hp["loss"] - hx["loss"]) < 0.05
        assert abs(hp["acc_tr"] - hx["acc_tr"]) < 0.12
    np.testing.assert_allclose(res_p.w_ih, res_x.w_ih, atol=0.05)


def test_trainer_pallas_dp_mesh_parity(rng):
    """Packed kernel under a 4x1 data-parallel mesh (shard_map + interpret)
    tracks the single-device pallas run."""
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.train.trainer import train_cbow

    n_paths, n_genes = 80, 300
    paths = (rng.random((n_paths, n_genes)) < 0.15).astype(np.int8)
    labels = (paths[:, :30].sum(axis=1) > paths[:, 30:60].sum(axis=1)
              ).astype(np.int32)
    common = dict(hidden=128, learning_rate=0.01, max_epochs=4,
                  compute_dtype="bfloat16", seed=5, use_pallas=True)
    res_one = train_cbow(paths, labels, **common)
    # Packed input through the DP mesh — the multi-chip production path.
    packed_in = np.packbits(paths != 0, axis=1)
    res_dp = train_cbow(packed_in, labels, packed_genes=n_genes,
                        mesh_ctx=make_mesh_context((4, 1)), **common)
    np.testing.assert_allclose(res_dp.w_ih, res_one.w_ih, atol=0.05)
    for h1, h2 in zip(res_one.history, res_dp.history):
        assert abs(h1["loss"] - h2["loss"]) < 0.05


def test_trainer_pallas_rejects_gene_sharding(rng):
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.train.trainer import train_cbow

    paths = (rng.random((16, 64)) < 0.2).astype(np.int8)
    labels = (rng.random(16) < 0.5).astype(np.int32)
    with pytest.raises(ValueError, match="gene-shard"):
        train_cbow(paths, labels, hidden=128, learning_rate=0.01,
                   max_epochs=1, compute_dtype="bfloat16", seed=0,
                   use_pallas=True, mesh_ctx=make_mesh_context((4, 2)))
