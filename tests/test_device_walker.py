"""Bit-exact device walker (ops/device_walker.py): splitmix64 lane-pair
fuzz battery, host-vs-device packed-row parity across shard plans and
thread counts, word-for-word suspend/resume rng parity (including the
depth-1-remaining and dead-end-at-resume edges), the cross-backend
walk-cache HIT contract, the dense-walker deprecation shim, the
device_walk fault drill (clean recompute, byte-identical), and the
fused --device-feed streaming run (zero ring puts, outputs
byte-identical to the native ring feed)."""
import shutil

import numpy as np
import pytest

from g2vec_tpu.ops import device_walker as dw
from g2vec_tpu.ops import host_walker as hw
from g2vec_tpu.resilience import faults

pytestmark = pytest.mark.device

g_plus_plus = shutil.which("g++")
needs_native = pytest.mark.skipif(
    g_plus_plus is None, reason="no C++ toolchain in this environment")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


def _rand_graph(G, E, r):
    src = r.integers(0, G, size=E).astype(np.int32)
    dst = r.integers(0, G, size=E).astype(np.int32)
    w = (r.random(E, dtype=np.float32)
         * (10.0 ** r.integers(-3, 4, size=E)).astype(np.float32))
    w[r.random(E) < 0.1] = 0.0          # exercise eligibility masking
    return src, dst, w


# ---- satellite 1: splitmix64 fuzz battery ---------------------------------

def test_splitmix64_device_words_match_reference_fuzz():
    """uint32-pair emulation vs the pure-Python reference, word for word,
    over random seeds and draw counts."""
    import jax.numpy as jnp

    r = np.random.default_rng(11)
    states = r.integers(0, 2**64, size=128, dtype=np.uint64)
    with dw._x64():
        sh, sl = dw._split_rng(states)
        sh, sl = jnp.asarray(sh), jnp.asarray(sl)
        for _ in range(7):              # 7 draws x 128 streams
            nsh, nsl, zh, zl = dw._splitmix64_device(sh, sl)
            new = dw._join_rng(np.asarray(nsh), np.asarray(nsl))
            out = dw._join_rng(np.asarray(zh), np.asarray(zl))
            u_dev = np.asarray(dw._uniform01_device(zh, zl))
            for i, s in enumerate(states):
                want_state, want_word = dw.splitmix64_ref(int(s))
                assert int(new[i]) == want_state
                assert int(out[i]) == want_word
                # The split-sum uniform is the EXACT f64 the C++ walker
                # computes from the same word.
                assert u_dev[i] == float(want_word >> 11) * 2.0**-53
            states = new
            sh, sl = nsh, nsl


def test_init_state_numpy_twin_derivation():
    """init_walk_state_np == seed ^ (sid * GOLDEN) advanced by one
    discarded splitmix64 call, for edge-case seeds."""
    wids = np.arange(37, dtype=np.uint64)
    for seed in (0, 1, 2**63, 2**64 - 1, 0xDEADBEEF):
        got = dw.init_walk_state_np(seed, wids)
        for i in range(len(wids)):
            raw = (seed ^ (int(wids[i]) * dw.GOLDEN)) & dw._MASK64
            want, _ = dw.splitmix64_ref(raw)      # discard advances state
            assert int(got[i]) == want


@needs_native
def test_init_state_matches_native():
    from g2vec_tpu.native.walker_bindings import init_walk_state

    wids = np.arange(64, dtype=np.uint64)
    for seed in (0, 7, 2**63 + 5, 2**64 - 1):
        assert np.array_equal(init_walk_state(seed, wids),
                              dw.init_walk_state_np(seed, wids))


# ---- tentpole: packed-row bitwise parity ----------------------------------

@needs_native
def test_packed_rows_parity_across_graphs():
    r = np.random.default_rng(3)
    for trial in range(6):
        G = int(r.integers(5, 150))
        E = int(r.integers(0, G * 6 + 1))
        src, dst, w = _rand_graph(G, E, r)
        L = int(r.integers(1, 10))      # includes len_path=1
        reps = int(r.integers(1, 4))
        seed = int(r.integers(0, 2**63))
        host = hw.walk_packed_rows(src, dst, w, G, len_path=L, reps=reps,
                                   seed=seed)
        dev = dw.walk_packed_rows_device(src, dst, w, G, len_path=L,
                                         reps=reps, seed=seed)
        assert host.shape == dev.shape
        assert host.tobytes() == dev.tobytes(), f"trial {trial}"


@needs_native
@pytest.mark.parametrize("shard_paths", [16, 64, 0])
@pytest.mark.parametrize("n_threads", [1, 3])
def test_shard_parity_across_plans_and_sampler_threads(shard_paths,
                                                       n_threads):
    """Device shards byte-identical to the host pool's at ANY shard plan
    and --sampler-threads setting (thread count must be a no-op)."""
    r = np.random.default_rng(17)
    G = 90
    src, dst, w = _rand_graph(G, 500, r)
    plan = hw.plan_shards(G, 2, shard_paths, len_path=7)
    for s in range(min(plan.n_shards, 4)):
        host = hw.walk_shard(src, dst, w, G, plan, s, seed=12345,
                             n_threads=n_threads)
        dev = dw.walk_shard_device(src, dst, w, G, plan, s, seed=12345)
        assert host.tobytes() == dev.tobytes()


# ---- suspend/resume: word-for-word WalkStateBatch parity ------------------

@needs_native
def test_suspend_resume_roundtrip_word_for_word():
    """Availability-masked advance on both backends: identical statuses,
    paths, AND rng words at every round — then a cross-backend resume
    (host-advanced states resumed on device, and vice versa)."""
    r = np.random.default_rng(23)
    G = 60
    src, dst, w = _rand_graph(G, 380, r)
    csr = hw.edges_to_csr(src, dst, w, G)
    L = 8
    plan = hw.plan_shards(G, 2, 48, len_path=L)
    st_h = hw.shard_walk_states(plan, 0, seed=99)
    st_d = hw.shard_walk_states(plan, 0, seed=99)
    for round_i in range(3):
        avail = (r.random(G) < 0.55).astype(np.uint8)
        if round_i == 2:
            avail = np.ones(G, np.uint8)   # final round: everyone finishes
        stat_h = hw.advance_walk_states(st_h, csr, G, avail, L)
        stat_d = dw.advance_walk_states_device(st_d, csr, G, avail, L)
        assert np.array_equal(stat_h, stat_d)
        assert np.array_equal(st_h.cur, st_d.cur)
        assert np.array_equal(st_h.pos, st_d.pos)
        assert np.array_equal(st_h.paths, st_d.paths)
        assert np.array_equal(st_h.rng, st_d.rng)   # word-for-word
    assert stat_h.max() == 0

    # Cross-backend handoff: advance on one backend, resume on the other.
    st_a = hw.shard_walk_states(plan, 1, seed=99)
    st_b = hw.shard_walk_states(plan, 1, seed=99)
    avail = (np.arange(G) % 3 != 0).astype(np.uint8)
    hw.advance_walk_states(st_a, csr, G, avail, L)       # host first
    dw.advance_walk_states_device(st_b, csr, G, avail, L)  # device first
    full = np.ones(G, np.uint8)
    sa = dw.advance_walk_states_device(st_a, csr, G, full, L)  # dev resume
    sb = hw.advance_walk_states(st_b, csr, G, full, L)         # host resume
    assert np.array_equal(sa, sb)
    assert np.array_equal(st_a.paths, st_b.paths)
    assert np.array_equal(st_a.rng, st_b.rng)


def test_depth_1_remaining_finishes_without_availability():
    """A walker with one slot remaining finishes; a walker already full
    never consults availability (the host loop checks plen < len_path
    FIRST) — pins the device kernel's gate ordering."""
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 0], np.int32)
    w = np.array([1.0, 1.0], np.float32)
    G, L = 2, 2
    csr = hw.edges_to_csr(src, dst, w, G)
    avail = np.array([1, 0], np.uint8)   # target node unavailable
    rng0 = dw.init_walk_state_np(5, np.arange(1, dtype=np.uint64))
    paths = np.full((1, L), -1, np.int32)
    paths[0, 0] = 0
    states = hw.WalkStateBatch(row=np.zeros(1, np.int64),
                               cur=np.zeros(1, np.int32), rng=rng0.copy(),
                               pos=np.ones(1, np.int32), paths=paths)
    status = dw.advance_walk_states_device(states, csr, G, avail, L)
    assert status[0] == 0                # finished, NOT suspended
    assert states.pos[0] == 2 and states.paths[0, 1] == 1
    want_rng, _ = dw.splitmix64_ref(int(rng0[0]))   # exactly one draw
    assert int(states.rng[0]) == want_rng


def test_dead_end_at_resume_freezes_rng():
    """A suspended walker that resumes into a dead end exits WITHOUT
    drawing — the rng word stays frozen at its suspension value."""
    src = np.array([0, 1], np.int32)
    dst = np.array([1, 0], np.int32)
    w = np.array([1.0, 1.0], np.float32)
    G, L = 2, 3
    csr = hw.edges_to_csr(src, dst, w, G)
    rng0 = dw.init_walk_state_np(9, np.arange(1, dtype=np.uint64))
    paths = np.full((1, L), -1, np.int32)
    paths[0, 0] = 0
    states = hw.WalkStateBatch(row=np.zeros(1, np.int64),
                               cur=np.zeros(1, np.int32), rng=rng0.copy(),
                               pos=np.ones(1, np.int32), paths=paths)
    # Walk 0 -> 1 (one draw), then suspend: node 1 unavailable.
    status = dw.advance_walk_states_device(
        states, csr, G, np.array([1, 0], np.uint8), L)
    assert status[0] == 1 and states.cur[0] == 1 and states.pos[0] == 2
    after_draw, _ = dw.splitmix64_ref(int(rng0[0]))
    assert int(states.rng[0]) == after_draw
    # Resume fully available: 1's only neighbor (0) is visited -> dead
    # end, no draw, rng unchanged.
    status = dw.advance_walk_states_device(
        states, csr, G, np.ones(G, np.uint8), L)
    assert status[0] == 0
    assert int(states.rng[0]) == after_draw          # frozen
    assert states.pos[0] == 2                        # truncated path


# ---- satellite 2: cross-backend walk-cache contract -----------------------

@needs_native
def test_walk_cache_cross_backend_hit(tmp_path):
    """Host-populated walk-cache entries HIT for device runs and vice
    versa: both backends key under ONE PRNG family (NATIVE_FAMILY)
    because their packed rows are byte-identical."""
    from g2vec_tpu.cache import NATIVE_FAMILY, WalkCache, walk_cache_key

    r = np.random.default_rng(31)
    G = 40
    src, dst, w = _rand_graph(G, 220, r)
    kw = dict(len_path=5, reps=2, seed=77)
    host_set = hw.generate_path_set_native(src, dst, w, G, **kw)
    dev_set = dw.generate_path_set_device(src, dst, w, G, **kw)
    assert host_set == dev_set           # identical BYTES, not just stats

    key = walk_cache_key(src, dst, w, G, family=NATIVE_FAMILY, **kw)
    # host populates -> device-keyed lookup hits
    cache = WalkCache(str(tmp_path / "walks"))
    cache.store(key, host_set, G, meta={"group": "g"})
    assert cache.load(key) == dev_set
    # device populates -> host-keyed lookup hits
    cache2 = WalkCache(str(tmp_path / "walks2"))
    cache2.store(key, dev_set, G, meta={"group": "g"})
    assert cache2.load(key) == host_set


def test_pipeline_keys_both_backends_under_native_family():
    """The family-selection sites must never split the key space again —
    a spurious DEVICE_FAMILY key would force a miss on backend flip."""
    import re

    for path in ("g2vec_tpu/pipeline.py", "g2vec_tpu/batch/engine.py"):
        text = open(path).read()
        for m in re.finditer(r"family\s*=\s*([A-Z_]+)", text):
            assert m.group(1) == "NATIVE_FAMILY", path


# ---- satellite 3: dense walker retirement ---------------------------------

def test_dense_walker_deprecation_shim():
    """The dense [G, G] paths stay callable (small/test graphs) but warn
    — no caller silently regresses to dense."""
    import jax

    from g2vec_tpu.ops.walker import generate_path_set, random_walks

    adj = np.zeros((4, 4), np.float32)
    adj[0, 1] = adj[1, 2] = adj[2, 3] = 1.0
    with pytest.warns(DeprecationWarning, match="dense"):
        visited = np.asarray(random_walks(
            adj, np.array([0], np.int32), jax.random.key(0), 4))
    assert visited[0, 0] and visited.shape == (1, 4)
    with pytest.warns(DeprecationWarning, match="dense"):
        ps = generate_path_set(adj, jax.random.key(0), len_path=3, reps=1)
    assert len(ps) >= 1

    # The sparse form (neighbor tables) stays warning-free.
    import warnings as _w

    from g2vec_tpu.ops.graph import neighbor_table

    table = neighbor_table(np.array([0, 1], np.int32),
                           np.array([1, 2], np.int32),
                           np.array([1.0, 1.0], np.float32), 3)
    with _w.catch_warnings():
        _w.simplefilter("error", DeprecationWarning)
        generate_path_set(table, jax.random.key(0), len_path=2, reps=1)


# ---- streaming: device backend + fused device feed ------------------------

def _stream_kwargs():
    G = 40
    def grp(seed):
        r = np.random.default_rng(seed)
        E = 240
        return (r.integers(0, G, E).astype(np.int32),
                r.integers(0, G, E).astype(np.int32),
                r.random(E, dtype=np.float32))
    return dict(
        groups=[grp(1), grp(2)], n_genes=G,
        genes=np.array([f"g{i}" for i in range(G)]), hidden=8,
        learning_rate=0.05, max_epochs=2, seed=3, walk_seed=5,
        len_path=5, reps=2, shard_paths=48, compute_dtype="float32")


def test_device_feed_streaming_byte_identical_zero_ring_puts():
    """The fused feed's pinned contract: epoch 0 makes ZERO host-ring
    puts (shards_emitted metric), saves H2D bytes, and the final outputs
    are byte-identical to --walker host (native ring) streaming at the
    same config."""
    from g2vec_tpu.train.stream import train_cbow_streaming
    from g2vec_tpu.utils.metrics_schema import EVENT_SCHEMAS

    kw = _stream_kwargs()
    ref = train_cbow_streaming(**kw)                       # native ring
    dev = train_cbow_streaming(**kw, walker_backend="device")
    fused = train_cbow_streaming(**kw, walker_backend="device",
                                 device_feed=True)
    ref_w = np.asarray(ref.train.w_ih)
    assert ref_w.tobytes() == np.asarray(dev.train.w_ih).tobytes()
    assert ref_w.tobytes() == np.asarray(fused.train.w_ih).tobytes()
    assert ref.gene_freq == fused.gene_freq
    assert ref.n_paths == fused.n_paths

    assert ref.stats.feed_mode == "ring"
    assert ref.stats.shards_emitted > 0
    assert fused.stats.feed_mode == "device"
    assert fused.stats.shards_emitted == 0       # zero host-ring puts
    assert fused.stats.h2d_bytes_saved > 0
    assert fused.stats.sampling_wall_s > 0

    # The stats carry exactly what the pipeline's device_walk metrics
    # event requires (paths_per_s derives from n_paths / sampling wall).
    schema = EVENT_SCHEMAS["device_walk"]
    assert set(schema["required"]) == {"feed_mode", "h2d_bytes_saved",
                                       "paths_per_s"}


def test_device_feed_resume_mid_epoch0_byte_identical(tmp_path):
    """Crash at an epoch-0 checkpoint cut, then resume: the async spool
    must have been drained BEFORE the cursor cut, so the resumed run
    (re-sampling from the cursor, replaying the spool for epochs 1..N)
    reproduces the uninterrupted native run byte for byte."""
    from g2vec_tpu.train.stream import train_cbow_streaming

    kw = _stream_kwargs()
    kw["max_epochs"] = 3
    ref = train_cbow_streaming(**kw)
    ck = dict(checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
    faults.install_plan("stage=stream_ckpt,kind=crash,epoch=0")
    with pytest.raises(faults.InjectedFault):
        train_cbow_streaming(**kw, walker_backend="device",
                             device_feed=True, **ck)
    faults._reset_for_tests()
    resumed = train_cbow_streaming(**kw, walker_backend="device",
                                   device_feed=True, resume=True, **ck)
    assert (np.asarray(ref.train.w_ih).tobytes()
            == np.asarray(resumed.train.w_ih).tobytes())


# ---- satellite 4: device_walk fault drill ---------------------------------

def test_device_walk_fault_mid_scan_clean_recompute():
    """A device_walk fault mid-scan recovers by a clean recompute and the
    recomputed outputs are byte-identical to the no-fault run."""
    from g2vec_tpu.train.stream import train_cbow_streaming

    kw = _stream_kwargs()
    clean = train_cbow_streaming(**kw, walker_backend="device",
                                 device_feed=True)
    faults.install_plan("stage=device_walk,kind=crash,epoch=0")
    try:
        faulted = train_cbow_streaming(**kw, walker_backend="device",
                                       device_feed=True)
    finally:
        faults.install_plan(None)
    assert faulted.stats.device_recomputes == 1
    assert (np.asarray(clean.train.w_ih).tobytes()
            == np.asarray(faulted.train.w_ih).tobytes())
    assert clean.gene_freq == faulted.gene_freq


def test_device_walk_fault_exhausted_retry_raises():
    """Two consecutive faults on the same shard exhaust the single
    clean-recompute retry — the failure must surface, not loop."""
    from g2vec_tpu.train.stream import train_cbow_streaming

    faults.install_plan("stage=device_walk,kind=crash,times=2")
    kw = _stream_kwargs()
    with pytest.raises(faults.InjectedFault):
        train_cbow_streaming(**kw, walker_backend="device",
                             device_feed=True)


# ---- config surface -------------------------------------------------------

def test_device_feed_cli_flags_roundtrip():
    from g2vec_tpu.config import config_from_args

    cfg = config_from_args(
        ["e.tsv", "c.tsv", "n.tsv", "out", "--train-mode", "streaming",
         "--walker-backend", "device", "--device-feed"])
    assert cfg.device_feed and cfg.walker_backend == "device"
    cfg.validate()
