"""L2 tests: gene-order invariant, restriction, label matching."""
import numpy as np
import pytest

from g2vec_tpu.io.readers import ExpressionData, NetworkData
from g2vec_tpu.preprocess import (
    SampleMismatchError,
    edges_to_indices,
    find_common_genes,
    make_gene2idx,
    match_labels,
    restrict_data,
    restrict_network,
)


def _toy():
    data = ExpressionData(
        sample=np.array(["S1", "S2"]),
        gene=np.array(["C", "A", "B", "Z"]),
        expr=np.array([[1, 2, 3, 4], [5, 6, 7, 8]], dtype=np.float32),
    )
    net = NetworkData(
        edges=[("A", "B"), ("B", "C"), ("A", "Q"), ("C", "A")],
        genes={"A", "B", "C", "Q"},
    )
    return data, net


def test_common_genes_sorted():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    assert common == ["A", "B", "C"]  # sorted, Q and Z dropped


def test_restrict_data_reorders_columns():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    r = restrict_data(data, common)
    assert list(r.gene) == ["A", "B", "C"]
    np.testing.assert_array_equal(r.expr, [[2, 3, 1], [6, 7, 5]])


def test_restrict_network_drops_noncommon_keeps_direction():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    r = restrict_network(net, common)
    assert r.edges == [("A", "B"), ("B", "C"), ("C", "A")]
    assert r.genes == {"A", "B", "C"}  # whole common set (ref quirk)


def test_edges_to_indices():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    rnet = restrict_network(net, common)
    g2i = make_gene2idx(np.array(common))
    src, dst = edges_to_indices(rnet, g2i)
    np.testing.assert_array_equal(src, [0, 1, 2])
    np.testing.assert_array_equal(dst, [1, 2, 0])


def test_match_labels_ok_and_missing():
    labels = match_labels({"S1": 0, "S2": 1}, np.array(["S1", "S2"]))
    np.testing.assert_array_equal(labels, [0, 1])
    with pytest.raises(SampleMismatchError, match="S3"):
        match_labels({"S1": 0}, np.array(["S1", "S3"]))


def test_synthetic_dataset_shapes(small_dataset, small_spec):
    expression, clinical, network, membership = small_dataset
    common = find_common_genes(network.genes, expression.gene)
    # all module genes survive the intersection; expr/net-only genes don't
    for mod in ("good", "poor", "shared"):
        assert set(membership[mod]) <= set(common)
    assert not any(g.startswith("XONL") for g in common)
    assert not any(g.startswith("NONL") for g in common)
    labels = match_labels(clinical, expression.sample)
    assert (labels == 0).sum() == small_spec.n_good
    assert (labels == 1).sum() == small_spec.n_poor
