"""L2 tests: gene-order invariant, restriction, label matching."""
import numpy as np
import pytest

from g2vec_tpu.io.readers import ExpressionData, NetworkData
from g2vec_tpu.preprocess import (
    SampleMismatchError,
    edges_to_indices,
    find_common_genes,
    make_gene2idx,
    match_labels,
    restrict_data,
    restrict_network,
)


def _toy():
    data = ExpressionData(
        sample=np.array(["S1", "S2"]),
        gene=np.array(["C", "A", "B", "Z"]),
        expr=np.array([[1, 2, 3, 4], [5, 6, 7, 8]], dtype=np.float32),
    )
    net = NetworkData(
        edges=[("A", "B"), ("B", "C"), ("A", "Q"), ("C", "A")],
        genes={"A", "B", "C", "Q"},
    )
    return data, net


def test_common_genes_sorted():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    assert common == ["A", "B", "C"]  # sorted, Q and Z dropped


def test_restrict_data_reorders_columns():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    r = restrict_data(data, common)
    assert list(r.gene) == ["A", "B", "C"]
    np.testing.assert_array_equal(r.expr, [[2, 3, 1], [6, 7, 5]])


def test_restrict_network_drops_noncommon_keeps_direction():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    r = restrict_network(net, common)
    assert r.edges == [("A", "B"), ("B", "C"), ("C", "A")]
    assert r.genes == {"A", "B", "C"}  # whole common set (ref quirk)


def test_edges_to_indices():
    data, net = _toy()
    common = find_common_genes(net.genes, data.gene)
    rnet = restrict_network(net, common)
    g2i = make_gene2idx(np.array(common))
    src, dst = edges_to_indices(rnet, g2i)
    np.testing.assert_array_equal(src, [0, 1, 2])
    np.testing.assert_array_equal(dst, [1, 2, 0])


def test_match_labels_ok_and_missing():
    labels = match_labels({"S1": 0, "S2": 1}, np.array(["S1", "S2"]))
    np.testing.assert_array_equal(labels, [0, 1])
    with pytest.raises(SampleMismatchError, match="S3"):
        match_labels({"S1": 0}, np.array(["S1", "S3"]))


def _labeled(n_good=10, n_poor=8):
    n = n_good + n_poor
    rng = np.random.default_rng(0)
    data = ExpressionData(
        sample=np.array([f"S{i:02d}" for i in range(n)]),
        gene=np.array(["A", "B", "C"]),
        expr=rng.normal(size=(n, 3)).astype(np.float32),
    )
    data.label = np.array([0] * n_good + [1] * n_poor)
    return data


def test_bootstrap_resample_deterministic_and_stratified():
    from g2vec_tpu.preprocess import subsample_patients

    data = _labeled()
    a = subsample_patients(data, 1.0, seed=3, with_replacement=True)
    b = subsample_patients(data, 1.0, seed=3, with_replacement=True)
    np.testing.assert_array_equal(a.sample, b.sample)
    np.testing.assert_array_equal(a.expr, b.expr)
    # Stratified: per-class draw counts equal the class sizes at f=1.0.
    assert (a.label == 0).sum() == 10 and (a.label == 1).sum() == 8
    # With replacement: some patient must repeat at full fraction
    # (P(no repeat) is vanishingly small), and rows stay sorted by
    # original position so duplicates are adjacent row copies.
    assert len(set(a.sample)) < len(a.sample)
    order = np.argsort(
        [int(s[1:]) for s in a.sample], kind="stable")
    np.testing.assert_array_equal(order, np.arange(len(a.sample)))
    c = subsample_patients(data, 1.0, seed=4, with_replacement=True)
    assert list(c.sample) != list(a.sample)


def test_bootstrap_resample_keeps_two_distinct_per_class():
    from g2vec_tpu.preprocess import subsample_patients

    data = _labeled(n_good=2, n_poor=2)
    # Any seed: the redraw loop guarantees >=2 distinct patients per
    # class even when a 2-row class would often draw one patient twice.
    for seed in range(20):
        r = subsample_patients(data, 1.0, seed, with_replacement=True)
        for cls in (0, 1):
            assert len(set(r.sample[r.label == cls])) >= 2, seed


def test_fold_assignments_partition_and_stratification():
    from g2vec_tpu.preprocess import fold_assignments

    data = _labeled(n_good=10, n_poor=8)
    folds = fold_assignments(data.label, 3, seed=5)
    # A partition: every patient lands in exactly one fold.
    assert folds.min() == 0 and folds.max() == 2
    # Stratified: per-class fold sizes differ by at most one.
    for cls in (0, 1):
        sizes = [((folds == k) & (data.label == cls)).sum()
                 for k in range(3)]
        assert max(sizes) - min(sizes) <= 1
    np.testing.assert_array_equal(
        folds, fold_assignments(data.label, 3, seed=5))
    assert list(folds) != list(fold_assignments(data.label, 3, seed=6))


def test_fold_assignments_rejects_thin_classes():
    from g2vec_tpu.preprocess import fold_assignments

    data = _labeled(n_good=10, n_poor=2)
    with pytest.raises(ValueError, match="class 1"):
        fold_assignments(data.label, 3, seed=0)
    with pytest.raises(ValueError, match="n_folds"):
        fold_assignments(data.label, 1, seed=0)


def test_fold_cohort_is_complement_row_subset():
    from g2vec_tpu.preprocess import fold_assignments, fold_cohort

    data = _labeled()
    folds = fold_assignments(data.label, 3, seed=5)
    for k in range(3):
        cohort = fold_cohort(data, 3, k, seed=5)
        want = data.sample[folds != k]
        np.testing.assert_array_equal(cohort.sample, want)
        np.testing.assert_array_equal(cohort.expr,
                                      data.expr[folds != k])
    with pytest.raises(ValueError, match="fold"):
        fold_cohort(data, 3, 3, seed=5)


def test_permute_labels_seeded_and_pure():
    from g2vec_tpu.preprocess import permute_labels

    data = _labeled()
    before = data.label.copy()
    a = permute_labels(data.label, 7)
    np.testing.assert_array_equal(data.label, before)  # input untouched
    np.testing.assert_array_equal(a, permute_labels(data.label, 7))
    assert sorted(a) == sorted(before)
    assert list(a) != list(before)


def test_synthetic_dataset_shapes(small_dataset, small_spec):
    expression, clinical, network, membership = small_dataset
    common = find_common_genes(network.genes, expression.gene)
    # all module genes survive the intersection; expr/net-only genes don't
    for mod in ("good", "poor", "shared"):
        assert set(membership[mod]) <= set(common)
    assert not any(g.startswith("XONL") for g in common)
    assert not any(g.startswith("NONL") for g in common)
    labels = match_labels(clinical, expression.sample)
    assert (labels == 0).sum() == small_spec.n_good
    assert (labels == 1).sum() == small_spec.n_poor
