"""Hybrid multi-slice mesh planning (parallel/distributed.plan_hybrid_mesh).

VERDICT item 5: the ICI/DCN axis assignment had no regression test — a
future refactor could silently put the per-matmul model all-reduce on DCN
(an order-of-magnitude collective slowdown on a real multi-slice pod) and
every CPU test would still pass. Here a 2-slice topology is faked with
mock devices carrying ``slice_index`` and the planning contract is pinned:
model stays inside a slice (ICI), data crosses slices (DCN), and a data
axis that cannot divide over the slices is a loud config error.
"""
from types import SimpleNamespace

import pytest

from g2vec_tpu.parallel.distributed import plan_hybrid_mesh


def _fake_pod(n_slices: int, per_slice: int):
    """Mock device objects: the only attribute the planner reads is
    slice_index (absent on CPU/older libtpu — covered below)."""
    return [SimpleNamespace(slice_index=s, id=s * per_slice + i)
            for s in range(n_slices) for i in range(per_slice)]


def test_two_slice_assignment_model_on_ici():
    # 2 slices x 4 chips, --mesh 4x2: the model axis (2) must stay whole
    # inside a slice; the data axis (4) factors as 2 slices x 2 chips.
    devices = _fake_pod(2, 4)
    per_slice, dcn = plan_hybrid_mesh(devices, data=4, model=2)
    assert per_slice == (2, 2)
    # DCN mesh shards ONLY the data axis — a model entry > 1 here would
    # put the per-matmul all-reduce on the slow cross-slice fabric.
    assert dcn == (2, 1)


def test_four_slice_pure_dp():
    devices = _fake_pod(4, 2)
    per_slice, dcn = plan_hybrid_mesh(devices, data=8, model=1)
    assert per_slice == (2, 1)
    assert dcn == (4, 1)


def test_divisibility_error_names_the_constraint():
    devices = _fake_pod(2, 4)
    with pytest.raises(ValueError, match="divisible by the slice count 2"):
        plan_hybrid_mesh(devices, data=3, model=2)  # 3 % 2 != 0


def test_single_slice_returns_none():
    # One slice -> no hybrid plan; the caller takes the ICI-contiguous
    # create_device_mesh path.
    assert plan_hybrid_mesh(_fake_pod(1, 8), data=4, model=2) is None


def test_no_slice_metadata_returns_none():
    # CPU devices / older libtpu expose no slice_index at all; getattr
    # defaults every device to slice 0 -> single-slice path.
    devices = [SimpleNamespace(id=i) for i in range(8)]
    assert plan_hybrid_mesh(devices, data=8, model=1) is None


def test_real_cpu_devices_take_single_slice_path():
    # End-to-end on the 8 virtual CPU devices: make_global_mesh must
    # build a working ('data','model') mesh through the non-hybrid
    # branch (CPU devices carry no slice metadata).
    import jax

    from g2vec_tpu.parallel.distributed import make_global_mesh

    ctx = make_global_mesh((4, 2))
    assert ctx.mesh is not None
    assert dict(zip(ctx.mesh.axis_names, ctx.mesh.devices.shape)) == {
        "data": 4, "model": 2}
    assert plan_hybrid_mesh(jax.devices(), 4, 2) is None
