"""Fleet e2e on virtual devices: the PR-2 acceptance matrix.

Two true multi-process scenarios through the real CLI launcher
(``--fleet-size``), each a full ``python -m g2vec_tpu`` fleet on CPU
virtual devices:

1. SIGKILL of rank 1 at a chosen epoch (the epoch-5 checkpoint-finalize
   boundary — the save is durable on every rank when the kill lands) →
   the supervisor detects the death, re-plans the 4-device ``4x1`` mesh to
   the surviving 2 devices (``2x1``), relaunches with ``--resume``, and
   the run completes with final vectors BIT-IDENTICAL to an uninterrupted
   fleet run: the walks re-execute bit-identically under any mesh (global
   stream identities), the restored trainer state reshards at load, and
   the degraded ``2x1`` mesh matches the per-rank local mesh of the
   2-rank fleet, so even retrained epochs reproduce the same arithmetic.

2. A ``process=1,kind=stall`` fault at the allgather seam → rank 0's
   watchdog raises PeerTimeoutError NAMING rank 1 within the configured
   deadline instead of blocking forever; the whole fleet fails fast.

Tier-1 via the ``fleet`` marker (pytest -m fleet selects just this
matrix); ~7 child interpreters total, so the configs stay tiny.
"""
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.fleet


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _env():
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("G2VEC_", "XLA_", "TPU_", "LIBTPU",
                                "PJRT_"))}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _cli(tsv_paths, result, ckpt, liveness, extra=()):
    args = [sys.executable, "-m", "g2vec_tpu",
            tsv_paths["expression"], tsv_paths["clinical"],
            tsv_paths["network"], result,
            "-p", "8", "-r", "2", "-s", "16", "-e", "12", "-l", "0.002",
            "-n", "5", "--seed", "0", "--compute-dtype", "float32",
            "--platform", "cpu", "--mesh", "4x1", "--fleet-size", "2",
            "--checkpoint-dir", ckpt, "--checkpoint-every", "3",
            "--checkpoint-layout", "sharded",
            "--fleet-liveness-dir", liveness,
            "--fleet-watchdog-deadline", "10",
            "--fleet-heartbeat-interval", "0.2"]
    return args + list(extra)


def test_fleet_sigkill_rank1_degraded_resume_bit_identical(tsv_paths,
                                                           tmp_path):
    env = _env()
    clean = subprocess.run(
        _cli(tsv_paths, str(tmp_path / "a"), str(tmp_path / "cka"),
             str(tmp_path / "La"),
             extra=["--supervise-retries", "0"]),
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert clean.returncode == 0, \
        f"stdout:{clean.stdout[-800:]}\nstderr:{clean.stderr[-2500:]}"

    mj = str(tmp_path / "m.jsonl")
    faulted = subprocess.run(
        _cli(tsv_paths, str(tmp_path / "b"), str(tmp_path / "ckb"),
             str(tmp_path / "Lb"),
             extra=["--metrics-jsonl", mj,
                    "--supervise-retries", "2",
                    "--supervise-backoff", "0.01",
                    "--fault-plan",
                    "process=1,stage=checkpoint_finalize,epoch=5,"
                    "kind=sigkill"]),
        capture_output=True, text=True, timeout=480, env=env, cwd=REPO)
    assert faulted.returncode == 0, \
        f"stdout:{faulted.stdout[-800:]}\nstderr:{faulted.stderr[-2500:]}"
    assert "re-planning mesh 4x1 -> 2x1" in faulted.stderr

    # Final vectors (and every other output) bit-identical to the
    # uninterrupted fleet run.
    for suffix in ("_vectors.txt", "_lgroups.txt", "_biomarkers.txt"):
        with open(str(tmp_path / "a") + suffix, "rb") as fa, \
                open(str(tmp_path / "b") + suffix, "rb") as fb:
            assert fa.read() == fb.read(), suffix

    # The metrics stream carries the fleet recovery story.
    with open(mj) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    names = [e["event"] for e in events]
    assert "fleet_peer_death" in names and "fleet_replan" in names
    assert "fleet_done" in names
    death = next(e for e in events if e["event"] == "fleet_peer_death")
    assert 1 in death["dead_ranks"]
    assert death["classified"] == "retryable"
    replan = next(e for e in events if e["event"] == "fleet_replan")
    assert replan["old_mesh"] == [4, 1] and replan["new_mesh"] == [2, 1]
    assert replan["surviving_ranks"] == 1
    relaunch = next(e for e in events if e["event"] == "fleet_launch")
    assert relaunch["resume"] is True and relaunch["ranks"] == 1
    # Heartbeats made it into the coordinator's stream.
    assert any(e["event"] == "heartbeat" for e in events)


def test_fleet_stall_at_allgather_names_rank_1(tsv_paths, tmp_path):
    liveness = str(tmp_path / "L")
    t0 = time.time()
    proc = subprocess.run(
        _cli(tsv_paths, str(tmp_path / "o"), str(tmp_path / "ck"), liveness,
             extra=["--supervise-retries", "0",
                    "--fleet-watchdog-deadline", "3",
                    "--fault-plan",
                    "process=1,stage=allgather,kind=stall,seconds=90"]),
        capture_output=True, text=True, timeout=180, env=_env(), cwd=REPO)
    wall = time.time() - t0
    assert proc.returncode != 0
    # Fast, named failure: nothing waited out the 90s stall.
    assert wall < 75, wall
    rank0_err = os.path.join(liveness, "logs-attempt0", "rank0.err")
    with open(rank0_err) as f:
        err = f.read()
    assert "PeerTimeoutError" in err
    assert "missing rank(s): [1]" in err
    # Liveness attribution saw a live-but-stalled peer, not a dead one.
    assert "rank 1" in err
    # The launcher relayed the named failure to its own stderr.
    assert "PeerTimeoutError" in proc.stderr
