"""Multi-host runtime helpers (parallel/distributed.py).

True multi-process runs need multiple hosts; here we validate everything
that can be validated in-process: global-mesh construction over the 8
virtual CPU devices, shape/divisibility errors, env-var plumbing, and the
coordinator gate. SURVEY.md §4 item 5 is the testing strategy.
"""
import numpy as np
import pytest

from g2vec_tpu.parallel import distributed as dist
from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def test_make_global_mesh_shapes():
    ctx = dist.make_global_mesh((4, 2))
    assert ctx.mesh.shape[DATA_AXIS] == 4
    assert ctx.mesh.shape[MODEL_AXIS] == 2
    assert ctx.n_devices == 8


def test_make_global_mesh_wrong_count():
    with pytest.raises(ValueError, match="needs 6 devices"):
        dist.make_global_mesh((3, 2))


def test_global_mesh_trains(rng):
    """A train step over the global mesh — same path dryrun_multichip uses."""
    from g2vec_tpu.train.trainer import train_cbow

    paths = (rng.random((48, 40)) < 0.2).astype(np.int8)
    labels = (rng.random(48) < 0.5).astype(np.int32)
    ctx = dist.make_global_mesh((2, 4))
    res = train_cbow(paths, labels, hidden=16, learning_rate=0.01,
                     max_epochs=2, compute_dtype="float32", seed=0,
                     mesh_ctx=ctx)
    assert res.w_ih.shape == (40, 16)
    assert np.isfinite(res.w_ih).all()


def test_initialize_env_plumbing(monkeypatch):
    """initialize() must read G2VEC_* env vars; we intercept the jax call."""
    import jax

    captured = {}

    def fake_init(**kwargs):
        captured.update(kwargs)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("G2VEC_COORDINATOR", "10.0.0.1:1234")
    monkeypatch.setenv("G2VEC_PROCESS_ID", "3")
    monkeypatch.setenv("G2VEC_NUM_PROCESSES", "8")
    dist.initialize()
    assert captured == {"coordinator_address": "10.0.0.1:1234",
                        "process_id": 3, "num_processes": 8}
    # Idempotent: a second call must not re-initialize.
    captured.clear()
    dist.initialize()
    assert captured == {}
    monkeypatch.setattr(dist, "_initialized", False)


def test_process_info_and_coordinator_single_process():
    info = dist.process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert dist.is_coordinator()


def test_cli_flags_parse():
    from g2vec_tpu.config import config_from_args

    cfg = config_from_args([
        "e.txt", "c.txt", "n.txt", "out", "--distributed",
        "--coordinator", "host:99", "--process-id", "1",
        "--num-processes", "4", "--mesh", "2x2"])
    assert cfg.distributed and cfg.coordinator == "host:99"
    assert cfg.process_id == 1 and cfg.num_processes == 4
    assert cfg.mesh_shape == (2, 2)


# --------------------------------------------- fetch_global (PR-2 satellite)

class _NonAddressable:
    """Stand-in for a jax.Array whose shards live on other processes'
    devices — unconstructible in one process, so only the attribute the
    router consults is modelled."""

    is_fully_addressable = False


@pytest.fixture(autouse=True)
def _inert_fleet():
    from g2vec_tpu.resilience import fleet

    fleet.configure()
    yield
    fleet.configure()


def test_fetch_global_sharded_array_virtual_devices():
    """Fully-addressable path on a REAL global array sharded over the 8
    virtual devices — the exact layout a single-host mesh run fetches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ctx = dist.make_global_mesh((4, 2))
    x = np.arange(32.0, dtype=np.float32).reshape(8, 4)
    arr = jax.device_put(x, NamedSharding(ctx.mesh, P(DATA_AXIS, MODEL_AXIS)))
    np.testing.assert_array_equal(dist.fetch_global(arr), x)


def test_fetch_global_non_addressable_routes_to_allgather(monkeypatch):
    from jax.experimental import multihost_utils

    sentinel = np.arange(6.0)
    calls = {}

    def fake_allgather(a, tiled=False):
        calls["tiled"] = tiled
        return sentinel

    monkeypatch.setattr(multihost_utils, "process_allgather", fake_allgather)
    out = dist.fetch_global(_NonAddressable())
    assert np.array_equal(out, sentinel)
    assert calls["tiled"] is True


def test_fetch_global_watchdog_names_the_hang(monkeypatch):
    """A peer that never joins the allgather must surface as a named
    PeerTimeoutError within the configured deadline, not an eternal block."""
    import time

    from jax.experimental import multihost_utils

    from g2vec_tpu.resilience import fleet

    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda a, tiled=False: time.sleep(30))
    fleet.configure(watchdog_deadline=0.3)
    t0 = time.time()
    with pytest.raises(fleet.PeerTimeoutError, match="fetch_global"):
        dist.fetch_global(_NonAddressable())
    assert time.time() - t0 < 5.0


# ---------------------------- sharded_native_path_set (PR-2 satellite)

def test_sharded_native_missing_toolchain_fails_every_rank(monkeypatch):
    """One host without g++ must fail with the clear cross-rank message —
    the availability agreement runs BEFORE any row gather, so no rank can
    wedge a half-entered collective. The agreement itself is symmetric
    (every rank computes the same gathered vector), so asserting rank 0's
    error text pins the message every rank raises."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    gathered = {}

    def fake_host_allgather(name, arr):
        gathered[name] = np.asarray(arr)
        return np.array([[True], [False]])

    monkeypatch.setattr(dist, "host_allgather", fake_host_allgather)
    with pytest.raises(RuntimeError, match=r"process\(es\) \[1\]"):
        dist.sharded_native_path_set(
            np.zeros(2, np.int32), np.ones(2, np.int32),
            np.ones(2, np.float32), 4, len_path=3, reps=1, seed=0)
    # The gate really consulted the collective agreement, not a local probe.
    assert "native_avail" in gathered


def test_host_allgather_single_process_identity():
    arr = np.arange(6.0).reshape(2, 3)
    out = dist.host_allgather("t", arr)
    assert out.shape == (1, 2, 3) and np.array_equal(out[0], arr)
