"""Multi-host runtime helpers (parallel/distributed.py).

True multi-process runs need multiple hosts; here we validate everything
that can be validated in-process: global-mesh construction over the 8
virtual CPU devices, shape/divisibility errors, env-var plumbing, and the
coordinator gate. SURVEY.md §4 item 5 is the testing strategy.
"""
import numpy as np
import pytest

from g2vec_tpu.parallel import distributed as dist
from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def test_make_global_mesh_shapes():
    ctx = dist.make_global_mesh((4, 2))
    assert ctx.mesh.shape[DATA_AXIS] == 4
    assert ctx.mesh.shape[MODEL_AXIS] == 2
    assert ctx.n_devices == 8


def test_make_global_mesh_wrong_count():
    with pytest.raises(ValueError, match="needs 6 devices"):
        dist.make_global_mesh((3, 2))


def test_global_mesh_trains(rng):
    """A train step over the global mesh — same path dryrun_multichip uses."""
    from g2vec_tpu.train.trainer import train_cbow

    paths = (rng.random((48, 40)) < 0.2).astype(np.int8)
    labels = (rng.random(48) < 0.5).astype(np.int32)
    ctx = dist.make_global_mesh((2, 4))
    res = train_cbow(paths, labels, hidden=16, learning_rate=0.01,
                     max_epochs=2, compute_dtype="float32", seed=0,
                     mesh_ctx=ctx)
    assert res.w_ih.shape == (40, 16)
    assert np.isfinite(res.w_ih).all()


def test_initialize_env_plumbing(monkeypatch):
    """initialize() must read G2VEC_* env vars; we intercept the jax call."""
    import jax

    captured = {}

    def fake_init(**kwargs):
        captured.update(kwargs)

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setenv("G2VEC_COORDINATOR", "10.0.0.1:1234")
    monkeypatch.setenv("G2VEC_PROCESS_ID", "3")
    monkeypatch.setenv("G2VEC_NUM_PROCESSES", "8")
    dist.initialize()
    assert captured == {"coordinator_address": "10.0.0.1:1234",
                        "process_id": 3, "num_processes": 8}
    # Idempotent: a second call must not re-initialize.
    captured.clear()
    dist.initialize()
    assert captured == {}
    monkeypatch.setattr(dist, "_initialized", False)


def test_process_info_and_coordinator_single_process():
    info = dist.process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert dist.is_coordinator()


def test_cli_flags_parse():
    from g2vec_tpu.config import config_from_args

    cfg = config_from_args([
        "e.txt", "c.txt", "n.txt", "out", "--distributed",
        "--coordinator", "host:99", "--process-id", "1",
        "--num-processes", "4", "--mesh", "2x2"])
    assert cfg.distributed and cfg.coordinator == "host:99"
    assert cfg.process_id == 1 and cfg.num_processes == 4
    assert cfg.mesh_shape == (2, 2)
