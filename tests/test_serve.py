"""Serve subsystem (serve/): admission control, tenant fairness, job
joining, served-vs-solo byte parity, job-scoped metrics, cache_stats,
and the daemon lifecycle (warm-latency smoke, supervisor SIGKILL
re-queue) over real subprocesses.

The daemon's contract mirrors the batch engine's: residency is a pure
wall-clock optimization — a served job's output files must be byte-for-
byte what the same config produces solo. The in-process tests drive
ServeDaemon.admit/step directly (no sockets, no threads) so scheduling
decisions are deterministic and assertable; the subprocess tests cover
the socket front-end, the watchdog, and the crash-recovery journal.
"""
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from g2vec_tpu.resilience import faults

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


@pytest.fixture(scope="module")
def tsv_paths(tmp_path_factory):
    from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

    spec = SyntheticSpec(n_good=24, n_poor=20, module_size=12,
                         n_background=24, n_expr_only=4, n_net_only=4,
                         module_chords=2, background_edges=40, seed=7)
    out = tmp_path_factory.mktemp("syn")
    return write_synthetic_tsv(spec, str(out))


def _job(tsv_paths, tmp_path, name, **overrides):
    job = dict(
        expression_file=tsv_paths["expression"],
        clinical_file=tsv_paths["clinical"],
        network_file=tsv_paths["network"],
        result_name=os.path.join(str(tmp_path), "out", name),
        lenPath=8, numRepetition=2, sizeHiddenlayer=16, epoch=30,
        learningRate=0.05, numBiomarker=5, compute_dtype="float32",
        walker_backend="device")
    job.update(overrides)
    return job


def _daemon(tmp_path, **opt_overrides):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=os.path.join(str(tmp_path), "serve.sock"),
        state_dir=os.path.join(str(tmp_path), "state"), **opt_overrides)
    return ServeDaemon(opts, console=lambda s: None)


def _result(daemon, job_id):
    path = os.path.join(daemon.opts.state_dir, "results", f"{job_id}.json")
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------

def test_queue_full_rejects_with_structured_error(tsv_paths, tmp_path):
    d = _daemon(tmp_path, queue_depth=2)
    try:
        ok1 = d.admit({"tenant": "a",
                       "job": _job(tsv_paths, tmp_path, "a1")})
        ok2 = d.admit({"tenant": "b",
                       "job": _job(tsv_paths, tmp_path, "b1")})
        assert ok1["event"] == ok2["event"] == "accepted"
        rej = d.admit({"tenant": "c",
                       "job": _job(tsv_paths, tmp_path, "c1")})
        assert rej["event"] == "rejected"
        assert rej["error"] == "queue_full"
        assert rej["queue_depth"] == 2
        # Rejected jobs are NOT journaled — a restart must not resurrect
        # work the client was told to resubmit.
        journaled = os.listdir(os.path.join(d.opts.state_dir, "jobs"))
        assert len(journaled) == 2
    finally:
        d.close()


def test_bad_jobs_reject_at_admission_naming_the_problem(
        tsv_paths, tmp_path):
    d = _daemon(tmp_path)
    try:
        for payload, needle in [
            ({"job": {**_job(tsv_paths, tmp_path, "x"),
                      "cache_dir": "/tmp/x"}}, "cache_dir"),
            ({"job": {**_job(tsv_paths, tmp_path, "x"),
                      "mesh_shape": "2x1"}}, "mesh_shape"),
            ({"job": {**_job(tsv_paths, tmp_path, "x"),
                      "learningRate": -1}}, "learningRate"),
            ({"job": {**_job(tsv_paths, tmp_path, "x"),
                      "variants": [{"train_seed": -2}]}}, "train_seed"),
            ({"job": {**_job(tsv_paths, tmp_path, "x"),
                      "variants": [], }}, "variants"),
            ({"job": {**_job(tsv_paths, tmp_path, "x"),
                      "variants": [{}], "seeds": 2}}, "seeds"),
            ({"job": "nope"}, "object"),
            ({"tenant": "", "job": _job(tsv_paths, tmp_path, "x")},
             "tenant"),
        ]:
            rej = d.admit(payload)
            assert rej["event"] == "rejected", payload
            assert rej["error"] == "bad_job"
            assert needle in rej["detail"], (needle, rej["detail"])
        assert d._queue.depth() == 0
    finally:
        d.close()


def test_requeue_bypasses_quota_and_shed_but_not_queue_full(
        tsv_paths, tmp_path):
    # A failover resubmission (requeue=True + the replica's relay
    # token, set only by the router's journal migration) already paid
    # the SLO gates at first admission — the client holds an ack, so
    # shedding or rate-limiting it now would turn a replica death into
    # a lost job. Capacity is a real resource bound though: queue_full
    # must still apply.
    d = _daemon(tmp_path, tenant_quotas="gold:0.001:1", shed=True,
                queue_depth=3)
    try:
        tok = d._relay_token
        ok = d.admit({"tenant": "gold",
                      "job": _job(tsv_paths, tmp_path, "q1")})
        assert ok["event"] == "accepted"
        # Bucket drained (burst 1, ~no refill): normal submit limited…
        rej = d.admit({"tenant": "gold",
                       "job": _job(tsv_paths, tmp_path, "q2")})
        assert rej["error"] == "tenant_quota"
        # …and so is a FORGED requeue — the flag alone (which any
        # client holding the shared fleet auth_token can send) must not
        # open the gate; only the state-dir relay token does.
        forged = d.admit({"tenant": "gold", "requeue": True,
                          "relay_token": "not-the-token",
                          "job": _job(tsv_paths, tmp_path, "q2")})
        assert forged["error"] == "tenant_quota"
        # …but the migration requeue of already-acked work is not.
        re1 = d.admit({"tenant": "gold", "requeue": True,
                       "relay_token": tok,
                       "job": _job(tsv_paths, tmp_path, "q2")})
        assert re1["event"] == "accepted"
        # Shed gate: with 10 s/job evidence and a non-empty queue, a
        # 1 s-deadline submit is shed — unless it is a (proven) requeue.
        with d._lock:
            d._service_times.append(10.0)
        rej2 = d.admit({"tenant": "silver", "deadline_s": 1.0,
                        "job": _job(tsv_paths, tmp_path, "s1")})
        assert rej2["error"] == "shed"
        forged2 = d.admit({"tenant": "silver", "deadline_s": 1.0,
                           "requeue": True,
                           "job": _job(tsv_paths, tmp_path, "s1")})
        assert forged2["error"] == "shed"
        re2 = d.admit({"tenant": "silver", "deadline_s": 1.0,
                       "requeue": True, "relay_token": tok,
                       "job": _job(tsv_paths, tmp_path, "s1b")})
        assert re2["event"] == "accepted"
        # Queue now holds 3 of 3: even a requeue is refused on capacity
        # (the router leaves the entry journaled for corpse recovery).
        full = d.admit({"tenant": "gold", "requeue": True,
                        "relay_token": tok,
                        "job": _job(tsv_paths, tmp_path, "q3")})
        assert full["error"] == "queue_full"
    finally:
        d.close()


def test_requeue_preserves_deadline_clock(tsv_paths, tmp_path):
    # submitted_at pass-through is honored ONLY with a relay-token-
    # proven requeue: migration must not reset a deadline clock, but an
    # ordinary client — including one waving the requeue flag, which
    # the shared fleet auth_token cannot distinguish from the router —
    # must not be able to back- or forward-date its own deadline.
    d = _daemon(tmp_path)
    try:
        t0 = time.time()
        jobs_dir = os.path.join(d.opts.state_dir, "jobs")
        for payload in (
            {"tenant": "a", "submitted_at": 123.0,
             "job": _job(tsv_paths, tmp_path, "n1")},
            {"tenant": "a", "submitted_at": 123.0, "requeue": True,
             "job": _job(tsv_paths, tmp_path, "n1b")},
            {"tenant": "a", "submitted_at": 123.0, "requeue": True,
             "relay_token": "forged",
             "job": _job(tsv_paths, tmp_path, "n1c")},
        ):
            ok = d.admit(payload)
            assert ok["event"] == "accepted"
            with open(os.path.join(jobs_dir,
                                   ok["job_id"] + ".json")) as f:
                rec = json.load(f)
            assert rec["submitted_at"] >= t0
            # Relay metadata never reaches the journal — a later
            # failover resubmit of this record must not replay a
            # client-chosen clock.
            for k in ("requeue", "submitted_at", "relay_token"):
                assert k not in rec["payload"]
        re1 = d.admit({"tenant": "a", "submitted_at": 123.0,
                       "requeue": True, "relay_token": d._relay_token,
                       "job": _job(tsv_paths, tmp_path, "n2")})
        assert re1["event"] == "accepted"
        with open(os.path.join(jobs_dir, re1["job_id"] + ".json")) as f:
            rec2 = json.load(f)
        assert rec2["submitted_at"] == pytest.approx(123.0)
    finally:
        d.close()


def test_config_from_job_whitelist():
    from g2vec_tpu.config import SERVE_JOB_KEYS, config_from_job

    base = {"expression_file": "E", "clinical_file": "C",
            "network_file": "N", "result_name": "R"}
    cfg = config_from_job({**base, "epoch": 40, "train_seed": 7})
    assert (cfg.epoch, cfg.train_seed) == (40, 7)
    # Infrastructure fields are not job-settable, by whitelist.
    for infra in ("cache_dir", "supervise", "fleet_size", "distributed",
                  "checkpoint_dir", "manifest", "batch_seeds", "platform"):
        assert infra not in SERVE_JOB_KEYS
        with pytest.raises(ValueError, match=infra):
            config_from_job({**base, infra: 1})
    with pytest.raises(ValueError, match="result_name"):
        config_from_job({k: v for k, v in base.items()
                         if k != "result_name"})


# ---------------------------------------------------------------------------
# Scheduling: fairness + shape-compatible joining + parity
# ---------------------------------------------------------------------------

def test_fair_queue_round_robin_and_take_compatible():
    from g2vec_tpu.serve.daemon import QueueFull, ServeJob, _FairQueue

    def mk(tenant, i, key=("k",)):
        j = ServeJob(job_id=f"{tenant}{i}", tenant=tenant, cfg=None,
                     variants=[], raw={}, submitted_at=float(i))
        j.join_key = key
        return j

    q = _FairQueue(depth=8)
    for j in [mk("a", 0), mk("a", 1), mk("a", 2), mk("b", 0), mk("c", 0)]:
        q.push(j)
    order = [q.pop(timeout=0).job_id for _ in range(5)]
    # Round-robin across tenants: a burst from 'a' cannot starve b/c.
    assert order == ["a0", "b0", "c0", "a1", "a2"]
    assert q.pop(timeout=0) is None

    q = _FairQueue(depth=3)
    q.push(mk("a", 0))
    q.push(mk("a", 1, key=("other",)))
    q.push(mk("b", 0))
    with pytest.raises(QueueFull):
        q.push(mk("c", 9))
    first = q.pop(timeout=0)
    taken = q.take_compatible(first.join_key, limit=4)
    # Only the compatible job joins; the other stays queued in order.
    assert [j.job_id for j in taken] == ["b0"]
    assert q.pop(timeout=0).job_id == "a1"


def test_join_compatible_jobs_parity_and_job_metrics(tsv_paths, tmp_path):
    """Two shape-compatible jobs from different tenants coalesce into ONE
    engine batch (one walk product set, one vmapped bucket); an
    incompatible job runs in its own batch; every served output is
    byte-identical to its solo twin; every lane event in the daemon
    stream carries job_id."""
    mj = os.path.join(str(tmp_path), "serve-metrics.jsonl")
    d = _daemon(tmp_path, metrics_jsonl=mj, max_join=4)
    try:
        a = d.admit({"tenant": "alice",
                     "job": {**_job(tsv_paths, tmp_path, "a"),
                             "variants": [{"name": "v0", "train_seed": 1}]}})
        b = d.admit({"tenant": "bob",
                     "job": {**_job(tsv_paths, tmp_path, "b"),
                             "variants": [{"name": "v0", "train_seed": 2}]}})
        c = d.admit({"tenant": "alice",
                     "job": {**_job(tsv_paths, tmp_path, "c",
                                    sizeHiddenlayer=24)}})
        assert {a["event"], b["event"], c["event"]} == {"accepted"}
        assert d.step() == 2          # a + b joined (same join key)
        assert d.step() == 1          # c alone (different trainer shape)
        ra, rb, rc = (_result(d, r["job_id"]) for r in (a, b, c))
        assert ra["batch"] == rb["batch"] and ra["joined_jobs"] == 2
        assert rc["joined_jobs"] == 1 and rc["batch"] != ra["batch"]
        # One walk product pair for the joined batch, shared.
        st = d.status()
        assert st["jobs_done"] == 3
        assert st["engine"]["batches_executed"] == 2
        assert st["engine"]["warm_shapes"], "warm-shape inventory empty"
        assert st["cache"]["walk"].get("store", 0) >= 0  # tiers present
        assert {"walk", "compile", "autotune"} <= set(st["cache"])

        # Byte parity: every served lane == its solo twin.
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        os.makedirs(os.path.join(str(tmp_path), "solo"), exist_ok=True)
        for rec, jobd, vobj in [
                (ra, _job(tsv_paths, tmp_path, "a"),
                 {"name": "v0", "train_seed": 1}),
                (rb, _job(tsv_paths, tmp_path, "b"),
                 {"name": "v0", "train_seed": 2}),
                (rc, _job(tsv_paths, tmp_path, "c", sizeHiddenlayer=24),
                 {"name": "v"})]:
            cfg = config_from_job(
                {**jobd, "result_name": os.path.join(
                    str(tmp_path), "solo", rec["job_id"])})
            v = _variant_from_dict(0, vobj, cfg)
            sr = solo_run(lane_config(cfg, v), console=lambda s: None)
            served = sorted(rec["variants"][v.name]["outputs"])
            for fa, fb in zip(served, sorted(sr.output_files)):
                with open(fa, "rb") as x, open(fb, "rb") as y:
                    assert x.read() == y.read(), \
                        f"{rec['job_id']}: {fa} differs from solo {fb}"

        # Job attribution in ONE daemon stream: every lane-scoped event
        # names its job; seq stays monotonic across interleaved jobs.
        with open(mj) as f:
            events = [json.loads(line) for line in f]
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        lane_events = [e for e in events if "lane" in e]
        assert lane_events and all("job_id" in e for e in lane_events)
        ids = {r["job_id"] for r in (ra, rb, rc)}
        assert {e["job_id"] for e in lane_events} == ids
        for kind in ("job_accepted", "job_done"):
            assert {e["job_id"] for e in events
                    if e["event"] == kind} == ids
    finally:
        d.close()


def test_retryable_batch_failure_requeues_job_in_process(
        tsv_paths, tmp_path):
    """A retryable failure (injected crash) re-queues the job; the next
    cycle completes it. A fatal failure fails it with a classified
    record."""
    d = _daemon(tmp_path, job_retries=1,
                fault_plan="stage=train,kind=crash")
    try:
        ok = d.admit({"job": _job(tsv_paths, tmp_path, "r1")})
        assert d.step() == 0              # crash -> re-queued
        assert d.step() == 1              # once-only fault spent -> done
        rec = _result(d, ok["job_id"])
        assert rec["status"] == "done"

        faults.install_plan("stage=train,kind=fatal")
        bad = d.admit({"job": _job(tsv_paths, tmp_path, "r2")})
        assert d.step() == 0
        rec2 = _result(d, bad["job_id"])
        assert rec2["status"] == "failed"
        assert rec2["classified"] == "fatal"
        assert "InjectedFatal" in rec2["error"]
    finally:
        d.close()


# ---------------------------------------------------------------------------
# Subprocess lifecycle: socket front-end, warm latency, SIGKILL recovery
# ---------------------------------------------------------------------------

def _spawn_daemon(tmp_path, tsv_paths, extra=()):
    sock = os.path.join(str(tmp_path), "g.sock")
    state = os.path.join(str(tmp_path), "state")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    log = open(os.path.join(str(tmp_path), "daemon.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "g2vec_tpu", "serve", "--socket", sock,
         "--state-dir", state, "--platform", "cpu",
         "--cache-dir", os.path.join(str(tmp_path), "cache"), *extra],
        env=env, stdout=log, stderr=subprocess.STDOUT)
    return proc, sock, state, env


def test_serve_smoke_first_result_beats_cold_solo(tsv_paths, tmp_path):
    """The daemon zero->aha: start, submit (cold), submit again (warm) —
    the warm job's first-result latency beats a whole cold solo process —
    /status answers over both dialects, clean shutdown exits 0."""
    from g2vec_tpu.serve import client

    proc, sock, state, env = _spawn_daemon(tmp_path, tsv_paths)
    try:
        assert client.wait_ready(sock, 120), "daemon never became ready"
        job = {**_job(tsv_paths, tmp_path, "smoke1"), "epoch": 10}
        evs = client.submit_job(sock, job, timeout=300)
        assert evs[-1]["event"] == "job_done"
        t0 = time.time()
        evs2 = client.submit_job(
            sock, {**job, "result_name": os.path.join(
                str(tmp_path), "out", "smoke2"), "train_seed": 5},
            timeout=300)
        warm_latency = time.time() - t0
        assert evs2[-1]["event"] == "job_done"

        # Cold solo baseline: a fresh process for the SAME config pays
        # startup + compiles; the warm daemon must beat the whole run.
        t0 = time.time()
        cold = subprocess.run(
            [sys.executable, "-m", "g2vec_tpu", job["expression_file"],
             job["clinical_file"], job["network_file"],
             os.path.join(str(tmp_path), "out", "cold"), "-p", "8",
             "-r", "2", "-s", "16", "-e", "10", "-l", "0.05", "-n", "5",
             "--compute-dtype", "float32", "--platform", "cpu",
             "--walker-backend", "device", "--train-seed", "5"],
            capture_output=True, text=True, env=env, timeout=300)
        cold_wall = time.time() - t0
        assert cold.returncode == 0, cold.stderr[-500:]
        assert warm_latency < cold_wall, \
            f"warm served {warm_latency:.2f}s !< cold solo {cold_wall:.2f}s"

        st = client.status(sock)
        assert st["jobs_done"] == 2
        assert st["engine"]["walk_tier"]["memo_hits"] >= 2  # warm job
        assert st["cache"]["compile"].get("program_hit", 0) > 0
        # HTTP dialect on the same socket.
        import socket as socklib

        s = socklib.socket(socklib.AF_UNIX)
        s.connect(sock)
        s.sendall(b"GET /status HTTP/1.0\r\n\r\n")
        resp = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            resp += chunk
        s.close()
        assert resp.startswith(b"HTTP/1.0 200")
        assert json.loads(resp.split(b"\r\n\r\n", 1)[1])["jobs_done"] == 2

        assert client.shutdown(sock)["event"] == "shutting_down"
        assert proc.wait(timeout=60) == 0
        assert not os.path.exists(sock), "socket not cleaned up"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


def test_supervised_daemon_survives_sigkill_and_requeues(
        tsv_paths, tmp_path):
    """The acceptance drill: SIGKILL mid-train (injected, once) kills the
    daemon; the supervisor relaunches it; the journal re-queues the
    in-flight job; it completes against the restored warm disk caches
    with outputs byte-identical to a solo run."""
    from g2vec_tpu.serve import client

    proc, sock, state, env = _spawn_daemon(
        tmp_path, tsv_paths,
        extra=("--supervise", "--supervise-backoff", "0.1",
               "--fault-plan", "stage=train,kind=sigkill"))
    try:
        assert client.wait_ready(sock, 120), "daemon never became ready"
        job = {**_job(tsv_paths, tmp_path, "k1"), "epoch": 10}
        with pytest.raises(client.ServeConnectionLost) as ei:
            client.submit_job(sock, job, timeout=300)
        job_id = ei.value.job_id
        assert job_id, "job died before acknowledgement"
        rec = client.poll_result(state, job_id, deadline_s=240)
        assert rec["status"] == "done"
        outs = rec["variants"]["v"]["outputs"]
        assert all(os.path.exists(p) for p in outs)
        assert client.wait_ready(sock, 60), "relaunched daemon not serving"

        # Correctness of the recovered outputs: byte-equal to solo.
        solo = subprocess.run(
            [sys.executable, "-m", "g2vec_tpu", job["expression_file"],
             job["clinical_file"], job["network_file"],
             os.path.join(str(tmp_path), "out", "ksolo"), "-p", "8",
             "-r", "2", "-s", "16", "-e", "10", "-l", "0.05", "-n", "5",
             "--compute-dtype", "float32", "--platform", "cpu",
             "--walker-backend", "device"],
            capture_output=True, text=True, env=env, timeout=300)
        assert solo.returncode == 0, solo.stderr[-500:]
        for p in outs:
            suffix = p.rsplit("_", 1)[1]
            twin = os.path.join(str(tmp_path), "out", f"ksolo_{suffix}")
            with open(p, "rb") as a, open(twin, "rb") as b:
                assert a.read() == b.read(), f"{p} differs from {twin}"

        client.shutdown(sock)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            # The supervisor owns a child daemon; take the tree down.
            try:
                os.kill(proc.pid, signal.SIGTERM)
            except OSError:
                pass
            proc.kill()
            proc.wait()


def test_bench_serve_ab_smoke():
    """bench.py --_serve_ab at ultra-toy scale emits a serve_runs_per_hour
    line whose on-the-spot byte-identity check passed."""
    env = {**os.environ, "G2VEC_BENCH_SERVE_JOBS": "2",
           "G2VEC_BENCH_SERVE_REPS": "1", "G2VEC_BENCH_SERVE_EPOCHS": "5",
           "G2VEC_BENCH_SERVE_ARRIVAL": "0.2"}
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--_serve_ab"],
        capture_output=True, text=True, timeout=420, env=env)
    assert proc.returncode == 0, proc.stderr[-800:]
    lines = [json.loads(ln) for ln in proc.stdout.splitlines()
             if ln.strip().startswith("{")]
    assert len(lines) == 1
    line = lines[0]
    assert line["metric"] == "serve_runs_per_hour"
    assert line["value"] and line["value"] > 0
    assert line["bit_identical"] is True
    assert line["jobs"] == 2
    assert line["p50_latency_s"] > 0 and line["p99_latency_s"] > 0
    assert line["baseline_runs_per_hour"] > 0


# ---------------------------------------------------------------------------
# PR 9: job lifecycle — priority/aging, deadlines, cancel, drain, resume
# ---------------------------------------------------------------------------

import shutil as _shutil
import threading

needs_native = pytest.mark.skipif(_shutil.which("g++") is None,
                                  reason="no C++ toolchain")


def _stream_job(tsv_paths, tmp_path, name, **overrides):
    return _job(tsv_paths, tmp_path, name,
                train_mode="streaming", walker_backend="native",
                shard_paths=16, **overrides)


def test_priority_classes_and_aging_pop_order():
    from g2vec_tpu.serve.daemon import ServeJob, _FairQueue

    def mk(i, p):
        return ServeJob(job_id=f"j{i}", tenant="t", cfg=None, variants=[],
                        raw={}, submitted_at=0.0, priority=p)

    q = _FairQueue(depth=8, aging_s=0.2)
    for i, p in enumerate(("batch", "interactive", "batch", "interactive")):
        q.push(mk(i, p))
    assert q.depths() == {"interactive": 2, "batch": 2}
    assert q.pop(timeout=0).job_id == "j1"      # interactive cuts the line
    time.sleep(0.25)                            # j0/j2 age past the bound
    q.push(mk(4, "interactive"))
    assert q.pop(timeout=0).job_id == "j0"      # aged batch outranks
    assert q.pop(timeout=0).job_id == "j2"      # still aged
    assert q.pop(timeout=0).job_id == "j3"      # back to strict priority
    assert q.pop(timeout=0).job_id == "j4"
    assert q.remove("zz") is None
    q.push(mk(5, "batch"))
    assert q.remove("j5").job_id == "j5"        # targeted pull (cancel)
    assert q.depth() == 0


def test_submit_validation_rejects_bad_priority_and_deadline(
        tsv_paths, tmp_path):
    d = _daemon(tmp_path)
    try:
        for payload, needle in [
            ({"priority": "urgent",
              "job": _job(tsv_paths, tmp_path, "x")}, "priority"),
            ({"deadline_s": -1,
              "job": _job(tsv_paths, tmp_path, "x")}, "deadline_s"),
            ({"deadline_s": True,
              "job": _job(tsv_paths, tmp_path, "x")}, "deadline_s"),
        ]:
            rej = d.admit(payload)
            assert rej["event"] == "rejected" and rej["error"] == "bad_job"
            assert needle in rej["detail"], (needle, rej["detail"])
    finally:
        d.close()


def test_job_lifecycle_state_machine_pinned(tsv_paths, tmp_path):
    """Satellite pin: a completed job's job_state stream is exactly
    queued -> started -> (checkpointed|resumed)* -> done, and /status
    republishes the per-state counters."""
    import re

    mj = os.path.join(str(tmp_path), "lc.jsonl")
    d = _daemon(tmp_path, metrics_jsonl=mj)
    try:
        ok = d.admit({"priority": "interactive",
                      "job": _job(tsv_paths, tmp_path, "lc1")})
        assert ok["event"] == "accepted" and ok["priority"] == "interactive"
        assert d.step() == 1
        st = d.status()
        assert st["draining"] is False
        assert st["job_states"]["queued"] == 1
        assert st["job_states"]["started"] == 1
        assert st["job_states"]["done"] == 1
        assert st["queued_by_priority"] == {"interactive": 0, "batch": 0}
        with open(mj) as f:
            events = [json.loads(line) for line in f]
        states = [e["state"] for e in events
                  if e["event"] == "job_state"
                  and e.get("job_id") == ok["job_id"]]
        assert re.fullmatch(r"queued started ((checkpointed|resumed) )*done",
                            " ".join(states)), states
    finally:
        d.close()


@needs_native
def test_streaming_serve_job_checkpoints_and_cleans_cursor(
        tsv_paths, tmp_path):
    """A streaming job under the daemon checkpoints its cursor beneath
    <state-dir>/ckpt/<job_id>.<variant> while running and removes it at
    the terminal state (a finished job must never leave a cursor)."""
    mj = os.path.join(str(tmp_path), "sc.jsonl")
    d = _daemon(tmp_path, metrics_jsonl=mj)
    try:
        ok = d.admit({"job": _stream_job(tsv_paths, tmp_path, "sj",
                                         epoch=6, checkpoint_every=1)})
        assert ok["event"] == "accepted"
        assert d.step() == 1
        rec = _result(d, ok["job_id"])
        assert rec["status"] == "done"
        with open(mj) as f:
            events = [json.loads(line) for line in f]
        states = [e["state"] for e in events
                  if e["event"] == "job_state"
                  and e.get("job_id") == ok["job_id"]]
        assert "checkpointed" in states
        assert states[0] == "queued" and states[-1] == "done"
        ckpt_root = os.path.join(d.opts.state_dir, "ckpt")
        leftovers = [p for p in (os.listdir(ckpt_root)
                                 if os.path.isdir(ckpt_root) else [])
                     if p.startswith(ok["job_id"])]
        assert leftovers == [], leftovers
    finally:
        d.close()


def test_cancel_queued_job_is_immediate(tsv_paths, tmp_path):
    d = _daemon(tmp_path)
    try:
        ok = d.admit({"job": _job(tsv_paths, tmp_path, "cq")})
        resp = d.cancel_job(ok["job_id"])
        assert resp["event"] == "cancelled" and resp["where"] == "queued"
        rec = _result(d, ok["job_id"])
        assert rec["status"] == "cancelled"
        assert d._queue.depth() == 0
        assert os.listdir(os.path.join(d.opts.state_dir, "jobs")) == []
        assert d.cancel_job("nope")["event"] == "error"
    finally:
        d.close()


def test_cancel_running_job_is_cooperative(tsv_paths, tmp_path):
    """Cancel lands while the batch executes; the trainers' check hook
    raises JobCancelled at the next boundary; the record is terminal
    ``cancelled`` and the daemon keeps serving."""
    d = _daemon(tmp_path)
    try:
        # Cold first batch: seconds of walk + compile run before the first
        # trainer boundary, so a cancel set as soon as the job is running
        # is guaranteed to precede the first check() call.
        ok = d.admit({"job": _job(tsv_paths, tmp_path, "cr")})
        got = {}

        def _cancel():
            deadline = time.time() + 30
            while time.time() < deadline:
                with d._lock:
                    running = ok["job_id"] in d._running
                if running:
                    got["resp"] = d.cancel_job(ok["job_id"])
                    return
                time.sleep(0.02)

        t = threading.Thread(target=_cancel)
        t.start()
        done = d.step()
        t.join(timeout=30)
        assert got["resp"]["event"] == "cancelling", got
        assert done == 0
        rec = _result(d, ok["job_id"])
        assert rec["status"] == "cancelled"
        # The daemon is still alive and serving.
        ok2 = d.admit({"job": _job(tsv_paths, tmp_path, "cr2", epoch=6)})
        assert d.step() == 1
        assert _result(d, ok2["job_id"])["status"] == "done"
    finally:
        d.close()


def test_deadline_exceeded_while_queued(tsv_paths, tmp_path):
    d = _daemon(tmp_path)
    try:
        ok = d.admit({"deadline_s": 0.15,
                      "job": _job(tsv_paths, tmp_path, "dq")})
        time.sleep(0.3)
        assert d.step(timeout=0.1) == 0          # expired before execution
        rec = _result(d, ok["job_id"])
        assert rec["status"] == "deadline_exceeded"
        st = d.status()
        assert st["job_states"]["deadline_exceeded"] == 1
    finally:
        d.close()


def test_client_retry_backoff_and_structured_timeouts(tmp_path):
    """Satellite: submit_and_wait retries connect failures with backoff +
    jitter and every timeout path raises ServeTimeout naming the job."""
    import random

    from g2vec_tpu.serve import client

    missing = os.path.join(str(tmp_path), "nope.sock")
    t0 = time.time()
    with pytest.raises(client.ServeTimeout, match="4 attempt"):
        client.submit_and_wait(missing, {"x": 1}, retries=3,
                               backoff=0.01, jitter=0.01,
                               rng=random.Random(7))
    assert time.time() - t0 < 5                  # bounded, no hang
    with pytest.raises(client.ServeTimeout, match="job jX") as ei:
        client.poll_result(str(tmp_path), "jX", deadline_s=0.2,
                           interval=0.05)
    assert ei.value.job_id == "jX"
    assert isinstance(ei.value, TimeoutError)    # still catchable as stdlib


@needs_native
def test_graceful_drain_sigterm_checkpoints_and_resumes(tsv_paths, tmp_path):
    """Acceptance drill: SIGTERM with an in-flight streaming job and a
    queued full-batch job -> daemon exits 0 within the drain deadline,
    the streaming cursor is on disk, both jobs stay journaled; a restart
    re-queues both and completes them (streaming resumed, zero re-walks)."""
    from g2vec_tpu.serve import client

    mj = os.path.join(str(tmp_path), "drain.jsonl")
    proc, sock, state, env = _spawn_daemon(
        tmp_path, tsv_paths, extra=("--metrics-jsonl", mj))
    holder = {}

    def _submit(key, job):
        try:
            holder[key] = client.submit_job(sock, job, timeout=600)
        except client.ServeConnectionLost as e:
            holder[key + "_lost"] = e.job_id

    try:
        assert client.wait_ready(sock, 120), "daemon never became ready"
        job_a = _stream_job(tsv_paths, tmp_path, "drainA", epoch=60,
                            stream_patience=60, checkpoint_every=1)
        job_b = {**_job(tsv_paths, tmp_path, "drainB"), "epoch": 6}
        ta = threading.Thread(target=_submit, args=("a", job_a))
        ta.start()
        deadline = time.time() + 180
        st = {"running": []}
        while time.time() < deadline and not st["running"]:
            try:
                st = client.status(sock)
            except OSError:
                pass
            time.sleep(0.1)
        assert st["running"], "streaming job never started"
        tb = threading.Thread(target=_submit, args=("b", job_b))
        tb.start()
        deadline = time.time() + 60
        while time.time() < deadline and st["queued"] == 0:
            st = client.status(sock)
            time.sleep(0.05)
        assert st["queued"] == 1, "full-batch job never queued"

        os.kill(proc.pid, signal.SIGTERM)
        assert proc.wait(timeout=180) == 0        # graceful exit code
        ta.join(timeout=30)
        tb.join(timeout=30)
        a_id = (holder["a"][0]["job_id"] if "a" in holder
                else holder["a_lost"])
        b_id = (holder["b"][0]["job_id"] if "b" in holder
                else holder["b_lost"])
        assert a_id and b_id
        journaled = set(os.listdir(os.path.join(state, "jobs")))
        assert journaled == {f"{a_id}.json", f"{b_id}.json"}, journaled

        # Restart on the same state dir: journal re-queues, streaming
        # resumes from its cursor, both jobs reach done.
        proc2, sock, state, env = _spawn_daemon(
            tmp_path, tsv_paths, extra=("--metrics-jsonl", mj))
        try:
            rec_a = client.poll_result(state, a_id, deadline_s=420)
            rec_b = client.poll_result(state, b_id, deadline_s=420)
            assert rec_a["status"] == "done" and rec_b["status"] == "done"
            with open(mj) as f:
                events = [json.loads(line) for line in f]
            a_states = [e["state"] for e in events
                        if e.get("event") == "job_state"
                        and e.get("job_id") == a_id]
            assert "drained" in a_states and "resumed" in a_states
            assert a_states[-1] == "done"
            streams = [e for e in events if e.get("event") == "stream"
                       and e.get("job_id") == a_id]
            assert streams and streams[-1]["resumed"] == 1
            assert streams[-1]["rewalks"] == 0     # no re-walk after resume
            client.shutdown(sock)
            assert proc2.wait(timeout=120) == 0
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


@needs_native
def test_streaming_serve_sigkill_resumes_mid_epoch_byte_identical(
        tsv_paths, tmp_path):
    """THE acceptance drill: a streaming serve job SIGKILLed at the
    stream_ckpt seam (mid-epoch, right after a cursor checkpoint
    finalizes) -> supervisor relaunches -> journal re-queues -> the job
    resumes from the cursor and completes with outputs byte-identical to
    the same config run solo, uninterrupted."""
    from g2vec_tpu.serve import client

    mj = os.path.join(str(tmp_path), "kk.jsonl")
    proc, sock, state, env = _spawn_daemon(
        tmp_path, tsv_paths,
        extra=("--supervise", "--supervise-backoff", "0.1",
               "--fault-plan", "stage=stream_ckpt,kind=sigkill,epoch=1",
               "--metrics-jsonl", mj))
    try:
        assert client.wait_ready(sock, 120), "daemon never became ready"
        job = _stream_job(tsv_paths, tmp_path, "kk", epoch=12,
                          checkpoint_every=1)
        with pytest.raises(client.ServeConnectionLost) as ei:
            client.submit_job(sock, job, timeout=600)
        job_id = ei.value.job_id
        assert job_id, "job died before acknowledgement"
        rec = client.poll_result(state, job_id, deadline_s=420)
        assert rec["status"] == "done"
        outs = rec["variants"]["v"]["outputs"]
        assert outs and all(os.path.exists(p) for p in outs)

        with open(mj) as f:
            events = [json.loads(line) for line in f]
        states = [e["state"] for e in events
                  if e.get("event") == "job_state"
                  and e.get("job_id") == job_id]
        assert "checkpointed" in states        # cursor written pre-kill
        assert "resumed" in states             # picked up after relaunch
        assert states[-1] == "done"

        # Byte parity: the resumed served outputs == the solo twin's.
        from g2vec_tpu.batch.engine import _variant_from_dict, lane_config
        from g2vec_tpu.config import config_from_job
        from g2vec_tpu.pipeline import run as solo_run

        cfg = config_from_job(
            {**job, "result_name": os.path.join(str(tmp_path), "out",
                                                "kksolo")})
        v = _variant_from_dict(0, {"name": "v"}, cfg)
        sr = solo_run(lane_config(cfg, v), console=lambda s: None)
        for fa, fb in zip(sorted(outs), sorted(sr.output_files)):
            with open(fa, "rb") as x, open(fb, "rb") as y:
                assert x.read() == y.read(), f"{fa} differs from {fb}"

        client.shutdown(sock)
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            try:
                os.kill(proc.pid, signal.SIGTERM)
            except OSError:
                pass
            proc.kill()
            proc.wait()
