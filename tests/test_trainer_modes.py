"""The PR-4 trainer modes: fused-eval fold, epoch supersteps, donated
carry, and the packed-kernel autotuner.

The contract under test is the one ARCHITECTURE.md §9 and the trainer
module docstring document, float32 throughout:

- superstep-K and donation are BITWISE the shipping chunk loop — selects
  with a true predicate and buffer renaming do not touch arithmetic —
  pinned here across a shape battery;
- fused-eval is bitwise on every accuracy, every early-stop decision and
  the epoch count (exact 0/1 counting), while losses and the final
  embeddings may sit within ~2 ulp on XLA:CPU: the fused body is a
  different program, and XLA decides fma contraction per program (the
  module docstring records the failed attempts at closing this);
- every mode is run-to-run deterministic (bitwise).

A committed golden (tests/golden/trainer_modes.json) pins the shipping
trajectory so a change that shifts ALL modes together is caught too
(regenerate intentionally with G2VEC_REGEN_GOLDEN=1).
"""
import hashlib
import json
import os

import numpy as np
import pytest

GOLDEN = os.path.join(os.path.dirname(__file__), "golden",
                      "trainer_modes.json")


def _data(seed=5, n_paths=120, n_genes=64, noise=0.25):
    """Planted signal + label noise: the noise makes val accuracy dip
    within a few epochs, so the parity runs exercise the early-stop
    select logic (pinned by test_shipping_run_early_stops)."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        idx = rng.choice(half, size=6, replace=False) + lab * half
        paths[i, idx] = 1
        paths[i, rng.choice(n_genes, size=3, replace=False)] = 1
    flip = rng.random(n_paths) < noise
    return paths, np.where(flip, 1 - labels, labels)


def _train(paths, labels, **kw):
    from g2vec_tpu.train import train_cbow

    base = dict(hidden=16, learning_rate=0.05, max_epochs=40,
                compute_dtype="float32", seed=0)
    base.update(kw)
    return train_cbow(paths, labels, **base)


def _fingerprint(res):
    return {
        "w_ih_sha256": hashlib.sha256(
            np.ascontiguousarray(res.w_ih).tobytes()).hexdigest(),
        "stop_epoch": res.stop_epoch,
        "stopped_early": res.stopped_early,
        "acc_val": float(res.acc_val),
        "history": [[h["epoch"], h["acc_val"], h["acc_tr"], h["loss"]]
                    for h in res.history],
    }


def _assert_decisions_bitwise(a, b, what):
    """The robust half of the contract: accuracies, early-stop decisions
    and epoch counts are exact counting arithmetic — bitwise under ANY
    program schedule."""
    assert a.stop_epoch == b.stop_epoch, what
    assert a.stopped_early == b.stopped_early, what
    assert len(a.history) == len(b.history), what
    for ha, hb in zip(a.history, b.history):
        for k in ("epoch", "acc_val", "acc_tr"):
            assert ha[k] == hb[k], (what, ha["epoch"], k, ha[k], hb[k])
    assert float(a.acc_val) == float(b.acc_val), what
    assert float(a.acc_tr) == float(b.acc_tr), what


def _assert_bitwise(a, b, what):
    _assert_decisions_bitwise(a, b, what)
    np.testing.assert_array_equal(a.w_ih, b.w_ih, err_msg=what)
    for ha, hb in zip(a.history, b.history):
        assert ha["loss"] == hb["loss"], (what, ha["epoch"], ha, hb)


def _assert_fused_parity(a, b, what):
    """Fused-eval contract: decisions bitwise; losses/embeddings within
    ~2 ulp of float32 (cross-program fma context on XLA:CPU)."""
    _assert_decisions_bitwise(a, b, what)
    for ha, hb in zip(a.history, b.history):
        assert ha["loss"] == pytest.approx(hb["loss"], rel=1e-6), (
            what, ha["epoch"], ha["loss"], hb["loss"])
    np.testing.assert_allclose(a.w_ih, b.w_ih, rtol=0, atol=1e-6,
                               err_msg=what)


@pytest.fixture(scope="module")
def shipping():
    """The shipping chunk loop: no fused eval, no superstep, no donation."""
    paths, labels = _data()
    return _train(paths, labels, fused_eval=False, epoch_superstep=1,
                  donate=False)


def test_shipping_run_early_stops(shipping):
    # The planted data must actually exercise the dip path, or the parity
    # claims below would never cover the early-stop select logic.
    assert shipping.stopped_early
    assert 1 < len(shipping.history) < 40


def test_fused_eval_parity(shipping):
    paths, labels = _data()
    fused = _train(paths, labels, fused_eval=True, epoch_superstep=1,
                   donate=False)
    _assert_fused_parity(fused, shipping, "fused-eval vs shipping")


def test_fused_eval_deterministic(shipping):
    paths, labels = _data()
    a = _train(paths, labels, fused_eval=True, epoch_superstep=8,
               donate=True)
    b = _train(paths, labels, fused_eval=True, epoch_superstep=8,
               donate=True)
    _assert_bitwise(a, b, "fused mode run-to-run")


@pytest.mark.parametrize("combo", [
    dict(seed=7, n_paths=200, n_genes=100, hidden=16, lr=0.05),
    dict(seed=9, n_paths=80, n_genes=48, hidden=32, lr=0.01),
    dict(seed=13, n_paths=150, n_genes=96, hidden=8, lr=0.1),
])
def test_mode_parity_shape_battery(combo):
    """The contract must hold at shapes it was not tuned on: per combo,
    superstep+donate bitwise, fused within the documented envelope."""
    paths, labels = _data(seed=combo["seed"], n_paths=combo["n_paths"],
                          n_genes=combo["n_genes"])
    base = dict(hidden=combo["hidden"], learning_rate=combo["lr"],
                max_epochs=20)
    ship = _train(paths, labels, fused_eval=False, epoch_superstep=1,
                  donate=False, **base)
    hard = _train(paths, labels, fused_eval=False, epoch_superstep=8,
                  donate=True, **base)
    _assert_bitwise(hard, ship, f"superstep+donate @ {combo}")
    fused = _train(paths, labels, fused_eval=True, epoch_superstep=8,
                   donate=True, **base)
    _assert_fused_parity(fused, ship, f"fused @ {combo}")


@pytest.mark.parametrize("k", [2, 8, 64])
def test_superstep_bitwise_parity(shipping, k):
    paths, labels = _data()
    res = _train(paths, labels, fused_eval=False, epoch_superstep=k,
                 donate=False)
    _assert_bitwise(res, shipping, f"superstep K={k} vs shipping")


def test_donate_bitwise_parity(shipping):
    paths, labels = _data()
    res = _train(paths, labels, fused_eval=False, epoch_superstep=1,
                 donate=True)
    _assert_bitwise(res, shipping, "donate vs shipping")


def test_all_modes_together_parity(shipping):
    paths, labels = _data()
    res = _train(paths, labels, fused_eval=True, epoch_superstep=8,
                 donate=True)
    _assert_fused_parity(res, shipping, "fused+superstep+donate vs shipping")


def test_no_early_stop_run_parity():
    # A run capped BEFORE its dip epoch: the superstep masking and the
    # fused boundary eval must agree with shipping on the truncated
    # history too (different code path: limit, not dip, ends the loop).
    paths, labels = _data(seed=11, noise=0.0)
    a = _train(paths, labels, max_epochs=5, fused_eval=False,
               epoch_superstep=1, donate=False)
    b = _train(paths, labels, max_epochs=5, fused_eval=True,
               epoch_superstep=4, donate=True)
    assert not a.stopped_early
    _assert_fused_parity(b, a, "all modes, epoch-capped run")


def test_modes_golden_pinned(shipping):
    """Every mode being bitwise-equal to each other cannot catch a change
    that shifts them ALL — pin the shared trajectory to committed bytes."""
    fp = _fingerprint(shipping)
    if os.environ.get("G2VEC_REGEN_GOLDEN") == "1":
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        with open(GOLDEN, "w") as f:
            json.dump(fp, f, indent=1)
            f.write("\n")
        pytest.skip("trainer-modes golden regenerated — review and commit")
    assert os.path.exists(GOLDEN), (
        f"missing fixture {GOLDEN}; regenerate with G2VEC_REGEN_GOLDEN=1")
    with open(GOLDEN) as f:
        want = json.load(f)
    assert fp == want, (
        "trainer trajectory drifted from the committed golden — if "
        "intentional, regenerate with G2VEC_REGEN_GOLDEN=1 and commit")


def test_donation_roundtrip_under_resume(tmp_path):
    """Interrupt + --resume with the donated carry: the restored snapshot
    may alias params leaf-for-leaf, and donation requires distinct
    buffers — the resume path must copy, and the resumed run must land
    bitwise on the uninterrupted run's results."""
    paths, labels = _data(seed=11, noise=0.0)
    common = dict(fused_eval=True, epoch_superstep=4, donate=True,
                  checkpoint_every=4)
    # The straight run checkpoints too (into its own dir): chunk size is
    # part of the compiled program, and the bitwise claim compares the
    # SAME programs with and without the interruption.
    straight = _train(paths, labels, max_epochs=12,
                      checkpoint_dir=str(tmp_path / "ck_straight"), **common)
    ck = str(tmp_path / "ck")
    _train(paths, labels, max_epochs=8, checkpoint_dir=ck, **common)
    resumed = _train(paths, labels, max_epochs=12, checkpoint_dir=ck,
                     resume=True, **common)
    np.testing.assert_array_equal(resumed.w_ih, straight.w_ih)
    assert resumed.stop_epoch == straight.stop_epoch
    assert float(resumed.acc_val) == float(straight.acc_val)
    # The resumed history covers only the continued epochs — but they
    # must be the straight run's bytes for the same epoch indices.
    straight_by_epoch = {h["epoch"]: h for h in straight.history}
    assert resumed.history, "resume re-ran nothing"
    for h in resumed.history:
        want = straight_by_epoch[h["epoch"]]
        for k in ("acc_val", "acc_tr", "loss"):
            assert h[k] == want[k], (h["epoch"], k)


def test_superstep_validation():
    from g2vec_tpu.train import train_cbow

    paths, labels = _data()
    with pytest.raises(ValueError, match="epoch_superstep"):
        train_cbow(paths, labels, hidden=16, learning_rate=0.05,
                   max_epochs=4, epoch_superstep=0)


# ---------------------------------------------------------------------------
# Packed-kernel autotuner: measure / persist / verify / invalidate.
# ---------------------------------------------------------------------------


def test_autotune_measures_installs_and_persists(tmp_path):
    from g2vec_tpu.ops import packed_matmul as pm

    path = str(tmp_path / "autotune" / "packed_matmul.json")
    pm.reset_tuned()
    tok0 = pm.tuned_token()
    ent = pm.autotune_packed_matmul(512, 1024, 128, interpret=True,
                                    iters=1, cache_path=path)
    assert ent["source"] == "measured"
    assert pm.tuned_token() == tok0 + 1
    assert tuple(ent["fwd"]) in pm.tile_candidates(512, 1024, 128)
    assert os.path.exists(path)
    tiles = pm.describe_tiles(512, 1024, 128)
    assert tiles["fwd"]["source"] == "autotuned"
    # In-memory hit: no re-measure, no token bump (the warm path relies
    # on this to keep the background-compiled executable valid).
    ent2 = pm.autotune_packed_matmul(512, 1024, 128, interpret=True,
                                     iters=1, cache_path=path)
    assert ent2["source"] == "memory" and pm.tuned_token() == tok0 + 1


def test_autotune_cache_hit_skips_sweep(tmp_path):
    from g2vec_tpu.ops import packed_matmul as pm

    path = str(tmp_path / "packed_matmul.json")
    pm.reset_tuned()
    pm.autotune_packed_matmul(512, 1024, 128, interpret=True, iters=1,
                              cache_path=path)
    pm.reset_tuned()           # fresh process stand-in: memory empty
    hit = pm.autotune_packed_matmul(512, 1024, 128, interpret=True,
                                    iters=1, cache_path=path)
    assert hit["source"] == "cache"
    assert pm.describe_tiles(512, 1024, 128)["fwd"]["source"] == "autotuned"


def test_autotune_stale_schema_remeasures(tmp_path):
    from g2vec_tpu.ops import packed_matmul as pm

    path = str(tmp_path / "packed_matmul.json")
    pm.reset_tuned()
    pm.autotune_packed_matmul(512, 1024, 128, interpret=True, iters=1,
                              cache_path=path)
    rec = json.load(open(path))
    rec["schema"] = -999       # an older kernel generation's record
    with open(path, "w") as f:
        json.dump(rec, f)
    pm.reset_tuned()
    assert pm.load_tuned(path, 512, 1024, 128, True) is None
    again = pm.autotune_packed_matmul(512, 1024, 128, interpret=True,
                                      iters=1, cache_path=path)
    assert again["source"] == "measured"
    assert json.load(open(path))["schema"] == pm.AUTOTUNE_SCHEMA


def test_autotune_rejects_illegal_persisted_plan(tmp_path):
    from g2vec_tpu.ops import packed_matmul as pm

    path = str(tmp_path / "packed_matmul.json")
    pm.reset_tuned()
    pm.autotune_packed_matmul(512, 1024, 128, interpret=True, iters=1,
                              cache_path=path)
    rec = json.load(open(path))
    (key,) = rec["entries"].keys()
    rec["entries"][key]["fwd"] = [999, 999]   # not a legal tile plan
    with open(path, "w") as f:
        json.dump(rec, f)
    pm.reset_tuned()
    assert pm.load_tuned(path, 512, 1024, 128, True) is None


def test_autotune_install_invalidates_chunk_fn_cache():
    import jax.numpy as jnp

    from g2vec_tpu.ops import packed_matmul as pm
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.train.trainer import _get_chunk_fn

    ctx = make_mesh_context(None)
    args = (0.01, jnp.float32, 0.5, ctx, 4)
    pm.reset_tuned()
    fn_a = _get_chunk_fn(*args, packed=True, interpret=True)
    assert _get_chunk_fn(*args, packed=True, interpret=True) is fn_a
    pm._install_tuned(512, 1024, 128, {"fwd": (256, 1), "bwd": (256, 1)})
    fn_b = _get_chunk_fn(*args, packed=True, interpret=True)
    assert fn_b is not fn_a, (
        "a tile install must invalidate the compiled chunk program")
    # The XLA (non-packed) program embeds no tiles: token-invariant.
    pm.reset_tuned()
    fn_x = _get_chunk_fn(*args, packed=False, interpret=False)
    pm._install_tuned(512, 1024, 128, {"fwd": (256, 1), "bwd": (256, 1)})
    assert _get_chunk_fn(*args, packed=False, interpret=False) is fn_x
    pm.reset_tuned()


def test_autotune_rejects_unpadded_shapes():
    from g2vec_tpu.ops import packed_matmul as pm

    with pytest.raises(ValueError, match="padded shapes"):
        pm.autotune_packed_matmul(500, 1024, 128, interpret=True)


def test_trainer_kernel_autotune_end_to_end(tmp_path):
    """train_cbow --kernel-autotune: sweeps at the run's exact shapes,
    persists under the cache path, and a second run cache-hits. Tile
    choice may regroup the kernel's f32 accumulation, so the claim is
    behavioral (close trajectories), not bitwise."""
    from g2vec_tpu.cache import autotune_cache_path
    from g2vec_tpu.ops import packed_matmul as pm
    from g2vec_tpu.train import train_cbow

    pm.reset_tuned()
    paths, labels = _data(n_paths=96, n_genes=700)
    path = autotune_cache_path(str(tmp_path))
    common = dict(hidden=128, learning_rate=0.01, max_epochs=3,
                  compute_dtype="bfloat16", seed=3, use_pallas=True)
    base = train_cbow(paths, labels, **common)
    tuned = train_cbow(paths, labels, kernel_autotune=True,
                       autotune_cache_path=path, **common)
    assert os.path.exists(path)
    assert np.isfinite(tuned.w_ih).all()
    np.testing.assert_allclose(tuned.w_ih, base.w_ih, atol=0.05)
    # Second autotuned run: the persisted plans satisfy it without a
    # re-measure (token stable), and results are bitwise-reproducible.
    tok = pm.tuned_token()
    again = train_cbow(paths, labels, kernel_autotune=True,
                       autotune_cache_path=path, **common)
    assert pm.tuned_token() == tok
    np.testing.assert_array_equal(again.w_ih, tuned.w_ih)
    pm.reset_tuned()
