"""The static-analysis suite, tested the way it runs: fixture trees.

Every checker gets (at least) a positive fixture — a tiny synthetic
repo tree exhibiting the bug class — and a clean twin proving the
checker is quiet on correct code. The suppression machinery (inline
waivers, the shrink-only baseline) is pinned too, because a linter
whose escape hatches silently fail teaches people to delete it.

The two tests that matter most:

- ``test_repo_is_clean`` runs the full suite over THIS repo with the
  committed baseline — the CI gate that keeps the invariants true;
- ``test_seeded_idem_race_is_caught`` re-introduces the PR 11 ``_idem``
  bug (removing the ``with self._idem_lock:`` around admit()'s dedup
  lookup) into a copy of daemon.py and asserts the lock-discipline
  checker catches it. A race lint that cannot re-find the race that
  motivated it is decoration.
"""
import os
import re
import threading
import time

import pytest

from g2vec_tpu.analyze.core import (load_baseline, run_analysis,
                                    save_baseline)

pytestmark = pytest.mark.analyze

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree(tmp_path, files):
    """Write ``{relpath: source}`` under tmp_path; return the root."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path)


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS = '''\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []            # guarded-by: _lock

    def ok(self):
        with self._lock:
            self._items.append(1)

    def bad(self):
        self._items.append(2)
'''


def test_lock_mutation_outside_lock_flagged(tmp_path):
    root = _tree(tmp_path, {"box.py": _LOCKED_CLASS})
    rep = run_analysis(root, checker_ids=["lock-discipline"])
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert f.context == "Box.bad" and "_items" in f.message \
        and "without holding" in f.message
    # The in-lock mutation and the __init__ assignment stayed quiet.


def test_lock_clean_code_is_quiet(tmp_path):
    clean = _LOCKED_CLASS.replace(
        "    def bad(self):\n        self._items.append(2)\n", "")
    root = _tree(tmp_path, {"box.py": clean})
    rep = run_analysis(root, checker_ids=["lock-discipline"])
    assert rep.clean and not rep.findings


def test_waiver_suppresses_and_requires_reason(tmp_path):
    # A reasoned waiver suppresses; a bare allow[] is not a waiver.
    waived = _LOCKED_CLASS.replace(
        "        self._items.append(2)",
        "        # analyze: allow[lock-discipline] single-threaded "
        "teardown\n        self._items.append(2)")
    root = _tree(tmp_path, {"box.py": waived})
    rep = run_analysis(root, checker_ids=["lock-discipline"])
    assert rep.clean and len(rep.waived) == 1

    bare = _LOCKED_CLASS.replace(
        "        self._items.append(2)",
        "        # analyze: allow[lock-discipline]\n"
        "        self._items.append(2)")
    rep2 = run_analysis(_tree(tmp_path / "b", {"box.py": bare}),
                        checker_ids=["lock-discipline"])
    assert len(rep2.findings) == 1 and not rep2.waived


def test_baseline_suppresses_then_goes_stale(tmp_path):
    root = _tree(tmp_path, {"box.py": _LOCKED_CLASS})
    base = str(tmp_path / "BASELINE.json")
    rep = run_analysis(root, checker_ids=["lock-discipline"])
    save_baseline(base, rep.findings)
    assert len(load_baseline(base)) == 1

    # Baselined: the finding no longer fails the run.
    rep2 = run_analysis(root, checker_ids=["lock-discipline"],
                        baseline_path=base)
    assert rep2.clean and len(rep2.baselined) == 1

    # Fix the code: the entry goes stale and FAILS (shrink-only).
    fixed = _LOCKED_CLASS.replace("        self._items.append(2)",
                                  "        pass")
    root3 = _tree(tmp_path / "fixed", {"box.py": fixed})
    rep3 = run_analysis(root3, checker_ids=["lock-discipline"],
                        baseline_path=base)
    assert not rep3.clean and len(rep3.stale_baseline) == 1


def test_check_then_act_across_release(tmp_path):
    src = '''\
import threading

class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._idem = {}             # guarded-by: _lock

    def admit(self, key):
        with self._lock:
            hit = self._idem.get(key)
        if hit is None:
            with self._lock:
                self._idem[key] = "fresh"
        return hit
'''
    rep = run_analysis(_tree(tmp_path, {"t.py": src}),
                       checker_ids=["lock-discipline"])
    msgs = [f.message for f in rep.findings]
    assert any("check-then-act" in m and "_idem" in m for m in msgs)

    # The atomic form — lookup and reservation one critical section —
    # is exactly what the checker asks for, and it is quiet.
    atomic = src.replace(
        '''        with self._lock:
            hit = self._idem.get(key)
        if hit is None:
            with self._lock:
                self._idem[key] = "fresh"''',
        '''        with self._lock:
            hit = self._idem.get(key)
            if hit is None:
                self._idem[key] = "fresh"''')
    rep2 = run_analysis(_tree(tmp_path / "ok", {"t.py": atomic}),
                        checker_ids=["lock-discipline"])
    assert rep2.clean


def test_lock_order_cycle_rejected(tmp_path):
    src = '''\
import threading

class TwoLocks:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._n = 0                 # guarded-by: _a_lock

    def forward(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def backward(self):
        with self._b_lock:
            with self._a_lock:
                pass
'''
    rep = run_analysis(_tree(tmp_path, {"t.py": src}),
                       checker_ids=["lock-discipline"])
    assert any("cycle" in f.message for f in rep.findings)


def test_holds_contract_enforced(tmp_path):
    src = '''\
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0                 # guarded-by: _lock

    # analyze: holds[_lock]
    def _bump(self):
        self._n += 1

    def good(self):
        with self._lock:
            self._bump()

    def bad(self):
        self._bump()
'''
    rep = run_analysis(_tree(tmp_path, {"q.py": src}),
                       checker_ids=["lock-discipline"])
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert f.context == "Q.bad" and "holds" in f.message


def test_condition_wrapping_lock_is_aliased(tmp_path):
    src = '''\
import threading

class Ring:
    def __init__(self):
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._items = []            # guarded-by: _lock

    def put(self, x):
        with self._not_empty:
            self._items.append(x)
            self._not_empty.notify()
'''
    rep = run_analysis(_tree(tmp_path, {"r.py": src}),
                       checker_ids=["lock-discipline"])
    assert rep.clean


# ---------------------------------------------------------------------------
# jax-purity
# ---------------------------------------------------------------------------

def test_jax_free_module_reaching_jax_flagged(tmp_path):
    files = {
        "g2vec_tpu/__init__.py": "",
        "g2vec_tpu/serve/__init__.py": "",
        # Declared jax-free, but reaches jax through a helper.
        "g2vec_tpu/serve/protocol.py":
            "from g2vec_tpu.serve import helper\n",
        "g2vec_tpu/serve/helper.py": "import jax\n",
    }
    rep = run_analysis(_tree(tmp_path, files),
                       checker_ids=["jax-purity"])
    assert any("jax" in f.message and f.path.endswith("protocol.py")
               for f in rep.findings)

    # Severing the edge makes it quiet.
    files["g2vec_tpu/serve/helper.py"] = "import os\n"
    rep2 = run_analysis(_tree(tmp_path / "ok", files),
                        checker_ids=["jax-purity"])
    assert rep2.clean


def test_staged_function_impurity_flagged(tmp_path):
    files = {
        "g2vec_tpu/__init__.py": "",
        "g2vec_tpu/ops/__init__.py": "",
        "g2vec_tpu/ops/kernel.py": '''\
import jax
import numpy as np

@jax.jit
def bad_step(x):
    return np.asarray(x) + 1

@jax.jit
def good_step(x):
    return x + 1
''',
    }
    rep = run_analysis(_tree(tmp_path, files),
                       checker_ids=["jax-purity"])
    assert len(rep.findings) == 1
    assert "np.asarray" in rep.findings[0].message


# ---------------------------------------------------------------------------
# fault-seams
# ---------------------------------------------------------------------------

def test_seam_registry_enforced(tmp_path):
    files = {
        "g2vec_tpu/resilience/faults.py":
            'SEAMS = ("alpha", "beta")\n',
        "g2vec_tpu/core.py": '''\
from g2vec_tpu.resilience.faults import fault_point

def work():
    fault_point("alpha")
    fault_point("typo_seam")
''',
        "tests/test_core.py": 'PLAN = "stage=alpha,kind=crash"\n',
    }
    rep = run_analysis(_tree(tmp_path, files),
                       checker_ids=["fault-seams"])
    msgs = " | ".join(f.message for f in rep.findings)
    assert "typo_seam" in msgs            # undeclared literal at a call
    assert "beta" in msgs                 # declared but never called


# ---------------------------------------------------------------------------
# metrics-schema
# ---------------------------------------------------------------------------

def test_event_schema_enforced(tmp_path):
    files = {
        "g2vec_tpu/utils/metrics_schema.py": '''\
EVENT_SCHEMAS = {
    "boot": {"required": ["rank"], "optional": ["note"]},
}
''',
        "g2vec_tpu/app.py": '''\
def go(metrics, extra):
    metrics.emit("boot", rank=0, note="hi")       # clean
    metrics.emit("boot", nope=1)                  # unknown field + no rank
    metrics.emit("mystery", x=1)                  # unknown kind
    metrics.emit("boot", **extra)                 # splat: no missing check
''',
    }
    rep = run_analysis(_tree(tmp_path, files),
                       checker_ids=["metrics-schema"])
    msgs = [f.message for f in rep.findings]
    assert any("nope" in m for m in msgs)
    assert any("rank" in m for m in msgs)
    assert any("mystery" in m for m in msgs)
    # Exactly the three: the clean site and the splat site are quiet.
    assert len(msgs) == 3


# ---------------------------------------------------------------------------
# config-doc-drift
# ---------------------------------------------------------------------------

def test_readme_flag_drift_flagged(tmp_path):
    files = {
        "g2vec_tpu/config.py": '''\
def build_parser(p):
    p.add_argument("--documented-flag", type=int)
    p.add_argument("--secret-flag", type=int)
''',
        "README.md": "Use `--documented-flag N` to tune things.\n",
    }
    rep = run_analysis(_tree(tmp_path, files),
                       checker_ids=["config-doc-drift"])
    assert len(rep.findings) == 1
    assert "--secret-flag" in rep.findings[0].message

    files["README.md"] += "Also `--secret-flag`.\n"
    rep2 = run_analysis(_tree(tmp_path / "ok", files),
                        checker_ids=["config-doc-drift"])
    assert rep2.clean


# ---------------------------------------------------------------------------
# the repo itself
# ---------------------------------------------------------------------------

def test_repo_is_clean():
    """The CI gate: the full suite over THIS repo, with the committed
    baseline, has zero active findings and zero stale entries — and
    finishes far inside the 30s budget."""
    t0 = time.perf_counter()
    rep = run_analysis(REPO, baseline_path=os.path.join(
        REPO, "ANALYZE_BASELINE.json"))
    elapsed = time.perf_counter() - t0
    assert rep.checkers_run == ["lock-discipline", "jax-purity",
                                "fault-seams", "metrics-schema",
                                "config-doc-drift", "epoch-stamp"]
    assert not rep.findings, \
        "\n".join(f"{f.location()}: [{f.checker}] {f.message}"
                  for f in rep.findings)
    assert not rep.stale_baseline
    assert elapsed < 30.0


def test_seeded_idem_race_is_caught(tmp_path):
    """Re-introduce the PR 11 bug: strip the ``with self._idem_lock:``
    around admit()'s dedup lookup in a COPY of daemon.py and prove the
    lock-discipline checker finds the unlocked mutation."""
    with open(os.path.join(REPO, "g2vec_tpu", "serve",
                           "daemon.py")) as f:
        src = f.read()
    pat = re.compile(
        r"^(\s*)with self\._idem_lock:\n"
        r"(\1    orig = self\._idem\.get\(job\.idem_key\)\n"
        r"\1    if orig is None:\n"
        r"\1        self\._idem\[job\.idem_key\] = job\.job_id\n"
        r"\1        reserved = True\n)", re.M)
    m = pat.search(src)
    assert m, "admit()'s idem critical section moved — update this test"
    dedented = "".join(line[4:] if line.strip() else line
                       for line in m.group(2).splitlines(keepends=True))
    mutated = src[:m.start()] + dedented + src[m.end():]
    root = _tree(tmp_path, {"g2vec_tpu/serve/daemon.py": mutated})
    rep = run_analysis(root, checker_ids=["lock-discipline"])
    hits = [f for f in rep.findings
            if "_idem" in f.message and f.context == "ServeDaemon.admit"]
    assert hits, [f.message for f in rep.findings]


def test_unknown_checker_id_raises():
    with pytest.raises(KeyError):
        run_analysis(REPO, checker_ids=["no-such-checker"])


# ---------------------------------------------------------------------------
# regression tests for the races the checker surfaced (and we fixed)
# ---------------------------------------------------------------------------

def _daemon(tmp_path):
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions
    opts = ServeOptions(
        socket_path=os.path.join(str(tmp_path), "serve.sock"),
        state_dir=os.path.join(str(tmp_path), "state"))
    return ServeDaemon(opts, console=lambda s: None)


def _hammer(n_threads, fn):
    errs = []

    def run():
        try:
            fn()
        except BaseException as e:      # pragma: no cover - diagnostic
            errs.append(e)

    ts = [threading.Thread(target=run) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


def test_job_id_serial_has_no_lost_updates(tmp_path):
    """``_serial += 1`` raced between connection threads before the fix:
    two admits could read the same value and mint colliding serials.
    After N increments from T threads the counter must be exactly N*T."""
    d = _daemon(tmp_path)
    ids = []

    def mint():
        for _ in range(200):
            ids.append(d._new_job_id())

    _hammer(8, mint)
    assert d._serial == 8 * 200
    serials = [i.split("-")[0] for i in ids]
    assert len(set(serials)) == len(serials)


def test_state_counts_have_no_lost_updates(tmp_path):
    """``_state_counts[state] += 1`` runs on the scheduler thread AND
    connection threads; unlocked, concurrent bumps vanish."""
    d = _daemon(tmp_path)

    def bump():
        for _ in range(300):
            d._job_state("jX", "queued")

    _hammer(6, bump)
    assert d._state_counts["queued"] == 6 * 300
    assert d.status()["job_states"]["queued"] == 6 * 300
