"""Sharded-training tests on the 8-virtual-device CPU mesh (SURVEY.md §4
item 5): DP+TP mesh runs produce the same numerics as single-device runs,
including with shard-uneven shapes (padding + masked means)."""
import numpy as np
import pytest

from g2vec_tpu.parallel.mesh import make_mesh_context, pad_to_multiple
from g2vec_tpu.train import train_cbow


def _data(rng, n_paths=100, n_genes=50):
    labels = (rng.random(n_paths) < 0.5).astype(np.int32)
    paths = np.zeros((n_paths, n_genes), dtype=np.int8)
    half = n_genes // 2
    for i, lab in enumerate(labels):
        idx = rng.choice(half, size=6, replace=False) + (0 if lab == 0 else half)
        paths[i, idx] = 1
    return paths, labels


def test_pad_to_multiple():
    assert pad_to_multiple(7, 4) == 8
    assert pad_to_multiple(8, 4) == 8
    assert pad_to_multiple(1, 1) == 1
    assert pad_to_multiple(0, 4) == 0


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_mesh_training_matches_single_device(rng, mesh_shape):
    # 100 paths and 50 genes are NOT divisible by most mesh axes — this
    # exercises the shard-even padding path too.
    paths, labels = _data(rng)
    kwargs = dict(hidden=8, learning_rate=0.05, max_epochs=6,
                  compute_dtype="float32", seed=0)
    single = train_cbow(paths, labels, **kwargs)
    ctx = make_mesh_context(mesh_shape)
    sharded = train_cbow(paths, labels, mesh_ctx=ctx, **kwargs)
    # Same split, same init, same math -> near-identical accuracies and
    # embeddings (tiny float drift from different reduction orders allowed).
    assert len(single.history) == len(sharded.history)
    for h1, h2 in zip(single.history, sharded.history):
        assert abs(h1["acc_val"] - h2["acc_val"]) < 1e-6
    np.testing.assert_allclose(single.w_ih, sharded.w_ih, rtol=5e-4, atol=1e-5)


def test_mesh_needs_enough_devices():
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh_context((8, 2))


def test_padded_genes_get_zero_update(rng):
    # 50 genes on a model axis of 4 -> pad to 52; the two pad rows of W_ih
    # must come back sliced off, and real outputs must be unaffected.
    paths, labels = _data(rng, n_paths=64, n_genes=50)
    ctx = make_mesh_context((2, 4))
    res = train_cbow(paths, labels, hidden=8, learning_rate=0.05,
                     max_epochs=3, compute_dtype="float32", seed=0,
                     mesh_ctx=ctx)
    assert res.w_ih.shape == (50, 8)
    assert np.isfinite(res.w_ih).all()
