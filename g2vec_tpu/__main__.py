"""CLI entry: ``python -m g2vec_tpu EXPR CLIN NET NAME [options]``.

Same invocation shape as the reference (``python G2Vec.py ...``,
README.md:15-19) plus the framework flags documented in
:mod:`g2vec_tpu.config`. Platform env vars are set BEFORE jax is imported
anywhere (the pipeline defers its jax imports for exactly this reason).
"""
from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    argv = list(argv) if argv is not None else sys.argv[1:]
    if argv and argv[0] == "serve":
        # Resident service mode: `g2vec serve --socket ... --state-dir ...`
        # (serve/cli.py). Dispatched BEFORE the classic parser — the
        # daemon has its own flag surface and, like the supervisors below,
        # must own platform/env setup before any jax import.
        from g2vec_tpu.serve.cli import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "analyze":
        # Static-analysis suite: `g2vec analyze [--json] ...`
        # (analyze/cli.py). Pure AST — never touches jax, so it is
        # dispatched before any platform/env setup. Exit codes: 0
        # clean, 1 findings, 2 usage.
        from g2vec_tpu.analyze.cli import analyze_main

        return analyze_main(argv[1:])
    from g2vec_tpu.config import config_from_args

    cfg = config_from_args(argv)
    if cfg.fleet_size:
        # Fleet launcher/supervisor: spawns one child per rank (the
        # children get --fleet-size scrubbed from their argv), watches
        # them, and on peer death re-plans the mesh over the surviving
        # devices and relaunches with --resume. Checked BEFORE any
        # jax/platform setup, like --supervise: the launcher holds no
        # accelerator state.
        from g2vec_tpu.resilience.fleet import supervise_fleet

        return supervise_fleet(cfg, argv)
    if cfg.supervise:
        # Child-process supervision: the supervisor re-invokes this module
        # (minus its own flags, plus --resume) so even a SIGKILL'd child —
        # the shape of a real TPU preemption — is restarted from its last
        # checkpoint. Checked BEFORE any jax/platform setup: the supervisor
        # process itself must hold no accelerator state.
        from g2vec_tpu.resilience.supervisor import supervise_cli

        return supervise_cli(cfg, argv)
    if cfg.compilation_cache or cfg.cache_dir:
        # Persistent-compile tier, wired through the env BEFORE jax comes
        # up anywhere in this process: the pipeline re-applies it via
        # jax.config (idempotent), but programs compiled earlier than
        # that — e.g. by --distributed init — must hit the cache too.
        from g2vec_tpu.cache import resolve_cache_tiers

        xla_dir, _ = resolve_cache_tiers(cfg.cache_dir,
                                         cfg.compilation_cache,
                                         walk_cache_enabled=False)
        if xla_dir:
            os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", xla_dir)
    if cfg.platform == "cpu" and cfg.mesh_shape:
        # Virtual-device convenience: an NxM mesh on CPU means the user wants
        # the sharding dry-run — give them the devices. XLA reads this flag
        # lazily at first backend creation, so it works even though a
        # sitecustomize may have imported jax already.
        need = cfg.mesh_shape[0] * cfg.mesh_shape[1]
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={need}").strip()
    if cfg.platform:
        os.environ["JAX_PLATFORMS"] = cfg.platform
        # A sitecustomize may already have pinned jax_platforms via
        # jax.config.update (which outranks the env var) — re-force it.
        import jax

        jax.config.update("jax_platforms", cfg.platform)
    if cfg.distributed:
        # Must happen before the first backend use in this process.
        from g2vec_tpu.parallel.distributed import initialize

        initialize(cfg.coordinator, cfg.process_id, cfg.num_processes)
    if cfg.scenario:
        # Statistical scenario engine: --scenario bootstrap|permutation|cv
        # expands into a seeded replicate manifest, runs it as one lane
        # batch, and reduces the outputs into <NAME>_stability.txt
        # (stats/). Validated mutually exclusive with --manifest/--seeds.
        from g2vec_tpu.stats.run import run_scenario

        run_scenario(cfg)
        return 0
    if cfg.manifest or cfg.batch_seeds:
        # Batch engine: N manifest lanes as shape-bucketed batched device
        # programs in THIS process (batch/engine.py). Validated
        # incompatible with --distributed/--supervise/--fleet-size above,
        # so the plain run path below never sees these flags.
        from g2vec_tpu.batch.engine import run_batch

        run_batch(cfg)
        return 0
    from g2vec_tpu.pipeline import run

    try:
        run(cfg)
    except BaseException:
        if cfg.distributed and not isinstance(
                (sys.exc_info()[1]), (KeyboardInterrupt, SystemExit)):
            # A failed distributed run must EXIT, not linger: interpreter
            # teardown blocks in the coordination-service shutdown waiting
            # for dead/stalled peers (the coordinator process hosts the
            # service), which would hold the fleet supervisor's
            # failure-detection hostage to the very hang the watchdog just
            # converted into an error. Print the classifiable traceback,
            # flush, and exit hard.
            import traceback

            traceback.print_exc()
            sys.stderr.flush()
            sys.stdout.flush()
            os._exit(1)
        raise
    return 0


if __name__ == "__main__":
    sys.exit(main())
