"""Dependency-driven stage overlap for the pipeline.

The seven stages ran strictly sequentially even though their resource
profiles barely intersect: stage 3's walks are host-core work (the native
CSR sampler never touches the device), while the multi-second XLA
compiles the later stages pay (the trainer chunk program, the k-means
program) need the device + one host core. GraphVite (arXiv:1903.00757)
calls this out as THE hybrid-system win — CPU-side sampling overlapped
with accelerator-side work. This module is the small scheduler that
expresses it:

- :meth:`OverlapScheduler.submit` registers a named task with optional
  dependencies (names of earlier tasks). A task runs on the scheduler's
  own executor as soon as its dependencies resolve. The executor is
  DISTINCT from the sampler range pool (ops/host_walker.py) — a stage
  task may fan out into and wait on that pool, and sharing one executor
  would let the waiter starve the ranges it waits for.
- :meth:`OverlapScheduler.result` joins a task, re-raising its exception.
- :meth:`OverlapScheduler.drain` joins everything. On failure the FIRST
  failing task's exception propagates (by submission order — determinism
  under concurrent failures), tasks whose dependencies failed are
  cancelled (marked, never started), and no thread is left waiting on a
  task that can no longer run — the no-deadlock contract the tier-1
  smoke test pins.

Accounting: a background task "saves" the wall time it ran while the
caller was NOT waiting on it: ``saved = duration - wait``, where wait is
the time :meth:`result`/:meth:`drain` actually blocked on it (floor 0).
Those per-task numbers land in the ``done`` metrics event as
``overlap_saved_s`` so ``stage_seconds`` stays attributable — a stage
that reads short because its compile was warmed elsewhere says so.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Iterable, Optional


class TaskCancelled(RuntimeError):
    """A task never ran because a dependency failed (or drain cancelled
    pending work after a failure)."""


class _Task:
    def __init__(self, name: str, fn: Callable, deps: tuple):
        self.name = name
        self.fn = fn
        self.deps = deps
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.started_at: Optional[float] = None
        self.duration = 0.0
        self.waited = 0.0       # seconds a joiner actually blocked on us


class OverlapScheduler:
    """A tiny named-task DAG over one ThreadPoolExecutor.

    Not a general executor: tasks are few (per-group walks, two compile
    warms), names are unique per run, and the scheduling policy is just
    "run when deps are done". That smallness is deliberate — the failure
    semantics (original exception, clean drain) must stay auditable.
    """

    def __init__(self, max_workers: int = 4):
        self._ex = ThreadPoolExecutor(max_workers=max_workers,
                                      thread_name_prefix="g2v-overlap")
        # _lock covers the registry shape (submit/prune/add_closer run
        # on different threads); unlocked READS in result/as_completed/
        # drain are safe because tasks are never mutated after submit —
        # only their _Task fields change, via each task's own Event.
        self._tasks: Dict[str, _Task] = {}      # guarded-by: _lock
        self._order: list = []                  # guarded-by: _lock
        self._lock = threading.Lock()
        self._done_cv = threading.Condition()
        self._closers: list = []                # guarded-by: _lock

    # ---- submission -------------------------------------------------------

    def submit(self, name: str, fn: Callable, *,
               deps: Iterable[str] = ()) -> None:
        """Register ``fn`` to run as soon as every task in ``deps`` has
        succeeded. Dependencies must already be submitted (the pipeline
        builds its DAG top-down)."""
        deps = tuple(deps)
        with self._lock:
            if name in self._tasks:
                raise ValueError(f"duplicate overlap task {name!r}")
            for d in deps:
                if d not in self._tasks:
                    raise ValueError(
                        f"task {name!r} depends on unsubmitted {d!r}")
            task = _Task(name, fn, deps)
            self._tasks[name] = task
            self._order.append(task)
        self._ex.submit(self._run, task)

    def _run(self, task: _Task) -> None:
        try:
            for d in task.deps:
                dep = self._tasks[d]
                dep.done.wait()
                if dep.error is not None:
                    raise TaskCancelled(
                        f"overlap task {task.name!r} cancelled: dependency "
                        f"{d!r} failed ({type(dep.error).__name__})")
            task.started_at = time.perf_counter()
            task.result = task.fn()
        except BaseException as e:  # noqa: BLE001 — joiner re-raises
            task.error = e
        finally:
            if task.started_at is not None:
                task.duration = time.perf_counter() - task.started_at
            task.done.set()
            with self._done_cv:
                self._done_cv.notify_all()

    # ---- joining ----------------------------------------------------------

    def has(self, name: str) -> bool:
        """Whether ``name`` was submitted (conditional joins)."""
        with self._lock:
            return name in self._tasks

    def result(self, name: str):
        """Block until ``name`` finishes; return its value or re-raise its
        exception. The block time is charged to the task's wait account
        (the part of its duration that did NOT overlap useful work)."""
        task = self._tasks[name]
        t0 = time.perf_counter()
        task.done.wait()
        task.waited += time.perf_counter() - t0
        if task.error is not None:
            raise task.error
        return task.result

    def as_completed(self, names: Iterable[str]):
        """Yield ``(name, result)`` over ``names`` in COMPLETION order —
        the batch engine integrates each lane's walk product the moment
        it lands instead of joining in submission order, so host-side
        integration overlaps the pool's remaining sampling. Ties (several
        tasks already done) yield in submission order for determinism. A
        failed task re-raises its exception when reached, like
        :meth:`result`; wait time is charged to the task yielded next
        (the one the caller actually blocked for)."""
        pending = list(names)
        for n in pending:
            if n not in self._tasks:
                raise KeyError(f"unknown overlap task {n!r}")
        index = {t.name: i for i, t in enumerate(self._order)}
        t0 = time.perf_counter()
        while pending:
            ready = [n for n in pending if self._tasks[n].done.is_set()]
            if not ready:
                with self._done_cv:
                    self._done_cv.wait(timeout=0.05)
                continue
            name = min(ready, key=index.__getitem__)
            pending.remove(name)
            task = self._tasks[name]
            task.waited += time.perf_counter() - t0
            t0 = time.perf_counter()
            if task.error is not None:
                raise task.error
            yield name, task.result

    def drain(self, raise_errors: bool = True) -> None:
        """Join every submitted task (dependency-cancelled ones included —
        they finish immediately by construction, so this cannot deadlock).
        With ``raise_errors``, re-raise the first REAL failure in
        submission order; TaskCancelled shadows of that failure are
        swallowed (the original exception is the one the caller must see).
        """
        for task in list(self._order):
            t0 = time.perf_counter()
            task.done.wait()
            task.waited += time.perf_counter() - t0
        if not raise_errors:
            return
        for task in list(self._order):
            if task.error is not None and not isinstance(task.error,
                                                         TaskCancelled):
                raise task.error

    def prune(self, prefix: str) -> int:
        """Forget every task whose name starts with ``prefix``, waiting
        first for any still in flight. A resident engine (batch/engine.py
        ``ResidentEngine``, the serve daemon) pushes unbounded batches
        through ONE scheduler; without pruning, each batch's walk/warm
        tasks — results included — would accumulate for the process
        lifetime. Per-batch name prefixes keep this safe: nothing outside
        the batch can depend on a pruned task. Returns the number
        removed."""
        with self._lock:
            victims = [t for t in self._order if t.name.startswith(prefix)]
        for t in victims:
            t.done.wait()
        with self._lock:
            for t in victims:
                self._tasks.pop(t.name, None)
                try:
                    # analyze: allow[lock-discipline] deliberate lock
                    # drop above: waiting for in-flight victims under
                    # _lock would deadlock submit(); the per-batch name-
                    # prefix contract (nothing submits into a batch
                    # being pruned) makes this re-acquire safe.
                    self._order.remove(t)
                except ValueError:
                    pass
        return len(victims)

    def add_closer(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Register an unblocker run at the START of :meth:`close`.

        The sampler->trainer streaming edge (train/stream.py) is the one
        task shape whose thread can legitimately BLOCK mid-run — a shard
        producer parked on a full ring. The plain drain contract ("every
        task finishes") only holds if something wakes it when the
        consumer is gone, so the edge registers its ring's ``cancel``
        here; close() then cannot deadlock on a producer whose consumer
        died in a foreground stage. Closers run in registration order;
        a closer's exception is swallowed (close is a ``finally`` path).
        Returns a deregistration thunk — a finished edge removes its
        closer so a resident engine's scheduler does not accumulate one
        per batch for the process lifetime.
        """
        with self._lock:
            self._closers.append(fn)

        def remove() -> None:
            with self._lock:
                try:
                    self._closers.remove(fn)
                except ValueError:
                    pass
        return remove

    def close(self) -> None:
        """Drain without raising, then shut the executor down. Safe in a
        ``finally``: a pipeline failing in a foreground stage must not
        hang on background tasks at teardown."""
        with self._lock:
            closers = list(self._closers)
        for fn in closers:
            try:
                fn()
            except Exception:  # noqa: BLE001 — teardown must proceed
                pass
        self.drain(raise_errors=False)
        self._ex.shutdown(wait=True)

    # ---- accounting -------------------------------------------------------

    def saved_seconds(self) -> Dict[str, float]:
        """Per-task overlap win: run time the caller never waited for."""
        out = {}
        for task in self._order:
            if task.error is not None or task.started_at is None:
                continue
            out[task.name] = round(max(0.0, task.duration - task.waited), 3)
        return out

    def __enter__(self) -> "OverlapScheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
