"""Host-side collectives over the JAX coordination-service KV store.

Why this exists: the multihost collectives this framework needs outside of
jit — the packed-row allgather behind ``sharded_native_path_set``, the
coordinator-broadcast checkpoint restore, the per-stage duration gather the
straggler detector runs — were all built on ``jax.experimental
.multihost_utils``, which lowers to XLA programs over a global mesh. Two
problems at fleet scale:

1. XLA collectives BLOCK FOREVER when a peer dies, stalls, or never joins —
   a single preempted host wedges every other rank with no diagnostic
   (the exact failure mode resilience/fleet.py exists to convert into a
   named, classified, retryable error).
2. The CPU backend cannot run cross-process XLA computations at all
   (``Multiprocess computations aren't implemented on the CPU backend``),
   so none of those paths could even be exercised by a real multi-process
   test off-TPU.

The coordination service (the distributed KV store + barriers every
``jax.distributed.initialize`` brings up, on every backend) solves both:
values are plain host bytes, every blocking read takes a deadline, and a
missed deadline identifies exactly WHICH rank never published — the
attribution a watchdog needs to say "rank 1 is the straggler" instead of
"something hung". These helpers are therefore the transport for every
host-data collective in ``parallel/distributed.py`` on backends without
cross-process XLA, and the fleet watchdog's rank-attribution source
everywhere.

Collective contract (same as multihost_utils): every process calls every
helper in the same program order. Keys are namespaced by a process-local
monotonically increasing sequence number, so the order itself is the only
thing that must agree; a restarted supervisor attempt starts a fresh
process and therefore a fresh sequence. Published values are left in the
store (the coordination service dies with the job; payloads here are
kilobytes except the checkpoint broadcast, which is one-shot per resume).
"""
from __future__ import annotations

import base64
import io
import itertools
import time
from typing import List, Optional

import numpy as np

#: Deadline used when the caller passes 0/None — effectively "block like the
#: legacy collective did", but still bounded so a wedged fleet eventually
#: surfaces an error instead of holding its slot forever.
DEFAULT_DEADLINE_S = 7 * 24 * 3600.0

_seq = itertools.count()


def kv_client():
    """The process's coordination-service client, or None outside a
    ``jax.distributed.initialize``-ed run."""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client
    except Exception:  # noqa: BLE001 — jax layout drift: treat as absent
        return None


def _is_deadline_error(e: BaseException) -> bool:
    msg = str(e)
    return "DEADLINE_EXCEEDED" in msg or "timed out" in msg.lower()


# The KV payload encoding rides the STRING key/value API: the pinned
# jaxlib's ``*_bytes`` variants segfault outright (observed on both the
# 1-byte and the get side), while string values are solid to multi-MB. The
# leading "1" frames the value so empty payloads (barriers) stay non-empty.

def _encode(payload: bytes) -> str:
    return "1" + base64.b64encode(payload).decode("ascii")


def _decode(value: str) -> bytes:
    return base64.b64decode(value[1:])


def allgather_bytes(name: str, payload: bytes, *,
                    deadline: Optional[float] = None) -> List[bytes]:
    """Gather one bytes payload per rank, in rank order. COLLECTIVE.

    On deadline expiry raises :class:`~g2vec_tpu.resilience.fleet
    .PeerTimeoutError` naming every rank whose payload never arrived —
    enriched with heartbeat-staleness detail when a liveness dir is
    configured (dead host vs live straggler).
    """
    import jax

    from g2vec_tpu.resilience import fleet
    from g2vec_tpu.resilience.faults import fault_point

    nproc = jax.process_count()
    if nproc == 1:
        return [payload]
    # The distributed fault seam: a scoped stall/kill here models a rank
    # that never reaches the collective. Fires BEFORE the publish so the
    # faulted rank's key stays absent — exactly what its peers then report.
    fault_point("allgather")
    client = kv_client()
    if client is None:
        raise RuntimeError(
            f"host collective {name!r} needs the coordination service; "
            "was jax.distributed.initialize() skipped?")
    rank = jax.process_index()
    seq = next(_seq)
    fleet.note_collective(name, seq)
    key = f"g2vec/ag/{seq}/{name}"
    client.key_value_set(f"{key}/{rank}", _encode(payload))
    budget = deadline if deadline else DEFAULT_DEADLINE_S
    t_end = time.monotonic() + budget
    out: List[Optional[bytes]] = [None] * nproc
    out[rank] = payload
    missing: List[int] = []
    for peer in range(nproc):
        if peer == rank:
            continue
        left_ms = max(1, int((t_end - time.monotonic()) * 1000))
        try:
            out[peer] = _decode(client.blocking_key_value_get(
                f"{key}/{peer}", left_ms))
        except Exception as e:  # noqa: BLE001 — classify, don't swallow
            if not _is_deadline_error(e):
                raise
            missing.append(peer)
    if missing:
        raise fleet.PeerTimeoutError(
            f"collective {name!r} (seq {seq}) exceeded its "
            f"{budget:.1f}s deadline; missing rank(s): {missing}"
            f"{fleet.describe_ranks(missing)}",
            collective=name, suspects=tuple(missing))
    return out  # type: ignore[return-value] — no None gaps past the raise


def allgather_array(name: str, arr: np.ndarray, *,
                    deadline: Optional[float] = None) -> np.ndarray:
    """process_allgather semantics for a host array: returns the
    ``[nproc, *arr.shape]`` stack (every rank must contribute one array of
    the same shape/dtype)."""
    arr = np.ascontiguousarray(arr)
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    parts = allgather_bytes(name, buf.getvalue(), deadline=deadline)
    return np.stack([np.load(io.BytesIO(p), allow_pickle=False)
                     for p in parts])


def broadcast_bytes(name: str, payload: Optional[bytes], *,
                    deadline: Optional[float] = None) -> bytes:
    """Rank 0 publishes ``payload``; every rank returns it. COLLECTIVE."""
    import jax

    from g2vec_tpu.resilience import fleet
    from g2vec_tpu.resilience.faults import fault_point

    nproc = jax.process_count()
    if nproc == 1:
        if payload is None:
            raise ValueError(f"broadcast {name!r}: rank 0 payload is None")
        return payload
    fault_point("allgather")
    client = kv_client()
    if client is None:
        raise RuntimeError(
            f"host broadcast {name!r} needs the coordination service; "
            "was jax.distributed.initialize() skipped?")
    seq = next(_seq)
    fleet.note_collective(name, seq)
    key = f"g2vec/bc/{seq}/{name}"
    if jax.process_index() == 0:
        if payload is None:
            raise ValueError(f"broadcast {name!r}: rank 0 payload is None")
        client.key_value_set(key, _encode(payload))
        return payload
    budget = deadline if deadline else DEFAULT_DEADLINE_S
    try:
        return _decode(client.blocking_key_value_get(
            key, max(1, int(budget * 1000))))
    except Exception as e:  # noqa: BLE001
        if not _is_deadline_error(e):
            raise
        raise fleet.PeerTimeoutError(
            f"broadcast {name!r} (seq {seq}) exceeded its {budget:.1f}s "
            f"deadline; missing rank(s): [0]{fleet.describe_ranks([0])}",
            collective=name, suspects=(0,)) from e


def barrier(name: str, *, deadline: Optional[float] = None) -> None:
    """All ranks rendezvous; stragglers are named on deadline expiry."""
    allgather_bytes(f"barrier/{name}", b"", deadline=deadline)


# ---------------------------------------------------------------------------
# Chunked explicit-key exchange — the walk-shard / gradient transport
# ---------------------------------------------------------------------------
#
# Two differences from the sequence-numbered collectives above, both forced
# by the sharded trainer (train/stream.py with a ShardContext):
#
# 1. **Explicit keys, no _seq.** The walk-shard exchange runs on the
#    producer thread while the trainer thread allreduces activations on the
#    main thread. Two threads drawing from one process-local sequence
#    counter interleave nondeterministically, so the "same program order"
#    contract of allgather_bytes cannot hold across threads. These helpers
#    instead take a caller-supplied key that is already globally unique and
#    deterministic (e.g. ``shard/{epoch}/{index}``) — blocking gets simply
#    wait for that key, so cross-thread interleaving is harmless.
# 2. **Chunking.** Walk-shard payloads at million-gene scale are multi-MB
#    (rows x ceil(G/8) bytes). The KV string values are solid to multi-MB
#    but not unbounded, and the ``*_bytes`` entry points that would lift the
#    limit segfault in the pinned jaxlib (see the framing note above _encode
#    — that workaround stays pinned here). Payloads are therefore split into
#    raw chunks of at most KV_CHUNK_BYTES before the base64 framing.

#: Raw payload bytes per KV value chunk. base64 expands 4/3, so the stored
#: string stays ~2.7MB — comfortably inside the observed multi-MB envelope.
KV_CHUNK_BYTES = 2 * 1024 * 1024


def put_bytes_chunked(key: str, payload: bytes, *, client=None,
                      chunk_bytes: int = KV_CHUNK_BYTES) -> int:
    """Publish ``payload`` under ``key`` as framed chunks; returns the
    chunk count. NOT collective — pure publish under an explicit key."""
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be positive, got {chunk_bytes}")
    client = client if client is not None else kv_client()
    if client is None:
        raise RuntimeError(
            f"put_bytes_chunked({key!r}) needs the coordination service; "
            "was jax.distributed.initialize() skipped?")
    n = max(1, -(-len(payload) // chunk_bytes))
    for i in range(n):
        chunk = payload[i * chunk_bytes:(i + 1) * chunk_bytes]
        client.key_value_set(f"{key}/c{i}", _encode(chunk))
    # Count published LAST: a reader that sees the count knows every chunk
    # key is already present (the service orders sets from one client).
    client.key_value_set(f"{key}/n", str(n))
    return n


def get_bytes_chunked(key: str, *, deadline: Optional[float] = None,
                      client=None, owner: Optional[int] = None) -> bytes:
    """Blocking read of a chunked payload published by
    :func:`put_bytes_chunked`. On deadline expiry raises PeerTimeoutError
    naming ``owner`` (when given) as the rank that never published."""
    from g2vec_tpu.resilience import fleet

    client = client if client is not None else kv_client()
    if client is None:
        raise RuntimeError(
            f"get_bytes_chunked({key!r}) needs the coordination service; "
            "was jax.distributed.initialize() skipped?")
    budget = deadline if deadline else DEFAULT_DEADLINE_S
    t_end = time.monotonic() + budget
    try:
        left_ms = max(1, int((t_end - time.monotonic()) * 1000))
        n = int(client.blocking_key_value_get(f"{key}/n", left_ms))
        parts = []
        for i in range(n):
            left_ms = max(1, int((t_end - time.monotonic()) * 1000))
            parts.append(_decode(client.blocking_key_value_get(
                f"{key}/c{i}", left_ms)))
    except Exception as e:  # noqa: BLE001 — classify, don't swallow
        if not _is_deadline_error(e):
            raise
        who = [] if owner is None else [owner]
        raise fleet.PeerTimeoutError(
            f"chunked get {key!r} exceeded its {budget:.1f}s deadline; "
            f"missing rank(s): {who}{fleet.describe_ranks(who)}",
            collective=key, suspects=tuple(who)) from e
    return b"".join(parts)


def exchange_bytes(key: str, payload: Optional[bytes], owner: int, *,
                   deadline: Optional[float] = None,
                   chunk_bytes: int = KV_CHUNK_BYTES) -> bytes:
    """Rank ``owner`` publishes ``payload`` under the explicit ``key``;
    every rank returns it. Single-process: a passthrough.

    The walk-shard transport: unlike :func:`broadcast_bytes` this is safe to
    call concurrently from multiple threads because the key carries all the
    coordination state (callers must make keys unique and agree on the
    owner — in the sharded trainer both derive from the shard index).
    """
    import jax

    if jax.process_count() == 1:
        if payload is None:
            raise ValueError(f"exchange {key!r}: owner payload is None")
        return payload
    if jax.process_index() == owner:
        if payload is None:
            raise ValueError(f"exchange {key!r}: owner payload is None")
        put_bytes_chunked(f"g2vec/xc/{key}", payload,
                          chunk_bytes=chunk_bytes)
        return payload
    return get_bytes_chunked(f"g2vec/xc/{key}", deadline=deadline,
                             owner=owner)
