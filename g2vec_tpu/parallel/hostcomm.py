"""Host-side collectives over the JAX coordination-service KV store.

Why this exists: the multihost collectives this framework needs outside of
jit — the packed-row allgather behind ``sharded_native_path_set``, the
coordinator-broadcast checkpoint restore, the per-stage duration gather the
straggler detector runs — were all built on ``jax.experimental
.multihost_utils``, which lowers to XLA programs over a global mesh. Two
problems at fleet scale:

1. XLA collectives BLOCK FOREVER when a peer dies, stalls, or never joins —
   a single preempted host wedges every other rank with no diagnostic
   (the exact failure mode resilience/fleet.py exists to convert into a
   named, classified, retryable error).
2. The CPU backend cannot run cross-process XLA computations at all
   (``Multiprocess computations aren't implemented on the CPU backend``),
   so none of those paths could even be exercised by a real multi-process
   test off-TPU.

The coordination service (the distributed KV store + barriers every
``jax.distributed.initialize`` brings up, on every backend) solves both:
values are plain host bytes, every blocking read takes a deadline, and a
missed deadline identifies exactly WHICH rank never published — the
attribution a watchdog needs to say "rank 1 is the straggler" instead of
"something hung". These helpers are therefore the transport for every
host-data collective in ``parallel/distributed.py`` on backends without
cross-process XLA, and the fleet watchdog's rank-attribution source
everywhere.

Collective contract (same as multihost_utils): every process calls every
helper in the same program order. Keys are namespaced by a process-local
monotonically increasing sequence number, so the order itself is the only
thing that must agree; a restarted supervisor attempt starts a fresh
process and therefore a fresh sequence. Published values are left in the
store (the coordination service dies with the job; payloads here are
kilobytes except the checkpoint broadcast, which is one-shot per resume).
"""
from __future__ import annotations

import base64
import io
import itertools
import time
from typing import List, Optional

import numpy as np

#: Deadline used when the caller passes 0/None — effectively "block like the
#: legacy collective did", but still bounded so a wedged fleet eventually
#: surfaces an error instead of holding its slot forever.
DEFAULT_DEADLINE_S = 7 * 24 * 3600.0

_seq = itertools.count()


def kv_client():
    """The process's coordination-service client, or None outside a
    ``jax.distributed.initialize``-ed run."""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client
    except Exception:  # noqa: BLE001 — jax layout drift: treat as absent
        return None


def _is_deadline_error(e: BaseException) -> bool:
    msg = str(e)
    return "DEADLINE_EXCEEDED" in msg or "timed out" in msg.lower()


# The KV payload encoding rides the STRING key/value API: the pinned
# jaxlib's ``*_bytes`` variants segfault outright (observed on both the
# 1-byte and the get side), while string values are solid to multi-MB. The
# leading "1" frames the value so empty payloads (barriers) stay non-empty.

def _encode(payload: bytes) -> str:
    return "1" + base64.b64encode(payload).decode("ascii")


def _decode(value: str) -> bytes:
    return base64.b64decode(value[1:])


def allgather_bytes(name: str, payload: bytes, *,
                    deadline: Optional[float] = None) -> List[bytes]:
    """Gather one bytes payload per rank, in rank order. COLLECTIVE.

    On deadline expiry raises :class:`~g2vec_tpu.resilience.fleet
    .PeerTimeoutError` naming every rank whose payload never arrived —
    enriched with heartbeat-staleness detail when a liveness dir is
    configured (dead host vs live straggler).
    """
    import jax

    from g2vec_tpu.resilience import fleet
    from g2vec_tpu.resilience.faults import fault_point

    nproc = jax.process_count()
    if nproc == 1:
        return [payload]
    # The distributed fault seam: a scoped stall/kill here models a rank
    # that never reaches the collective. Fires BEFORE the publish so the
    # faulted rank's key stays absent — exactly what its peers then report.
    fault_point("allgather")
    client = kv_client()
    if client is None:
        raise RuntimeError(
            f"host collective {name!r} needs the coordination service; "
            "was jax.distributed.initialize() skipped?")
    rank = jax.process_index()
    seq = next(_seq)
    fleet.note_collective(name, seq)
    key = f"g2vec/ag/{seq}/{name}"
    client.key_value_set(f"{key}/{rank}", _encode(payload))
    budget = deadline if deadline else DEFAULT_DEADLINE_S
    t_end = time.monotonic() + budget
    out: List[Optional[bytes]] = [None] * nproc
    out[rank] = payload
    missing: List[int] = []
    for peer in range(nproc):
        if peer == rank:
            continue
        left_ms = max(1, int((t_end - time.monotonic()) * 1000))
        try:
            out[peer] = _decode(client.blocking_key_value_get(
                f"{key}/{peer}", left_ms))
        except Exception as e:  # noqa: BLE001 — classify, don't swallow
            if not _is_deadline_error(e):
                raise
            missing.append(peer)
    if missing:
        raise fleet.PeerTimeoutError(
            f"collective {name!r} (seq {seq}) exceeded its "
            f"{budget:.1f}s deadline; missing rank(s): {missing}"
            f"{fleet.describe_ranks(missing)}",
            collective=name, suspects=tuple(missing))
    return out  # type: ignore[return-value] — no None gaps past the raise


def allgather_array(name: str, arr: np.ndarray, *,
                    deadline: Optional[float] = None) -> np.ndarray:
    """process_allgather semantics for a host array: returns the
    ``[nproc, *arr.shape]`` stack (every rank must contribute one array of
    the same shape/dtype)."""
    arr = np.ascontiguousarray(arr)
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    parts = allgather_bytes(name, buf.getvalue(), deadline=deadline)
    return np.stack([np.load(io.BytesIO(p), allow_pickle=False)
                     for p in parts])


def broadcast_bytes(name: str, payload: Optional[bytes], *,
                    deadline: Optional[float] = None) -> bytes:
    """Rank 0 publishes ``payload``; every rank returns it. COLLECTIVE."""
    import jax

    from g2vec_tpu.resilience import fleet
    from g2vec_tpu.resilience.faults import fault_point

    nproc = jax.process_count()
    if nproc == 1:
        if payload is None:
            raise ValueError(f"broadcast {name!r}: rank 0 payload is None")
        return payload
    fault_point("allgather")
    client = kv_client()
    if client is None:
        raise RuntimeError(
            f"host broadcast {name!r} needs the coordination service; "
            "was jax.distributed.initialize() skipped?")
    seq = next(_seq)
    fleet.note_collective(name, seq)
    key = f"g2vec/bc/{seq}/{name}"
    if jax.process_index() == 0:
        if payload is None:
            raise ValueError(f"broadcast {name!r}: rank 0 payload is None")
        client.key_value_set(key, _encode(payload))
        return payload
    budget = deadline if deadline else DEFAULT_DEADLINE_S
    try:
        return _decode(client.blocking_key_value_get(
            key, max(1, int(budget * 1000))))
    except Exception as e:  # noqa: BLE001
        if not _is_deadline_error(e):
            raise
        raise fleet.PeerTimeoutError(
            f"broadcast {name!r} (seq {seq}) exceeded its {budget:.1f}s "
            f"deadline; missing rank(s): [0]{fleet.describe_ranks([0])}",
            collective=name, suspects=(0,)) from e


def barrier(name: str, *, deadline: Optional[float] = None) -> None:
    """All ranks rendezvous; stragglers are named on deadline expiry."""
    allgather_bytes(f"barrier/{name}", b"", deadline=deadline)
