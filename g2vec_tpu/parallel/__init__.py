"""Parallelism: device mesh, sharding specs, distributed init.

The reference is single-process/single-device (SURVEY.md §2); here DP and TP
are first-class. The strategy (SURVEY.md §2 "Parallelism strategies"):

- **DP**: shard the path batch over the ``data`` mesh axis; gradient psum is
  inserted by GSPMD because params are replicated along ``data``.
- **TP**: shard the gene axis — rows of ``W_ih`` and columns of the multi-hot
  ``X`` — over the ``model`` axis; the hidden activations of ``X @ W_ih``
  are psum-reduced over ``model`` by GSPMD.
- PP/EP/CP/SP are structurally inapplicable (no layer stack, no experts, no
  sequence axis — paths are orderless gene sets); the gene axis IS this
  workload's long-context axis, and TP over it is its scaling story.
"""
from g2vec_tpu.parallel.mesh import MeshContext, make_mesh_context  # noqa: F401
